"""Benchmark: flagship Transformer LM training throughput on one chip.

Mirrors the reference's benchmark harness (examples/cpp/Transformer/
transformer.cc:183-211: timed training loop printing ELAPSED TIME /
THROUGHPUT) with the reference model scale (hidden 1024, 16 heads, 12
layers, seq 512 — TransformerConfig, transformer.cc:79-85) recast as the
decoder-only LM, and adds the MFU accounting BASELINE.md targets.

Prints the primary JSON line
  {"metric": "transformer_lm_tokens_per_sec_per_chip", "value": N,
   "unit": "tokens/s", "vs_baseline": MFU / 0.35}
**LAST** — the driver parses the LAST line as the number of record, so any
secondary legs (the TPU seq-4096 long-context leg) print before it.
(vs_baseline = fraction of the 35%-MFU north-star target, BASELINE.json.)
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12  # bf16
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v3" in kind:
        return 123e12
    if "v6" in kind:
        return 918e12
    return 2e12  # CPU fallback so the harness still runs


def _hbm_stats(device) -> dict:
    """{peak_hbm_bytes, hbm_bytes_in_use} from the backend allocator, or
    {} when the platform has no memory_stats (XLA:CPU)."""
    try:
        stats = device.memory_stats()
    except Exception:  # pragma: no cover - platform-dependent
        stats = None
    if not stats:
        return {}
    out = {}
    if stats.get("peak_bytes_in_use") is not None:
        out["peak_hbm_bytes"] = int(stats["peak_bytes_in_use"])
    if stats.get("bytes_in_use") is not None:
        out["hbm_bytes_in_use"] = int(stats["bytes_in_use"])
    return out


def _addressable_bytes_per_chip(tree) -> int:
    """Bytes of `tree`'s leaves resident on device 0 — the per-chip
    at-rest footprint a sharded layout actually achieves (replicated
    leaves count in full; 1/shards leaves count their one shard)."""
    import jax

    dev0 = jax.devices()[0]
    total = 0
    for leaf in jax.tree.leaves(tree):
        for sh in getattr(leaf, "addressable_shards", ()):
            if sh.device == dev0:
                total += int(sh.data.size) * sh.data.dtype.itemsize
    return total


def _measure_lm(cfg, batch: int, steps: int, warmup: int, on_tpu: bool,
                tune=None, out: dict = None):
    """(tokens/s, MFU) of one LM training config, or (None, None) when
    every retry reads as a backend fluke (>100% MFU). `tune(config)`, when
    given, mutates the FFConfig before the model is built — the ablation
    legs use it to flip kernel layout / collective-overlap / mesh knobs
    against an otherwise identical measurement. `out`, when a dict, is
    filled with the leg's memory forensics: allocator stats after warmup
    (resident state incl. masters + optimizer slots — the reading the
    weight-update-sharding ablation compares) and the compile's
    update-sharding decision."""
    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import build_transformer_lm
    from flexflow_tpu.models.transformer import transformer_lm_flops_per_token

    from flexflow_tpu import telemetry

    config = FFConfig()
    config.batch_size = batch
    if on_tpu:
        # full mixed-precision policy: bf16 activations, fp32 master weights
        from flexflow_tpu.fftype import DataType

        config.computation_dtype = DataType.DT_BFLOAT16
    if tune is not None:
        tune(config)
    ff = FFModel(config)
    build_transformer_lm(ff, cfg, batch_size=batch)
    with telemetry.span("bench.compile", seq=cfg.sequence_length):
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        step_fn = ff.executor.build_train_step()

    rs = np.random.RandomState(0)
    toks = rs.randint(0, cfg.vocab_size,
                      (batch, cfg.sequence_length)).astype(np.int32)
    pos = np.tile(np.arange(cfg.sequence_length, dtype=np.int32), (batch, 1))
    labels = rs.randint(0, cfg.vocab_size,
                        (batch, cfg.sequence_length, 1)).astype(np.int32)
    batch_data = ff._make_batch({"tokens": toks, "positions": pos}, labels)

    import statistics

    import jax.numpy as jnp

    state = (ff._params, ff._state, ff._opt_slots, ff._step, ff._counters)
    rng = jax.random.key(0)

    # RELAY-IMMUNE two-point measurement (methodology established against
    # the tunneled backend in scripts/debug_calibrate.py, also used by the
    # cost-model calibration): the whole measured run is ONE jitted
    # fori_loop of train steps (the Legion begin_trace/end_trace replay
    # loop, transformer.cc:183-197, collapsed into a single executable —
    # per-step host dispatch cannot pollute the reading) with a DYNAMIC
    # trip count, synchronized by FETCHING the step counter
    # (block_until_ready does not reliably synchronize through the relay;
    # a fetch does, at a large constant cost), timed at n and 3n steps —
    # the slope is the true per-step time with every constant relay
    # overhead cancelled exactly.
    def loop_fn():
        @jax.jit
        def loop(st, r, batch, n):
            def body(_, carry):
                st, r = carry
                r, sub = jax.random.split(r)
                out = step_fn(*st, sub, batch)
                return (out[:5], r)

            return jax.lax.fori_loop(0, n, body, (st, r))

        return loop

    loop = loop_fn()

    def sync(st):
        return int(jax.device_get(st[3]))  # step counter: forces completion

    with telemetry.span("bench.warmup", steps=warmup):
        st, rng = loop(state, rng, batch_data, jnp.int32(warmup))
        sync(st)  # compile + warm

    if out is not None:
        out.update(_hbm_stats(jax.devices()[0]))
        upd = getattr(ff, "_update_sharding", None) or {}
        out["update_sharding"] = bool(upd.get("enabled"))
        out["update_stage"] = int(upd.get("stage", 0))
        out["update_shards"] = int(upd.get("shards", 1))
        # addressable parameter bytes on chip 0 AT REST — the reading
        # the stage-3 1/shards layout shrinks (stage ≤ 2 keeps it flat)
        out["addressable_param_bytes_per_chip"] = (
            _addressable_bytes_per_chip(ff._params))
        pred = upd.get("predicted") or {}
        if pred:
            out["predicted_mem_bytes_per_chip"] = (
                pred["sharded_mem_bytes"] if upd.get("enabled")
                else pred["replicated_mem_bytes"])

    def t_of(n, st, rng):
        ts = []
        with telemetry.span("bench.measure", steps=n):
            for _ in range(3):
                t0 = time.perf_counter()
                st, rng = loop(st, rng, batch_data, jnp.int32(n))
                sync(st)
                ts.append(time.perf_counter() - t0)
        return statistics.median(ts), st, rng

    flops_per_token = transformer_lm_flops_per_token(cfg)
    peak = _peak_flops(jax.devices()[0])
    # guard against measurement flukes (the relay occasionally acks without
    # executing — a negative or implausible slope): retry until plausible
    for _ in range(3):
        t1, st, rng = t_of(steps, st, rng)
        t2, st, rng = t_of(3 * steps, st, rng)
        per_step = (t2 - t1) / (2 * steps)
        if per_step <= 0:
            continue
        tokens_per_sec = batch * cfg.sequence_length / per_step
        mfu = tokens_per_sec * flops_per_token / peak
        if not on_tpu or mfu <= 1.0:
            return tokens_per_sec, mfu
    return None, None


def _measure_fit_loop(cfg, batch: int, batches_per_epoch: int,
                      epochs_timed: int, pipeline_steps: int, on_tpu: bool):
    """tokens/s of the REAL `fit` loop — the throughput training jobs
    actually see, unlike the scan-slope leg's device-time ceiling.
    pipeline_steps=1 is the eager per-step loop; >1 routes through the
    pipelined engine (fused chunk dispatch + async prefetch, engine/).
    The gap between this leg and the slope metric is the dispatch +
    input-pipeline overhead the engine exists to remove."""
    import time as _time

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu import telemetry
    from flexflow_tpu.models import build_transformer_lm

    config = FFConfig()
    config.batch_size = batch
    if on_tpu:
        from flexflow_tpu.fftype import DataType

        config.computation_dtype = DataType.DT_BFLOAT16
    ff = FFModel(config)
    build_transformer_lm(ff, cfg, batch_size=batch)
    with telemetry.span("bench.fit.compile", pipeline_steps=pipeline_steps):
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    n = batches_per_epoch * batch
    rs = np.random.RandomState(0)
    x = {
        "tokens": rs.randint(0, cfg.vocab_size,
                             (n, cfg.sequence_length)).astype(np.int32),
        "positions": np.tile(
            np.arange(cfg.sequence_length, dtype=np.int32), (n, 1)),
    }
    labels = rs.randint(0, cfg.vocab_size,
                        (n, cfg.sequence_length, 1)).astype(np.int32)

    fit_kw = dict(batch_size=batch, shuffle=False, verbose=False,
                  pipeline_steps=pipeline_steps)
    with telemetry.span("bench.fit.warmup", pipeline_steps=pipeline_steps):
        ff.fit(x, labels, epochs=1, **fit_kw)  # compile + warm
    with telemetry.span("bench.fit.measure", pipeline_steps=pipeline_steps):
        t0 = _time.perf_counter()
        ff.fit(x, labels, epochs=epochs_timed, **fit_kw)
        dt = _time.perf_counter() - t0
    tokens = epochs_timed * batches_per_epoch * batch * cfg.sequence_length
    return tokens / dt


def _fit_loop_legs(cfg, batch: int, on_tpu: bool,
                   pipeline_steps: int = 4) -> dict:
    """Eager + pipelined fit-loop legs; archived in the BENCH json (the
    payload's fit_loop field) so the bench-vs-fit gap stays tracked. On
    TPU the flagship model runs as-is (the relay's ~0.2-1.5 ms/step
    dispatch is the overhead under test); the CPU smoke swaps in a
    dispatch-bound config — local-CPU dispatch is ~50 µs, so against the
    smoke model's ~40 ms steps the loop overhead the engine removes
    would be invisible noise."""
    from flexflow_tpu.models import TransformerLMConfig

    if on_tpu:
        batches_per_epoch, epochs_timed = 16, 2
    else:
        cfg = TransformerLMConfig(
            vocab_size=256, hidden_size=64, num_heads=2, num_layers=1,
            sequence_length=64, attention_impl="xla")
        batch, batches_per_epoch, epochs_timed = 4, 32, 2
    eager = _measure_fit_loop(cfg, batch, batches_per_epoch, epochs_timed,
                              1, on_tpu)
    piped = _measure_fit_loop(cfg, batch, batches_per_epoch, epochs_timed,
                              pipeline_steps, on_tpu)
    return {
        "eager_tokens_per_sec": round(eager, 2),
        "pipelined_tokens_per_sec": round(piped, 2),
        "pipeline_steps": pipeline_steps,
        "speedup": round(piped / eager, 4) if eager > 0 else None,
    }


def _attention_ablation_legs(lcfg, batch: int, steps: int, warmup: int,
                             on_tpu: bool, packed_tps) -> dict:
    """seq-4096 attention-ablation legs: attribute the long-context gain
    to its round-7 components (docs/performance.md "Long-context path").

    - flash_packed vs flash_transposed: the relayout-free packed kernels
      (lane-offset / head-group BlockSpecs on the (b, s, h·d) projection
      layout) vs the head-transposed kernels whose (b,s,h,d)↔(b,h,s,d)
      copies PERF.md measured at ~0.8 ms/step on the flagship.
    - ring_overlap vs ring_serial: the sequence-parallel ring path with
      the double-buffered hop-before-compute ppermute pipeline vs the
      serial compute-then-hop ablation (--no-overlap-collectives), seq
      axis sharded over every local device. Skipped (null) on one chip —
      there is no ring to overlap.

    All legs reuse the slope methodology of `_measure_lm`; the packed
    reading is the already-measured seq-4096 leg, passed in so the
    number of record and its ablation baseline come from one run."""
    import dataclasses

    import jax

    legs = {
        "flash_packed_tokens_per_sec":
            None if packed_tps is None else round(packed_tps, 2),
    }
    tps_t, _ = _measure_lm(
        lcfg, batch, steps, warmup, on_tpu,
        tune=lambda c: setattr(c, "flash_packed_layout", False))
    legs["flash_transposed_tokens_per_sec"] = (
        None if tps_t is None else round(tps_t, 2))
    if packed_tps and tps_t:
        legs["packed_vs_transposed"] = round(packed_tps / tps_t, 4)

    n = jax.local_device_count()
    if n > 1:
        rcfg = dataclasses.replace(lcfg, attention_impl="ring")

        def ring_tune(overlap):
            def tune(c):
                c.mesh_axis_sizes = (1, 1, 1, n)  # data,model,pipe,seq
                c.enable_sample_parallel = True
                c.search_budget = 4
                c.overlap_collectives = overlap

            return tune

        for name, overlap in (("ring_overlap", True),
                              ("ring_serial", False)):
            tps_r, _ = _measure_lm(rcfg, batch, steps, warmup, on_tpu,
                                   tune=ring_tune(overlap))
            legs[f"{name}_tokens_per_sec"] = (
                None if tps_r is None else round(tps_r, 2))
        ro = legs.get("ring_overlap_tokens_per_sec")
        rs = legs.get("ring_serial_tokens_per_sec")
        if ro and rs:
            legs["overlap_vs_serial"] = round(ro / rs, 4)
        legs["ring_seq_shards"] = n
    else:
        legs["ring_overlap_tokens_per_sec"] = None
        legs["ring_serial_tokens_per_sec"] = None
    return legs


def _grad_sync_legs(cfg, batch: int, steps: int, warmup: int,
                    on_tpu: bool) -> dict:
    """Weight-update-sharding ablation (round 8, docs/performance.md
    "Weight-update sharding"): the same LM on a pure-dp mesh over all
    local devices, measured three ways —

    - replicated: the baseline serial gradient allreduce + every replica
      redundantly holding fp32 masters + optimizer slots and running the
      full update (--no-weight-update-sharding);
    - sharded_overlap: ZeRO-style 1/dp update with the grad reduce-scatter
      free to overlap backward compute and the updated-param all-gather
      deferred into each consumer's first use (--weight-update-sharding);
    - sharded_serial: same 1/dp state, overlap pricing/schedule off
      (--no-overlap-collectives) — isolates the overlap contribution from
      the memory win.

    Each leg records the allocator's resident bytes after warmup (masters
    + slots live there — the 1/dp saving shows up directly) next to its
    tokens/s. Also includes a ring_reduce_scatter microbench: the
    free-scheduled ppermute pipeline vs the barrier-forced serial
    hop-then-add ablation on a gradient-sized buffer — the schedule the
    sharded grad sync lowers to, measured in isolation."""
    import jax

    n = min(jax.local_device_count(), batch)
    legs = {"update_shards": n}
    if n <= 1:
        legs["skipped"] = "single device — no grad sync to shard"
        return legs

    def dp_tune(wus, overlap=True):
        def tune(c):
            c.mesh_axis_sizes = (n, 1, 1, 1)
            c.weight_update_sharding = wus
            c.overlap_collectives = overlap

        return tune

    for name, wus, overlap in (("replicated", False, True),
                               ("sharded_overlap", True, True),
                               ("sharded_serial", True, False)):
        mem: dict = {}
        tps, _ = _measure_lm(cfg, batch, steps, warmup, on_tpu,
                             tune=dp_tune(wus, overlap), out=mem)
        legs[f"{name}_tokens_per_sec"] = (
            None if tps is None else round(tps, 2))
        if "hbm_bytes_in_use" in mem:
            legs[f"{name}_hbm_bytes_in_use"] = mem["hbm_bytes_in_use"]
        if "predicted_mem_bytes_per_chip" in mem:
            legs[f"{name}_predicted_mem_bytes_per_chip"] = round(
                mem["predicted_mem_bytes_per_chip"])
    so, rep = (legs.get("sharded_overlap_tokens_per_sec"),
               legs.get("replicated_tokens_per_sec"))
    ss = legs.get("sharded_serial_tokens_per_sec")
    if so and rep:
        legs["sharded_overlap_vs_replicated"] = round(so / rep, 4)
    if so and ss:
        legs["overlap_vs_serial"] = round(so / ss, 4)

    try:
        legs["rs_microbench"] = _ring_rs_microbench(n)
    except Exception as e:  # pragma: no cover - defensive
        print(f"bench: ring-RS microbench failed: {e}", file=sys.stderr)
    return legs


def _ring_rs_microbench(n: int, rows: int = 4096, cols: int = 512,
                        iters: int = 8) -> dict:
    """Seconds per reduce-scatter of a (rows, cols) fp32 buffer over a
    dp=n mesh: the free-scheduled ppermute pipeline
    (parallel.ops.ring_reduce_scatter — each hop independent of the
    local chunk add beside it) vs the serial ablation whose
    optimization barrier forces every add to wait for its hop. Two-point
    slope over a jitted fori_loop, like every other bench leg."""
    import functools

    import jax
    import jax.numpy as jnp

    from flexflow_tpu.machine import MeshShape, build_mesh
    from flexflow_tpu.parallel.ops import ring_reduce_scatter

    rows -= rows % (n * n)
    mesh = build_mesh(MeshShape((n, 1, 1, 1)))
    x = jnp.arange(rows * cols, dtype=jnp.float32).reshape(rows, cols)
    out = {}
    for name, overlap in (("overlap", True), ("serial", False)):
        rs = functools.partial(ring_reduce_scatter, mesh=mesh,
                               axis_name="data", overlap=overlap)

        @jax.jit
        def loop(x0, m):
            def body(_, acc):
                # rescale so the collective (not the arithmetic) dominates
                # and the loop-carried value stays finite
                return jnp.tile(rs(acc) * 1e-3, (n, 1))

            return jax.lax.fori_loop(0, m, body, x0)

        jax.block_until_ready(loop(x, jnp.int32(iters)))  # compile + warm
        t1 = time.perf_counter()
        jax.block_until_ready(loop(x, jnp.int32(iters)))
        t1 = time.perf_counter() - t1
        t2 = time.perf_counter()
        jax.block_until_ready(loop(x, jnp.int32(3 * iters)))
        t2 = time.perf_counter() - t2
        out[f"{name}_s"] = max((t2 - t1) / (2 * iters), 0.0)
    if out.get("serial_s"):
        out["overlap_vs_serial"] = round(
            out["serial_s"] / out["overlap_s"], 4) if out["overlap_s"] else None
    out["bytes"] = rows * cols * 4
    return out


def _param_sharding_legs(cfg, batch: int, steps: int, warmup: int,
                         on_tpu: bool) -> dict:
    """ZeRO-3 / FSDP ablation (docs/performance.md "Parameter sharding"):
    the same LM on a pure-dp mesh over all local devices, measured four
    ways —

    - replicated: every chip holds the full model + full optimizer state
      (--weight-update-sharding=off);
    - stage2: masters/grads/slots 1/dp, params gathered-and-resident
      (=stage2);
    - stage3: params sharded at rest, per-layer just-in-time ring
      all-gather issued one layer ahead, gathered copy dropped after
      last use (=stage3);
    - stage3_serial: same layout, --no-overlap-collectives — isolates
      the one-layer-ahead overlap from the memory win.

    Each leg reports tokens/s, per-step seconds, ADDRESSABLE param bytes
    on chip 0 at rest (the 1/shards reading), allocator peak HBM (null
    on XLA:CPU), and the realized update stage. Plus a ring_all_gather
    overlap-vs-serial microbench — the gather schedule measured in
    isolation, the AG twin of the grad-sync RS microbench."""
    import jax

    n = min(jax.local_device_count(), batch)
    legs = {"shards": n}
    if n <= 1:
        legs["skipped"] = "single device — nothing to shard"
        return legs

    def tune_of(stage, overlap=True):
        def tune(c):
            c.mesh_axis_sizes = (n, 1, 1, 1)
            c.weight_update_sharding = stage >= 2
            c.weight_update_stage = stage
            c.overlap_collectives = overlap

        return tune

    for name, stage, overlap in (("replicated", 0, True),
                                 ("stage2", 2, True),
                                 ("stage3", 3, True),
                                 ("stage3_serial", 3, False)):
        mem: dict = {}
        tps, _ = _measure_lm(cfg, batch, steps, warmup, on_tpu,
                             tune=tune_of(stage, overlap), out=mem)
        legs[name] = {
            "tokens_per_sec": None if tps is None else round(tps, 2),
            "step_time_s": (None if not tps else
                            round(batch * cfg.sequence_length / tps, 6)),
            "addressable_param_bytes_per_chip":
                mem.get("addressable_param_bytes_per_chip"),
            "peak_hbm_bytes": mem.get("peak_hbm_bytes"),
            "update_stage": mem.get("update_stage"),
        }
    rep = legs["replicated"]
    s3 = legs["stage3"]
    if rep.get("addressable_param_bytes_per_chip") and \
            s3.get("addressable_param_bytes_per_chip"):
        legs["param_bytes_ratio"] = round(
            rep["addressable_param_bytes_per_chip"]
            / s3["addressable_param_bytes_per_chip"], 4)
    if rep.get("tokens_per_sec") and s3.get("tokens_per_sec"):
        legs["stage3_vs_replicated"] = round(
            s3["tokens_per_sec"] / rep["tokens_per_sec"], 4)
    ss = legs["stage3_serial"]
    if ss.get("tokens_per_sec") and s3.get("tokens_per_sec"):
        legs["overlap_vs_serial"] = round(
            s3["tokens_per_sec"] / ss["tokens_per_sec"], 4)
    try:
        legs["ag_microbench"] = _ring_ag_microbench(n)
    except Exception as e:  # pragma: no cover - defensive
        print(f"bench: ring-AG microbench failed: {e}", file=sys.stderr)
    return legs


def _ring_ag_microbench(n: int, rows: int = 4096, cols: int = 512,
                        iters: int = 8) -> dict:
    """Seconds per all-gather of a (rows, cols) fp32 buffer sharded over
    a dp=n mesh: the hop-before-use double-buffered ppermute ring
    (parallel.ops.ring_all_gather — the stage-3 per-layer gather
    schedule) vs the serial ablation whose barrier makes every hop wait
    for the previous local write. Two-point slope over a jitted
    fori_loop, like every other bench leg."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from flexflow_tpu.machine import MeshShape, build_mesh
    from flexflow_tpu.parallel.ops import ring_all_gather

    rows -= rows % n
    mesh = build_mesh(MeshShape((n, 1, 1, 1)))
    sharded = NamedSharding(mesh, P("data", None))
    x = jax.device_put(
        jnp.arange(rows * cols, dtype=jnp.float32).reshape(rows, cols),
        sharded)
    out = {}
    for name, overlap in (("overlap", True), ("serial", False)):
        ag = functools.partial(ring_all_gather, mesh=mesh,
                               axis_name="data", overlap=overlap)

        @jax.jit
        def loop(x0, m):
            def body(_, acc):
                # gather, rescale, re-slice to the at-rest layout (the
                # slice is local/free — the gather dominates)
                full = ag(acc) * 1e-3
                return jax.lax.with_sharding_constraint(full, sharded)

            return jax.lax.fori_loop(0, m, body, x0)

        jax.block_until_ready(loop(x, jnp.int32(iters)))  # compile + warm
        t1 = time.perf_counter()
        jax.block_until_ready(loop(x, jnp.int32(iters)))
        t1 = time.perf_counter() - t1
        t2 = time.perf_counter()
        jax.block_until_ready(loop(x, jnp.int32(3 * iters)))
        t2 = time.perf_counter() - t2
        out[f"{name}_s"] = max((t2 - t1) / (2 * iters), 0.0)
    if out.get("serial_s") and out.get("overlap_s"):
        out["overlap_vs_serial"] = round(
            out["serial_s"] / out["overlap_s"], 4)
    out["bytes"] = rows * cols * 4
    return out


def _rules_leg() -> dict:
    """Rule-registry pin (ffrules, analysis/rules.py): the content
    fingerprint of the STATIC generated rule set (no bench leg builds a
    graph exhibiting the data-driven families, so the static registry is
    exactly what every leg's search rewrote with), plus the wall time of
    the full five-pass verification sweep. Raises if the registry fails
    verification — the caller records the failure as a payload-level
    marker so a capture searched under unsound rules is never mistaken
    for a clean one."""
    from flexflow_tpu import FFConfig
    from flexflow_tpu.analysis import rules as ffrules

    sys.argv = [sys.argv[0]]
    cfg = FFConfig()
    mesh_sizes = {"data": 2, "model": 4, "dcn": 1, "seq": 1}
    cfg.mesh_axis_sizes = tuple(mesh_sizes.values())
    t0 = time.perf_counter()
    res = ffrules.verify_registry(mesh_sizes, cfg)
    wall = time.perf_counter() - t0
    errs = res.errors()
    if errs:
        raise RuntimeError(
            f"rule registry failed verification: "
            f"{[str(f) for f in errs[:3]]}")
    clean = res.by_code("rules_clean")[0]
    return {
        "fingerprint": clean.details["fingerprint"],
        "rules": clean.details["rules"],
        "scope": "static_registry",
        "verify_wall_s": round(wall, 3),
    }


def _warmstart_legs() -> dict:
    """Cold-vs-warm time-to-first-step against one fresh --warmstart-dir
    (compile start → first optimizer step done — the restart latency the
    warm-start subsystem exists to collapse, docs/performance.md "Warm
    start & compile caching"). Archived in the BENCH payload so the
    warm/cold ratio is tracked per round.

    Both legs run in this process, so jax's in-memory compilation
    memoization (keyed by HLO hash) is cleared between them — the warm
    leg must be served by the ON-DISK layers (persistent XLA executable
    cache + plan cache + calibration DB), exactly what a restarted
    process would hit. Multi-chip fleets also exercise the plan cache
    (search + calibration on the cold leg, fingerprint hit on the warm);
    a single-device fleet has no search, so there the legs measure the
    executable-cache layer alone."""
    import tempfile
    import time as _time

    import jax

    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, SGDOptimizer,
    )

    wdir = tempfile.mkdtemp(prefix="bench_warmstart_")
    multi = jax.device_count() > 1
    batch = 16

    def leg(tag: str) -> float:
        from flexflow_tpu import telemetry

        jax.clear_caches()
        config = FFConfig()
        config.batch_size = batch
        config.warmstart_dir = wdir
        if multi:
            config.search_budget = 4
            config.enable_parameter_parallel = True
            config.search_calibrate = 1
        ff = FFModel(config)
        # explicit names: default layer names embed a process-global guid
        # counter, and the two legs' fingerprints must match
        x = ff.create_tensor((batch, 256), name="ws_x")
        t = x
        for i in range(6):
            t = ff.dense(t, 256, ActiMode.AC_MODE_RELU, name=f"ws_fc{i}")
        ff.dense(t, 32, name="ws_head")
        rs = np.random.RandomState(0)
        X = rs.randn(batch, 256).astype(np.float32)
        Y = rs.randint(0, 32, (batch, 1)).astype(np.int32)
        with telemetry.span("bench.warmstart", leg=tag):
            t0 = _time.perf_counter()
            ff.compile(
                optimizer=SGDOptimizer(lr=0.01),
                loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
            # one optimizer step: first-step latency includes the train
            # step's jit compile + first batch staging
            ff.fit(X, Y, epochs=1, batch_size=batch, shuffle=False,
                   verbose=False)
            dt = _time.perf_counter() - t0
        return dt

    try:
        cold = leg("cold")
        warm = leg("warm")
    finally:
        # the dir only exists to connect the two legs; no compiles happen
        # after these legs, so the (process-global) cache pointer going
        # stale with it is harmless
        import shutil

        shutil.rmtree(wdir, ignore_errors=True)
    return {
        "cold_time_to_first_step_s": round(cold, 4),
        "warm_time_to_first_step_s": round(warm, 4),
        "speedup": round(cold / warm, 4) if warm > 0 else None,
    }


def _migration_legs(cfg, on_tpu: bool) -> dict:
    """fftrans migration leg: measured in-process migration seconds vs
    the TransitionPlan's predicted cost (docs/analysis.md "Transition
    verification") — a dp stage-3 trained model migrated live to a
    replicated hybrid mesh, no checkpoint-restart round trip. The
    measured/predicted fidelity ratio is the datapoint the future
    re-planner's pay-off rule needs: a re-shard pays for itself only
    when the predicted migration seconds (this leg calibrates the
    prediction) undercut the drift it removes."""
    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import build_transformer_lm
    from flexflow_tpu.resilience import migrate_state

    n_dev = jax.device_count()
    if n_dev < 4:
        return {"skipped": f"{n_dev} device(s) — no cross-mesh migration"}

    def build(mesh, stage3):
        # argv is restored below: a leg failure must not leak the
        # stage-3 flag into the later warm-start legs' FFConfig parse
        sys.argv = [sys.argv[0]] + (
            ["--weight-update-sharding=stage3"] if stage3 else [])
        config = FFConfig()
        config.mesh_axis_sizes = mesh
        config.batch_size = 4
        ff = FFModel(config)
        build_transformer_lm(ff, cfg, batch_size=4)
        ff.compile(optimizer=SGDOptimizer(lr=0.01, momentum=0.9),
                   loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        return ff

    saved_argv = list(sys.argv)
    try:
        old = build((4, 1, 1, 1), stage3=True)
        rs = np.random.RandomState(0)
        X = {"tokens": rs.randint(
                0, cfg.vocab_size,
                (4, cfg.sequence_length)).astype(np.int32),
             "positions": np.tile(
                 np.arange(cfg.sequence_length, dtype=np.int32), (4, 1))}
        Y = rs.randint(0, cfg.vocab_size,
                       (4, cfg.sequence_length, 1)).astype(np.int32)
        old.fit(X, Y, epochs=1, batch_size=4, shuffle=False,
                verbose=False)
        new = build((2, 2, 1, 1), stage3=False)
        section = migrate_state(old, new)
    finally:
        sys.argv = saved_argv
    predicted = section["predicted_s"]
    measured = section["measured_s"]
    return {
        "transfers": len(section["transfers"]),
        "bytes_on_wire": int(sum(section["bytes_on_wire"].values())),
        "predicted_s": round(predicted, 6),
        "measured_s": round(measured, 6),
        # >1 = the plan is optimistic on this backend (XLA:CPU pays
        # dispatch per leaf); the re-planner consumes this ratio as its
        # calibration factor
        "measured_vs_predicted": (round(measured / predicted, 4)
                                  if predicted > 0 else None),
        "stage3_src": True,
        "errors": (section.get("analysis") or {}).get("errors"),
    }


def _elastic_legs(cfg, on_tpu: bool) -> dict:
    """ffelastic leg: the cost of staying live through a re-plan
    (elastic/, docs/elastic.md). One dp=4 LM takes an injected 50x
    drift perturbation mid-fit; the leg records how long the loop ran
    on the stale plan (trigger latency), what the online re-search
    cost, what the migration cost vs its fftrans prediction (the
    fidelity ratio the payoff rule calibrates from), and how many
    steps until the drift monitor read clean again (steps-to-recover)."""
    import tempfile

    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import build_transformer_lm

    n_dev = jax.device_count()
    if n_dev < 4:
        return {"skipped": f"{n_dev} device(s) — no dp=4 elastic leg"}

    saved_argv = list(sys.argv)
    tdir = tempfile.mkdtemp(prefix="bench_elastic_")
    try:
        sys.argv = [sys.argv[0], "--telemetry-dir", tdir, "--diagnostics"]
        config = FFConfig()
        config.mesh_axis_sizes = (4, 1, 1, 1)
        config.batch_size = 4
        ff = FFModel(config)
        build_transformer_lm(ff, cfg, batch_size=4)
        ff.compile(optimizer=SGDOptimizer(lr=0.01, momentum=0.9),
                   loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        rs = np.random.RandomState(0)
        n = 24  # 6 steps/epoch
        X = {"tokens": rs.randint(
                0, cfg.vocab_size,
                (n, cfg.sequence_length)).astype(np.int32),
             "positions": np.tile(
                 np.arange(cfg.sequence_length, dtype=np.int32), (n, 1))}
        Y = rs.randint(0, cfg.vocab_size,
                       (n, cfg.sequence_length, 1)).astype(np.int32)
        ff.fit(X, Y, epochs=1, batch_size=4, shuffle=False, verbose=False)

        ctrl = ff.enable_elastic(
            cooldown_steps=0, horizon_steps=1000,
            visible_devices_fn=lambda: jax.devices()[:4])
        diag = ff.get_diagnostics()
        # the injected perturbation: the monitor now reads every step
        # as a 50x excursion over the plan's claimed makespan
        diag.drift.set_prediction((ff._predicted_step_s or 1e-3) / 50)

        step_times = []  # (step, device_time_s) during the elastic fit
        orig_on_step = diag.on_step

        def probe(rec):
            orig_on_step(rec)
            if ctrl.decisions:
                # freeze after the first decision: the recovery window
                # must not be polluted by a second re-plan
                ctrl.cooldown_steps = 10_000
            dev = rec.get("device_time_s")
            if dev is not None:
                step_times.append((int(rec.get("step", 0)), float(dev)))

        diag.on_step = probe
        ff.fit(X, Y, epochs=2, batch_size=4, shuffle=False, verbose=False)
    finally:
        sys.argv = saved_argv

    drifts = [d for d in ctrl.decisions if d.get("trigger") == "drift"]
    if not drifts:
        return {"skipped": "no drift decision fired", "decisions": 0}
    d0 = drifts[0]
    # steps-to-recover: first post-decision step whose device time is
    # back within 2x the pre-decision norm (the re-plan step itself
    # carries the recompile+migration spike)
    dstep = int(d0["step"])
    pre = sorted(t for s, t in step_times if s <= dstep)
    norm = pre[len(pre) // 2] if pre else None
    rec_step = next((s for s, t in step_times
                     if s > dstep and norm and t <= 2 * norm), None)
    pred = d0.get("predicted_migration_s")
    meas = d0.get("migration_measured_s")
    return {
        "decision": d0.get("decision"),
        "decisions": len(ctrl.decisions),
        # steps the loop ran on the stale plan between the advisory and
        # the decision (the controller consumes at the next boundary)
        "trigger_latency_steps": int(d0["step"])
        - int(d0["advisory"]["step"]),
        "research_s": round(d0.get("research_s") or 0.0, 6),
        "migration_predicted_s": (None if pred is None
                                  else round(pred, 6)),
        "migration_measured_s": (None if meas is None
                                 else round(meas, 6)),
        "migration_measured_vs_predicted": (
            round(meas / pred, 4)
            if pred and meas and pred > 0 else None),
        "steps_to_recover": (rec_step - dstep
                             if rec_step is not None else None),
        "lhs_s": d0.get("lhs_s"),
        "rhs_s": d0.get("rhs_s"),
    }


def _serving_legs(cfg, on_tpu: bool) -> dict:
    """Serving legs: requests/s/chip + decode tokens/s/chip through the
    continuous-batching engine (serving/) — the ROADMAP's "millions of
    users" metric next to the training slope — plus the PAGED-KV
    shared-prefix leg (`serving.paged` in the BENCH payload): the same
    engine re-run on a trace where every prompt opens with one system
    prompt, reporting prefix_hit_rate, cow_copies, and
    slots_at_fixed_hbm (contiguous KV rows ÷ the pool's peak working
    set — the vLLM capacity-recovery metric; ISSUE 11's bar is >= 2x).
    Completions are asserted bit-identical across layouts. The decode
    executables are warmed by one throwaway request so each measured
    drain is steady-state continuous batching. scripts/serve_bench.py is
    the standalone, load-tunable twin."""
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu import telemetry
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm

    if on_tpu:
        n_requests, slots, prompt_len, max_new = 32, 8, 8, 16
        shared_prefix, block = 64, 16
        sp_prompt_len = 96
    else:
        cfg = TransformerLMConfig(
            vocab_size=256, hidden_size=64, num_heads=2, num_layers=1,
            sequence_length=64, attention_impl="xla")
        n_requests, slots, prompt_len, max_new = 8, 4, 8, 8
        shared_prefix, block = 9, 4
        sp_prompt_len = 12
    config = FFConfig()
    config.batch_size = slots
    if on_tpu:
        from flexflow_tpu.fftype import DataType

        config.computation_dtype = DataType.DT_BFLOAT16
    ff = FFModel(config)
    build_transformer_lm(ff, cfg, batch_size=slots)
    with telemetry.span("bench.serve.compile"):
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    def drain(engine, prompts, tag):
        with telemetry.span("bench.serve.warmup", leg=tag):
            engine.generate(prompts[:1])  # compile buckets + decode step
        engine.reset_stats()
        for p in prompts:
            engine.submit(p)
        with telemetry.span("bench.serve.measure", leg=tag,
                            requests=len(prompts)):
            engine.run_until_drained()
        return ([r.generated for r in engine.scheduler.completed],
                engine.metrics_summary())

    rs = np.random.RandomState(0)
    prompts = [rs.randint(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]
    engine = ff.serve(slots=slots, max_new_tokens=max_new, prefill_chunk=8)
    _, stats = drain(engine, prompts, "uniform")
    out = {
        "requests_per_sec_per_chip": round(
            stats.get("requests_per_sec_per_chip", 0.0), 4),
        "decode_tokens_per_sec_per_chip": round(
            stats.get("decode_tokens_per_sec_per_chip", 0.0), 2),
        "requests": stats["requests_completed"],
        "slots": slots,
        "max_new_tokens": max_new,
        "kv_layout": stats["kv_layout"],
        "ttft_p50_s": round(stats.get("ttft_p50_s", 0.0), 4),
        # drain-count accounting: prompts that finished without emitting
        # a token are excluded from the TTFT denominator by design
        "no_token_requests": stats.get("no_token_requests", 0),
    }
    # request-grain tail latency from the engine's mergeable histograms
    # (engine.metrics_summary) — present whenever the measured window
    # saw the observation
    for short in ("queue_wait", "ttft", "tbt", "e2e"):
        for q in ("p50", "p95", "p99"):
            key = f"{short}_{q}_s"
            if key in stats:
                out[key] = round(stats[key], 6)

    # paged shared-prefix leg vs the contiguous ablation on one trace
    system = rs.randint(1, cfg.vocab_size, shared_prefix).tolist()
    tail = max(1, sp_prompt_len - shared_prefix)
    sp = [system + rs.randint(1, cfg.vocab_size, tail).tolist()
          if i else list(system) for i in range(n_requests)]
    paged_eng = ff.serve(slots=slots, max_new_tokens=max_new,
                         prefill_chunk=8, kv_layout="paged",
                         kv_block_size=block)
    paged_out, pst = drain(paged_eng, sp, "shared-prefix-paged")
    contig_eng = ff.serve(slots=slots, max_new_tokens=max_new,
                          prefill_chunk=8, kv_layout="contiguous")
    contig_out, cst = drain(contig_eng, sp, "shared-prefix-contiguous")
    if paged_out != contig_out:
        raise AssertionError(
            "paged completions diverge from contiguous on the "
            "shared-prefix trace")
    out["paged"] = {
        "shared_prefix": shared_prefix,
        "kv_block_size": pst["kv_block_size"],
        "requests_per_sec_per_chip": round(
            pst.get("requests_per_sec_per_chip", 0.0), 4),
        "contiguous_requests_per_sec_per_chip": round(
            cst.get("requests_per_sec_per_chip", 0.0), 4),
        "prefix_hit_rate": round(pst.get("prefix_hit_rate", 0.0), 4),
        "cow_copies": pst.get("cow_copies", 0),
        "kv_blocks_in_use_peak": pst.get("kv_blocks_in_use_peak", 0),
        "kv_hbm_bytes_per_layer": pst.get("kv_hbm_bytes_per_layer", 0),
        "contiguous_kv_hbm_bytes_per_layer": cst.get(
            "kv_hbm_bytes_per_layer", 0),
        # the engine's one definition of the capacity-recovery ratio
        # (serving/engine.py stats() `kv_peak_vs_contiguous`)
        "slots_at_fixed_hbm": round(pst["kv_peak_vs_contiguous"], 4),
    }

    # disaggregated leg (`serving.disagg` in the BENCH payload): the
    # same shared-prefix trace through serve(disaggregate=True) — two
    # Unity plans on disjoint sub-meshes at EQUAL total chips — next to
    # the unified paged engine above: TTFT/TBT p50/p95 side by side,
    # every KV handoff's measured-vs-predicted seconds, and the
    # decode-side radix hit rate on a SECOND wave after a full drain
    # with and without the cross-time cache (prefix_cache=False is the
    # ablation: prefixes die with their last resident). Needs >= 2
    # devices to split; a 1-chip run records why it skipped.
    import jax

    if jax.device_count() >= 2:
        try:
            out["disagg"] = _disagg_serving_leg(
                ff, telemetry, sp, slots, max_new, block,
                sorted(paged_eng.scheduler.completed,
                       key=lambda r: r.request_id), pst)
        except Exception as e:
            out["disagg"] = {"skipped": f"{type(e).__name__}: {e}"}
    else:
        out["disagg"] = {"skipped": "single device — no chips to split"}

    # speculative leg (`serving.spec` in the BENCH payload): the same
    # shared-prefix trace through serve(speculate=True, draft_model=...)
    # with a seed-clone drafter (the all-accept extreme — the verify-path
    # ceiling on untrained weights), colocated so no extra chips are
    # consumed: TBT p50/p95 + decode tokens/s/chip next to the unified
    # paged engine, plus the acceptance rate and the payoff gate's
    # decision tally. Bit-identity to the unified drain is asserted —
    # speculation is a latency optimization, never a sampling change.
    try:
        out["spec"] = _spec_serving_leg(
            ff, cfg, telemetry, sp, slots, max_new, block,
            sorted(paged_eng.scheduler.completed,
                   key=lambda r: r.request_id), pst)
    except Exception as e:
        out["spec"] = {"skipped": f"{type(e).__name__}: {e}"}
    return out


def _spec_serving_leg(ff, lm_cfg, telemetry, prompts, slots, max_new,
                      block, unified_done, unified_stats) -> dict:
    """One `serving.spec` payload: the shared-prefix trace through the
    speculative engine (seed-clone drafter, colocated), asserted
    bit-identical to the unified paged drain (`unified_done`, sorted by
    request id)."""
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import build_transformer_lm

    dconfig = FFConfig()
    dconfig.batch_size = slots
    draft = FFModel(dconfig)
    build_transformer_lm(draft, lm_cfg, batch_size=slots)
    with telemetry.span("bench.serve.compile", leg="spec-drafter"):
        draft.compile(
            optimizer=SGDOptimizer(lr=0.01),
            loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    eng = ff.serve(speculate=True, draft_model=draft, slots=slots,
                   max_new_tokens=max_new, prefill_chunk=8,
                   kv_block_size=block)
    with telemetry.span("bench.serve.warmup", leg="spec"):
        # full-trace warmup: compiles the decode buckets AND the
        # drafter/verify executables, and warms the acceptance EMA so
        # the measured wave runs on a calibrated payoff gate
        eng.generate(prompts)
    eng.reset_stats()
    for p in prompts:
        eng.submit(p)
    with telemetry.span("bench.serve.measure", leg="spec",
                        requests=len(prompts)):
        eng.run_until_drained()
    done = sorted(eng.scheduler.completed, key=lambda r: r.request_id)
    if [r.generated for r in done] != [r.generated for r in unified_done]:
        raise AssertionError(
            "speculative completions diverge from the unified paged "
            "engine on the shared-prefix trace")
    st = eng.metrics_summary()
    sp = eng.stats()["speculation"]
    leg = {
        "draft_chips": eng.draft_chips,
        "k_max": eng.k_max,
        "rounds": sp["rounds"],
        "acceptance_rate": round(sp["acceptance_rate"], 4),
        "acceptance_ema": round(sp["acceptance_ema"], 4),
        "decision_counts": sp["decision_counts"],
        "requests": len(prompts),
        "decode_tokens_per_sec_per_chip": round(
            st.get("decode_tokens_per_sec_per_chip", 0.0), 2),
        "unified_decode_tokens_per_sec_per_chip": round(
            unified_stats.get("decode_tokens_per_sec_per_chip", 0.0), 2),
    }
    for q in ("p50", "p95"):
        key = f"tbt_{q}_s"
        if key in st:
            leg[key] = round(st[key], 6)
        if key in unified_stats:
            leg[f"unified_{key}"] = round(unified_stats[key], 6)
    return leg


def _disagg_serving_leg(ff, telemetry, prompts, slots, max_new, block,
                        unified_done, unified_stats) -> dict:
    """One `serving.disagg` payload: the shared-prefix trace through the
    disaggregated engine, asserted bit-identical to the unified paged
    drain (`unified_done`, sorted by request id), with the cross-time
    radix ablation run on a separate prefix_cache=False engine."""

    def wave(engine, tag):
        engine.reset_stats()
        for p in prompts:
            engine.submit(p)
        with telemetry.span("bench.serve.measure", leg=tag,
                            requests=len(prompts)):
            engine.run_until_drained()
        done = sorted(engine.completed, key=lambda r: r.request_id)
        return [r.generated for r in done], engine.metrics_summary()

    dis = ff.serve(disaggregate=True, slots=slots, max_new_tokens=max_new,
                   prefill_chunk=8, kv_block_size=block)
    with telemetry.span("bench.serve.warmup", leg="disagg"):
        dis.generate(prompts[:1])
    done, dst = wave(dis, "disagg")
    if done != [r.generated for r in unified_done]:
        raise AssertionError(
            "disaggregated completions diverge from the unified paged "
            "engine on the shared-prefix trace")
    fully_cached = sum(1 for h in dis.handoffs
                       if h["injected_blocks"] == 0)
    # second wave AFTER the full drain: every hit here crossed a drain
    # boundary, i.e. came from the cross-time radix cache
    _, dst2 = wave(dis, "disagg-wave2")
    fully_cached += sum(1 for h in dis.handoffs
                        if h["injected_blocks"] == 0)

    # ablation: same engine shape, prefix_cache=False — the registry
    # dies with its residents, so wave 2 restarts cold
    nc = ff.serve(disaggregate=True, slots=slots, max_new_tokens=max_new,
                  prefill_chunk=8, kv_block_size=block, prefix_cache=False)
    with telemetry.span("bench.serve.warmup", leg="disagg-nocache"):
        nc.generate(prompts[:1])
    nc_done, _ = wave(nc, "disagg-nocache")
    if nc_done != done:
        raise AssertionError(
            "prefix_cache=False completions diverge — the cross-time "
            "cache changed tokens")
    _, nst2 = wave(nc, "disagg-nocache-wave2")

    leg = {
        "prefill_chips": dis.prefill_chips,
        "decode_chips": dis.decode_chips,
        "kv_block_size": block,
        "requests": len(prompts),
        "requests_per_sec_per_chip": round(
            dst.get("requests_per_sec_per_chip", 0.0), 4),
        "unified_requests_per_sec_per_chip":
            unified_stats.get("requests_per_sec_per_chip", 0.0),
        # handoff plane: measured wall next to the fftrans prediction,
        # summed over the measured wave (disagg_section carries the
        # per-handoff records + verified programs in the strategy report)
        "handoffs": dst.get("handoffs", 0) + dst2.get("handoffs", 0),
        "fully_cached_handoffs": fully_cached,
        "handoff_predicted_s": round(dst2.get("handoff_predicted_s", 0.0)
                                     + dst.get("handoff_predicted_s", 0.0),
                                     6),
        "handoff_measured_s": round(dst2.get("handoff_measured_s", 0.0)
                                    + dst.get("handoff_measured_s", 0.0),
                                    6),
        # post-drain wave hit rates: with the cross-time radix cache vs
        # the prefix_cache=False ablation at identical load
        "prefix_hit_rate_cross_time": round(
            (dst2.get("decode") or {}).get("prefix_hit_rate", 0.0), 4),
        "prefix_hit_rate_no_cross_time": round(
            (nst2.get("decode") or {}).get("prefix_hit_rate", 0.0), 4),
    }
    # TTFT observes on the prefill side, TBT on the decode side; the
    # unified engine's flat keys sit next to them for the equal-chips
    # comparison
    pre, dec = dst.get("prefill") or {}, dst.get("decode") or {}
    for short, side in (("ttft", pre), ("queue_wait", pre), ("tbt", dec)):
        for q in ("p50", "p95"):
            key = f"{short}_{q}_s"
            if key in side:
                leg[key] = round(side[key], 6)
            if key in unified_stats:
                leg[f"unified_{key}"] = round(unified_stats[key], 6)
    return leg


def main():
    # --telemetry-dir DIR: archive this run's host-side timeline + metrics
    # (trace.json / metrics.jsonl) so BENCH numbers come with forensics.
    # Parsed here because the harness deliberately clears argv below (the
    # model under test must not inherit bench flags).
    argv = sys.argv[1:]
    telemetry_dir = None
    if "--telemetry-dir" in argv:
        i = argv.index("--telemetry-dir")
        if i + 1 >= len(argv) or argv[i + 1].startswith("--"):
            print("bench: --telemetry-dir requires a directory argument",
                  file=sys.stderr)
            sys.exit(2)
        telemetry_dir = argv[i + 1]
    sys.argv = [sys.argv[0]]
    import jax

    from flexflow_tpu import telemetry
    from flexflow_tpu.models import TransformerLMConfig

    session = None
    if telemetry_dir:
        session = telemetry.activate(telemetry.TelemetrySession(telemetry_dir))
        session.write_manifest()
    try:
        _bench_body(jax, TransformerLMConfig, telemetry, session)
    finally:
        # the timeline must survive a mid-bench crash — that is exactly
        # when the archived trace is wanted (close() is idempotent; the
        # success path already closed with the bench event recorded)
        if session is not None:
            session.close()


def _bench_body(jax, TransformerLMConfig, telemetry, session):
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = TransformerLMConfig(
            vocab_size=32000, hidden_size=1024, num_heads=16, num_layers=12,
            sequence_length=512, attention_impl="flash",
        )
        batch = 8
        steps, warmup = 20, 3
    else:  # CPU smoke mode
        cfg = TransformerLMConfig(
            vocab_size=512, hidden_size=128, num_heads=4, num_layers=2,
            sequence_length=128, attention_impl="xla",
        )
        batch = 4
        steps, warmup = 5, 1

    primary_mem: dict = {}
    tokens_per_sec, mfu = _measure_lm(cfg, batch, steps, warmup, on_tpu,
                                      out=primary_mem)

    seq4096 = None
    if on_tpu and tokens_per_sec is not None:
        # secondary LONG-CONTEXT leg (seq 4096, same model family): the
        # regime where flash's causal block-skipping and the online-softmax
        # path actually matter — quantifies the exceeds-reference
        # long-context capability (SURVEY §5). Printed BEFORE the primary
        # line (the driver's number of record is the LAST line — r05's
        # record was accidentally this leg, a phantom 41% regression);
        # failures only print to stderr.
        try:
            lcfg = TransformerLMConfig(
                vocab_size=32000, hidden_size=1024, num_heads=16,
                num_layers=12, sequence_length=4096,
                attention_impl="flash",
            )
            tps4k, mfu4k = _measure_lm(lcfg, batch=1, steps=5, warmup=1,
                                       on_tpu=on_tpu)
            if tps4k is not None:
                seq4096 = {
                    "metric": "transformer_lm_tokens_per_sec_per_chip_seq4096",
                    "value": round(tps4k, 2),
                    "unit": "tokens/s",
                    "vs_baseline": round(mfu4k / 0.35, 4),
                }
                # attention-ablation legs (round 7): transposed vs packed
                # kernel, ring overlap on/off — the BENCH payload must
                # attribute the long-context number to its components
                try:
                    seq4096["ablation"] = _attention_ablation_legs(
                        lcfg, batch=1, steps=5, warmup=1, on_tpu=on_tpu,
                        packed_tps=tps4k)
                except Exception as e:  # pragma: no cover - defensive
                    print(f"bench: attention ablation failed: {e}",
                          file=sys.stderr)
                print(json.dumps(seq4096))
            else:
                print("bench: long-context leg read as fluke, skipped",
                      file=sys.stderr)
        except Exception as e:  # pragma: no cover - defensive
            print(f"bench: long-context leg failed: {e}", file=sys.stderr)

    # fit-loop legs (eager vs --pipeline-steps): the throughput training
    # jobs actually see, printed as secondary lines AND archived inside
    # the primary payload so the bench-vs-fit gap is tracked per round
    fit_loop = None
    try:
        fit_loop = _fit_loop_legs(cfg, batch, on_tpu)
        print(json.dumps({
            "metric": "transformer_lm_fit_tokens_per_sec_eager",
            "value": fit_loop["eager_tokens_per_sec"],
            "unit": "tokens/s",
        }))
        print(json.dumps({
            "metric": "transformer_lm_fit_tokens_per_sec_pipelined",
            "value": fit_loop["pipelined_tokens_per_sec"],
            "pipeline_steps": fit_loop["pipeline_steps"],
            "speedup_vs_eager_fit": fit_loop["speedup"],
            "unit": "tokens/s",
        }))
    except Exception as e:  # pragma: no cover - defensive
        print(f"bench: fit-loop leg failed: {e}", file=sys.stderr)

    # grad-sync ablation legs (round 8): replicated allreduce vs ZeRO-
    # sharded update with/without overlap, with per-leg resident HBM so
    # the 1/dp optimizer-state saving lands next to tokens/s/chip
    grad_sync = None
    try:
        grad_sync = _grad_sync_legs(cfg, batch, steps, warmup, on_tpu)
        print(json.dumps({
            "metric": "grad_sync_ablation",
            **{k: v for k, v in grad_sync.items() if k != "rs_microbench"},
        }))
    except Exception as e:  # pragma: no cover - defensive
        print(f"bench: grad-sync ablation failed: {e}", file=sys.stderr)

    # param-sharding ablation legs (ZeRO-3/FSDP): replicated vs stage-2
    # vs stage-3 (±overlap) with addressable param bytes/chip at rest,
    # peak HBM and step time, plus the ring_all_gather microbench
    param_sharding = None
    try:
        param_sharding = _param_sharding_legs(cfg, batch, steps, warmup,
                                              on_tpu)
        print(json.dumps({
            "metric": "param_sharding_ablation",
            **{k: v for k, v in param_sharding.items()
               if k != "ag_microbench"},
        }))
    except Exception as e:  # pragma: no cover - defensive
        print(f"bench: param-sharding ablation failed: {e}",
              file=sys.stderr)

    # serving leg: requests/s/chip + decode tokens/s/chip through the
    # continuous-batching engine, as secondary lines + a `serving` field
    # in the primary payload
    serving = None
    try:
        serving = _serving_legs(cfg, on_tpu)
        print(json.dumps({
            "metric": "serving_requests_per_sec_per_chip",
            "value": serving["requests_per_sec_per_chip"],
            "unit": "req/s",
        }))
        print(json.dumps({
            "metric": "serving_decode_tokens_per_sec_per_chip",
            "value": serving["decode_tokens_per_sec_per_chip"],
            "unit": "tokens/s",
        }))
        if "paged" in serving:
            print(json.dumps({
                "metric": "serving_paged_slots_at_fixed_hbm",
                "value": serving["paged"]["slots_at_fixed_hbm"],
                "prefix_hit_rate": serving["paged"]["prefix_hit_rate"],
                "unit": "x contiguous",
            }))
        dg = serving.get("disagg") or {}
        if "prefill_chips" in dg:
            # the disaggregation headline: TTFT p95 at equal total chips
            # vs the unified engine, and the cross-time radix ablation
            print(json.dumps({
                "metric": "serving_disagg_ttft_p95_s",
                "value": dg.get("ttft_p95_s"),
                "unified_ttft_p95_s": dg.get("unified_ttft_p95_s"),
                "chips": f"{dg['prefill_chips']}p+{dg['decode_chips']}d",
                "unit": "s",
            }))
            print(json.dumps({
                "metric": "serving_disagg_prefix_hit_rate_cross_time",
                "value": dg.get("prefix_hit_rate_cross_time"),
                "no_cross_time": dg.get("prefix_hit_rate_no_cross_time"),
            }))
        sg = serving.get("spec") or {}
        if "rounds" in sg:
            # the speculation headline: TBT p95 vs plain decode at the
            # same chips, with the acceptance rate that priced the gate
            print(json.dumps({
                "metric": "serving_spec_tbt_p95_s",
                "value": sg.get("tbt_p95_s"),
                "unified_tbt_p95_s": sg.get("unified_tbt_p95_s"),
                "acceptance_rate": sg.get("acceptance_rate"),
                "rounds": sg.get("rounds"),
                "unit": "s",
            }))
    except Exception as e:  # pragma: no cover - defensive
        print(f"bench: serving leg failed: {e}", file=sys.stderr)

    # migration leg (fftrans): measured in-process migration seconds vs
    # the TransitionPlan's prediction on this mesh — the cost-model
    # fidelity datapoint the re-planner's pay-off rule will consume
    migration = None
    try:
        migration = _migration_legs(cfg, on_tpu)
        print(json.dumps({
            "metric": "migration_seconds",
            **{k: migration[k] for k in
               ("predicted_s", "measured_s", "measured_vs_predicted",
                "transfers", "bytes_on_wire")
               if k in migration},
            "unit": "s",
        }))
    except Exception as e:  # pragma: no cover - defensive
        print(f"bench: migration leg failed: {e}", file=sys.stderr)

    # elastic leg (ffelastic): one injected-drift live re-plan — trigger
    # latency, online re-search seconds, migration measured vs
    # predicted, and steps-to-recover, as a secondary line + an
    # `elastic` field in the primary payload
    elastic = None
    try:
        elastic = _elastic_legs(cfg, on_tpu)
        print(json.dumps({
            "metric": "elastic_replan",
            **{k: elastic[k] for k in
               ("decision", "trigger_latency_steps", "research_s",
                "migration_predicted_s", "migration_measured_s",
                "migration_measured_vs_predicted", "steps_to_recover",
                "skipped")
               if k in elastic},
            "unit": "s",
        }))
    except Exception as e:  # pragma: no cover - defensive
        print(f"bench: elastic leg failed: {e}", file=sys.stderr)

    # rule-registry leg (ffrules, BENCH hygiene): pin the substitution
    # rule set the plans in this capture were searched under — the
    # content fingerprint (the component that joins the warm-start plan
    # address) plus the full five-pass verification wall time, so the
    # next driver capture can tell "rules changed" from "cost model
    # drifted" when a searched plan moves
    rules_leg = None
    try:
        rules_leg = _rules_leg()
        print(json.dumps({
            "metric": "rules_verify_wall_s",
            "value": rules_leg["verify_wall_s"],
            "rules": rules_leg["rules"],
            "fingerprint": rules_leg["fingerprint"][:16],
            "unit": "s",
        }))
    except Exception as e:  # pragma: no cover - defensive
        # the failure itself is recorded in the payload: a capture whose
        # registry failed verification (or could not be fingerprinted)
        # must never read as a clean capture
        rules_leg = {"error": f"{type(e).__name__}: {e}"}
        print(f"bench: rules leg failed: {e}", file=sys.stderr)

    # warm-start legs: cold-vs-warm time-to-first-step against one shared
    # --warmstart-dir (secondary line + archived in the primary payload)
    warmstart = None
    try:
        warmstart = _warmstart_legs()
        print(json.dumps({
            "metric": "warmstart_time_to_first_step_s",
            "cold": warmstart["cold_time_to_first_step_s"],
            "warm": warmstart["warm_time_to_first_step_s"],
            "speedup": warmstart["speedup"],
            "unit": "s",
        }))
    except Exception as e:  # pragma: no cover - defensive
        print(f"bench: warm-start leg failed: {e}", file=sys.stderr)

    # one payload feeds both the archived metrics record and the printed
    # line of record — they must never drift apart
    payload = {
        "metric": "transformer_lm_tokens_per_sec_per_chip",
        "value": None if tokens_per_sec is None else round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": None if tokens_per_sec is None else round(mfu / 0.35, 4),
        # allocator peak of the primary leg (null where the backend has no
        # memory_stats, e.g. XLA:CPU): the reading the 1/dp optimizer-
        # state saving moves — compare against grad_sync's per-leg
        # resident bytes
        "peak_hbm_bytes_per_chip": primary_mem.get("peak_hbm_bytes"),
    }
    if seq4096 is not None:
        payload["seq4096"] = seq4096
    if fit_loop is not None:
        payload["fit_loop"] = fit_loop
    if grad_sync is not None:
        payload["grad_sync"] = grad_sync
    if param_sharding is not None:
        payload["param_sharding"] = param_sharding
    if serving is not None:
        payload["serving"] = serving
    if migration is not None:
        payload["migration"] = migration
    if elastic is not None:
        payload["elastic"] = elastic
    if warmstart is not None:
        payload["warmstart"] = warmstart
    if rules_leg is not None:
        payload["rules"] = rules_leg
    if tokens_per_sec is None:
        # a physically impossible reading must never become the number of
        # record: emit null and fail so the driver records the fluke as a
        # fluke instead of a result
        print("bench: all retries read >100% MFU — backend measurement "
              "fluke, result is NOT trustworthy", file=sys.stderr)
        print(json.dumps(payload))
        if session is not None:
            telemetry.event("bench", fluke=True, **payload)
            session.close()
        sys.exit(1)
    if session is not None:
        telemetry.event("bench", **payload)
        session.close()
    # primary metric LAST — the driver parses the last line as the number
    # of record
    print(json.dumps(payload))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
