"""Benchmark: flagship Transformer LM training throughput on one chip.

Mirrors the reference's benchmark harness (examples/cpp/Transformer/
transformer.cc:183-211: timed training loop printing ELAPSED TIME /
THROUGHPUT) with the reference model scale (hidden 1024, 16 heads, 12
layers, seq 512 — TransformerConfig, transformer.cc:79-85) recast as the
decoder-only LM, and adds the MFU accounting BASELINE.md targets.

Prints ONE JSON line:
  {"metric": "transformer_lm_tokens_per_sec_per_chip", "value": N,
   "unit": "tokens/s", "vs_baseline": MFU / 0.35}
(vs_baseline = fraction of the 35%-MFU north-star target, BASELINE.json.)
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12  # bf16
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v3" in kind:
        return 123e12
    if "v6" in kind:
        return 918e12
    return 2e12  # CPU fallback so the harness still runs


def main():
    sys.argv = [sys.argv[0]]
    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import (
        TransformerLMConfig,
        build_transformer_lm,
    )
    from flexflow_tpu.models.transformer import transformer_lm_flops_per_token

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = TransformerLMConfig(
            vocab_size=32000, hidden_size=1024, num_heads=16, num_layers=12,
            sequence_length=512, attention_impl="flash",
        )
        batch = 8
        steps, warmup = 20, 3
    else:  # CPU smoke mode
        cfg = TransformerLMConfig(
            vocab_size=512, hidden_size=128, num_heads=4, num_layers=2,
            sequence_length=128, attention_impl="xla",
        )
        batch = 4
        steps, warmup = 5, 1

    config = FFConfig()
    config.batch_size = batch
    if on_tpu:
        # full mixed-precision policy: bf16 activations, fp32 master weights
        from flexflow_tpu.fftype import DataType

        config.computation_dtype = DataType.DT_BFLOAT16
    ff = FFModel(config)
    build_transformer_lm(ff, cfg, batch_size=batch)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    step_fn = ff.executor.build_train_step()

    rs = np.random.RandomState(0)
    toks = rs.randint(0, cfg.vocab_size,
                      (batch, cfg.sequence_length)).astype(np.int32)
    pos = np.tile(np.arange(cfg.sequence_length, dtype=np.int32), (batch, 1))
    labels = rs.randint(0, cfg.vocab_size,
                        (batch, cfg.sequence_length, 1)).astype(np.int32)
    batch_data = ff._make_batch({"tokens": toks, "positions": pos}, labels)

    state = (ff._params, ff._state, ff._opt_slots, ff._step, ff._counters)
    rng = jax.random.key(0)

    # the whole measured loop is ONE jitted scan (the Legion begin_trace/
    # end_trace replay loop, transformer.cc:183-197, collapsed into a single
    # executable): per-step host dispatch — which can be tens of ms through
    # a tunneled backend — cannot pollute the measurement
    def run_n(n):
        def body(carry, _):
            st, r = carry
            r, sub = jax.random.split(r)
            p, s, o, stp, c, l = step_fn(*st, sub, batch_data)
            return ((p, s, o, stp, c), r), l

        @jax.jit
        def loop(st, r):
            (st, r), losses = jax.lax.scan(body, (st, r), None, length=n)
            return st, r, losses

        return loop

    # the warmup loop is load-bearing beyond warmup: its OUTPUT arrays have
    # executable-result layouts, so the timed executable compiles once for
    # those and its second call hits the cache — feeding fresh device_put
    # arrays directly makes the timed call recompile (~40s on-clock).
    warm_loop = run_n(warmup)
    st, rng, _ = warm_loop(state, rng)
    jax.block_until_ready(st[0])
    # warm the timed executable by running it once (NOT via AOT
    # lower().compile(): on the tunneled backend the AOT call path
    # bypasses the plugin's fast dispatch and measures ~10x slow); the
    # extra run costs ~1s of device time and keeps compilation plus any
    # first-call placement work off the clock
    timed_loop = run_n(steps)
    st, rng, _ = timed_loop(st, rng)
    jax.block_until_ready(st[0])

    def measure(st, rng):
        t0 = time.perf_counter()
        st2, rng2, _ = timed_loop(st, rng)
        jax.block_until_ready(st2[0])
        return time.perf_counter() - t0, st2, rng2

    flops_per_token = transformer_lm_flops_per_token(cfg)
    peak = _peak_flops(dev)
    # guard against measurement flukes (the tunneled backend occasionally
    # acks a dispatch without executing, reading as >>100% MFU — physically
    # impossible): retry up to 3 times until the reading is plausible
    for _ in range(3):
        dt, st, rng = measure(st, rng)
        tokens_per_sec = steps * batch * cfg.sequence_length / dt
        mfu = tokens_per_sec * flops_per_token / peak
        if not on_tpu or mfu <= 1.0:
            break
    else:
        # a physically impossible reading must never become the number of
        # record: emit null and fail so the driver records the fluke as a
        # fluke instead of a result
        print("bench: all retries read >100% MFU — backend measurement "
              "fluke, result is NOT trustworthy", file=sys.stderr)
        print(json.dumps({
            "metric": "transformer_lm_tokens_per_sec_per_chip",
            "value": None,
            "unit": "tokens/s",
            "vs_baseline": None,
        }))
        sys.exit(1)
    print(json.dumps({
        "metric": "transformer_lm_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.35, 4),
    }))


if __name__ == "__main__":
    main()
