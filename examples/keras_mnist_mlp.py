"""Keras MNIST MLP with callbacks — the reference example pattern
(examples/python/keras/func_mnist_mlp.py: Sequential/functional model,
LearningRateScheduler + VerifyMetrics callbacks, keras.datasets.mnist).
Uses the synthetic dataset fallback when the real archive is absent (no
network egress); the ≥90% accuracy gate is enforced by VerifyMetrics."""

import sys

sys.path.insert(0, ".")

import numpy as np

from flexflow_tpu.keras import (
    Dense,
    Input,
    LearningRateScheduler,
    Model,
    SGD,
    VerifyMetrics,
)
from flexflow_tpu.keras.datasets import mnist


def schedule(epoch):
    return 0.02 if epoch < 2 else 0.01


def main():
    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype(np.float32) / 255.0
    y_train = y_train.reshape(-1, 1).astype(np.int32)

    inp = Input(shape=(784,))
    t = Dense(128, activation="relu")(inp)
    t = Dense(64, activation="relu")(t)
    out = Dense(10, activation="softmax")(t)
    model = Model(inp, out)
    model.compile(optimizer=SGD(learning_rate=0.02),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x_train, y_train, epochs=4,
              callbacks=[LearningRateScheduler(schedule),
                         VerifyMetrics(0.90)])
    print("final accuracy:",
          model.ffmodel.get_perf_metrics().get_accuracy())


if __name__ == "__main__":
    main()
