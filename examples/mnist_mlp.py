"""MNIST MLP (reference examples/python/native/mnist_mlp.py). Uses synthetic
MNIST-shaped data when the real dataset is unavailable; asserts the >=90%
train-accuracy gate on the synthetic separable set."""

import sys

sys.path.insert(0, ".")

import numpy as np

from flexflow_tpu import (
    FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
)
from flexflow_tpu.models import build_mnist_mlp


def main():
    config = FFConfig()
    ff = FFModel(config)
    build_mnist_mlp(ff, batch_size=config.batch_size)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY,
                 MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    rs = np.random.RandomState(0)
    centers = rs.randn(10, 784) * 2.0
    y = rs.randint(0, 10, 8192)
    x = (centers[y] + rs.randn(8192, 784)).astype(np.float32)
    ff.fit(x, y.reshape(-1, 1).astype(np.int32), epochs=config.epochs)
    acc = ff.get_perf_metrics().get_accuracy()
    print("final accuracy:", acc)
    assert acc >= 0.9, f"accuracy gate failed: {acc}"


if __name__ == "__main__":
    main()
