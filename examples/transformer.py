"""Transformer benchmark example (reference examples/cpp/Transformer/
transformer.cc). Same CLI flags: --num-layers, --hidden-size, --num-heads,
--sequence-length; prints ELAPSED TIME / THROUGHPUT like transformer.cc:208.
"""

import sys
import time

sys.path.insert(0, ".")

import numpy as np


def parse_tf_args(argv):
    from flexflow_tpu.models import TransformerConfig

    c = TransformerConfig()
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--num-layers":
            i += 1; c.num_layers = int(argv[i])
        elif a == "--hidden-size":
            i += 1; c.hidden_size = int(argv[i])
        elif a == "--num-heads":
            i += 1; c.num_heads = int(argv[i])
        elif a == "--sequence-length":
            i += 1; c.sequence_length = int(argv[i])
        elif a == "--embedding-size":
            i += 1; c.embedding_size = int(argv[i])
        i += 1
    return c


def main():
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import build_transformer

    tf_config = parse_tf_args(sys.argv[1:])
    config = FFConfig()
    ff = FFModel(config)
    build_transformer(ff, tf_config, batch_size=config.batch_size)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)

    rs = np.random.RandomState(0)
    num_samples = config.batch_size * 4
    x = rs.randn(num_samples, tf_config.sequence_length,
                 tf_config.hidden_size).astype(np.float32)
    y = rs.randn(num_samples, tf_config.sequence_length, 1).astype(np.float32)
    ff.fit(x, y, epochs=1)  # warmup
    t0 = time.time()
    ff.fit(x, y, epochs=config.epochs)
    dt = time.time() - t0
    thru = config.epochs * num_samples / dt
    print(f"ELAPSED TIME = {dt:.4f}s, THROUGHPUT = {thru:.2f} samples/s")


if __name__ == "__main__":
    main()
