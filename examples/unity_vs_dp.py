"""Unity-searched strategy vs data-parallel-only comparison.

The OSDI'22 AE pattern (reference scripts/osdi22ae/bert.sh: run the same
model twice, with search and with --only-data-parallel, compare throughput).
The searched plan is exported once (--export-strategy analog) and the third
run REPLAYS it via import without re-searching, demonstrating the
strategy-file round trip (model.cc:3599-3608). Runs on the virtual CPU mesh
by default so it works anywhere:

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/unity_vs_dp.py --mesh 2,4,1,1 --budget 8
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, ".")

import numpy as np


HIDDEN = 4096
if "--hidden" in sys.argv:
    i = sys.argv.index("--hidden")
    HIDDEN = int(sys.argv[i + 1])
    del sys.argv[i : i + 2]


def run(only_dp: bool, export_to: str = "", import_from: str = ""):
    import jax

    if jax.default_backend() == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, SGDOptimizer,
    )

    config = FFConfig()
    config.only_data_parallel = only_dp
    config.export_strategy_file = export_to
    config.import_strategy_file = import_from
    if not only_dp and not import_from and config.search_budget == 0:
        config.search_budget = 8
    batch = config.batch_size
    ff = FFModel(config)
    x = ff.create_tensor((batch, 512), name="input")
    t = x
    for i in range(4):
        t = ff.dense(t, HIDDEN, ActiMode.AC_MODE_RELU, name=f"fc{i}")
    t = ff.dense(t, 10, name="head")
    t = ff.softmax(t, name="sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    rs = np.random.RandomState(0)
    xs = rs.randn(batch * 4, 512).astype(np.float32)
    ys = rs.randint(0, 10, (batch * 4, 1)).astype(np.int32)
    ff.fit(xs, ys, epochs=1, batch_size=batch)  # warmup + compile
    t0 = time.time()
    ff.fit(xs, ys, epochs=2, batch_size=batch)
    dt = time.time() - t0
    thru = 2 * 4 * batch / dt
    return thru


if __name__ == "__main__":
    import json

    plan = os.path.join(tempfile.gettempdir(), "unity_plan.json")
    dp = run(only_dp=True)
    unity = run(only_dp=False, export_to=plan)
    replay = run(only_dp=False, import_from=plan)
    print(f"DP-only:       {dp:.1f} samples/s")
    print(f"Unity:         {unity:.1f} samples/s")
    print(f"Unity (replay): {replay:.1f} samples/s  (imported {plan}, "
          f"no re-search)")
    print(f"speedup:  {unity / dp:.2f}x")
    # machine-readable artifact (the AE scripts' measured-result analog)
    artifact = os.environ.get("UNITY_VS_DP_ARTIFACT", "unity_vs_dp.json")
    with open(artifact, "w") as f:
        json.dump({
            "dp_samples_per_s": round(dp, 2),
            "unity_samples_per_s": round(unity, 2),
            "unity_replay_samples_per_s": round(replay, 2),
            "speedup": round(unity / dp, 3),
            "plan_file": plan,
        }, f, indent=1)
    print(f"wrote {artifact}")
