"""flexflow_tpu: a TPU-native distributed DNN training framework.

Same capabilities as FlexFlow (PCG parallelism IR + Unity strategy search +
full operator/model surface), re-designed for TPU: JAX/XLA/Pallas compute,
GSPMD sharding over an ICI mesh, collectives instead of task-based data
movement. See SURVEY.md for the capability map against the reference.
"""

from .config import FFConfig, FFIterationConfig
from .fftype import (
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OperatorType,
    ParameterSyncType,
    PoolType,
    RegularizerMode,
)
from .initializer import (
    ConstantInitializer,
    GlorotUniformInitializer,
    Initializer,
    NormInitializer,
    UniformInitializer,
    ZeroInitializer,
)
from .machine import MachineResource, MachineView, MeshShape, build_mesh
from .metrics import Metrics, PerfMetrics
from .model import FFModel
from . import parallel  # registers parallel-op OpDefs
from . import resilience  # checkpointing / elastic resume / preemption
from . import serving  # decode-graph inference + continuous batching
from . import telemetry  # tracer + run metrics + leveled logging
from .parallel import Strategy
from .optimizer import AdamOptimizer, Optimizer, SGDOptimizer
from .tensor import ParallelDim, ParallelTensor, ParallelTensorShape, Tensor

__version__ = "0.1.0"
