"""ffcheck: static plan verification + JAX-hazard lint as a compile gate.

GSPMD (Xu et al. 2021, PAPERS.md "Analysis") frames sharding propagation
as a dataflow analysis that can run independently of the executor; Unity
searched plans (PAPER.md §0) are only as trustworthy as the invariants
verifiable before launch. This package is that verifier: a pass pipeline
over (PCG, Strategy, mesh) that runs at compile time on EVERY plan
source (search | cache | checkpoint | import | manual | default) and as
a standalone CI gate (`scripts/ffcheck.py`), cross-checking the plan the
same way `verify_report_total` cross-checks the makespan identity.

Passes (docs/analysis.md has the full catalog):

1. `sharding_dataflow`  — re-derive per-tensor/per-edge shardings and
   flag axis reuse, oversharded/indivisible dims, replica-dim
   inconsistencies, and implicit (unpriced) reshards.
2. `memory_liveness`    — static peak per-chip HBM over the fwd+bwd
   schedule (masters, slots, weight-update sharding included), with a
   per-op timeline and a cross-check against the cost model's estimate;
   a predicted OOM fails compile before it ever reaches the device.
3. `collective_uniformity` — ring permutations are complete bijections,
   reduce-scatter bucket order is deterministic, no collective hides in
   a coordinator-only branch (multihost deadlock).
4. `donation_aliasing`  — donated step buffers are never read host-side
   after the call; the donation registry is re-derived from executor.py
   and cross-checked.
5. `dtype_flow`         — ffsan's precision lattice over the PCG under
   the mixed-precision policy: low-precision accumulation over large
   reductions, fp32-master bypass, downcast→upcast round trips, dtype
   mismatches across parallel-op edges (numerics.py).
6. `spmd_uniformity`    — host-divergent branches feeding collectives or
   traced code (the r13 divergence class, generalized); the module also
   hosts the opt-in runtime fingerprint barrier (spmd.py,
   `--spmd-barrier`).

A third static-analysis layer, **fftrans** (transition.py), verifies the
TRANSITION between two plans for the same PCG — state-mapping
completeness, gather paths out of ZeRO at-rest layouts, transition-time
memory, ring bijectivity + topological transfer order, and schedule
uniformity — and prices the migration (`predicted_s` reproduces from the
strategy-report `transition` section alone). It gates the elastic-resume
restore path (resilience/reshard.py) and the in-process live migration
(resilience/migrate.py), the gating half of live re-planning
(ROADMAP item 2).

A fourth layer, **ffrules** (rules.py), verifies the SUBSTITUTION RULES
the search rewrites with (TASO/PET discipline, PAPERS.md "Substitution
verification"): symbolic shape/dtype transfer on prime-valued dims,
parallel-state soundness with a nonlinear probe on every mapped output,
a semantic-equivalence oracle executing src and rewritten graphs
fwd+bwd at dtype-ULP tolerance, boundary-precondition fuzz, and
registry determinism (the `rules_fingerprint` that joins the warm-start
plan address). External `--substitution-json` rules verify at LOAD
(`RuleVerificationError`; `--no-verify-rules` downgrades); the
`rule_verify` compile pass records the verdict + active rule-set
fingerprint in the report, and `scripts/ffrules.py` sweeps the full
generated registry in CI.

Findings land in the `analysis` section of strategy_report.json
(severity error/warning/info); errors abort compile unless
`--no-verify-plan`. `scripts/fflint.py` runs the source-level hazard
rules (analysis/lint.py) repo-wide as the sibling CI gate; the runtime
NaN-provenance sanitizer (`--sanitize-numerics`, flexflow_tpu/
sanitize.py) is ffsan's dynamic half.
"""

from __future__ import annotations

import time
from typing import Optional

from . import (
    collectives,
    donation,
    lint,
    memory,
    numerics,
    rules,
    sharding,
    sources,
    spmd,
    transition,
)
from .findings import (
    AnalysisResult,
    Finding,
    PlanVerificationError,
    SEV_ERROR,
    SEV_INFO,
    SEV_WARNING,
)

__all__ = [
    "AnalysisContext", "AnalysisResult", "Finding",
    "PlanVerificationError", "RuleVerificationError", "run_analysis",
    "verify_plan",
    "verify_strategy", "PASSES", "SEV_ERROR", "SEV_WARNING", "SEV_INFO",
    "collectives", "donation", "lint", "memory", "numerics", "rules",
    "sharding", "sources", "spmd", "transition",
]

# (name, runner) in execution order; each runner is
# fn(graph, mesh, ctx) -> list[Finding]. Passes 5 and 6 are the ffsan
# layer (dtype-flow numerics + SPMD uniformity, ISSUE 10); pass 7 is the
# ffrules layer's compile-side hook (the heavy per-rule verification
# runs at rule load time and in the scripts/ffrules.py CI sweep — the
# compile pass surfaces the recorded load verdict + the active rule
# set's fingerprint into the report).
PASSES = (
    ("sharding_dataflow", sharding.run),
    ("memory_liveness", memory.run),
    ("collective_uniformity", collectives.run),
    ("donation_aliasing", donation.run),
    ("dtype_flow", numerics.run),
    ("spmd_uniformity", spmd.run),
    ("rule_verify", rules.run),
)

RuleVerificationError = rules.RuleVerificationError


class AnalysisContext:
    """Everything a pass may consult beyond (graph, mesh). All fields
    optional — passes degrade to the checks their inputs allow."""

    def __init__(self, machine=None, cost_model=None, opt_slots: int = 1,
                 update_specs=None, training: bool = True,
                 hbm_cap_bytes: float = 0.0, config=None,
                 update_stage: int = 0, plan_source: str = ""):
        self.machine = machine
        self.cost_model = cost_model
        self.opt_slots = opt_slots
        self.update_specs = update_specs or {}
        # weight-update sharding stage the executor runs (0 | 2 | 3):
        # stage 3 drops the resident gathered weight copies from the
        # persistent set and adds the two-layers-in-flight transient
        self.update_stage = update_stage
        self.training = training
        self.hbm_cap_bytes = hbm_cap_bytes
        # FFConfig (or None): the dtype-flow pass reads the
        # mixed-precision policy (computation_dtype / tensor-op math)
        # from the same source the executor lowers
        self.config = config
        # where the plan came from (search|cache|checkpoint|import|
        # manual|default|broadcast|replan — model._plan_source; replan
        # is a live ffelastic re-plan whose underlying origin rides
        # model._plan_origin): the ffrules
        # pass only stamps a rule-set fingerprint on plans a rewrite
        # search (now, or the cached search with the same rule address)
        # actually produced
        self.plan_source = plan_source


def run_analysis(graph, mesh, ctx: Optional[AnalysisContext] = None,
                 passes=None) -> AnalysisResult:
    """Run the pass pipeline over a materialized (graph, mesh). A pass
    that crashes reports itself as an error finding instead of taking
    the compile down with an analysis bug."""
    result = AnalysisResult()
    t0 = time.perf_counter()
    for name, runner in (passes or PASSES):
        try:
            result.extend(runner(graph, mesh, ctx), pass_name=name)
        except Exception as e:
            # the verifier must not be the crash — AND a verifier bug
            # must not block every compile: a crashed pass is a WARNING
            # (visible in the report/logs), not an abort-grade error;
            # only findings about the PLAN carry error severity
            result.extend([Finding(
                SEV_WARNING, "analysis_crash",
                f"pass {name} crashed (its checks did NOT run): "
                f"{type(e).__name__}: {e}")],
                pass_name=name)
        result.passes_run.append(name)
    result.elapsed_s = time.perf_counter() - t0
    return result


def context_for_model(model, cost_model=None) -> AnalysisContext:
    """AnalysisContext off a model mid-compile (executor built)."""
    from ..fftype import CompMode
    from ..search.cost_model import CostModel
    from ..search.machine_model import machine_model_for_mesh

    machine = getattr(cost_model, "machine", None)
    if machine is None:
        machine = machine_model_for_mesh(
            model.mesh, num_hosts=model.config.num_nodes)
    if cost_model is None:
        # the memory cross-check needs the pricer's own estimate even
        # when no search ran this compile — build one pricing the
        # ADOPTED update mode (same rule choose_update_sharding leaves
        # the search's cost model in)
        cost_model = CostModel(
            machine,
            opt_slots=(model.optimizer.num_slots
                       if model.optimizer is not None else 1))
        upd = getattr(model, "_update_sharding", None) or {}
        cost_model.update_sharding = bool(upd.get("enabled"))
        cost_model.param_gather = upd.get("stage", 0) == 3
        cost_model.overlap_update = (
            bool(upd.get("enabled"))
            and bool(model.config.overlap_collectives))
    cap = (model.config.device_mem if model.config.device_mem > 0
           else machine.chip.hbm_bytes)
    return AnalysisContext(
        machine=machine,
        cost_model=cost_model,
        opt_slots=(model.optimizer.num_slots
                   if model.optimizer is not None else 1),
        update_specs=(model.executor.update_specs
                      if model.executor is not None else {}),
        update_stage=(model.executor.update_stage
                      if model.executor is not None else 0),
        training=(model.config.computation_mode
                  == CompMode.COMP_MODE_TRAINING),
        hbm_cap_bytes=cap,
        config=model.config,
        plan_source=getattr(model, "_plan_source", ""),
    )


def verify_plan(model, cost_model=None) -> AnalysisResult:
    """The compile gate: run every pass on the model's materialized plan,
    stash the result (`model._analysis` — strategy_report.json picks it
    up), and raise PlanVerificationError on errors unless
    --no-verify-plan. Runs on every plan source — search, cache,
    checkpoint, import, manual, default — because each of them reaches
    the executor through the same compile."""
    from .. import telemetry
    from ..telemetry import log as fflog

    with telemetry.span("compile.verify"):
        ctx = context_for_model(model, cost_model=cost_model)
        result = run_analysis(model.graph, model.mesh, ctx)
    model._analysis = result
    s = result.summary()
    telemetry.event(
        "plan_verify", plan_source=getattr(model, "_plan_source", "none"),
        elapsed_s=result.elapsed_s, **s)
    errs = result.errors()
    if errs:
        if model.config.verify_plan:
            raise PlanVerificationError(result)
        fflog.warning(
            "plan verification found %d error(s) (--no-verify-plan: "
            "launching anyway): %s", len(errs),
            "; ".join(str(f) for f in errs[:5]))
    for f in result.warnings():
        fflog.debug("ffcheck: %s", f)
    return result


def verify_strategy(overrides: dict, graph, mesh_axes) -> None:
    """Strategy-level verification for the adoption paths (import, plan
    cache, checkpoint manifest): every problem the sharding pass can see
    without materialized placements. Raises ValueError listing all
    problems — the warm-start paths catch it as a cache miss and
    re-search; --import-strategy surfaces it to the user."""
    axes = mesh_axes
    if hasattr(axes, "shape"):
        axes = dict(axes.shape)
    findings = sharding.verify_strategy(overrides, graph, axes)
    errs = [f for f in findings if f.severity == SEV_ERROR]
    if errs:
        raise ValueError(
            "strategy does not apply to this graph/mesh:\n  "
            + "\n  ".join(str(f) for f in errs))
