"""Pass 3 — collective-uniformity checker.

SPMD collectives are correct only when every participant executes the
same schedule with the same arguments. Three checks:

1. **Ring permutations are complete bijections.** Every ring body in the
   runtime (ring attention's double-buffered KV rotation, the decomposed
   allgather-matmul, the ring reduce-scatter, the ppermute hop
   calibrator) builds its schedule from ONE shared helper —
   `parallel.ops.ring_permutation(n)` — and this pass validates that
   helper's output for every op in the plan that lowers to a ring: each
   source exactly once, each destination exactly once, full coverage of
   range(n). A partial or duplicated permutation silently DROPS shards
   (jax.lax.ppermute zero-fills missing destinations) — the result is
   wrong values, not an error. (The pipeline fill/drain shift in
   parallel/pipeline.py is deliberately partial and is not a ring; it is
   exempt by construction.)

2. **Reduce-scatter bucket order is deterministic.** The sharded weight
   update's per-layer buckets must be emitted in topological order —
   the order `Executor._build_update_specs` walks — on every process;
   a bucket order derived from an unordered container would interleave
   differently across hosts and deadlock the collective stream.

3. **No collective behind a coordinator-only branch.** The
   `distributed.broadcast_json` idiom gates the PAYLOAD on
   `is_coordinator()`, never the collective; a collective inside the
   branch is a fleet deadlock. Checked at the source level (lint rule
   `coordinator_collective`) over the runtime modules.
"""

from __future__ import annotations

from ..fftype import OperatorType as OT
from .findings import Finding, SEV_ERROR, SEV_INFO
from .sources import runtime_findings

PASS_NAME = "collective_uniformity"


def check_permutation(perm, n: int, where: str = "") -> list[Finding]:
    """Validate one ring permutation: a complete bijection on range(n)."""
    findings: list[Finding] = []
    srcs = [s for s, _ in perm]
    dsts = [d for _, d in perm]
    problems = []
    if sorted(srcs) != list(range(n)):
        problems.append(f"sources {sorted(set(srcs))} != 0..{n - 1}")
    if sorted(dsts) != list(range(n)):
        problems.append(f"destinations {sorted(set(dsts))} != 0..{n - 1}")
    oob = [(s, d) for s, d in perm
           if not (0 <= s < n and 0 <= d < n)]
    if oob:
        problems.append(f"out-of-range pairs {oob[:4]}")
    if problems:
        findings.append(Finding(
            SEV_ERROR, "bad_permutation",
            f"ring permutation over {n} shards is not a complete "
            f"bijection ({'; '.join(problems)}) — ppermute zero-fills "
            f"missing destinations, silently corrupting the ring",
            where=where,
            details={"n": n, "perm": [list(p) for p in perm[:16]]}))
    return findings


def _ring_ops(graph, axis_sizes) -> list[tuple[str, int]]:
    """(where, ring size) for every op in the plan that lowers to a ring
    schedule on this mesh — attribution for the per-size builder check
    below."""
    from ..machine import AXIS_SEQ

    out = []
    seq_deg = axis_sizes.get(AXIS_SEQ, 1)
    for node in graph.topo_order():
        impl = getattr(node.params, "impl", "")
        if (node.op_type == OT.OP_MULTIHEAD_ATTENTION
                and impl == "ring" and seq_deg > 1):
            out.append((f"{node.name} (ring attention over "
                        f"{AXIS_SEQ}={seq_deg})", seq_deg))
    return out


def run(graph, mesh, ctx=None) -> list[Finding]:
    from ..parallel.ops import ring_permutation

    axis_sizes = {k: int(v) for k, v in dict(mesh.shape).items()}
    findings: list[Finding] = []

    # 1) ring permutations: validate the SHARED schedule builder
    # (parallel.ops.ring_permutation) once per DISTINCT ring size any
    # ring body could run over on this mesh — every axis of size > 1,
    # not just the ops the plan names. The library ring bodies
    # (allgather_matmul, ring_reduce_scatter, the hop calibrator) all
    # build from the same helper, so a per-size check covers them even
    # when nothing in the plan routes through them yet; the plan's own
    # ring ops (+ the sharded update's reduce-scatter axes) attach as
    # attribution in the finding's `where`.
    rings = _ring_ops(graph, axis_sizes)
    update_specs = (getattr(ctx, "update_specs", None)
                    if ctx is not None else None) or {}
    update_axes = sorted({
        ax for spec, _shape in update_specs.values()
        for entry in spec if entry is not None
        for ax in (entry if isinstance(entry, tuple) else (entry,))})
    for ax in update_axes:
        n = axis_sizes.get(ax, 1)
        if n > 1:
            rings.append((f"weight-update reduce-scatter over {ax}={n}",
                          n))
    checked = 0
    for n in sorted({s for s in axis_sizes.values() if s > 1}):
        axes = sorted(a for a, s in axis_sizes.items() if s == n)
        users = [w for w, rn in rings if rn == n]
        where = (f"axes {axes} (size {n})"
                 + (f": {'; '.join(users)}" if users else ""))
        findings.extend(check_permutation(ring_permutation(n), n, where))
        checked += 1

    # 2) reduce-scatter bucket order: the update-spec emission order must
    # follow the topological schedule (the order GSPMD sees the pins)
    if update_specs:
        topo_pos = {n.name: i for i, n in enumerate(graph.topo_order())}
        seq = [topo_pos.get(node_name, -1)
               for (node_name, _w) in update_specs.keys()]
        known = [p for p in seq if p >= 0]
        if known != sorted(known):
            findings.append(Finding(
                SEV_ERROR, "nondeterministic_bucket_order",
                "weight-update buckets are not emitted in topological "
                "order — per-host divergence in reduce-scatter issue "
                "order deadlocks the collective stream",
                details={"positions": known[:32]}))

    # 3) coordinator-only collectives in the runtime host code (plus,
    # once, any scan-infrastructure failure — unparseable module —
    # downgraded to warning by the analysis_crash policy)
    from .sources import scan_problems

    findings.extend(runtime_findings(("coordinator_collective",)))
    findings.extend(scan_problems())

    if not findings:
        findings.append(Finding(
            SEV_INFO, "collectives_clean",
            f"{checked} ring schedule(s) bijective, "
            f"{len(update_specs)} update bucket(s) in deterministic "
            f"order, no coordinator-gated collectives"))
    return findings
