"""Pass 4 — donation/aliasing checker.

Every hot-loop executable donates its carried state (train step, chunked
scan, decode step's KV caches): on backends that honor donation the
input buffer is DEAD after the call, and a host-side read of it returns
garbage or raises — but only on those backends, so the bug ships green
from a CPU test run. Two checks:

1. **Reuse-after-donation** (lint rule `donated_reuse`): at every call
   site of a known donated executable, a buffer passed at a donated
   argnum must be rebound by the call's own assignment (the carry
   pattern) or never referenced again. Scanned over the runtime modules
   (model.fit's step loop, the pipelined engine's chunk dispatch, the
   serving engine's decode step).

2. **Registry cross-check**: the analysis's own table of donated argnums
   (`lint.DONATED_CALLEES`) is verified against `executor.py`'s AST —
   the `donate_argnums=_donate_argnums((...))` declarations inside each
   `build_*` method. The checker re-derives the donation contract from
   the source instead of trusting its own table, the same
   independent-re-derivation discipline as the sharding pass; if the
   executor grows or changes a donated argnum and the table lags, the
   pass fails loudly instead of silently scanning with stale argnums.
"""

from __future__ import annotations

import ast
import os

from .findings import Finding, SEV_ERROR, SEV_INFO
from .lint import DONATED_CALLEES
from .sources import package_root, runtime_findings

PASS_NAME = "donation_aliasing"

# executor build method → the call-site names its executable binds to
# (the names runtime code assigns the jitted fn to)
BUILDER_CALLEES = {
    "build_train_step": ("step_fn", "_train_step"),
    "build_chunked_train_step": ("chunk_fn",),
    "build_eval_step": ("eval_fn", "_eval_step"),
    "build_decode_step": ("_step_fn", "_decode_step"),
    # speculative decoding's batched multi-token verification: the
    # target's KV state is donated, so the engine rebinds it per call
    "build_verify_step": ("_verify_fn", "_verify_step"),
    "build_block_copy": ("_copy_fn",),
    # disaggregated serving's KV handoff landing: the decode-side pools
    # are donated, so the coordinator rebinds the decode state
    "build_kv_inject": ("_inject_fn",),
    # stage-3 (ZeRO-3/FSDP) full-gather of the sharded-at-rest param
    # tree: callers must rebind the donated tree (bench/smoke pattern)
    "build_param_gather": ("_gather_fn", "gather_fn"),
}


def executor_donation_table(executor_path: str = "") -> dict:
    """{build method name: donated argnums tuple} extracted from
    executor.py's AST — the ground truth the registry is checked
    against."""
    path = executor_path or os.path.join(package_root(), "executor.py")
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    out: dict[str, tuple] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef) or \
                not node.name.startswith("build_"):
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            for kw in call.keywords:
                if kw.arg != "donate_argnums":
                    continue
                v = kw.value
                # donate_argnums=_donate_argnums((0, 1, ...)) or a bare
                # tuple literal
                if isinstance(v, ast.Call) and v.args:
                    v = v.args[0]
                if isinstance(v, ast.Tuple):
                    try:
                        nums = tuple(ast.literal_eval(v))
                    except (ValueError, SyntaxError):
                        continue
                    out[node.name] = nums
    return out


_registry_cache: dict = {}


def registry_problems(executor_path: str = "") -> list[Finding]:
    """Cross-check DONATED_CALLEES against the executor source. Cached
    per path for the life of the process (the source cannot change under
    a running compile)."""
    hit = _registry_cache.get(executor_path)
    if hit is not None:
        return list(hit)
    findings = _registry_problems_uncached(executor_path)
    _registry_cache[executor_path] = list(findings)
    return findings


def _registry_problems_uncached(executor_path: str = "") -> list[Finding]:
    findings: list[Finding] = []
    try:
        table = executor_donation_table(executor_path)
    except (OSError, SyntaxError) as e:
        return [Finding(
            SEV_ERROR, "donation_registry_mismatch",
            f"could not read executor donation declarations: {e}",
            pass_name=PASS_NAME)]
    for builder, callees in BUILDER_CALLEES.items():
        actual = table.get(builder)
        if actual is None:
            findings.append(Finding(
                SEV_ERROR, "donation_registry_mismatch",
                f"executor has no donate_argnums declaration for "
                f"{builder}() — registry expects one",
                where=f"executor.py:{builder}"))
            continue
        for callee in callees:
            expected = DONATED_CALLEES.get(callee)
            if expected != actual:
                findings.append(Finding(
                    SEV_ERROR, "donation_registry_mismatch",
                    f"registry says {callee}() donates {expected}, "
                    f"executor.{builder}() declares {actual} — the "
                    f"donated-reuse scan would run with stale argnums",
                    where=f"executor.py:{builder}",
                    details={"registry": list(expected or ()),
                             "executor": list(actual)}))
    for builder in table:
        if builder not in BUILDER_CALLEES:
            findings.append(Finding(
                SEV_ERROR, "donation_registry_mismatch",
                f"executor.{builder}() declares donation but the "
                f"registry has no call-site names for it — its call "
                f"sites are unscanned",
                where=f"executor.py:{builder}"))
    return findings


def run(graph, mesh, ctx=None) -> list[Finding]:
    findings = registry_problems()
    findings.extend(runtime_findings(("donated_reuse",)))
    if not findings:
        findings.append(Finding(
            SEV_INFO, "donation_clean",
            f"{len(BUILDER_CALLEES)} donated executables: registry "
            f"matches executor declarations, no host-side reuse of "
            f"donated buffers"))
    return findings
