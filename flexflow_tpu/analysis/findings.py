"""Finding / AnalysisResult: the structured output of every ffcheck pass.

GSPMD (Xu et al. 2021, PAPERS.md "Analysis") frames sharding propagation
as a dataflow analysis whose result is checkable independently of the
executor; this module is the vocabulary those checks report in. A
`Finding` is one fact about a (PCG, Strategy, mesh) triple — an invariant
violation (severity "error": the plan must not launch), a hazard worth a
look ("warning"), or context ("info"). `AnalysisResult` aggregates the
findings of a pass pipeline run and serializes into the `analysis`
section of strategy_report.json, so run_doctor / CI can gate on it the
same way they gate on the makespan identity.

Finding codes are STABLE identifiers (tests and the ffcheck fuzzer key on
them); add new codes rather than renaming existing ones.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

SEV_ERROR = "error"
SEV_WARNING = "warning"
SEV_INFO = "info"
_SEVERITIES = (SEV_ERROR, SEV_WARNING, SEV_INFO)

# Stable finding codes, by pass. The plan-mutation fuzzer
# (tests/test_analysis.py) injects one corruption per code and asserts
# ffcheck reports exactly that code.
#   sharding dataflow:   axis_reuse, indivisible_dim, unknown_axis,
#                        replica_dim, implicit_reshard, unknown_node,
#                        unknown_output, unknown_weight, rank_mismatch,
#                        overshard
#   memory liveness:     oom_predicted, memory_model_divergence,
#                        memory_timeline
#   collective checks:   bad_permutation, nondeterministic_bucket_order,
#                        coordinator_collective
#   donation/aliasing:   donated_reuse, donation_registry_mismatch
#   dtype flow (ffsan):  low_precision_accum, master_bypass,
#                        downcast_roundtrip, parallel_dtype_mismatch,
#                        numerics_clean
#   spmd uniformity:     host_divergent_branch, spmd_clean
#   transition (fftrans): dropped_state, unmapped_state,
#                        state_dtype_change, state_shape_change,
#                        missing_gather_path, kv_pool_mismatch,
#                        transition_oom, transition_memory_timeline,
#                        bad_transfer_permutation,
#                        nontopological_transfer_order,
#                        migration_donation_hazard,
#                        transfer_schedule_divergence, transition_clean
#   rule verify (ffrules): rule_shape_mismatch, rule_dtype_mismatch,
#                        rule_replica_dim_leak, rule_degree_violation,
#                        rule_partial_sum_nonlinear,
#                        rule_numeric_divergence, rule_matcher_unsound,
#                        rule_verification_crash,
#                        rule_registry_nondeterministic,
#                        rule_uninstantiable, rule_unassignable,
#                        rule_oracle_skipped, rules_clean,
#                        rules_fingerprint
#   lint (fflint rules): host_sync_in_loop, unsorted_dict_hash,
#                        global_rng, time_in_trace,
#                        unverified_transition, unverified_rule_load,
#                        raw_timer_in_hot_path, unnamed_op_scope


@dataclass
class Finding:
    """One static-analysis fact. `where` names the node/edge/file the
    finding anchors to; `details` is JSON-able context (bytes, specs,
    line numbers, timelines)."""

    severity: str
    code: str
    message: str
    pass_name: str = ""
    where: str = ""
    details: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.severity not in _SEVERITIES:
            raise ValueError(
                f"severity must be one of {_SEVERITIES}, "
                f"got {self.severity!r}")

    def to_json(self) -> dict:
        out = {"severity": self.severity, "code": self.code,
               "pass": self.pass_name, "message": self.message}
        if self.where:
            out["where"] = self.where
        if self.details:
            out["details"] = self.details
        return out

    def __str__(self):
        loc = f" [{self.where}]" if self.where else ""
        return f"{self.severity.upper()} {self.code}{loc}: {self.message}"


class AnalysisResult:
    """Aggregated findings of one pass-pipeline run."""

    def __init__(self, findings: Optional[list[Finding]] = None,
                 passes_run: Optional[list[str]] = None):
        self.findings: list[Finding] = list(findings or [])
        self.passes_run: list[str] = list(passes_run or [])
        self.elapsed_s: float = 0.0

    def extend(self, findings, pass_name: str = ""):
        for f in findings:
            if pass_name and not f.pass_name:
                f.pass_name = pass_name
            self.findings.append(f)

    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == SEV_ERROR]

    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == SEV_WARNING]

    def by_code(self, code: str) -> list[Finding]:
        return [f for f in self.findings if f.code == code]

    @property
    def ok(self) -> bool:
        return not self.errors()

    def summary(self) -> dict:
        return {
            "errors": len(self.errors()),
            "warnings": len(self.warnings()),
            "info": len([f for f in self.findings
                         if f.severity == SEV_INFO]),
            "passes_run": list(self.passes_run),
        }

    def to_json(self) -> dict:
        out = self.summary()
        out["elapsed_s"] = self.elapsed_s
        out["findings"] = [f.to_json() for f in self.findings]
        return out

    def render(self, max_findings: int = 50) -> str:
        """Human-readable rendering (ffcheck's console output)."""
        s = self.summary()
        lines = [f"ffcheck: {s['errors']} error(s), {s['warnings']} "
                 f"warning(s), {s['info']} info "
                 f"({', '.join(self.passes_run) or 'no passes'})"]
        ranked = sorted(
            self.findings,
            key=lambda f: _SEVERITIES.index(f.severity))
        for f in ranked[:max_findings]:
            lines.append(f"  {f}")
        if len(ranked) > max_findings:
            lines.append(f"  ... {len(ranked) - max_findings} more")
        return "\n".join(lines)


class PlanVerificationError(ValueError):
    """Raised by the compile gate when a pass reports errors and
    --no-verify-plan was not passed. Carries the full result so callers
    (the warm-start miss path, tests) can inspect the findings."""

    def __init__(self, result: AnalysisResult):
        self.result = result
        errs = result.errors()
        head = "; ".join(str(f) for f in errs[:5])
        more = f" (+{len(errs) - 5} more)" if len(errs) > 5 else ""
        super().__init__(
            f"plan verification failed with {len(errs)} error(s): "
            f"{head}{more} — pass --no-verify-plan to launch anyway")
