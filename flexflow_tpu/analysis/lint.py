"""fflint: AST rules for the JAX hazards this codebase keeps re-fixing.

Every rule encodes a bug class a past PR fixed by hand:

- `host_sync_in_loop` — `jax.device_get` (a full device drain) inside a
  `for`/`while` loop. The r09 pipelined engine existed to remove exactly
  this from the step loop; new ones must not creep back in. Fetches
  behind a telemetry/diagnostics gate are exempt (the gate IS the fix),
  including gates bound to a local (`need_losses = tel is not None`).
- `unsorted_dict_hash` — a `for` loop over `.items()`/`.keys()`/
  `.values()` (not wrapped in `sorted(...)`) inside a fingerprint/hash
  function. Dict order is insertion order, so two processes that learned
  entries in different orders hash differently — a warm-start cache that
  misses across restarts for no reason (warmstart/fingerprint.py is
  keyed content-addressing; it must be order-free).
- `global_rng` — module-level `np.random.*` / stdlib `random.*` calls
  (not RandomState/default_rng instances). The r06 resilience PR
  replaced a global-RNG shuffle because it made resume non-replayable.
- `time_in_trace` — `time.*` / RNG calls inside a TRACED function (jit
  decorator, or passed to jit / shard_map / pallas_call / lax control
  flow). These execute once at trace time and bake a constant into the
  executable — the classic "why is my timestamp frozen" bug.
- `coordinator_collective` — a collective (barrier / broadcast_json /
  sync_global_devices / psum...) inside an `is_coordinator()` /
  `process_index() == 0` branch: the other processes never reach the
  collective, so the fleet deadlocks. The correct idiom is
  `broadcast_json(payload if is_coordinator() else None)` — gate the
  PAYLOAD, not the collective.
- `donated_reuse` — a buffer passed at a donated argnum of a known step
  executable (train step / chunked scan / decode step) and then read
  host-side without being rebound by the call's own assignment: the
  donated buffer is dead after the call on backends that honor donation.
- `low_precision_accum` — a summing reduction (`jnp.sum`/`mean`/
  `prod`/`cumsum`/`logsumexp`/`einsum`) whose argument is explicitly
  cast to bf16/fp16 (or whose `dtype=` pins a low-precision
  accumulator). Long low-precision sums drift (Micikevicius et al.,
  PAPERS.md "Numerics"); the codebase's convention is f32 accumulation
  with one final downcast (loss.py, ops/core.py) — the ffsan dtype-flow
  pass checks the same invariant at the graph level.
- `host_divergent_branch` — an `if` whose test calls a per-host-
  nondeterministic source (time.*, RNG, os.environ/getenv,
  socket.gethostname) guarding a collective (deadlock: some hosts never
  arrive — error) or a trace-entry call (hosts compile divergent
  executables — warning). The r13 multihost pricing divergence
  generalized: gate on a BROADCAST value, never a locally measured one.
- `unverified_transition` — a direct call to one of the state
  re-placement appliers (`place_update_sharded`, `place_like`,
  `restore_tree`) in a function that never consults the fftrans
  transition checker (analysis/transition.py). Re-placing live/restored
  state outside the checker-gated path is exactly how a dropped
  mapping, dtype drift, or a stage-3 shard without a gather path
  becomes a shape crash or silent corruption mid-restore — route
  through `migrate_state` / `verify_restore_transition` (a fresh-init
  placement at compile is not a transition: pragma it).
- `raw_timer_in_hot_path` — two or more bare `time.perf_counter()` /
  `time.time()` reads (a start/stop pair) inside a step/decode/prefill
  hot-path function outside `telemetry/`. A hand-rolled timer pair is a
  measurement the ffpulse metrics plane never sees — route it through
  `telemetry.span(...)` or `telemetry.observe(...)` so it lands in the
  mergeable histograms, or gate it behind a telemetry check. Sites
  where the raw read IS the product (the device-sync timing the span
  wraps, wall-clock pacing) carry the pragma.
- `unverified_rule_load` — a call that constructs or loads
  `GraphXfer`s (`load_rule_collection` without the verifying `config=`
  argument, `compile_pattern_rule`, `generate_all_pcg_xfers`) in a
  function that never consults the ffrules verifier
  (analysis/rules.py). Rules injected into the search unverified are
  exactly how an unsound rewrite becomes a silently-wrong plan — the
  r19 twin of `unverified_transition`; the built-in registry's own
  load sites are pragma'd because scripts/ffrules.py sweeps the full
  generated registry in CI.
- `unnamed_op_scope` — an op-dispatch call (`*.op_def.forward` /
  `*.op_def.backward`) in executor.py or ops/ with no lexically
  enclosing `jax.named_scope(...)` block. The ffscope profiling plane
  attributes trace events back to PCG nodes purely by named_scope
  labels (scope/attribution.py) — a dispatch outside a scope produces
  device time the attribution can only file as `unattributed_s`, so
  the fidelity table silently loses that op. Dispatches that run under
  a CALLER's named_scope (runtime nesting the AST cannot see, e.g. the
  stage-3 remat closure invoked from the scoped forward loop) carry
  the pragma.

Suppression: a trailing `# fflint: ok` (optionally naming codes,
`# fflint: ok host_sync_in_loop`) on the flagged line or its enclosing
`def` line. Used where the hazard is the point (calibration timing
loops fetch inside a loop BY DESIGN).

`scripts/fflint.py` is the CLI; the ffcheck pass pipeline reuses
`coordinator_collective` + `donated_reuse` as its source-level checks.
"""

from __future__ import annotations

import ast
import os

from .findings import Finding, SEV_ERROR, SEV_WARNING

PASS_NAME = "fflint"

ALL_RULES = ("host_sync_in_loop", "unsorted_dict_hash", "global_rng",
             "time_in_trace", "coordinator_collective", "donated_reuse",
             "low_precision_accum", "host_divergent_branch",
             "unverified_transition", "unverified_rule_load",
             "raw_timer_in_hot_path", "unnamed_op_scope")

# identifiers whose presence in an `if` test marks the branch as a
# telemetry/diagnostics gate (a gated fetch is the sanctioned pattern)
_GATE_IDS = ("tel", "telemetry", "diag", "diagnostics", "sampled",
             "verbose", "profiling", "debug")

_TIME_FUNCS = {"time", "perf_counter", "monotonic", "process_time",
               "time_ns", "perf_counter_ns", "monotonic_ns"}
_NP_RANDOM_OK = {"RandomState", "default_rng", "Generator",
                 "SeedSequence", "PCG64", "Philox", "MT19937"}
_PY_RANDOM_FUNCS = {"random", "randint", "choice", "choices", "shuffle",
                    "seed", "uniform", "randrange", "sample", "gauss",
                    "betavariate", "getrandbits"}
_COLLECTIVES = {"barrier", "broadcast_json", "sync_global_devices",
                "broadcast_one_to_all", "psum", "pmean", "pmax",
                "all_gather", "all_to_all", "ppermute",
                "process_allgather"}
_TRACE_ENTRY = {"jit", "scan", "fori_loop", "while_loop", "cond",
                "switch", "associative_scan", "shard_map", "pallas_call",
                "checkpoint", "remat", "vmap", "pmap", "grad",
                "value_and_grad"}

# donated-step callees (by last identifier) → donated argnums. MUST
# match the executor's _donate_argnums declarations — the ffcheck
# donation pass cross-checks this registry against executor.py's AST
# (analysis/donation.py), so the two cannot drift silently.
DONATED_CALLEES = {
    "step_fn": (0, 1, 2, 3, 4),       # build_train_step
    "_train_step": (0, 1, 2, 3, 4),
    "chunk_fn": (0, 1, 2, 3, 4),      # build_chunked_train_step
    "eval_fn": (2,),                  # build_eval_step
    "_eval_step": (2,),
    "_step_fn": (1,),                 # build_decode_step (KV-cache state)
    "_decode_step": (1,),
    "_verify_fn": (1,),               # build_verify_step (speculative)
    "_verify_step": (1,),
    "_copy_fn": (0,),                 # build_block_copy (paged KV pools)
    "_inject_fn": (0,),               # build_kv_inject (disagg handoff)
    "_gather_fn": (0,),               # build_param_gather (stage-3 tree)
    "gather_fn": (0,),
}

_HASH_FN_HINTS = ("fingerprint", "signature", "digest", "_sha", "hash")

# state re-placement appliers (the reshard-apply surface) and the
# fftrans checker entry points that gate them (analysis/transition.py,
# resilience/migrate.py) — a function calling an applier must also
# consult a checker, or the re-placement runs unverified
_TRANSITION_APPLIERS = {"place_update_sharded", "place_like",
                        "restore_tree"}
_TRANSITION_CHECKERS = {"verify_restore_transition", "verify_transition",
                        "gate_transition", "build_transition_plan",
                        "plan_model_transition", "migrate_state"}

# GraphXfer construct/load surface (search/substitution.py) and the
# ffrules checker entry points that gate it (analysis/rules.py) — a
# function loading rules must also consult the verifier, or pass
# config= to load_rule_collection (the loader then verifies internally)
_RULE_LOADERS = {"load_rule_collection", "compile_pattern_rule",
                 "generate_all_pcg_xfers"}
_RULE_CHECKERS = {"verify_rule", "verify_rules", "verify_registry",
                  "gate_loaded_rules", "RuleVerificationError"}

# summing reductions the low-precision-accumulation rule watches
# (order statistics — max/min/argmax — carry no accumulation error)
_SUM_FUNCS = {"sum", "mean", "prod", "cumsum", "logsumexp", "einsum"}

# hot-path function name hints for the raw-timer rule — the per-step /
# per-token functions whose measurements belong in the metrics plane
_HOT_PATH_HINTS = ("step", "decode", "prefill")
# bare-name timer calls (`from time import perf_counter` idiom); the
# dotted `time.X` forms reuse _TIME_FUNCS
_BARE_TIMER_NAMES = {"perf_counter", "monotonic", "perf_counter_ns",
                     "monotonic_ns"}


def _dotted(node) -> str:
    """Name/Attribute chain → dotted string ('' when not a pure chain)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _last_ident(node) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


class _FileLint:
    def __init__(self, src: str, path: str, select):
        self.tree = ast.parse(src)
        self.lines = src.splitlines()
        self.path = path
        self.select = set(select) if select else set(ALL_RULES)
        self.findings: list[Finding] = []
        self._parent_map = None  # built lazily (one full-tree walk)

    @property
    def _parents(self) -> dict:
        if self._parent_map is None:
            self._parent_map = {}
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parent_map[id(child)] = parent
        return self._parent_map

    # ------------------------------------------------------------ pragmas

    def _suppressed(self, node, code: str) -> bool:
        for ln in {getattr(node, "lineno", 0), self._def_line(node)}:
            if not (0 < ln <= len(self.lines)):
                continue
            line = self.lines[ln - 1]
            if "# fflint: ok" not in line:
                continue
            tail = line.split("# fflint: ok", 1)[1].strip()
            listed = [t.strip(",") for t in tail.split()
                      if t.strip(",") in ALL_RULES]
            if not listed or code in listed:
                return True
        return False

    def _def_line(self, node) -> int:
        cur = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur.lineno
            cur = self._parents.get(id(cur))
        return 0

    def _emit(self, node, severity, code, message, **details):
        if code not in self.select or self._suppressed(node, code):
            return
        self.findings.append(Finding(
            severity, code, message, pass_name=PASS_NAME,
            where=f"{self.path}:{getattr(node, 'lineno', 0)}",
            details=details or {}))

    # --------------------------------------------------------- rule: sync

    def _gate_names(self, fn) -> set:
        """Gate identifiers for one function: the builtin set plus any
        local assigned FROM a gated expression (need_losses = tel is not
        None)."""
        gates = set(_GATE_IDS)
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                tgt = node.targets[0].id
                if tgt in gates:
                    continue
                idents = {n.id for n in ast.walk(node.value)
                          if isinstance(n, ast.Name)}
                idents |= {n.attr for n in ast.walk(node.value)
                           if isinstance(n, ast.Attribute)}
                if any(any(g in i for g in gates) for i in idents):
                    gates.add(tgt)
                    changed = True
        return gates

    def _mentions_gate(self, test, gates) -> bool:
        for n in ast.walk(test):
            ident = ""
            if isinstance(n, ast.Name):
                ident = n.id
            elif isinstance(n, ast.Attribute):
                ident = n.attr
            if ident and any(g in ident for g in gates):
                return True
        return False

    def rule_host_sync_in_loop(self):
        for fn in ast.walk(self.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            gates = self._gate_names(fn)
            self._scan_sync(fn.body, gates, in_loop=False, gated=False)

    def _scan_sync(self, stmts, gates, in_loop, gated):
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs get their own pass
            if isinstance(node, (ast.For, ast.While)):
                self._scan_sync(node.body, gates, True, gated)
                self._scan_sync(node.orelse, gates, in_loop, gated)
                continue
            if isinstance(node, ast.If):
                g = gated or self._mentions_gate(node.test, gates)
                self._scan_sync(node.body, gates, in_loop, g)
                self._scan_sync(node.orelse, gates, in_loop, g)
                continue
            if isinstance(node, ast.With):
                self._scan_sync(node.body, gates, in_loop, gated)
                continue
            if isinstance(node, ast.Try):
                for sub in (node.body, node.orelse, node.finalbody):
                    self._scan_sync(sub, gates, in_loop, gated)
                for h in node.handlers:
                    self._scan_sync(h.body, gates, in_loop, gated)
                continue
            if not in_loop:
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                if _last_ident(call.func) != "device_get":
                    continue
                g = gated
                # conditional-expression gate: x if need_losses else None
                cur = call
                while cur is not None and not g:
                    if isinstance(cur, ast.IfExp) and \
                            self._mentions_gate(cur.test, gates):
                        g = True
                    cur = self._parents.get(id(cur))
                    if isinstance(cur, ast.stmt):
                        break
                if g:
                    continue
                self._emit(
                    call, SEV_WARNING, "host_sync_in_loop",
                    "jax.device_get inside a loop is a per-iteration "
                    "device drain — hoist it out, batch it per chunk, or "
                    "gate it behind telemetry/diagnostics")

    # --------------------------------------------------- rule: dict hash

    def rule_unsorted_dict_hash(self):
        for fn in ast.walk(self.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            hashy = any(h in fn.name.lower() for h in _HASH_FN_HINTS)
            if not hashy:
                for call in ast.walk(fn):
                    if isinstance(call, ast.Call):
                        d = _dotted(call.func)
                        if d.startswith("hashlib.") or \
                                _last_ident(call.func) == "_sha":
                            hashy = True
                            break
            if not hashy:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.For):
                    continue
                it = node.iter
                if isinstance(it, ast.Call) and \
                        isinstance(it.func, ast.Attribute) and \
                        it.func.attr in ("items", "keys", "values"):
                    self._emit(
                        node, SEV_WARNING, "unsorted_dict_hash",
                        f"iteration over .{it.func.attr}() inside hash "
                        f"function {fn.name}(): dict order is insertion "
                        f"order — wrap in sorted(...) so the digest is "
                        f"order-free")

    # --------------------------------------------------- rule: global rng

    def _rng_call(self, call) -> str:
        d = _dotted(call.func)
        parts = d.split(".")
        if len(parts) >= 3 and parts[-3] in ("np", "numpy") \
                and parts[-2] == "random" and \
                parts[-1] not in _NP_RANDOM_OK:
            return d
        if len(parts) == 2 and parts[0] == "random" \
                and parts[1] in _PY_RANDOM_FUNCS:
            return d
        return ""

    def rule_global_rng(self):
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            d = self._rng_call(call)
            if d:
                self._emit(
                    call, SEV_WARNING, "global_rng",
                    f"{d}() uses the process-global RNG — seed-keyed "
                    f"np.random.RandomState / default_rng keeps resume "
                    f"and multi-process runs replayable")

    # ------------------------------------------------- rule: time in jit

    def _traced_defs(self) -> set:
        """ids of FunctionDef nodes that are traced: jit-decorated, or
        referenced (possibly through functools.partial) as an argument
        of a trace-entry call (jit/shard_map/pallas_call/lax control
        flow) — plus every def nested inside one."""
        defs_by_name: dict[str, list] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)
        marked: set[int] = set()

        def mark_name(name: str):
            for d in defs_by_name.get(name, []):
                marked.add(id(d))

        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    tgt = dec.func if isinstance(dec, ast.Call) else dec
                    if _last_ident(tgt) in ("jit", "partial"):
                        if _last_ident(tgt) == "partial" and isinstance(
                                dec, ast.Call):
                            if not any(_last_ident(a) == "jit"
                                       for a in dec.args):
                                continue
                        marked.add(id(node))
            if not isinstance(node, ast.Call):
                continue
            if _last_ident(node.func) not in _TRACE_ENTRY:
                continue
            cands = list(node.args) + [k.value for k in node.keywords]
            for a in cands:
                if isinstance(a, ast.Call) and \
                        _last_ident(a.func) == "partial" and a.args:
                    a = a.args[0]
                if isinstance(a, (ast.Name, ast.Attribute)):
                    nm = _last_ident(a)
                    if nm:
                        mark_name(nm)
        # nested defs inside a traced def trace with it
        out = set(marked)
        changed = True
        while changed:
            changed = False
            for node in ast.walk(self.tree):
                if not isinstance(node,
                                  (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if id(node) not in out:
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)) \
                            and id(sub) not in out:
                        out.add(id(sub))
                        changed = True
        return out

    def rule_time_in_trace(self):
        traced = self._traced_defs()
        for fn in ast.walk(self.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if id(fn) not in traced:
                continue
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                d = _dotted(call.func)
                parts = d.split(".")
                bad = ""
                if len(parts) == 2 and parts[0] == "time" \
                        and parts[1] in _TIME_FUNCS:
                    bad = d
                elif d in ("datetime.now", "datetime.datetime.now",
                           "datetime.utcnow"):
                    bad = d
                elif self._rng_call(call):
                    bad = self._rng_call(call)
                if bad:
                    self._emit(
                        call, SEV_ERROR, "time_in_trace",
                        f"{bad}() inside traced function {fn.name}() "
                        f"executes ONCE at trace time and bakes a "
                        f"constant into the executable")

    # ------------------------------------- rule: coordinator collective

    def _is_coordinator_test(self, test) -> tuple[bool, bool]:
        """(gates_body, gates_orelse): does this `if` test make one
        branch coordinator-only? Handles `is_coordinator()`,
        `process_index() == 0`, and their negations."""
        neg = False
        inner = test
        while isinstance(inner, ast.UnaryOp) and \
                isinstance(inner.op, ast.Not):
            neg = not neg
            inner = inner.operand
        coord = False
        for n in ast.walk(inner):
            if isinstance(n, ast.Call) and \
                    _last_ident(n.func) == "is_coordinator":
                coord = True
            if isinstance(n, ast.Compare) and \
                    isinstance(n.left, ast.Call) and \
                    _last_ident(n.left.func) == "process_index":
                coord = True
        if not coord:
            return False, False
        return (not neg, neg)

    def rule_coordinator_collective(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.If):
                continue
            body_coord, orelse_coord = self._is_coordinator_test(node.test)
            for stmts, flagged in ((node.body, body_coord),
                                   (node.orelse, orelse_coord)):
                if not flagged:
                    continue
                for sub in stmts:
                    for call in ast.walk(sub):
                        if isinstance(call, ast.Call) and \
                                _last_ident(call.func) in _COLLECTIVES:
                            self._emit(
                                call, SEV_ERROR, "coordinator_collective",
                                f"collective "
                                f"{_last_ident(call.func)}() inside a "
                                f"coordinator-only branch: the other "
                                f"processes never reach it — multihost "
                                f"deadlock. Gate the PAYLOAD, not the "
                                f"collective (broadcast_json(x if "
                                f"is_coordinator() else None))")

    # ------------------------------------------- rule: donated reuse

    def rule_donated_reuse(self):
        # one cheap pre-scan: most files (and most functions) never call
        # a donated executable — only collect per-function load/store
        # events where a donated call actually appears
        calls = [n for n in ast.walk(self.tree)
                 if isinstance(n, ast.Call)
                 and _last_ident(n.func) in DONATED_CALLEES]
        if not calls:
            return
        involved: dict[int, ast.AST] = {}
        for c in calls:
            cur = self._parents.get(id(c))
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cur = self._parents.get(id(cur))
            if cur is not None:
                involved.setdefault(id(cur), cur)
        for fn in involved.values():
            events = []  # (lineno, col, kind, expr string)
            for node in ast.walk(fn):
                d = ""
                if isinstance(node, (ast.Name, ast.Attribute)):
                    d = _dotted(node)
                if not d:
                    continue
                kind = ("store" if isinstance(
                    getattr(node, "ctx", None), ast.Store) else "load")
                events.append((node.lineno, node.col_offset, kind, d))
            events.sort()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = _last_ident(node.func)
                donated = DONATED_CALLEES.get(callee)
                if donated is None:
                    continue
                stmt = self._enclosing_stmt(node)
                targets: set[str] = set()
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        for n in ast.walk(t):
                            if isinstance(n, (ast.Name, ast.Attribute)):
                                s = _dotted(n)
                                if s:
                                    targets.add(s)
                end = getattr(stmt, "end_lineno", node.lineno)
                for argnum in donated:
                    if argnum >= len(node.args):
                        continue
                    arg = node.args[argnum]
                    if not isinstance(arg, (ast.Name, ast.Attribute)):
                        continue
                    expr = _dotted(arg)
                    if not expr or expr in targets:
                        continue
                    nxt = next(
                        (e for e in events
                         if e[0] > end and e[3] == expr), None)
                    if nxt is not None and nxt[2] == "load":
                        self._emit(
                            node, SEV_ERROR, "donated_reuse",
                            f"{expr} passed at donated argnum {argnum} "
                            f"of {callee}() and read again at line "
                            f"{nxt[0]} without rebinding — the donated "
                            f"buffer is dead after the call",
                            reuse_line=nxt[0], argnum=argnum)

    def _enclosing_stmt(self, node):
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self._parents.get(id(cur))
        return cur

    # --------------------------------------- rule: low-precision accum

    def _low_precision_expr(self, node) -> str:
        """Name of the low-precision dtype an expression subtree pins
        ('' when none): an astype()/dtype= targeting bfloat16/float16."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and \
                    _last_ident(sub.func) == "astype":
                for a in sub.args:
                    d = _dotted(a) or (a.value if isinstance(
                        a, ast.Constant) and isinstance(a.value, str)
                        else "")
                    if isinstance(d, str) and d.split(".")[-1] in (
                            "bfloat16", "float16"):
                        return d
        return ""

    def rule_low_precision_accum(self):
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            if _last_ident(call.func) not in _SUM_FUNCS:
                continue
            lp = ""
            for kw in call.keywords:
                if kw.arg in ("dtype", "preferred_element_type"):
                    d = _dotted(kw.value)
                    if d.split(".")[-1] in ("bfloat16", "float16"):
                        lp = d
            if not lp:
                for a in call.args:
                    lp = self._low_precision_expr(a)
                    if lp:
                        break
            if lp:
                self._emit(
                    call, SEV_WARNING, "low_precision_accum",
                    f"{_last_ident(call.func)}() accumulates in "
                    f"{lp.split('.')[-1]} — long low-precision sums "
                    f"drift; reduce in f32 and downcast the result "
                    f"(loss.py / ops/core.py convention)")

    # ------------------------------------ rule: host-divergent branch

    def _divergent_source(self, test) -> str:
        """Dotted name of a per-host-nondeterministic call in an `if`
        test ('' when none)."""
        for n in ast.walk(test):
            if not isinstance(n, ast.Call):
                continue
            d = _dotted(n.func)
            parts = d.split(".")
            if len(parts) == 2 and parts[0] == "time" \
                    and parts[1] in _TIME_FUNCS:
                return d
            if self._rng_call(n):
                return d
            if d in ("os.getenv", "os.environ.get",
                     "socket.gethostname", "platform.node"):
                return d
        return ""

    def rule_host_divergent_branch(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.If):
                continue
            src = self._divergent_source(node.test)
            if not src:
                continue
            for stmts in (node.body, node.orelse):
                for sub in stmts:
                    for call in ast.walk(sub):
                        if not isinstance(call, ast.Call):
                            continue
                        callee = _last_ident(call.func)
                        if callee in _COLLECTIVES:
                            self._emit(
                                call, SEV_ERROR,
                                "host_divergent_branch",
                                f"collective {callee}() behind a branch "
                                f"on {src}() — hosts evaluate the test "
                                f"differently and some never reach the "
                                f"collective: fleet deadlock. Decide on "
                                f"the coordinator and broadcast_json "
                                f"the verdict", source=src)
                        elif callee in _TRACE_ENTRY:
                            self._emit(
                                call, SEV_WARNING,
                                "host_divergent_branch",
                                f"trace entry {callee}() behind a "
                                f"branch on {src}() — hosts may compile "
                                f"divergent executables (the r13 "
                                f"pricing-divergence class); key the "
                                f"decision on broadcast state",
                                source=src)

    # ------------------------------------ rule: unverified transition

    def _enclosing_def(self, node):
        cur = self._parents.get(id(node))
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cur = self._parents.get(id(cur))
        return cur

    def rule_unverified_transition(self):
        calls = [n for n in ast.walk(self.tree)
                 if isinstance(n, ast.Call)
                 and _last_ident(n.func) in _TRANSITION_APPLIERS]
        if not calls:
            return
        # checker references per enclosing def (None = module level):
        # any Name/Attribute mention counts — the gate may be called,
        # passed, or imported-and-called under an alias attribute
        gated_scopes: set[int] = set()
        for node in ast.walk(self.tree):
            ident = ""
            if isinstance(node, ast.Name):
                ident = node.id
            elif isinstance(node, ast.Attribute):
                ident = node.attr
            if ident in _TRANSITION_CHECKERS:
                scope = self._enclosing_def(node)
                gated_scopes.add(id(scope) if scope is not None else 0)
        for call in calls:
            scope = self._enclosing_def(call)
            sid = id(scope) if scope is not None else 0
            if sid in gated_scopes:
                continue
            callee = _last_ident(call.func)
            self._emit(
                call, SEV_WARNING, "unverified_transition",
                f"{callee}() re-places state outside the fftrans "
                f"checker-gated path — a dropped mapping / dtype drift "
                f"/ missing gather path here surfaces as corruption "
                f"mid-restore; route through migrate_state / "
                f"verify_restore_transition (fresh-init placement at "
                f"compile is exempt: pragma it)")

    # ------------------------------------ rule: unverified rule load

    def rule_unverified_rule_load(self):
        calls = [n for n in ast.walk(self.tree)
                 if isinstance(n, ast.Call)
                 and _last_ident(n.func) in _RULE_LOADERS]
        if not calls:
            return
        gated_scopes: set[int] = set()
        for node in ast.walk(self.tree):
            ident = ""
            if isinstance(node, ast.Name):
                ident = node.id
            elif isinstance(node, ast.Attribute):
                ident = node.attr
            if ident in _RULE_CHECKERS:
                scope = self._enclosing_def(node)
                gated_scopes.add(id(scope) if scope is not None else 0)
        def _is_none(node) -> bool:
            return isinstance(node, ast.Constant) and node.value is None

        for call in calls:
            callee = _last_ident(call.func)
            if callee == "load_rule_collection":
                # the loader verifies internally when handed a config
                # (keyword or third positional) — that call IS the
                # gate. A literal None is NOT a config: the loader
                # skips verification for it.
                gated = any(kw.arg == "config" and not _is_none(kw.value)
                            for kw in call.keywords)
                if len(call.args) >= 3 and not _is_none(call.args[2]):
                    gated = True
                if gated:
                    continue
            scope = self._enclosing_def(call)
            sid = id(scope) if scope is not None else 0
            if sid in gated_scopes:
                continue
            self._emit(
                call, SEV_WARNING, "unverified_rule_load",
                f"{callee}() constructs/loads GraphXfers outside an "
                f"ffrules-verifier-consulting function — an unsound "
                f"rule injected into the search becomes a silently "
                f"wrong plan; pass config= to load_rule_collection or "
                f"route through analysis.rules.verify_rules (the "
                f"CI-swept built-in registry is exempt: pragma it)")

    # ---------------------------------- rule: raw timer in hot path

    def _timer_call(self, call) -> str:
        d = _dotted(call.func)
        parts = d.split(".")
        if len(parts) == 2 and parts[0] == "time" \
                and parts[1] in _TIME_FUNCS:
            return d
        if len(parts) == 1 and parts[0] in _BARE_TIMER_NAMES:
            return d
        return ""

    def rule_raw_timer_in_hot_path(self):
        # telemetry/ is the one place raw clock reads are the point:
        # the span/observe implementations themselves
        if "telemetry" in os.path.normpath(self.path).split(os.sep):
            return
        for fn in ast.walk(self.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(h in fn.name.lower() for h in _HOT_PATH_HINTS):
                continue
            gates = self._gate_names(fn)
            timers = []
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call) or \
                        not self._timer_call(call):
                    continue
                if self._enclosing_def(call) is not fn:
                    continue  # nested defs get their own pass
                # a read inside an `if tel is not None:` branch is the
                # sanctioned gated-measurement idiom
                gated = False
                cur = self._parents.get(id(call))
                while cur is not None and cur is not fn:
                    if isinstance(cur, ast.If) and \
                            self._mentions_gate(cur.test, gates):
                        gated = True
                        break
                    cur = self._parents.get(id(cur))
                if not gated:
                    timers.append(call)
            if len(timers) < 2:
                continue  # a lone read is not a measurement pair
            second = sorted(timers, key=lambda c: (c.lineno,
                                                   c.col_offset))[1]
            self._emit(
                second, SEV_WARNING, "raw_timer_in_hot_path",
                f"{len(timers)} bare timer reads in hot-path function "
                f"{fn.name}() — a hand-rolled start/stop pair the "
                f"metrics plane never sees; wrap the region in "
                f"telemetry.span(...) or feed the delta to "
                f"telemetry.observe(...) so it lands in the mergeable "
                f"histograms", timer_reads=len(timers))

    # ------------------------------------ rule: unnamed op scope

    def rule_unnamed_op_scope(self):
        # only where op dispatch lives: the executor's forward/backward
        # paths and the ops/ package — the cost model's calibration
        # harness times ops standalone (no trace to attribute) and is
        # out of scope by construction
        parts = os.path.normpath(self.path).split(os.sep)
        if os.path.basename(self.path) != "executor.py" \
                and "ops" not in parts:
            return
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            d = _dotted(call.func)
            if not (d.endswith(".op_def.forward")
                    or d.endswith(".op_def.backward")
                    or d in ("op_def.forward", "op_def.backward")):
                continue
            named = False
            cur = self._parents.get(id(call))
            while cur is not None:
                if isinstance(cur, ast.With):
                    for item in cur.items:
                        ce = item.context_expr
                        if isinstance(ce, ast.Call) and \
                                _last_ident(ce.func) == "named_scope":
                            named = True
                if isinstance(cur, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    break  # runtime nesting is invisible past a def
                cur = self._parents.get(id(cur))
            if named:
                continue
            self._emit(
                call, SEV_WARNING, "unnamed_op_scope",
                f"{d}() dispatched outside jax.named_scope — its device "
                f"time cannot be attributed back to the PCG node by the "
                f"ffscope profiling plane (scope/attribution.py maps "
                f"trace events via scope labels); wrap the dispatch in "
                f"`with jax.named_scope(node.name):` (a dispatch that "
                f"runs under a caller's scope is exempt: pragma it)")

    # ---------------------------------------------------------------- run

    def run(self) -> list[Finding]:
        for rule in ALL_RULES:
            if rule in self.select:
                getattr(self, f"rule_{rule}")()
        self.findings.sort(key=lambda f: f.where)
        return self.findings


def lint_source(src: str, path: str = "<string>",
                select=None) -> list[Finding]:
    """Lint one source string. Raises SyntaxError on unparseable input."""
    return _FileLint(src, path, select).run()


def lint_file(path: str, select=None) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        return lint_source(src, path, select)
    except SyntaxError as e:
        return [Finding(SEV_ERROR, "parse_error",
                        f"could not parse: {e}", pass_name=PASS_NAME,
                        where=f"{path}:{e.lineno or 0}")]


_EXCLUDE_DIRS = {"__pycache__", ".git", ".github", "node_modules"}


def iter_py_files(root: str, exclude=()):
    exclude = set(exclude)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in _EXCLUDE_DIRS and d not in exclude)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_paths(paths, select=None, exclude=()) -> list[Finding]:
    findings: list[Finding] = []
    for p in paths:
        if os.path.isdir(p):
            for f in iter_py_files(p, exclude=exclude):
                findings.extend(lint_file(f, select))
        else:
            findings.extend(lint_file(p, select))
    return findings
