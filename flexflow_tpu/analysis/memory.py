"""Pass 2 — static memory liveness over the fwd+bwd schedule.

The cost model prices per-chip memory as a per-op SUM (op_cost's
weight_mem + act_bytes, the MemoryUsage analog); that is an upper bound
with no notion of WHEN bytes are live. This pass walks the training
step's actual schedule — forward in topo order (activations retained for
the backward), then backward in reverse (activations freed after their
VJP consumes them, transient activation-gradients live across each bwd
op) — and produces:

- a per-op memory TIMELINE (phase, op, live bytes) so an OOM is
  attributable to the op where the peak lands, before it ever reaches
  the device;
- the static peak per-chip HBM, counting fp32 masters, gradients,
  optimizer slots, and the weight-update sharding layout
  (`executor.update_specs` — masters/slots at 1/shards, one gathered
  compute copy), using the SAME per-buffer accounting rules as
  `CostModel.op_cost` so the two estimates are commensurable;
- a cross-check against the cost model's own estimate
  (`memory_model_divergence` when they disagree beyond the transient
  slack liveness legitimately adds).

OOM gating is deliberately two-keyed: `oom_predicted` is an ERROR only
when BOTH accountings (liveness peak and the cost-model sum) exceed the
per-chip cap — the gate must never abort a plan the priced search
already accepted as fitting on a number the search never saw — and a
WARNING when liveness alone crosses the cap.
"""

from __future__ import annotations

import math

from ..fftype import OperatorType as OT
from .findings import Finding, SEV_ERROR, SEV_INFO, SEV_WARNING

PASS_NAME = "memory_liveness"

_SKIP = (OT.OP_INPUT, OT.OP_WEIGHT, OT.OP_NOOP)
_TIMELINE_CAP = 512


def _shard_bytes(shape, assignment, axis_sizes, el_bytes) -> float:
    n = 1.0
    for i, dim in enumerate(shape):
        deg = 1
        if assignment and i < len(assignment):
            for ax in assignment[i]:
                deg *= axis_sizes.get(ax, 1)
        n *= max(1, math.ceil(dim / deg))
    return n * el_bytes


def _logical_assignment(pt):
    return tuple(a for d, a in zip(pt.shape.dims, pt.axis_assignment)
                 if not d.is_replica_dim)


def analyze(graph, mesh, *, opt_slots: int = 1, update_specs=None,
            training: bool = True, update_stage: int = 0) -> dict:
    """Static per-chip memory model of one training (or inference) step.
    Returns {persistent_bytes, peak_bytes, peak_at, timeline,
    weight_bytes, activation_bytes, gather_peak_bytes}.

    `update_stage` 3 (ZeRO-3/FSDP) changes the sharded weights'
    accounting: the resident gathered compute copy leaves the persistent
    set (weights live 1/shards at rest) and each op's gathered copies
    become a TRANSIENT in the timeline — the op's own gather plus the
    one-layer-ahead prefetch, so at most two gathered layers are in
    flight at any point of the fwd (and of the bwd, which re-gathers in
    reverse order). This is the accounting the acceptance criterion's
    "1/shards at rest + transient gather" check verifies."""
    from ..search.cost_model import dtype_bytes
    from ..parallel.ops import _spec_assignment

    axis_sizes = {k: int(v) for k, v in dict(mesh.shape).items()}
    update_specs = update_specs or {}
    order = graph.topo_order()
    stage3 = update_stage >= 3 and training

    # ---- persistent: masters + grads + optimizer slots (+ the gathered
    # compute copy under a sharded update), per the op_cost rules. An
    # inference compile (serving decode graphs) carries NO grads or
    # optimizer state — trainable weights cost 1x, not (2 + opt_slots)x,
    # or a trained-then-served model would overstate its footprint ~3x
    # and trip the OOM gate on a serving launch that actually fits.
    persistent = 0.0
    weight_bytes = 0.0
    # stage 3: per owning node, the FULL bytes of its gathered weight
    # copies — a transient charged while the node (or its one-ahead
    # prefetch) is in flight, not a persistent resident
    gather_of: dict[int, float] = {}
    for node in order:
        if getattr(node, "weight_source", None):
            continue  # tied weights live under the source node
        for ws in node.weight_specs:
            el = dtype_bytes(ws.dtype)
            base = _spec_assignment(
                node.weight_axes.get(ws.name), len(ws.shape))
            wb = _shard_bytes(ws.shape, base, axis_sizes, el)
            upd = update_specs.get((node.name, ws.name))
            if not ws.trainable or not training:
                persistent += wb
                weight_bytes += wb
            elif upd is not None:
                rest = _shard_bytes(
                    ws.shape, _spec_assignment(upd[0], len(ws.shape)),
                    axis_sizes, el)
                if stage3:
                    # weights 1/shards at rest; the gathered copy is a
                    # transient (two layers in flight, charged below)
                    persistent += rest * (2 + opt_slots)
                    weight_bytes += rest * (2 + opt_slots)
                    gather_of[node.guid] = gather_of.get(
                        node.guid, 0.0) + wb
                else:
                    # gathered compute copy + master/grad/slots at
                    # 1/shards (stage 2)
                    persistent += wb + rest * (2 + opt_slots)
                    weight_bytes += wb + rest * (2 + opt_slots)
            else:
                persistent += wb * (2 + opt_slots)
                weight_bytes += wb * (2 + opt_slots)

    # ---- activation liveness: fwd retains every activation for the
    # backward; bwd frees each node's inputs after its VJP runs and
    # carries the output-gradient transiently
    act_bytes_of: dict[tuple[int, int], float] = {}
    for node in order:
        for i, pt in enumerate(node.outputs):
            shape = pt.shape.logical_shape
            act_bytes_of[(node.guid, i)] = _shard_bytes(
                shape, _logical_assignment(pt), axis_sizes,
                dtype_bytes(pt.dtype))

    timeline: list[dict] = []
    live = persistent
    peak = persistent
    peak_at = "(weights)"
    compute_nodes = [n for n in order if n.op_type not in _SKIP]
    total_act = 0.0
    # stage-3 transient gather in flight per schedule position: the
    # node's own gathered copies + the one-layer-ahead prefetch (fwd:
    # the NEXT gathering node; bwd: the PREVIOUS one — the reverse walk
    # prefetches in reverse). At most two gathered layers live at once.
    g = [gather_of.get(n.guid, 0.0) for n in compute_nodes]
    nxt_g = [0.0] * len(g)
    run = 0.0
    for t in range(len(g) - 1, -1, -1):
        nxt_g[t] = run
        if g[t] > 0:
            run = g[t]
    prv_g = [0.0] * len(g)
    run = 0.0
    for t in range(len(g)):
        prv_g[t] = run
        if g[t] > 0:
            run = g[t]
    fwd_inflight = [a + b for a, b in zip(g, nxt_g)]
    bwd_inflight = [a + b for a, b in zip(g, prv_g)]
    gather_peak = max(fwd_inflight + bwd_inflight, default=0.0)
    # inference: no backward retains anything — an activation dies after
    # its LAST consumer in the topo schedule
    last_use: dict[tuple[int, int], int] = {}
    free_at: dict[int, list] = {}
    if not training:
        # only compute-node outputs ever enter `live` below — freeing an
        # OP_INPUT producer's bytes would subtract what was never added
        # and understate every later timeline entry
        compute_guids = {n.guid for n in compute_nodes}
        for t, node in enumerate(compute_nodes):
            for e in graph.in_edges[node.guid]:
                if e.src not in compute_guids:
                    continue
                key = (e.src, e.src_idx)
                last_use[key] = max(last_use.get(key, -1), t)
        for key, last in last_use.items():
            free_at.setdefault(last, []).append(key)
    for t, node in enumerate(compute_nodes):
        for i in range(len(node.outputs)):
            b = act_bytes_of.get((node.guid, i), 0.0)
            live += b
            total_act += b
        here = live + fwd_inflight[t]
        timeline.append({"phase": "fwd", "op": node.name,
                         "live_bytes": here})
        if here > peak:
            peak, peak_at = here, f"fwd:{node.name}"
        if not training:
            for key in free_at.get(t, ()):
                live -= act_bytes_of.get(key, 0.0)
    if training:
        for t in range(len(compute_nodes) - 1, -1, -1):
            node = compute_nodes[t]
            # transient: the cotangent of this node's output(s) — and,
            # under stage 3, its re-gathered weight copies — coexists
            # with the still-retained forward activations
            grad = sum(act_bytes_of.get((node.guid, i), 0.0)
                       for i in range(len(node.outputs)))
            here = live + grad + bwd_inflight[t]
            if here > peak:
                peak, peak_at = here, f"bwd:{node.name}"
            timeline.append({"phase": "bwd", "op": node.name,
                             "live_bytes": here})
            for i in range(len(node.outputs)):
                live -= act_bytes_of.get((node.guid, i), 0.0)
    return {
        "persistent_bytes": persistent,
        "weight_bytes": weight_bytes,
        "activation_bytes": total_act,
        "peak_bytes": peak,
        "peak_at": peak_at,
        "gather_peak_bytes": gather_peak,
        "timeline": timeline[:_TIMELINE_CAP],
    }


def _cost_model_memory(graph, cost_model) -> float:
    """The pricer's own per-chip memory figure on the materialized
    assignments (the Σ op_cost memory the search/update-sharding decision
    consumed) — the number this pass cross-checks against."""
    mem = 0.0
    gather_peak = 0.0
    for node in graph.topo_order():
        if node.op_type in _SKIP or node.is_parallel_op:
            continue
        in_shapes = [pt.shape.logical_shape for pt in node.inputs]
        in_assigns = [_logical_assignment(pt) for pt in node.inputs]
        cmx = cost_model.op_cost(
            node, [_logical_assignment(pt) for pt in node.outputs],
            dict(node.weight_axes), in_shapes, in_assigns)
        mem += cmx.memory
        gather_peak = max(gather_peak, cmx.gather_bytes)
    # stage 3: the evaluators' two-gathered-layers-in-flight charge —
    # the same rule, so the cross-check stays commensurable
    return mem + 2.0 * gather_peak


def run(graph, mesh, ctx=None) -> list[Finding]:
    opt_slots = getattr(ctx, "opt_slots", 1) if ctx is not None else 1
    update_specs = (getattr(ctx, "update_specs", None)
                    if ctx is not None else None)
    update_stage = (getattr(ctx, "update_stage", 0)
                    if ctx is not None else 0)
    training = getattr(ctx, "training", True) if ctx is not None else True
    cap = getattr(ctx, "hbm_cap_bytes", 0.0) if ctx is not None else 0.0
    cost_model = getattr(ctx, "cost_model", None) if ctx is not None \
        else None

    m = analyze(graph, mesh, opt_slots=opt_slots,
                update_specs=update_specs, training=training,
                update_stage=update_stage)
    findings: list[Finding] = []
    top = sorted(m["timeline"], key=lambda t: -t["live_bytes"])[:8]
    details = {
        "peak_bytes": m["peak_bytes"],
        "peak_at": m["peak_at"],
        "persistent_bytes": m["persistent_bytes"],
        "weight_bytes": m["weight_bytes"],
        "activation_bytes": m["activation_bytes"],
        "gather_peak_bytes": m.get("gather_peak_bytes", 0.0),
        "update_stage": update_stage,
        "hbm_cap_bytes": cap,
        "top_live": top,
    }

    cm_mem = None
    if cost_model is not None:
        try:
            cm_mem = _cost_model_memory(graph, cost_model)
            details["cost_model_bytes"] = cm_mem
        except Exception as e:
            # the cross-check degrading to unavailable must be VISIBLE:
            # it silently downgrades the OOM gate below to warning-only
            cm_mem = None
            details["cost_model_error"] = f"{type(e).__name__}: {e}"
    findings.append(Finding(
        SEV_INFO, "memory_timeline",
        f"static peak {m['peak_bytes'] / 2**20:.2f} MiB/chip at "
        f"{m['peak_at']} (persistent "
        f"{m['persistent_bytes'] / 2**20:.2f} MiB)",
        details=details))

    if cm_mem is not None and cm_mem > 0:
        ratio = m["peak_bytes"] / cm_mem
        # liveness legitimately adds transient cotangent slack above the
        # pricer's Σ and legitimately frees nothing below it at this
        # granularity; a large gap either way means the two accountings
        # drifted (a new buffer class one of them does not know about)
        if ratio > 1.5 or ratio < 0.1:
            findings.append(Finding(
                SEV_WARNING, "memory_model_divergence",
                f"liveness peak {m['peak_bytes'] / 2**20:.2f} MiB vs "
                f"cost-model estimate {cm_mem / 2**20:.2f} MiB "
                f"(ratio {ratio:.2f}) — the accountings drifted",
                details={"peak_bytes": m["peak_bytes"],
                         "cost_model_bytes": cm_mem}))

    if cap and cap > 0 and m["peak_bytes"] > cap:
        # two-keyed on purpose (module docstring): ERROR only when both
        # accountings exceed the cap; liveness alone — including when
        # the cost-model estimate is unavailable — stays a warning, so a
        # verifier-side accounting gap can never abort a plan the priced
        # search accepted as fitting
        both = cm_mem is not None and cm_mem > cap
        timeline_head = [t for t in m["timeline"]
                         if t["live_bytes"] > cap][:4]
        findings.append(Finding(
            SEV_ERROR if both else SEV_WARNING,
            "oom_predicted",
            f"predicted per-chip HBM {m['peak_bytes'] / 2**20:.2f} MiB "
            f"exceeds the {cap / 2**20:.2f} MiB cap at {m['peak_at']}"
            + ("" if both
               else " (liveness only — cost-model estimate "
                    + ("unavailable" if cm_mem is None
                       else "disagrees") + ")"),
            details={"peak_bytes": m["peak_bytes"], "cap_bytes": cap,
                     "peak_at": m["peak_at"],
                     "first_over_cap": timeline_head}))
    return findings
