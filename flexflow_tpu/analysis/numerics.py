"""Pass 5 — dtype-flow numerics verifier (the static half of ffsan).

GSPMD (Xu et al. 2021, PAPERS.md "Analysis") verifies sharding by
propagating it as a dataflow lattice; mixed-precision training practice
(Micikevicius et al., ICLR 2018, PAPERS.md "Numerics") defines the
matching *precision* invariants: large reductions accumulate in fp32,
trainable weights keep an fp32 master copy, and no tensor round-trips
through a narrower dtype than its consumers need. This pass propagates a
precision lattice through the PCG under the plan — the same
compute_dtype / matmul_dtype / fp32-master policy `executor.py` lowers —
and reports violations BEFORE the first step ever produces a NaN the
`nan_loss` health rule can only post-mortem.

Checks (finding codes are stable identifiers, findings.py):

1. `low_precision_accum`  — a reduction summing many low-precision terms
   without fp32 accumulation: Reduce ops (ops/shape_ops.py accumulates
   in the carried dtype), partial-sum `Reduction` parallel ops over many
   shards, and a grad reduce-scatter whose weight dtype is low-precision.
   Ops in `F32_INTERNAL` (softmax / layernorm / batchnorm / linear /
   batch-matmul / attention — each verified to upcast internally, see
   the registry's source anchors) are exempt.
2. `master_bypass`        — a trainable weight declared in a low-precision
   dtype under the bf16 policy: gradients would accumulate into bf16
   state, bypassing the fp32-master path `_cast_compute`'s VJP provides.
   Error: silent training-quality corruption.
3. `downcast_roundtrip`   — an explicit Cast down followed (through
   value-preserving / parallel ops) by a Cast back up: the information
   is already destroyed, the round trip just spends HBM bandwidth.
4. `parallel_dtype_mismatch` — a parallel op (Combine / Repartition /
   Replicate / Reduction / ...) whose output dtype differs from its
   input's: parallel ops re-place values, they must never transform
   them. Error: the plan materialized an impossible edge.
"""

from __future__ import annotations

from ..fftype import DataType, OperatorType as OT, PARALLEL_OP_TYPES
from .findings import Finding, SEV_ERROR, SEV_INFO, SEV_WARNING

PASS_NAME = "dtype_flow"

LOW_PRECISION = frozenset({DataType.DT_HALF, DataType.DT_BFLOAT16})
_FLOATING = frozenset({DataType.DT_HALF, DataType.DT_BFLOAT16,
                       DataType.DT_FLOAT, DataType.DT_DOUBLE})
# lattice order: wider wins at a join
_WIDTH = {DataType.DT_HALF: 16, DataType.DT_BFLOAT16: 16,
          DataType.DT_FLOAT: 32, DataType.DT_DOUBLE: 64}

# Ops whose forward accumulates in fp32 regardless of the carried
# activation dtype — each entry names the source anchor that upcasts, so
# the exemption is auditable (and removable if the kernel changes).
F32_INTERNAL = {
    OT.OP_SOFTMAX: "ops/core.py _softmax_forward astype(float32)",
    OT.OP_LAYERNORM: "ops/core.py _ln_forward fp32 statistics",
    OT.OP_BATCHNORM: "ops/core.py _bn_forward fp32 statistics",
    OT.OP_LINEAR: "ops/core.py preferred_element_type=float32",
    OT.OP_BATCHMATMUL: "ops/core.py preferred_element_type=float32",
    OT.OP_MULTIHEAD_ATTENTION:
        "ops/attention.py preferred_element_type=float32",
    OT.OP_INC_MULTIHEAD_ATTENTION:
        "ops/inc_attention.py preferred_element_type=float32",
    OT.OP_PAGED_INC_MULTIHEAD_ATTENTION:
        "ops/inc_attention.py (paged) preferred_element_type=float32",
}

# reduce ops that SUM (max/min/argmax are order statistics — no
# accumulation error to speak of; prod shares sum's compounding)
_SUMMING_REDUCES = frozenset({OT.OP_REDUCE_SUM, OT.OP_REDUCE_MEAN,
                              OT.OP_MEAN, OT.OP_REDUCE_PROD})

# the dims an accumulation must cover before low-precision summing is
# worth a warning (Micikevicius et al. §4: loss scaling exists because
# long bf16/fp16 sums drift; short ones are benign)
ACCUM_ELEMS_WARN = 1024
# partial-sum terms (Reduction degree / reduce-scatter shards) threshold
ACCUM_TERMS_WARN = 32

# ops that only re-place or re-view their input — the dtype (and any
# downcast) flows through them untouched
_VALUE_PRESERVING = PARALLEL_OP_TYPES | {
    OT.OP_NOOP, OT.OP_IDENTITY, OT.OP_RESHAPE, OT.OP_TRANSPOSE,
    OT.OP_SQUEEZE, OT.OP_UNSQUEEZE, OT.OP_DROPOUT,
}


def _is_float(dt: DataType) -> bool:
    return DataType(dt) in _FLOATING


def effective_dtypes(graph, compute_dtype):
    """{(guid, out_idx) -> DataType}: the dtype each tensor is CARRIED in
    at runtime under the mixed-precision policy — declared float dtypes
    collapse to the compute dtype (executor._cast_compute casts params
    and inputs; ops emit `astype(x.dtype)`), explicit Cast ops pin their
    target, integers pass through."""
    eff: dict[tuple[int, int], DataType] = {}
    for node in graph.topo_order():
        in_dts = []
        for e in sorted(graph.in_edges[node.guid],
                        key=lambda e: e.dst_idx):
            dt = eff.get((e.src, e.src_idx))
            if dt is not None:
                in_dts.append(dt)
        for i, pt in enumerate(node.outputs):
            dt = DataType(pt.dtype)
            if node.op_type == OT.OP_CAST:
                dt = DataType(getattr(node.params, "dtype", dt))
            elif node.op_type in _VALUE_PRESERVING and in_dts:
                dt = in_dts[0]
            elif (_is_float(dt) and compute_dtype is not None):
                dt = DataType(compute_dtype)
            eff[(node.guid, i)] = dt
    return eff


def _reduced_extent(node) -> int:
    """Number of accumulated terms of a Reduce node: product of the
    reduced dims (input elements / output elements)."""
    if not node.inputs or not node.outputs:
        return 0
    n_in = node.inputs[0].shape.num_elements()
    n_out = max(1, node.outputs[0].shape.num_elements())
    return max(1, n_in // n_out)


def _walk_value_preserving(graph, node):
    """Yield the transitive consumers of `node` reached only through
    value-preserving ops (the ops a downcast flows through unchanged)."""
    seen = set()
    frontier = [node]
    while frontier:
        cur = frontier.pop()
        for e in graph.out_edges[cur.guid]:
            nxt = graph.nodes[e.dst]
            if nxt.guid in seen:
                continue
            seen.add(nxt.guid)
            yield nxt
            if nxt.op_type in _VALUE_PRESERVING:
                frontier.append(nxt)


def run(graph, mesh, ctx=None) -> list[Finding]:
    config = getattr(ctx, "config", None) if ctx is not None else None
    training = bool(getattr(ctx, "training", True)) if ctx else True
    compute_dtype = getattr(config, "computation_dtype", None) \
        if config is not None else None
    update_specs = (getattr(ctx, "update_specs", None)
                    if ctx is not None else None) or {}
    findings: list[Finding] = []
    eff = effective_dtypes(graph, compute_dtype)
    order = graph.topo_order()
    weight_specs_by_node = {n.name: {ws.name: ws for ws in n.weight_specs}
                            for n in order}

    lp_tensors = sum(1 for dt in eff.values() if dt in LOW_PRECISION)

    for node in order:
        out_dt = eff.get((node.guid, 0))

        # 4) parallel ops must be dtype-preserving re-placements
        if node.is_parallel_op and node.inputs and node.outputs:
            in_dt = DataType(node.inputs[0].dtype)
            decl = DataType(node.outputs[0].dtype)
            if decl != in_dt:
                findings.append(Finding(
                    SEV_ERROR, "parallel_dtype_mismatch",
                    f"parallel op {node.name} ({node.op_type.name}) "
                    f"declares output {decl.name} for input {in_dt.name} "
                    f"— parallel ops re-place values, they cannot "
                    f"transform dtypes; the plan materialized an "
                    f"impossible edge",
                    where=node.name,
                    details={"input": in_dt.name, "output": decl.name}))

        # 1) low-precision accumulation
        if out_dt in LOW_PRECISION and node.op_type not in F32_INTERNAL:
            if node.op_type in _SUMMING_REDUCES:
                extent = _reduced_extent(node)
                if extent >= ACCUM_ELEMS_WARN:
                    findings.append(Finding(
                        SEV_WARNING, "low_precision_accum",
                        f"{node.name} ({node.op_type.name}) sums "
                        f"{extent} terms in {out_dt.name} (ops/"
                        f"shape_ops.py accumulates in the carried "
                        f"dtype) — route through fp32 or shrink the "
                        f"reduction (Micikevicius et al. §4)",
                        where=node.name,
                        details={"terms": extent, "dtype": out_dt.name}))
            elif node.op_type == OT.OP_REDUCTION:
                degree = int(getattr(node.params, "degree", 0) or 0)
                if degree >= ACCUM_TERMS_WARN:
                    findings.append(Finding(
                        SEV_WARNING, "low_precision_accum",
                        f"{node.name} sums {degree} partial results in "
                        f"{out_dt.name} — a wide partial-sum Reduction "
                        f"under the bf16 policy drifts; prefer an fp32 "
                        f"upcast before the combine",
                        where=node.name,
                        details={"terms": degree, "dtype": out_dt.name}))

        # 2) fp32-master bypass
        if (training and compute_dtype is not None
                and not getattr(node, "weight_source", None)):
            for ws in node.weight_specs:
                if ws.trainable and DataType(ws.dtype) in LOW_PRECISION:
                    findings.append(Finding(
                        SEV_ERROR, "master_bypass",
                        f"{node.name}.{ws.name} is a trainable "
                        f"{DataType(ws.dtype).name} weight under the "
                        f"{DataType(compute_dtype).name} policy — "
                        f"gradients would accumulate into low-precision "
                        f"state instead of the fp32 master "
                        f"(_cast_compute's VJP), silently corrupting "
                        f"training (Micikevicius et al. §3.1)",
                        where=f"{node.name}.{ws.name}",
                        details={"dtype": DataType(ws.dtype).name}))

        # 3) downcast → upcast round trip through value-preserving ops
        if node.op_type == OT.OP_CAST and node.inputs:
            src_dt = eff.get((graph.in_edges[node.guid][0].src,
                              graph.in_edges[node.guid][0].src_idx))
            dst_dt = eff.get((node.guid, 0))
            if (src_dt is not None and dst_dt is not None
                    and _is_float(src_dt) and _is_float(dst_dt)
                    and _WIDTH[dst_dt] < _WIDTH[src_dt]):
                for consumer in _walk_value_preserving(graph, node):
                    if consumer.op_type != OT.OP_CAST:
                        continue
                    up_dt = eff.get((consumer.guid, 0))
                    if (up_dt is not None and _is_float(up_dt)
                            and _WIDTH[up_dt] > _WIDTH[dst_dt]):
                        findings.append(Finding(
                            SEV_WARNING, "downcast_roundtrip",
                            f"{node.name} casts {src_dt.name} down to "
                            f"{dst_dt.name} and {consumer.name} casts "
                            f"back up to {up_dt.name} with only "
                            f"value-preserving ops between — the "
                            f"precision is already lost; the round trip "
                            f"spends HBM bandwidth for nothing",
                            where=node.name,
                            details={"down": dst_dt.name,
                                     "up": up_dt.name,
                                     "upcast_at": consumer.name}))
                        break

    # 1b) grad reduce-scatter buckets summing in a low-precision dtype
    # (with fp32 masters the grads are fp32 by construction — this fires
    # exactly when master_bypass broke that invariant for a sharded
    # weight, naming the collective that multiplies the damage)
    for (node_name, w_name), (spec, _shape) in update_specs.items():
        ws = weight_specs_by_node.get(node_name, {}).get(w_name)
        if ws is not None and DataType(ws.dtype) in LOW_PRECISION:
            findings.append(Finding(
                SEV_WARNING, "low_precision_accum",
                f"grad reduce-scatter for {node_name}.{w_name} sums "
                f"shards in {DataType(ws.dtype).name} — the sharded "
                f"update accumulates cross-replica gradients in the "
                f"weight dtype",
                where=f"{node_name}.{w_name}",
                details={"dtype": DataType(ws.dtype).name,
                         "spec": str(spec)}))

    if not findings:
        cd = (DataType(compute_dtype).name
              if compute_dtype is not None else "fp32")
        findings.append(Finding(
            SEV_INFO, "numerics_clean",
            f"{len(eff)} tensors through the precision lattice "
            f"(compute dtype {cd}, {lp_tensors} low-precision): "
            f"accumulations fp32-safe, masters fp32, no downcast "
            f"round trips, parallel edges dtype-uniform"))
    return findings
