"""ffrules: substitution-rule verifier — the fourth static-analysis layer.

TASO (Jia et al., SOSP '19 — PAPERS.md "Substitution verification") showed
that rewrite-based graph optimizers must formally verify every substitution
against operator semantics rather than trust the rule author; PET (Wang et
al., OSDI '21) extended the discipline to partially-equivalent transforms
with automated correction. Our Unity-style candidate generator
(search/substitution.py) ships ~30 hand-coded `GraphXfer` generators plus a
JSON loader that injects *external* rules straight into the search — this
module is the trust boundary that proves a rule is safe to hand to the
search before any candidate it produces can win a plan.

Five passes, reported through the ffcheck findings machinery
(docs/analysis.md "ffrules" has the catalog):

1. **symbolic shape/dtype transfer** — instantiate the rule's src pattern
   with dimension variables valued at distinct primes × the LCM of the
   rule's harvested divisibility constraints (Schwartz–Zippel style: two
   disagreeing shape polynomials cannot coincide on two independent prime
   assignments), apply the rewrite, and require identical global
   shape/dtype on every `mapped_output` — for *all* legal inputs, not the
   one a concrete test happened to use.
2. **parallel-state soundness** — `propagate_parallel_state` on the
   instantiated dst must yield a valid degree configuration: degree
   products conserved per dim at the rewrite boundary, replica-dim
   bookkeeping consistent, and no partial-sum state escaping into a
   nonlinear consumer (each mapped output is probed with a downstream
   nonlinear op — the generalization of
   `test_partial_sum_through_nonlinear_rejected` to the whole registry).
3. **semantic equivalence oracle** — auto-build a minimal concrete graph
   instantiating the src pattern, apply the rewrite, execute BOTH graphs
   through the executor on a 1-device CPU mesh (weights equal by
   name-seeded init; parallel ops are runtime identities at global-array
   level), and assert dtype-ULP-bounded numerical equality forward and
   backward (parameter cotangents).
4. **precondition completeness** — fuzz near-boundary shapes (indivisible
   dims, degree == dim, rank-1 tiny extents) and require that the matcher
   refuses, the rewrite raises (candidate discarded — fail-safe), or the
   result stays sound; a rule that can match-and-corrupt is reported as
   `rule_matcher_unsound`.
5. **registry determinism** — `generate_all_pcg_xfers` must emit a
   stable, content-hashable rule set (sorted by name, deduped); the
   resulting `rules_fingerprint` joins the warm-start plan fingerprint
   (warmstart/fingerprint.py) so a changed rule set can never replay a
   stale cached plan.

Gate: `load_rule_collection` (search/substitution.py) verifies every JSON
rule at load through `gate_loaded_rules` — an unsound external rule raises
a structured `RuleVerificationError` naming the rule and finding class;
`--no-verify-rules` downgrades to a logged warning, and the verdict is
recorded in strategy_report.json's `analysis` section via the `rule_verify`
compile pass (`run`). `scripts/ffrules.py` sweeps the full generated
registry in CI with a corruption self-test.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
from types import SimpleNamespace
from typing import Optional

from ..fftype import ActiMode, DataType, OperatorType as OT
from .findings import (
    AnalysisResult,
    Finding,
    SEV_ERROR,
    SEV_INFO,
    SEV_WARNING,
)

PASS_NAME = "rule_verify"

# Stable finding codes (the ffrules corruption self-test keys on them):
#   rule_shape_mismatch        mapped output's global shape changes
#   rule_dtype_mismatch        mapped output's dtype drifts
#   rule_replica_dim_leak      replica dim dropped/leaked at the boundary
#   rule_degree_violation      degree products not conserved per dim
#   rule_partial_sum_nonlinear partial sums escape into a nonlinear op
#   rule_numeric_divergence    oracle fwd/bwd mismatch beyond ULP bound
#   rule_matcher_unsound       matcher accepts a boundary shape the
#                              rewrite then corrupts (match-and-corrupt)
#   rule_verification_crash    verification itself crashed on the rule
#                              (malformed params/constraints) — refused
#   rule_registry_nondeterministic  generator emits an unstable rule set
#   rule_uninstantiable        verifier could not synthesize a legal
#                              instance (warning — rule unverified)
#   rule_unassignable          degrees carry no legal mesh-axis
#                              assignment on this mesh (warning)
#   rule_oracle_skipped        oracle skipped (fresh dst weights /
#                              non-float output) — info
#   rules_clean / rules_fingerprint   markers (info)

_ERROR_CODES = (
    "rule_shape_mismatch", "rule_dtype_mismatch", "rule_replica_dim_leak",
    "rule_degree_violation", "rule_partial_sum_nonlinear",
    "rule_numeric_divergence", "rule_matcher_unsound",
    "rule_verification_crash", "rule_registry_nondeterministic",
)


class RuleVerificationError(ValueError):
    """Raised by the load gate when a substitution rule fails
    verification and --no-verify-rules was not passed. Carries the full
    AnalysisResult; the message names the offending rule(s) and finding
    class(es) so a refused external rule file is actionable."""

    def __init__(self, result: AnalysisResult):
        self.result = result
        errs = result.errors()
        by_rule: dict[str, list[str]] = {}
        for f in errs:
            by_rule.setdefault(f.where or "<registry>", []).append(f.code)
        head = "; ".join(
            f"{rule}: {sorted(set(codes))}"
            for rule, codes in list(by_rule.items())[:4])
        more = f" (+{len(by_rule) - 4} more)" if len(by_rule) > 4 else ""
        super().__init__(
            f"substitution-rule verification failed for "
            f"{len(by_rule)} rule(s): {head}{more} — fix the rule or pass "
            f"--no-verify-rules to load anyway (findings downgrade to "
            f"warnings)")


class InstantiationError(ValueError):
    """The verifier could not build a legal concrete instance of a rule's
    src pattern (constraints unsatisfiable by the param synthesizer)."""


# ------------------------------------------------------------- dim contexts

def _lcm(values) -> int:
    out = 1
    for v in values:
        v = int(v)
        if v > 1:
            out = out * v // math.gcd(out, v)
    return out


def harvest_degrees(xfer, mesh_sizes: dict) -> list[int]:
    """Divisibility constraints a rule imposes: the degrees of every
    statically-evaluable dst parallel-op param, `mod` constraint divisors
    recorded by the JSON compiler, and the mesh axis sizes the rule's
    declared axes ride (so instance dims divide cleanly everywhere)."""
    degs = set()
    for dx in getattr(xfer, "dst_ops", ()):
        mk = getattr(dx, "make_params", None)
        if mk is None:
            continue
        try:
            p = mk({})
        except Exception:
            continue  # match-dependent params — degrees found elsewhere
        d = getattr(p, "degree", None)
        if isinstance(d, int):
            degs.add(d)
        for ax in getattr(p, "axes", ()) or ():
            s = mesh_sizes.get(ax)
            if isinstance(s, int):
                degs.add(s)
    for ops in (getattr(xfer, "src_ops", ()), getattr(xfer, "dst_ops", ())):
        for op in ops:
            for spec in getattr(op, "_constraint_specs", ()) or ():
                if "mod" in spec:
                    try:
                        degs.add(int(spec["mod"]))
                    except (TypeError, ValueError):
                        pass
    return sorted(d for d in degs if d > 1)


def _dim_env(L: int, scheme: str) -> dict:
    """One dimension-variable assignment. `sym1`/`sym2` value each dim
    role at a distinct prime × L (L = lcm of the rule's divisibility
    constraints) — the polynomial-identity-testing trick: a shape
    function the rewrite changes cannot agree on two independent prime
    assignments. `oracle` keeps extents small enough to execute;
    `indivisible`/`degree_eq`/`tiny` are the pass-4 boundary probes."""
    Lh = max(1, L)
    if scheme == "sym1":
        e = dict(B=5, F=7, O=11, S=3, C=2, HW=6, V=13, EH=17)
    elif scheme == "sym2":
        e = dict(B=13, F=5, O=7, S=11, C=3, HW=10, V=19, EH=23)
    elif scheme == "oracle":
        e = dict(B=2, F=2, O=3, S=2, C=1, HW=2, V=5, EH=2)
    elif scheme == "degree_eq":
        # every dim exactly at the largest divisibility boundary
        return dict(B=Lh, F=Lh, O=Lh, S=Lh, C=Lh, HW=2 * Lh, V=Lh + 5,
                    heads=Lh, E=2 * Lh, K=2, scheme=scheme)
    elif scheme == "indivisible":
        # L+1 is coprime to every divisor of L — no rule degree divides it
        n = Lh + 1
        return dict(B=n, F=n, O=n, S=n, C=n, HW=2 * n, V=n + 6,
                    heads=Lh, E=3 * Lh, K=2, scheme=scheme)
    elif scheme == "tiny":
        return dict(B=1, F=1, O=1, S=1, C=1, HW=2, V=3, heads=1, E=1,
                    K=1, scheme=scheme)
    else:
        raise ValueError(f"unknown dim scheme {scheme!r}")
    env = {k: v * Lh for k, v in e.items() if k != "EH"}
    env["heads"] = Lh
    env["E"] = Lh * e["EH"]
    env["K"] = 2
    env["scheme"] = scheme
    return env


# --------------------------------------------------------- param synthesis

def _unary_types():
    return (OT.OP_RELU, OT.OP_GELU, OT.OP_SIGMOID, OT.OP_TANH, OT.OP_ELU,
            OT.OP_IDENTITY, OT.OP_EXP, OT.OP_SIN, OT.OP_COS, OT.OP_RSQRT)


def _param_candidates(op_type: OT, env: dict, n_inputs: int,
                      prior_params: list):
    """Candidate param structs for one pattern op, most-common first; the
    synthesizer picks the first satisfying every opaque constraint."""
    from ..ops.attention import MultiHeadAttentionParams
    from ..ops.core import (
        Conv2DParams,
        EmbeddingParams,
        LinearParams,
        Pool2DParams,
        SoftmaxParams,
    )
    from ..ops.elementwise import ElementBinaryParams, ElementUnaryParams
    from ..ops.shape_ops import CastParams, ConcatParams

    acts = (ActiMode.AC_MODE_NONE, ActiMode.AC_MODE_RELU,
            ActiMode.AC_MODE_SIGMOID, ActiMode.AC_MODE_GELU,
            ActiMode.AC_MODE_TANH)
    if op_type == OT.OP_LINEAR:
        for act in acts:
            for ub in (True, False):
                yield LinearParams(env["O"], use_bias=ub, activation=act)
    elif op_type == OT.OP_MULTIHEAD_ATTENTION:
        yield MultiHeadAttentionParams(embed_dim=env["E"],
                                       num_heads=env["heads"])
    elif op_type == OT.OP_CONV2D:
        for act in (ActiMode.AC_MODE_NONE, ActiMode.AC_MODE_RELU):
            for ub in (True, False):
                yield Conv2DParams(env["O"], 3, 3, 1, 1, 1, 1, groups=1,
                                   use_bias=ub, activation=act)
    elif op_type == OT.OP_POOL2D:
        yield Pool2DParams(2, 2, 2, 2, 0, 0)
    elif op_type == OT.OP_SOFTMAX:
        yield SoftmaxParams(-1)
    elif op_type in _unary_types():
        yield ElementUnaryParams(op_type)
    elif op_type in (OT.OP_EW_ADD, OT.OP_EW_SUB, OT.OP_EW_MUL,
                     OT.OP_EW_DIV, OT.OP_EW_MAX, OT.OP_EW_MIN):
        yield ElementBinaryParams(op_type)
    elif op_type == OT.OP_CONCAT:
        yield ConcatParams(axis=1, n=max(2, n_inputs))
    elif op_type == OT.OP_EMBEDDING:
        yield EmbeddingParams(env["V"], env["O"])
    elif op_type == OT.OP_CAST:
        yield CastParams(DataType.DT_FLOAT)
    elif op_type == OT.OP_GROUP_BY:
        from ..ops.moe import GroupByParams

        for n in (2, 4, 3, 1, 5, 6, 7, 8):
            yield GroupByParams(n, 1.0)
    elif op_type == OT.OP_AGGREGATE:
        from ..ops.moe import AggregateParams

        gb_n = next((p.n for p in prior_params
                     if hasattr(p, "n") and hasattr(p, "alpha")), 2)
        yield AggregateParams(gb_n)
    else:
        yield None


def _apply_spec_hints(params, specs, env):
    """Honor the JSON compiler's recorded eq/mod constraint specs on a
    candidate (opaque closures are probed instead)."""
    if params is None or not specs:
        return params
    for spec in specs:
        attr = spec.get("attr")
        if not attr or not hasattr(params, attr):
            return None
        try:
            if "eq" in spec:
                from ..search.substitution import _resolve_attr_value

                params = dataclasses.replace(
                    params, **{attr: _resolve_attr_value(spec["eq"])})
            elif "mod" in spec:
                d = int(spec["mod"])
                v = int(getattr(params, attr))
                if d > 0 and v % d:
                    params = dataclasses.replace(
                        params, **{attr: v + (-v % d)})
        except (TypeError, ValueError):
            return None
    return params


def _synthesize_params(px, env: dict, prior_params: list):
    specs = getattr(px, "_constraint_specs", ()) or ()
    for cand in _param_candidates(px.op_type, env, len(px.inputs),
                                  prior_params):
        cand = _apply_spec_hints(cand, specs, env)
        if cand is None and specs:
            continue
        probe = SimpleNamespace(params=cand)
        try:
            if all(c(probe) for c in px.constraints):
                return cand
        except Exception:
            continue
    raise InstantiationError(
        f"no synthesizable params satisfy the constraints of pattern op "
        f"{px.op_type.name}")


def _slot_template(op_type: OT, pos: int, env: dict, params):
    """(logical shape, dtype) of a free input slot, keyed by its first
    consumer's op type and argument position."""
    f32, i32 = DataType.DT_FLOAT, DataType.DT_INT32
    if op_type == OT.OP_MULTIHEAD_ATTENTION:
        return (env["B"], env["S"], env["E"]), f32
    if op_type in (OT.OP_CONV2D, OT.OP_POOL2D):
        return (env["B"], env["C"], env["HW"], env["HW"]), f32
    if op_type == OT.OP_EMBEDDING:
        return (env["B"], env["S"]), i32
    if op_type == OT.OP_GROUP_BY:
        if pos == 1:
            return (env["B"], env["K"]), i32
        return (env["B"], env["F"]), f32
    if op_type == OT.OP_AGGREGATE:
        if pos in (1, 2):
            return (env["B"], env["K"]), i32
        if pos == 3:
            return (env["B"], getattr(params, "n", 2)), f32
        return (env["B"], env["K"]), f32
    return (env["B"], env["F"]), f32


# ------------------------------------------------------------ instantiation

def instantiate_rule(xfer, env: dict):
    """Build a minimal concrete PCG instantiating `xfer`'s src pattern,
    with one nonlinear probe consumer per mapped output (the probe is how
    a partial-sum replica dim escaping the rewrite is detected, and how
    the mapped dst tensor is recovered after `apply` by name).

    Returns (graph, node_by_opx, probe_names). Raises InstantiationError
    when the pattern cannot be legally instantiated under `env`."""
    from ..pcg.graph import Graph, OpNode
    from ..search.substitution import propagate_parallel_state
    from ..tensor import ParallelTensor, ParallelTensorShape

    g = Graph()
    node_by_opx: dict = {}
    input_nodes: dict[int, OpNode] = {}
    prior_params: list = []

    def _out_dtype(op_type, params, in_dtypes):
        if op_type == OT.OP_EMBEDDING:
            return params.data_type
        if op_type == OT.OP_CAST:
            return params.dtype
        return in_dtypes[0] if in_dtypes else DataType.DT_FLOAT

    for i, px in enumerate(xfer.src_ops):
        params = _synthesize_params(px, env, prior_params)
        prior_params.append(params)
        wired = []
        for pos, tx in enumerate(px.inputs):
            if tx.op is None:
                node = input_nodes.get(tx.idx)
                if node is None:
                    shape, dt = _slot_template(px.op_type, pos, env, params)
                    node = OpNode(OT.OP_INPUT, None,
                                  name=f"__ffrules_in_{tx.idx}")
                    node.outputs = [ParallelTensor(
                        ParallelTensorShape.from_shape(shape, dt),
                        name=node.name)]
                    g.add_node(node)
                    input_nodes[tx.idx] = node
                wired.append((node, 0))
            else:
                src = node_by_opx.get(tx.op)
                if src is None:
                    raise InstantiationError(
                        f"pattern op input references an op declared "
                        f"later ({px.op_type.name} slot {pos})")
                wired.append((src, tx.idx))
        node = OpNode(px.op_type, params,
                      name=f"__ffrules_{px.op_type.name.lower()}_{i}")
        g.add_node(node)
        for pos, (src, sidx) in enumerate(wired):
            if sidx >= len(src.outputs):
                raise InstantiationError(
                    f"{src.name} has no output {sidx}")
            g.add_edge(src, node, sidx, pos)
        in_shapes = [src.outputs[sidx].shape.logical_shape
                     for src, sidx in wired]
        in_dtypes = [src.outputs[sidx].dtype for src, sidx in wired]
        try:
            node.weight_specs = node.op_def.weights(params, in_shapes)
        except NotImplementedError:
            node.weight_specs = []
        except Exception as e:
            raise InstantiationError(
                f"{px.op_type.name}.weights() rejected the instance: {e}")
        try:
            outs = node.op_def.infer_shapes(params, in_shapes)
        except Exception as e:
            raise InstantiationError(
                f"{px.op_type.name}.infer_shapes() rejected the "
                f"instance: {e}")
        dt = _out_dtype(px.op_type, params, in_dtypes)
        node.outputs = [ParallelTensor(
            ParallelTensorShape.from_shape(s, dt),
            name=f"{node.name}_out{j}") for j, s in enumerate(outs)]
        node_by_opx[px] = node

    from ..ops.elementwise import ElementUnaryParams

    probe_names = []
    for j, (src_tx, _) in enumerate(xfer.mapped_outputs):
        owner = node_by_opx.get(src_tx.op)
        if owner is None:
            raise InstantiationError("mapped output names no source op")
        probe = OpNode(OT.OP_RELU, ElementUnaryParams(OT.OP_RELU),
                       name=f"__ffrules_probe_{j}")
        g.add_node(probe)
        g.add_edge(owner, probe, src_tx.idx, 0)
        probe_names.append(probe.name)

    try:
        propagate_parallel_state(g)
    except ValueError as e:
        raise InstantiationError(f"src instance has invalid state: {e}")
    return g, node_by_opx, probe_names


def _intended_match(xfer, graph, node_by_opx):
    """The match binding each pattern op to the node we instantiated for
    it (the matcher may also bind probes; those are instrumentation
    artifacts, not the rule's own match)."""
    for m in xfer.find_matches(graph):
        if all(m.ops.get(px) is node for px, node in node_by_opx.items()):
            return m
    return None


def _mapped_pairs(src_graph, dst_graph, probe_names):
    """[(src tensor, dst tensor)] per mapped output, recovered through the
    probe consumers (clones keep names across `apply`)."""
    def probe_input(g, name):
        node = next(n for n in g.topo_order() if n.name == name)
        e = sorted(g.in_edges[node.guid], key=lambda e: e.dst_idx)[0]
        return g.nodes[e.src].outputs[e.src_idx]

    return [(probe_input(src_graph, nm), probe_input(dst_graph, nm))
            for nm in probe_names]


def _classify_apply_error(e: Exception) -> str:
    s = str(e).lower()
    if "nonlinear" in s or "partial" in s or "identical replicas" in s:
        return "rule_partial_sum_nonlinear"
    if "replica" in s:
        return "rule_replica_dim_leak"
    return "rule_degree_violation"


# ------------------------------------------------------------------ passes

def _check_transfer(xfer, env: dict, where: str,
                    fuzz: bool = False) -> list[Finding]:
    """Passes 1+2 (and, with fuzz=True, pass 4) on one dim assignment:
    instantiate, match, apply, compare the mapped boundary tensors."""
    sev = SEV_ERROR
    unsound = "rule_matcher_unsound" if fuzz else None

    def finding(code, msg, **details):
        return Finding(sev, unsound or code, msg, pass_name=PASS_NAME,
                       where=where,
                       details={"scheme": env.get("scheme"),
                                "underlying": code, **details})

    try:
        g, node_by_opx, probes = instantiate_rule(xfer, env)
    except InstantiationError as e:
        if fuzz:
            return []  # boundary instance illegal — nothing to match
        return [Finding(SEV_WARNING, "rule_uninstantiable",
                        f"could not instantiate src pattern: {e}",
                        pass_name=PASS_NAME, where=where,
                        details={"scheme": env.get("scheme")})]
    m = _intended_match(xfer, g, node_by_opx)
    if m is None:
        if fuzz:
            return []  # matcher refused the boundary shape — sound
        return [Finding(SEV_WARNING, "rule_uninstantiable",
                        "matcher does not match its own src pattern on a "
                        "legal instance", pass_name=PASS_NAME, where=where,
                        details={"scheme": env.get("scheme")})]
    try:
        ng = xfer.apply(g, m)
    except (ValueError, TypeError) as e:
        # TypeError covers malformed external rules whose params crash
        # the shape transforms — same refusal path, attributed
        if fuzz:
            return []  # rewrite refused the candidate — fail-safe
        code = _classify_apply_error(e)
        return [finding(code,
                        f"rewrite raises on every legal instance "
                        f"({type(e).__name__}: {e})")]

    out = []
    for j, (src_pt, dst_pt) in enumerate(_mapped_pairs(g, ng, probes)):
        tag = f"mapped_output {j}"
        if src_pt.shape.logical_shape != dst_pt.shape.logical_shape:
            out.append(finding(
                "rule_shape_mismatch",
                f"{tag}: global shape {src_pt.shape.logical_shape} -> "
                f"{dst_pt.shape.logical_shape}",
                src=repr(src_pt.shape), dst=repr(dst_pt.shape)))
            continue
        if src_pt.dtype != dst_pt.dtype:
            out.append(finding(
                "rule_dtype_mismatch",
                f"{tag}: dtype {src_pt.dtype.name} -> "
                f"{dst_pt.dtype.name}"))
        if (src_pt.shape.num_replica_dims
                != dst_pt.shape.num_replica_dims):
            out.append(finding(
                "rule_replica_dim_leak",
                f"{tag}: replica dims {src_pt.shape.num_replica_dims} -> "
                f"{dst_pt.shape.num_replica_dims} (a consumer outside the "
                f"rewrite would silently see replicated state)",
                src=repr(src_pt.shape), dst=repr(dst_pt.shape)))
            continue
        src_deg = [d.degree for d in src_pt.shape.dims
                   if not d.is_replica_dim]
        dst_deg = [d.degree for d in dst_pt.shape.dims
                   if not d.is_replica_dim]
        if src_deg != dst_deg:
            out.append(finding(
                "rule_degree_violation",
                f"{tag}: per-dim degrees {src_deg} -> {dst_deg} — the "
                f"rewrite changes the boundary tensor's parallel state "
                f"without combining back",
                src=repr(src_pt.shape), dst=repr(dst_pt.shape)))
    return out


def _check_assignable(xfer, env: dict, mesh_sizes: dict,
                      where: str) -> list[Finding]:
    """Pass-2 tail: the rewritten graph's degrees must admit a mesh-axis
    assignment on this mesh (axis products carry the degrees, no axis
    reused within one tensor)."""
    from ..search.substitution import assign_axes_from_degrees

    try:
        g, node_by_opx, _ = instantiate_rule(xfer, env)
        m = _intended_match(xfer, g, node_by_opx)
        if m is None:
            return []
        ng = xfer.apply(g, m)
    except (InstantiationError, ValueError):
        return []  # already reported by _check_transfer
    shim = SimpleNamespace(shape=dict(mesh_sizes))
    try:
        assign_axes_from_degrees(ng, shim)
    except ValueError as e:
        return [Finding(
            SEV_WARNING, "rule_unassignable",
            f"rewritten degrees admit no mesh-axis assignment on "
            f"{dict(mesh_sizes)}: {e}", pass_name=PASS_NAME, where=where)]
    return []


def _oracle_config():
    import sys

    from ..config import FFConfig

    saved = sys.argv
    sys.argv = saved[:1] or ["ffrules"]
    try:
        cfg = FFConfig()
    finally:
        sys.argv = saved
    cfg.mesh_axis_sizes = tuple(
        1 for _ in cfg.mesh_shape().axis_names)
    cfg.batch_size = 1
    return cfg


def _ulp_close(a, b, ulps: int = 128) -> bool:
    import numpy as np

    a = np.asarray(a)
    b = np.asarray(b)
    if a.shape != b.shape:
        return False
    if not np.issubdtype(a.dtype, np.floating):
        return bool(np.array_equal(a, b))
    eps = float(np.finfo(a.dtype).eps)
    scale = max(1.0, float(np.max(np.abs(a))) if a.size else 1.0)
    return bool(np.allclose(np.asarray(a, np.float64),
                            np.asarray(b, np.float64),
                            rtol=ulps * eps, atol=ulps * eps * scale))


def _check_oracle(xfer, env: dict, where: str) -> list[Finding]:
    """Pass 3: execute src and rewritten graphs through the executor on a
    1-device CPU mesh and require ULP-bounded equality fwd + bwd. Weight
    equality across the two graphs is by construction: `init_variables`
    seeds every weight by (node name, weight name), and `apply` carries
    names through the rewrite."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..executor import Executor
    from ..fftype import LossType
    from ..machine import build_mesh
    from ..metrics import Metrics
    from ..optimizer import SGDOptimizer

    def finding(code, msg, **details):
        return Finding(SEV_ERROR, code, msg, pass_name=PASS_NAME,
                       where=where, details={"scheme": "oracle", **details})

    try:
        g, node_by_opx, probes = instantiate_rule(xfer, env)
        m = _intended_match(xfer, g, node_by_opx)
        if m is None:
            raise InstantiationError("matcher found no match")
        ng = xfer.apply(g, m)
    except (InstantiationError, ValueError):
        return []  # pass 1/2 report instantiation/apply problems
    # fresh dst compute ops declare NEW weights the rewrite re-initializes
    # (e.g. the fused Experts kernel) — numerics are not name-comparable
    matched_names = {n.name for n in node_by_opx.values()}
    for node in ng.topo_order():
        if (node.weight_specs and node.name not in matched_names
                and not node.name.startswith("__ffrules_")):
            return [Finding(
                SEV_INFO, "rule_oracle_skipped",
                f"dst op {node.name} declares fresh weights — oracle "
                f"compares name-seeded weights only", pass_name=PASS_NAME,
                where=where)]

    cfg = _oracle_config()
    mesh = build_mesh(cfg.mesh_shape())
    loss = LossType.LOSS_IDENTITY
    metrics = Metrics.from_list(loss, [])
    opt = SGDOptimizer(lr=0.01)
    rng = jax.random.key(0)
    rs = np.random.RandomState(0)

    # one shared input dict (both graphs name inputs identically); int
    # inputs stay in the consumer's legal index range
    def _int_hi(graph, node):
        for e in graph.out_edges[node.guid]:
            dst = graph.nodes[e.dst]
            if dst.op_type == OT.OP_EMBEDDING:
                return dst.params.num_entries
            if dst.op_type == OT.OP_GROUP_BY and e.dst_idx == 1:
                return dst.params.n
            if dst.op_type == OT.OP_AGGREGATE and e.dst_idx in (1, 2):
                return dst.params.n
        return env["V"]

    inputs = {}
    for node in g.topo_order():
        if node.op_type != OT.OP_INPUT:
            continue
        shape = node.outputs[0].shape.logical_shape
        if node.outputs[0].dtype == DataType.DT_INT32:
            inputs[node.name] = rs.randint(
                0, max(2, _int_hi(g, node)), shape).astype(np.int32)
        else:
            inputs[node.name] = rs.randn(*shape).astype(np.float32)

    def run(graph):
        probe = next(n for n in graph.topo_order()
                     if n.name == probes[0])
        e = sorted(graph.in_edges[probe.guid], key=lambda e: e.dst_idx)[0]
        mapped = graph.nodes[e.src]
        ex = Executor(graph, mesh, cfg, loss, metrics, opt, mapped,
                      jax.sharding.PartitionSpec())
        params, state = ex.init_variables(rng)
        out, _, aux = ex._apply(params, state, inputs, training=False,
                                rng=rng)
        grads = None
        if jnp.issubdtype(jnp.asarray(out).dtype, jnp.floating):
            def scalar(p):
                o, _, a = ex._apply(p, state, inputs, training=False,
                                    rng=rng)
                return jnp.sum(jnp.asarray(o, jnp.float32)) + (
                    jnp.asarray(a, jnp.float32) if a is not None else 0.0)

            grads = jax.grad(scalar)(params)
        return out, grads, params

    try:
        out_a, grads_a, params_a = run(g)
    except Exception as e:
        return [Finding(
            SEV_WARNING, "rule_oracle_skipped",
            f"oracle could not execute the SRC instance "
            f"({type(e).__name__}: {e}) — numerics unverified",
            pass_name=PASS_NAME, where=where)]
    try:
        out_b, grads_b, params_b = run(ng)
    except Exception as e:
        # the source instance executed fine and the REWRITTEN graph did
        # not: the rule emits graphs that crash at runtime
        return [finding(
            "rule_numeric_divergence",
            f"rewritten graph fails to execute "
            f"({type(e).__name__}: {e})")]

    out = []
    a, b = np.asarray(out_a), np.asarray(out_b)
    if a.dtype != b.dtype:
        out.append(finding(
            "rule_dtype_mismatch",
            f"executed mapped output dtype {a.dtype} -> {b.dtype}"))
    elif a.shape != b.shape:
        out.append(finding(
            "rule_shape_mismatch",
            f"executed mapped output shape {a.shape} -> {b.shape}"))
    elif not _ulp_close(a, b):
        diff = float(np.max(np.abs(a.astype(np.float64)
                                   - b.astype(np.float64))))
        out.append(finding(
            "rule_numeric_divergence",
            f"forward mapped output diverges (max |delta| = {diff:.3e} "
            f"beyond the {a.dtype} ULP bound)", max_abs_delta=diff))
    if grads_a is not None and grads_b is not None and not out:
        for name in sorted(set(params_a) & set(params_b)):
            for w in sorted(set(params_a[name]) & set(params_b[name])):
                ga = np.asarray(grads_a[name][w])
                gb = np.asarray(grads_b[name][w])
                if ga.shape != gb.shape or not _ulp_close(ga, gb,
                                                          ulps=256):
                    out.append(finding(
                        "rule_numeric_divergence",
                        f"backward diverges on d/d({name}.{w})"))
                    return out
    return out


# --------------------------------------------------------------- serialize

def serialize_rule(xfer) -> dict:
    """Canonical JSON-able description of a GraphXfer: structure, static
    params, constraint specs where the JSON compiler recorded them, and
    opaque-constraint counts. This is what the registry fingerprint and
    the determinism check hash."""
    src_ix = {op: i for i, op in enumerate(xfer.src_ops)}
    dst_ix = {op: i for i, op in enumerate(xfer.dst_ops)}

    def ref(tx):
        if tx.op is None:
            return ["$", tx.idx]
        if tx.op in src_ix:
            return ["src", src_ix[tx.op], tx.idx]
        if tx.op in dst_ix:
            return ["dst", dst_ix[tx.op], tx.idx]
        return ["?", -1, tx.idx]

    def static_params(op):
        mk = getattr(op, "make_params", None)
        if mk is None:
            return ""
        try:
            return repr(mk({}))
        except Exception:
            return "<match-dependent>"

    return {
        "name": xfer.name,
        "src": [{
            "op": op.op_type.name,
            "in": [ref(t) for t in op.inputs],
            "outs": len(op.outputs),
            "constraints": (list(getattr(op, "_constraint_specs", ()))
                            or len(op.constraints)),
        } for op in xfer.src_ops],
        "dst": [{
            "op": op.op_type.name,
            "in": [ref(t) for t in op.inputs],
            "match": src_ix.get(op.match_src, -1),
            "params": static_params(op),
        } for op in xfer.dst_ops],
        "map": [[ref(s), ref(d)] for s, d in xfer.mapped_outputs],
    }


def rules_fingerprint(xfers) -> str:
    """Content hash of a rule set — order-free (entries sorted), so it
    joins the warm-start plan fingerprint as a stable component: a
    changed/added/removed rule changes the plan address and a stale
    cached plan can never replay against a different rule set."""
    entries = sorted(
        json.dumps(serialize_rule(x), sort_keys=True) for x in xfers)
    return hashlib.sha256(
        json.dumps({"v": 1, "rules": entries}).encode()).hexdigest()


# ------------------------------------------------------------- entry points

def verify_rule(xfer, mesh, *, oracle: bool = True,
                fuzz: bool = True) -> list[Finding]:
    """All per-rule passes (1-4) on one GraphXfer. `mesh` is anything
    with a `.shape` mapping (a jax Mesh or a {axis: size} shim)."""
    sizes = dict(getattr(mesh, "shape", mesh))
    where = f"rule:{xfer.name}"
    key = (json.dumps(serialize_rule(xfer), sort_keys=True),
           tuple(sorted(sizes.items())), bool(oracle), bool(fuzz))
    cached = _VERIFY_CACHE.get(key)
    if cached is not None:
        return list(cached)
    L = _lcm(harvest_degrees(xfer, sizes) + [s for s in sizes.values()])
    findings: list[Finding] = []
    # pass 1+2: symbolic transfer on two independent prime assignments
    for scheme in ("sym1", "sym2"):
        findings.extend(_check_transfer(xfer, _dim_env(L, scheme), where))
        if findings:
            break  # one assignment suffices to refuse; skip the second
    if not any(f.severity == SEV_ERROR for f in findings):
        findings.extend(
            _check_assignable(xfer, _dim_env(L, "sym1"), sizes, where))
        # pass 3: semantic equivalence oracle
        if oracle:
            findings.extend(
                _check_oracle(xfer, _dim_env(L, "oracle"), where))
        # pass 4: precondition completeness (boundary fuzz)
        if fuzz:
            for scheme in ("indivisible", "degree_eq", "tiny"):
                findings.extend(_check_transfer(
                    xfer, _dim_env(L, scheme), where, fuzz=True))
    _VERIFY_CACHE[key] = list(findings)
    return findings


_VERIFY_CACHE: dict = {}


def verify_rules(xfers, mesh, *, oracle: bool = True,
                 fuzz: bool = True) -> AnalysisResult:
    """Verify a rule list (passes 1-4 per rule)."""
    import time

    xfers = list(xfers)
    result = AnalysisResult(passes_run=[PASS_NAME])
    t0 = time.perf_counter()
    for x in xfers:
        try:
            fs = verify_rule(x, mesh, oracle=oracle, fuzz=fuzz)
        except Exception as e:
            # a rule that CRASHES verification (malformed params the
            # transforms choke on, a constraint that raises) is refused
            # with a structured error, never a raw traceback through
            # the load gate
            fs = [Finding(
                SEV_ERROR, "rule_verification_crash",
                f"rule crashed verification ({type(e).__name__}: {e}) "
                f"— an unverifiable rule cannot be trusted",
                pass_name=PASS_NAME,
                where=f"rule:{getattr(x, 'name', '?')}")]
        result.extend(fs, pass_name=PASS_NAME)
    if result.ok:
        result.extend([Finding(
            SEV_INFO, "rules_clean",
            f"{len(xfers)} rule(s) verified clean",
            pass_name=PASS_NAME,
            details={"fingerprint": rules_fingerprint(xfers),
                     "rules": len(xfers)})])
    result.elapsed_s = time.perf_counter() - t0
    return result


def verify_registry(mesh, config, graph=None, *, oracle: bool = True,
                    fuzz: bool = True) -> AnalysisResult:
    """Pass 5 + per-rule passes over the FULL generated registry: two
    independent generator runs must serialize identically, sorted by name
    and deduped, and every rule must verify clean."""
    from ..search.substitution import generate_all_pcg_xfers

    shim = (mesh if hasattr(mesh, "shape")
            else SimpleNamespace(shape=dict(mesh)))
    a = generate_all_pcg_xfers(shim, config, graph)  # fflint: ok unverified_rule_load
    b = generate_all_pcg_xfers(shim, config, graph)  # fflint: ok unverified_rule_load
    findings: list[Finding] = []
    sa = [json.dumps(serialize_rule(x), sort_keys=True) for x in a]
    sb = [json.dumps(serialize_rule(x), sort_keys=True) for x in b]
    if sa != sb:
        findings.append(Finding(
            SEV_ERROR, "rule_registry_nondeterministic",
            "two generate_all_pcg_xfers runs serialize differently — the "
            "registry fingerprint (and the warm-start plan address) would "
            "churn per process", pass_name=PASS_NAME))
    names = [x.name for x in a]
    if len(set(names)) != len(names):
        dupes = sorted({n for n in names if names.count(n) > 1})
        findings.append(Finding(
            SEV_ERROR, "rule_registry_nondeterministic",
            f"registry contains duplicate rule names: {dupes[:4]}",
            pass_name=PASS_NAME))
    if names != sorted(names):
        findings.append(Finding(
            SEV_ERROR, "rule_registry_nondeterministic",
            "registry is not name-sorted — emission order is not a "
            "stable content address", pass_name=PASS_NAME))
    result = verify_rules(a, mesh, oracle=oracle, fuzz=fuzz)
    result.findings = findings + result.findings
    return result


# ------------------------------------------------- corruption self-test

def selftest_classes() -> list:
    """The corruption corpus: one deliberately-unsound GraphXfer per
    unsound-rule class, each expected to be caught as EXACTLY its class.
    Shared by scripts/ffrules.py (CI self-test) and tests/test_ffrules.py
    so the two can never drift. Returns [(class name, xfer, expected
    finding code)]."""
    from ..ops.shape_ops import CastParams
    from ..parallel.ops import (
        ReductionParams,
        RepartitionParams,
        ReplicateParams,
    )
    from ..search.substitution import GraphXfer, OpX

    def lin_src(x):
        inp = x.new_input(0)
        return inp, OpX(OT.OP_LINEAR, (inp,), constraints=(
            lambda n: n.params.activation == ActiMode.AC_MODE_NONE,))

    out = []

    # 1) wrong output shape: the dst op silently doubles out_channels
    x = GraphXfer("selftest_wrong_output_shape")
    inp, lin1 = lin_src(x)
    bad = OpX(OT.OP_LINEAR, (inp,), match_src=lin1,
              make_params=lambda m, s=lin1: dataclasses.replace(
                  m[s].params, out_channels=m[s].params.out_channels * 2))
    x.src_ops = [lin1]
    x.dst_ops = [bad]
    x.map_output(lin1.outputs[0], bad.outputs[0])
    out.append(("wrong_output_shape", x, "rule_shape_mismatch"))

    # 2) dtype drift: a bf16 cast interposed before the mapped output
    x = GraphXfer("selftest_dtype_drift")
    inp, lin1 = lin_src(x)
    lin2 = OpX(OT.OP_LINEAR, (inp,), match_src=lin1)
    cast = OpX(OT.OP_CAST, (lin2.outputs[0],),
               make_params=lambda m: CastParams(DataType.DT_BFLOAT16))
    x.src_ops = [lin1]
    x.dst_ops = [lin2, cast]
    x.map_output(lin1.outputs[0], cast.outputs[0])
    out.append(("dtype_drift", x, "rule_dtype_mismatch"))

    # 3) dropped replica dim: Replicate inserted, never combined/reduced
    x = GraphXfer("selftest_dropped_replica_dim")
    inp = x.new_input(0)
    r1 = OpX(OT.OP_RELU, (inp,))
    repl = OpX(OT.OP_REPLICATE, (inp,),
               make_params=lambda m: ReplicateParams(2, ("data",)))
    r2 = OpX(OT.OP_RELU, (repl.outputs[0],), match_src=r1)
    x.src_ops = [r1]
    x.dst_ops = [repl, r2]
    x.map_output(r1.outputs[0], r2.outputs[0])
    out.append(("dropped_replica_dim", x, "rule_replica_dim_leak"))

    # 4) degree-product violation: Repartition with no Combine back —
    # the boundary tensor leaves the rewrite sharded
    x = GraphXfer("selftest_degree_product_violation")
    inp, lin1 = lin_src(x)
    rep = OpX(OT.OP_REPARTITION, (inp,),
              make_params=lambda m: RepartitionParams(0, 2, ("data",)))
    lin2 = OpX(OT.OP_LINEAR, (rep.outputs[0],), match_src=lin1)
    x.src_ops = [lin1]
    x.dst_ops = [rep, lin2]
    x.map_output(lin1.outputs[0], lin2.outputs[0])
    out.append(("degree_product_violation", x, "rule_degree_violation"))

    # 5) partial sums through a nonlinear op: row-parallel Linear's
    # partial-sum output fed through ReLU before the Reduction
    x = GraphXfer("selftest_partial_sum_nonlinear")
    inp, lin1 = lin_src(x)
    rep = OpX(OT.OP_REPARTITION, (inp,),
              make_params=lambda m: RepartitionParams(1, 2, ("data",)))
    lin2 = OpX(OT.OP_LINEAR, (rep.outputs[0],), match_src=lin1)
    relu = OpX(OT.OP_RELU, (lin2.outputs[0],))
    red = OpX(OT.OP_REDUCTION, (relu.outputs[0],),
              make_params=lambda m: ReductionParams(2, ("data",)))
    x.src_ops = [lin1]
    x.dst_ops = [rep, lin2, relu, red]
    x.map_output(lin1.outputs[0], red.outputs[0])
    out.append(("partial_sum_nonlinear", x, "rule_partial_sum_nonlinear"))

    # 6) matcher accepting indivisible dims: on even out_channels the
    # rewrite is the identity (every non-boundary pass is clean); on an
    # odd boundary shape it silently truncates the feature dim —
    # match-and-corrupt, exactly what precondition fuzzing exists for
    x = GraphXfer("selftest_matcher_indivisible")
    inp, lin1 = lin_src(x)
    bad = OpX(OT.OP_LINEAR, (inp,), match_src=lin1,
              make_params=lambda m, s=lin1: dataclasses.replace(
                  m[s].params,
                  out_channels=(m[s].params.out_channels // 2) * 2))
    x.src_ops = [lin1]
    x.dst_ops = [bad]
    x.map_output(lin1.outputs[0], bad.outputs[0])
    out.append(("matcher_indivisible", x, "rule_matcher_unsound"))

    # 7) numeric divergence with identical shape/dtype/parallel state:
    # the rewrite silently swaps in a sigmoid activation
    x = GraphXfer("selftest_numeric_divergence")
    inp, lin1 = lin_src(x)
    bad = OpX(OT.OP_LINEAR, (inp,), match_src=lin1,
              make_params=lambda m, s=lin1: dataclasses.replace(
                  m[s].params, activation=ActiMode.AC_MODE_SIGMOID))
    x.src_ops = [lin1]
    x.dst_ops = [bad]
    x.map_output(lin1.outputs[0], bad.outputs[0])
    out.append(("numeric_divergence", x, "rule_numeric_divergence"))
    return out


# ----------------------------------------------------------- the load gate

# load-time verdicts per JSON rule file (abspath), surfaced into
# strategy_report.json's analysis section by the rule_verify compile pass
_LOAD_RESULTS: dict[str, AnalysisResult] = {}


def gate_loaded_rules(xfers, mesh, config, path: str) -> AnalysisResult:
    """Verify externally-loaded (JSON) rules at load time. Errors raise
    RuleVerificationError naming rule + finding class unless
    --no-verify-rules, which downgrades to a logged warning; either way
    the verdict is recorded for the compile report."""
    from ..telemetry import log as fflog

    result = verify_rules(xfers, mesh)
    # the compile pass (run) reuses these instead of re-loading the file
    result.rules_fingerprint = rules_fingerprint(xfers)
    result.rules_count = len(list(xfers))
    _LOAD_RESULTS[os.path.abspath(path)] = result
    errs = result.errors()
    if errs:
        if getattr(config, "verify_rules", True):
            raise RuleVerificationError(result)
        fflog.warning(
            "ffrules: %d unsound substitution rule(s) in %s "
            "(--no-verify-rules: loading anyway): %s", len(errs), path,
            "; ".join(str(f) for f in errs[:5]))
    return result


# ------------------------------------------------- compile-gate pass hook

def run(graph, mesh, ctx) -> list[Finding]:
    """The `rule_verify` entry in the ffcheck pass pipeline. Cheap by
    design (the full per-rule verification runs at rule LOAD time and in
    the scripts/ffrules.py CI sweep, not per compile): it surfaces the
    recorded load-time verdict for --substitution-json files (errors
    downgraded — load already gated) and stamps the active rule set's
    fingerprint into the report so the plan is auditable against the
    rules that searched it."""
    cfg = getattr(ctx, "config", None)
    if cfg is None:
        return []
    path = getattr(cfg, "substitution_json_path", None) or ""
    # mirror the do_search trigger in FFModel._compile_impl: ANY compile
    # that could have rewritten its graph carries a rule-set fingerprint
    # in the report (a budget-only search uses the generated registry
    # just as much as --enable-substitutions does)
    sizes = dict(getattr(mesh, "shape", {}) or {})
    n_dev = 1
    for v in sizes.values():
        n_dev *= int(v)
    uses_rules = (
        n_dev > 1
        and not getattr(cfg, "only_data_parallel", False)
        and (bool(path)
             or getattr(cfg, "enable_substitutions", False)
             or getattr(cfg, "search_budget", 0) > 0
             or getattr(cfg, "enable_parameter_parallel", False)
             or getattr(cfg, "enable_attribute_parallel", False)))
    # a manual/imported plan was never produced by THIS rule set — the
    # do_search gate (`self._strategy is None`) skips the search for
    # those sources, so a stamped fingerprint would claim an audit
    # trail the plan doesn't have. Cache/checkpoint replays keep the
    # stamp: their plan address already includes the rules component,
    # so the active rule set IS the one that searched them.
    if getattr(ctx, "plan_source", "") in ("manual", "import"):
        uses_rules = False
    if not uses_rules:
        return []
    findings: list[Finding] = []
    res = _LOAD_RESULTS.get(os.path.abspath(path)) if path else None
    if res is not None:
        for f in res.findings:
            sev = SEV_WARNING if f.severity == SEV_ERROR else f.severity
            findings.append(Finding(
                sev, f.code, f.message, pass_name=PASS_NAME,
                where=f.where, details=dict(f.details)))
    fp_known = getattr(res, "rules_fingerprint", None)
    if fp_known:
        # the load gate already fingerprinted exactly this rule set —
        # don't re-read and re-compile the file per compile
        findings.append(Finding(
            SEV_INFO, "rules_fingerprint",
            f"active substitution rule set: "
            f"{res.rules_count} rule(s)",
            pass_name=PASS_NAME,
            details={"fingerprint": fp_known, "rules": res.rules_count,
                     "source": "json"}))
        return findings
    try:
        from ..search.substitution import (
            generate_all_pcg_xfers,
            load_rule_collection,
        )

        if path:
            # fingerprint only: the search's own load site is the
            # verifying gate for this file
            xfers = load_rule_collection(path, mesh)  # fflint: ok unverified_rule_load
        else:
            xfers = generate_all_pcg_xfers(mesh, cfg, graph)  # fflint: ok unverified_rule_load
        findings.append(Finding(
            SEV_INFO, "rules_fingerprint",
            f"active substitution rule set: {len(xfers)} rule(s)",
            pass_name=PASS_NAME,
            details={"fingerprint": rules_fingerprint(xfers),
                     "rules": len(xfers),
                     "source": "json" if path else "generated"}))
    except Exception as e:
        findings.append(Finding(
            SEV_WARNING, "rules_fingerprint",
            f"active rule set could not be fingerprinted: {e}",
            pass_name=PASS_NAME))
    return findings
