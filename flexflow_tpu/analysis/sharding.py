"""Pass 1 — sharding dataflow verifier.

GSPMD (Xu et al. 2021) shows sharding propagation is a well-defined
dataflow analysis over the graph; this pass re-derives per-tensor /
per-edge sharding facts from the plan INDEPENDENTLY of the executor and
cross-checks, the same way `verify_report_total` cross-checks the
makespan identity. Two entry points:

- `verify_strategy(overrides, graph, mesh_axes)` — the strategy-level
  (pre-assignment) verifier: everything `Strategy.validate` historically
  checked (unknown nodes/weights, rank mismatches, absent mesh axes,
  indivisible dims) PLUS the check it was missing — the same mesh axis
  used on two different dims of one assignment, which builds an invalid
  `NamedSharding` that only explodes at device_put time. Runs on raw
  override dicts, so the warm-start plan cache and --import-strategy can
  gate BEFORE a stale plan touches the graph.

- `run(graph, mesh, ctx)` — the compile-time pass over MATERIALIZED
  placements (`ParallelTensor.axis_assignment`, `node.weight_axes`):
  re-checks every pinned assignment, validates replica-dim consistency,
  and walks each edge flagging IMPLICIT reshards — a layout-preserving
  consumer (elementwise chain, dropout, identity) pinned to a different
  spec than its producer, with no explicit parallel op on the edge.
  GSPMD will silently insert a collective there that no parallel-op node
  represents; the finding carries the collective's class and priced
  bytes/seconds so an unpriced reshard is visible before launch.
"""

from __future__ import annotations

from ..fftype import OperatorType as OT
from .findings import Finding, SEV_ERROR, SEV_INFO, SEV_WARNING

PASS_NAME = "sharding_dataflow"

# Ops whose output layout should equal their (first) input's layout: the
# op computes element-wise (or re-places nothing), so a differing pinned
# spec means GSPMD inserts a pure reshard on the edge — implicit, and
# invisible to anything that only looks for explicit parallel-op nodes.
_LAYOUT_PRESERVING = frozenset({
    OT.OP_RELU, OT.OP_GELU, OT.OP_SIGMOID, OT.OP_TANH, OT.OP_ELU,
    OT.OP_IDENTITY, OT.OP_DROPOUT, OT.OP_SCALAR_MULTIPLY,
    OT.OP_SCALAR_ADD, OT.OP_SCALAR_SUB, OT.OP_SCALAR_TRUE_DIV,
    OT.OP_EXP, OT.OP_SIN, OT.OP_COS, OT.OP_RSQRT, OT.OP_POW,
    OT.OP_EW_ADD, OT.OP_EW_SUB, OT.OP_EW_MUL, OT.OP_EW_DIV,
    OT.OP_EW_MAX, OT.OP_EW_MIN,
})


def _flat_axes(assignment):
    """Flatten a per-dim assignment (tuples of mesh-axis names) into
    [(dim, axis), ...]."""
    out = []
    for i, entry in enumerate(assignment or ()):
        for ax in (entry or ()):
            out.append((i, ax))
    return out


def assignment_problems(assignment, shape, axis_sizes: dict,
                        where: str) -> list[Finding]:
    """Check ONE per-dim axis assignment against its tensor shape and the
    mesh: unknown axes, per-assignment axis reuse (the invalid-
    NamedSharding bug Strategy.validate used to accept), oversharded and
    indivisible dims. `shape` entries may be None (dim size unknown —
    divisibility is skipped)."""
    findings: list[Finding] = []
    seen: dict[str, int] = {}
    for dim, ax in _flat_axes(assignment):
        if ax not in axis_sizes:
            findings.append(Finding(
                SEV_ERROR, "unknown_axis",
                f"mesh axis {ax!r} not in mesh {sorted(axis_sizes)}",
                where=f"{where} dim {dim}"))
            continue
        if ax in seen:
            findings.append(Finding(
                SEV_ERROR, "axis_reuse",
                f"mesh axis {ax!r} used on dim {seen[ax]} and dim {dim} "
                f"of one assignment (invalid NamedSharding: an axis may "
                f"shard a tensor at most once)",
                where=where,
                details={"axis": ax, "dims": [seen[ax], dim]}))
        else:
            seen[ax] = dim
    for i, entry in enumerate(assignment or ()):
        degree = 1
        for ax in (entry or ()):
            degree *= axis_sizes.get(ax, 1)
        if degree <= 1:
            continue
        size = shape[i] if i < len(shape) else None
        if size is None:
            continue
        if degree > size:
            findings.append(Finding(
                SEV_ERROR, "overshard",
                f"dim of size {size} sharded {degree} ways over "
                f"{tuple(entry)} — more shards than elements",
                where=f"{where} dim {i}",
                details={"size": int(size), "degree": int(degree)}))
        elif size % degree != 0:
            findings.append(Finding(
                SEV_ERROR, "indivisible_dim",
                f"dim of size {size} not divisible by total sharding "
                f"degree {degree} over {tuple(entry)}",
                where=f"{where} dim {i}",
                details={"size": int(size), "degree": int(degree)}))
    return findings


def _spec_to_assignment(spec, ndim: int):
    from ..parallel.ops import _spec_assignment

    return _spec_assignment(spec, ndim)


def verify_strategy(overrides: dict, graph, mesh_axes: dict
                    ) -> list[Finding]:
    """Strategy-level verification of an overrides dict against (graph,
    mesh axis sizes). The superset of the historical Strategy.validate
    checks — `Strategy.validate` delegates here, so the import path, the
    warm-start plan cache, and checkpoint plan adoption all inherit every
    new check for free."""
    axis_sizes = {k: int(v) for k, v in dict(mesh_axes).items()}
    nodes = {n.name: n for n in graph.topo_order()}
    findings: list[Finding] = []
    for name, ov in (overrides or {}).items():
        node = nodes.get(name)
        if node is None:
            findings.append(Finding(
                SEV_ERROR, "unknown_node",
                f"node {name!r} not in this graph (plan exported from a "
                f"different model?)", where=name))
            continue
        for idx, assignment in (ov.get("outputs") or {}).items():
            if idx >= len(node.outputs):
                findings.append(Finding(
                    SEV_ERROR, "unknown_output",
                    f"output index {idx} out of range "
                    f"({len(node.outputs)} outputs)",
                    where=f"{name}:output{idx}"))
                continue
            shape = node.outputs[idx].shape.logical_shape
            if len(assignment) != len(shape):
                findings.append(Finding(
                    SEV_ERROR, "rank_mismatch",
                    f"output {idx} assignment has {len(assignment)} dims, "
                    f"tensor has {len(shape)}",
                    where=f"{name}:output{idx}"))
                continue
            findings.extend(assignment_problems(
                assignment, shape, axis_sizes, f"{name}:output{idx}"))
        declared = {ws.name: ws for ws in node.weight_specs}
        for wname, spec in (ov.get("weights") or {}).items():
            ws = declared.get(wname)
            if ws is None:
                findings.append(Finding(
                    SEV_ERROR, "unknown_weight",
                    f"no weight named {wname!r} (has {sorted(declared)})",
                    where=f"{name}:{wname}"))
                continue
            if len(spec) > len(ws.shape):
                findings.append(Finding(
                    SEV_ERROR, "rank_mismatch",
                    f"weight {wname!r} spec has {len(spec)} dims, weight "
                    f"has {len(ws.shape)}",
                    where=f"{name}:{wname}"))
                continue
            findings.extend(assignment_problems(
                _spec_to_assignment(spec, len(ws.shape)), ws.shape,
                axis_sizes, f"{name}:{wname}"))
    return findings


def strategy_json_problems(strategy_json: dict) -> list[Finding]:
    """Graph-free sanity check of a serialized Strategy (the plan-cache
    entry format): per-assignment axis reuse is detectable from the JSON
    alone, so the cache can reject a poisoned entry without even
    decoding it against a graph."""
    findings: list[Finding] = []
    for name, ov in (strategy_json.get("nodes") or {}).items():
        for idx, assignment in (ov.get("outputs") or {}).items():
            seen: dict = {}
            for dim, entry in enumerate(assignment or []):
                for ax in (entry or []):
                    if ax in seen:
                        findings.append(Finding(
                            SEV_ERROR, "axis_reuse",
                            f"axis {ax!r} on dims {seen[ax]} and {dim}",
                            where=f"{name}:output{idx}"))
                    else:
                        seen[ax] = dim
        for wname, entries in (ov.get("weights") or {}).items():
            seen = {}
            for dim, entry in enumerate(entries or []):
                axes = (entry if isinstance(entry, list)
                        else [entry] if entry is not None else [])
                for ax in axes:
                    if ax in seen:
                        findings.append(Finding(
                            SEV_ERROR, "axis_reuse",
                            f"axis {ax!r} on dims {seen[ax]} and {dim}",
                            where=f"{name}:{wname}"))
                    else:
                        seen[ax] = dim
    return findings


def run(graph, mesh, ctx=None) -> list[Finding]:
    """Compile-time pass over the MATERIALIZED placements (every
    ParallelTensor's axis_assignment + every node's weight_axes) — the
    independent re-derivation that must agree with what the executor will
    pin. `ctx` optionally carries {machine, cost_model} for pricing the
    implicit-reshard findings."""
    axis_sizes = {k: int(v) for k, v in dict(mesh.shape).items()}
    findings: list[Finding] = []
    machine = getattr(ctx, "machine", None) if ctx is not None else None
    order = graph.topo_order()
    for node in order:
        for i, pt in enumerate(node.outputs):
            where = f"{node.name}:output{i}"
            dims = pt.shape.dims
            shape = [None if d.is_replica_dim else d.size for d in dims]
            findings.extend(assignment_problems(
                pt.axis_assignment, shape, axis_sizes, where))
            # replica-dim consistency: a replica dim exists only to count
            # replicas (size == degree by construction); an axis sharding
            # a replica dim that ALSO shards a logical dim of the same
            # tensor double-uses the axis exactly like in-assignment reuse
            logical_axes = {
                ax for d, entry in zip(dims, pt.axis_assignment)
                if not d.is_replica_dim for ax in entry}
            for d, entry in zip(dims, pt.axis_assignment):
                if not d.is_replica_dim:
                    continue
                if d.size != d.degree:
                    findings.append(Finding(
                        SEV_ERROR, "replica_dim",
                        f"replica dim size {d.size} != degree {d.degree}",
                        where=where))
                overlap = set(entry) & logical_axes
                if overlap:
                    findings.append(Finding(
                        SEV_ERROR, "replica_dim",
                        f"replica dim rides axes {sorted(overlap)} that "
                        f"also shard logical dims of this tensor",
                        where=where))
        for wname, spec in (node.weight_axes or {}).items():
            ws = next((w for w in node.weight_specs if w.name == wname),
                      None)
            if ws is None:
                findings.append(Finding(
                    SEV_ERROR, "unknown_weight",
                    f"placement for unknown weight {wname!r}",
                    where=f"{node.name}:{wname}"))
                continue
            findings.extend(assignment_problems(
                _spec_to_assignment(spec, len(ws.shape)), ws.shape,
                axis_sizes, f"{node.name}:{wname}"))

    # ---- implicit (unpriced) reshards: producer spec != consumer spec
    # on an edge with no explicit parallel op, where the consumer
    # preserves layout — GSPMD inserts a collective there that no
    # parallel-op node (and no op-semantics reshard) represents
    from ..search.cost_model import classify_reshard, dtype_bytes

    for node in order:
        if node.op_type not in _LAYOUT_PRESERVING or not node.outputs:
            continue
        out_pt = node.outputs[0]
        out_assign = tuple(
            a for d, a in zip(out_pt.shape.dims, out_pt.axis_assignment)
            if not d.is_replica_dim)
        for e in graph.in_edges[node.guid]:
            if e.dst_idx != 0:
                continue  # broadcasting second operands re-place freely
            src = graph.nodes[e.src]
            if src.op_type in (OT.OP_INPUT, OT.OP_WEIGHT):
                continue
            src_pt = src.outputs[e.src_idx]
            src_assign = tuple(
                a for d, a in zip(src_pt.shape.dims,
                                  src_pt.axis_assignment)
                if not d.is_replica_dim)
            if src_assign == out_assign:
                continue
            shape = src_pt.shape.logical_shape
            details = {
                "producer": src.name,
                "producer_spec": [list(a) for a in src_assign],
                "consumer_spec": [list(a) for a in out_assign],
            }
            msg = (f"layout-preserving {node.op_type.name} pinned to a "
                   f"different spec than its producer {src.name} — GSPMD "
                   f"inserts an implicit reshard on this edge (no "
                   f"parallel op represents it)")
            if machine is not None:
                try:
                    seconds = classify_reshard(
                        shape, src_assign, out_assign, src_pt.dtype,
                        machine)
                    details["priced_s"] = seconds
                    details["bytes"] = (
                        src_pt.shape.piece_elements()
                        * dtype_bytes(src_pt.dtype))
                except Exception:
                    pass
            findings.append(Finding(
                SEV_WARNING, "implicit_reshard", msg,
                where=f"{src.name} -> {node.name}", details=details))

    if not findings:
        findings.append(Finding(
            SEV_INFO, "sharding_clean",
            f"{len(order)} nodes: every assignment valid, no implicit "
            f"reshards on layout-preserving edges"))
    return findings
