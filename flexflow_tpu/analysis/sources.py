"""Source-level sub-checks of the ffcheck pipeline.

Passes 3 and 4 include two checks that live in HOST code, not the PCG:
coordinator-gated collectives (the multihost-deadlock idiom) and
donated-buffer reuse after a step call. Both are AST rules (analysis/
lint.py); this module scopes them to the runtime modules that actually
call distributed primitives or donated executables, and caches the scan
per process so the compile gate pays the file parse once, not once per
compile (the <5% compile-overhead budget).
"""

from __future__ import annotations

import os

from .findings import Finding
from .lint import lint_file

# The modules whose host code touches collectives or donated step
# executables — the blast radius of the two source-level hazards.
RUNTIME_MODULES = (
    "model.py",
    "executor.py",
    "distributed.py",
    "engine/pipelined.py",
    "serving/engine.py",
    "resilience/manager.py",
    "resilience/checkpointer.py",
    "warmstart/manager.py",
    "diagnostics/drift.py",
)

# the source-level rules the pass pipeline consumes; scanned together in
# ONE pass over the module set so the files are parsed once per process
# (pass 3 takes coordinator_collective, pass 4 donated_reuse, pass 6 —
# spmd_uniformity — host_divergent_branch)
_SOURCE_RULES = ("coordinator_collective", "donated_reuse",
                 "host_divergent_branch")

_cache: list | None = None


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _scan() -> list[Finding]:
    global _cache
    if _cache is None:
        root = package_root()
        findings: list[Finding] = []
        for rel in RUNTIME_MODULES:
            path = os.path.join(root, rel)
            if not os.path.exists(path):
                continue
            findings.extend(lint_file(path, select=_SOURCE_RULES))
        _cache = findings
    return _cache


def runtime_findings(rules: tuple[str, ...]) -> list[Finding]:
    """Findings of the source-level rules over the runtime modules,
    filtered to `rules`. The scan itself runs once per process and is
    cached (source files do not change under a running compile). Copies
    are returned with pass_name cleared so the consuming pass attributes
    them to itself in the report."""
    import dataclasses

    want = set(rules)
    return [dataclasses.replace(f, pass_name="")
            for f in _scan() if f.code in want]


def scan_problems() -> list[Finding]:
    """Scan infrastructure failures (an unparseable runtime module),
    downgraded to WARNING: the checks did not run — which must be
    visible — but a verifier-side failure must never abort every
    compile (the analysis_crash policy). Reported once, by pass 3."""
    import dataclasses

    return [dataclasses.replace(f, severity="warning", pass_name="")
            for f in _scan() if f.code == "parse_error"]
