"""Pass 6 — cross-host SPMD divergence detector (the SPMD half of ffsan).

Multi-controller JAX is correct only while every process traces and
dispatches the SAME program against the SAME plan. The repo has already
paid for two instances of the divergence class: r13's per-host pricing
divergence (calibration measured different costs per host, the
update-sharding auto verdict flipped on one of them — fixed by
`broadcast_json`-ing the coordinator's decision), and the
`coordinator_collective` deadlock idiom ffcheck pass 3 lints for. This
pass generalizes both:

1. **Static**: the `host_divergent_branch` lint rule (analysis/lint.py)
   over the runtime modules — an `if` whose test calls a per-host-
   nondeterministic source (time, RNG, environment, hostname) guarding a
   collective (deadlock: some hosts never arrive) or a trace-entry call
   (divergent executables: hosts compile different programs).
2. **Runtime** (opt-in, `--spmd-barrier`): `fingerprint_barrier` —
   before the first step, every process hashes the ingredients of its
   step executable (plan fingerprint + strategy, donation registry and
   the REALIZED donation probe verdict, update-spec layout, mesh axes,
   numerics policy) and compares against the coordinator's over the
   `broadcast_json` channel. A mismatch raises `SPMDDivergenceError` on
   every process in lockstep — a structured abort at t=0 instead of a
   silent hang or corrupted training hours later. Costs one small
   broadcast; zero when off.
"""

from __future__ import annotations

import hashlib
import json

from .findings import Finding, SEV_INFO
from .sources import runtime_findings

PASS_NAME = "spmd_uniformity"


class SPMDDivergenceError(RuntimeError):
    """Raised by the fingerprint barrier when the fleet's step
    fingerprints disagree. Carries both payloads so the first diverging
    component is printable; `peer_mismatch` marks the processes whose
    OWN fingerprint matched the coordinator's but which must still
    abort because a peer diverged (the lockstep half of the barrier)."""

    def __init__(self, local: dict, remote: dict,
                 peer_mismatch: bool = False):
        self.local = local
        self.remote = remote
        self.peer_mismatch = peer_mismatch
        if peer_mismatch:
            msg = ("SPMD fingerprint mismatch before the first step — "
                   "this process matches the coordinator, but a peer "
                   "process reported a divergent step fingerprint; "
                   "aborting in lockstep with it.")
        else:
            diverged = sorted(
                k for k in set(local) | set(remote)
                if local.get(k) != remote.get(k))
            msg = (
                "SPMD fingerprint mismatch before the first step — "
                "this process would run a different program than the "
                f"coordinator. Diverging component(s): {diverged}. "
                "Typical causes: per-host control flow on time/RNG/env "
                "(fflint host_divergent_branch), a plan adopted on one "
                "host only, or a donation probe succeeding on some "
                "hosts only.")
        super().__init__(msg)


def run(graph, mesh, ctx=None) -> list[Finding]:
    """Static half: host-divergent branches in the runtime host code.
    (Source scan is cached per process alongside the pass-3/4 rules —
    sources._scan — so the compile gate parses each module once.)"""
    findings = list(runtime_findings(("host_divergent_branch",)))
    if not findings:
        findings.append(Finding(
            SEV_INFO, "spmd_clean",
            "no host-divergent branches feeding collectives or traced "
            "code in the runtime modules"))
    return findings


# --------------------------------------------------------------- runtime


def fingerprint_payload(model) -> dict:
    """The per-process ingredients of the step executable, as a dict of
    stable digests. Everything here must be identical across processes
    for the fleet's SPMD programs to stay in lockstep; anything
    legitimately process-local (process_index, local device ids) must
    stay OUT."""
    from ..executor import _donation_supported
    from ..parallel.strategies import Strategy
    from .lint import DONATED_CALLEES

    def digest(obj) -> str:
        return hashlib.sha256(
            json.dumps(obj, sort_keys=True, default=str).encode()
        ).hexdigest()[:16]

    executor = model.executor
    update_specs = dict(executor.update_specs) if executor else {}
    cfg = model.config
    return {
        "graph": f"{model.graph.hash():016x}",
        "plan_fingerprint": str(model._plan_fingerprint),
        "strategy": digest(Strategy(model._strategy or {}).to_json()),
        "mesh_axes": digest({k: int(v)
                             for k, v in dict(model.mesh.shape).items()}),
        # the donation registry AND the probe's realized verdict: a
        # backend honoring donation on some hosts only compiles
        # different executables
        "donation": digest({
            "registry": {k: list(v) for k, v in DONATED_CALLEES.items()},
            "supported": _donation_supported()}),
        "update_specs": digest(sorted(
            (f"{n}/{w}", str(spec), list(shape))
            for (n, w), (spec, shape) in update_specs.items())),
        "numerics": digest({
            "computation_dtype": str(cfg.computation_dtype),
            "allow_tensor_op_math": bool(
                cfg.allow_tensor_op_math_conversion),
            "sanitize_numerics": bool(
                getattr(cfg, "sanitize_numerics", False)),
            "loss_type": str(model.loss_type),
            "opt_slots": (model.optimizer.num_slots
                          if model.optimizer is not None else 0)}),
    }


def step_fingerprint(model) -> str:
    """One digest over the full payload (the value logged/recorded)."""
    return hashlib.sha256(
        json.dumps(fingerprint_payload(model), sort_keys=True).encode()
    ).hexdigest()[:16]


def _gather_match_flags(match: bool) -> list:
    """All processes' match flags (default channel): a process_allgather
    so EVERY process learns whether ANY peer diverged — the raise must
    be fleet-wide, or the surviving processes deadlock in the next
    collective waiting for the one that aborted."""
    import jax

    if jax.process_count() <= 1:
        return [bool(match)]
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray([bool(match)]))
    return [bool(f) for f in np.asarray(flags).reshape(-1)]


def fingerprint_barrier(model, broadcast=None, gather=None) -> dict:
    """Cross-host uniformity barrier, two phases: (1) the coordinator
    broadcasts its fingerprint payload and every process compares;
    (2) the per-process match flags are allgathered so a mismatch
    raises SPMDDivergenceError on EVERY process in lockstep — including
    the coordinator and matching peers, who would otherwise proceed
    into the first collective and hang waiting for the aborted process.
    Returns the verdict record ({status, fingerprint}) that
    strategy_report.json and the compile metrics record carry.

    `broadcast` / `gather` default to the real multihost channels and
    are injectable so a divergence can be simulated single-process
    (tests, ffcheck self-test). Single-process runs with the default
    channels short-circuit to status "single_process"."""
    import jax

    from ..distributed import broadcast_json, is_coordinator

    payload = fingerprint_payload(model)
    fp = step_fingerprint(model)
    if broadcast is None and gather is None \
            and jax.process_count() <= 1:
        return {"status": "single_process", "fingerprint": fp}
    broadcast = broadcast or broadcast_json
    remote = broadcast(
        {"payload": payload, "fingerprint": fp}
        if is_coordinator() else None)
    match = remote.get("fingerprint") == fp
    flags = (gather or _gather_match_flags)(match)
    if not all(flags):
        if not match:
            raise SPMDDivergenceError(payload,
                                      remote.get("payload") or {})
        raise SPMDDivergenceError(payload, payload, peer_mismatch=True)
    return {"status": "ok", "fingerprint": fp}
