"""fftrans — static plan-transition verifier with priced migration plans.

ffcheck (analysis/) verifies every SINGLE plan before it touches device
memory; this module verifies the TRANSITION between two plans for the
same PCG — the missing half of live re-planning (ROADMAP item 2) and of
the elastic-resume paths, where an incompatibility (a dropped weight
mapping, dtype drift, a stage-3 at-rest shard re-placed without a gather
path, transition-time OOM with both layouts resident) historically
surfaced as a shape crash or silent corruption mid-restore. Gemini
(SOSP '23, PAPERS.md) motivates in-memory migration without a
checkpoint-restart round trip; GSPMD (Xu et al. 2021) is the model for
deriving the transfer program statically from the two sharding
assignments alone.

Given two `PlanSide`s — a live compiled model (`PlanSide.from_model`) or
a checkpoint's manifest + flat arrays (`PlanSide.from_checkpoint`) —
`build_transition_plan` derives a **TransitionPlan**: one `transfer`
per (section, node, weight) state leaf (params, fp32 masters, optimizer
slots, RNG/counters/step, and serving KV pools / caches), each carrying
the source→dest sharding pair and the transfer collectives GSPMD-style
derivation says the move needs (all_gather to unwind source shards,
all_to_all for axis moves, free local slices into the dest layout, a
host hop when the source is host-resident or the meshes share no
compatible layout). The plan is priced through the cost-model machinery
(`cost_model.price_transfer_collective`) and verified by
`verify_transition` through the ffcheck findings machinery:

  state_mapping          every old leaf maps (`dropped_state`), every
                         new leaf has a source (`unmapped_state`),
                         dtypes/shapes preserved (`state_dtype_change` /
                         `state_shape_change`), update-stage changes
                         route through a gather path
                         (`missing_gather_path`), KV pool geometry
                         matches (`kv_pool_mismatch`)
  transition_memory      per-chip peak over the transfer schedule — old
                         shard + new shard + transfer buffer liveness,
                         source shards donated as each transfer lands —
                         two-keyed against the HBM cap like ffcheck's
                         OOM gate (`transition_oom`)
  transfer_collectives   ring-permutation bijectivity for every ring the
                         transfers run (`bad_transfer_permutation`) and
                         topological transfer order
                         (`nontopological_transfer_order`)
  migration_donation     no source leaf donated twice
                         (`migration_donation_hazard`) and the migrate
                         apply path's own source is donated-reuse clean
  transfer_uniformity    the schedule digest re-derives from the sorted
                         canonical entries alone — the property that
                         makes every host build the SAME transfer
                         program (`transfer_schedule_divergence`)

The plan serializes into strategy_report.json as a `transition` section
with the makespan-identity treatment: `verify_transition_total` (and
run_doctor --check) recompute `predicted_s` from the per-transfer
entries ALONE under the documented rule — host hops serialize with
everything, ICI traffic on the same axis serializes, disjoint axes
overlap — so the predicted migration seconds reproduce from the JSON.
`resilience/migrate.py` executes a verified plan on live state
in-process (the elastic-resume reshard is a consumer via
`verify_restore_transition`).
"""

from __future__ import annotations

import hashlib
import json
import math
from dataclasses import dataclass, field
from typing import Optional

from .findings import (
    AnalysisResult,
    Finding,
    PlanVerificationError,
    SEV_ERROR,
    SEV_INFO,
)

PASS_NAMES = ("state_mapping", "transition_memory", "transfer_collectives",
              "migration_donation", "transfer_uniformity")

_TIMELINE_CAP = 256

# state-leaf name prefixes that identify serving KV block pools / caches
# (first-class non-trainable stateful parallel tensors, serving/): their
# geometry is load-bearing — a pool cannot be repacked to a different
# block size by a plain reshard, so mismatches get their own finding
# class instead of the generic shape check
_KV_POOL_PREFIXES = ("pool_k", "pool_v")
_KV_CACHE_PREFIXES = ("pool_k", "pool_v", "cache_k", "cache_v")


def _np_dtype_name(x) -> str:
    import numpy as np

    dt = getattr(x, "dtype", None)
    if dt is None:
        return str(np.asarray(x).dtype)
    return str(np.dtype(dt)) if not hasattr(dt, "name") else str(dt.name)


def _shard_bytes(shape, assignment, axis_sizes, el_bytes) -> float:
    n = 1.0
    for i, dim in enumerate(shape):
        deg = 1
        if assignment and i < len(assignment):
            for ax in assignment[i]:
                deg *= axis_sizes.get(ax, 1)
        n *= max(1, math.ceil(dim / deg))
    return n * el_bytes


def _assignment_of_leaf(leaf) -> Optional[tuple]:
    """Per-dim axis tuples of a live jax.Array's NamedSharding, or None
    when the leaf carries no named sharding (host array / scalar)."""
    from ..parallel.ops import _spec_assignment

    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    ndim = len(getattr(leaf, "shape", ()) or ())
    return _spec_assignment(spec, ndim)


@dataclass
class LeafInfo:
    """One state leaf on one side of the transition. `key` is the
    checkpoint flat-key space (`jax.tree_util.keystr` over
    `model_state_tree`), so the restore path, the migrate path, and this
    verifier all name leaves identically."""

    key: str
    shape: tuple
    dtype: str
    # per-dim tuples of mesh-axis names; None = host-resident (a
    # checkpoint's flat arrays) or unsharded scalar
    assignment: Optional[tuple] = None
    # carries a ZeRO at-rest update sharding (stage >= 2 masters/slots,
    # stage 3 params) — the leaves whose re-placement REQUIRES a gather
    update_sharded: bool = False
    kv_pool: bool = False
    # schedule position: dst-graph topological position of the owning
    # node (scalars/RNG ride last); the transfer order key
    topo_pos: int = 1 << 30


@dataclass
class PlanSide:
    """Everything the transition verifier needs to know about one side."""

    leaves: dict = field(default_factory=dict)  # key -> LeafInfo
    axis_sizes: dict = field(default_factory=dict)
    update_stage: int = 0
    plan_source: str = "none"
    kv_block_size: Optional[int] = None
    on_device: bool = True
    label: str = ""

    @staticmethod
    def from_model(model, label: str = "") -> "PlanSide":
        """Capture a compiled FFModel's full training/serving state
        layout: every `model_state_tree` leaf's shape, dtype, and
        materialized NamedSharding, plus the mesh, ZeRO stage, and KV
        geometry."""
        import jax.tree_util as jtu

        from ..fftype import OperatorType as OT
        from ..resilience.reshard import model_state_tree

        side = PlanSide(
            axis_sizes={k: int(v) for k, v in dict(model.mesh.shape).items()},
            update_stage=int((getattr(model, "_update_sharding", None)
                              or {}).get("stage", 0)),
            plan_source=getattr(model, "_plan_source", "none"),
            on_device=True,
            label=label or "model",
        )
        topo_pos = {n.name: i for i, n in enumerate(model.graph.topo_order())}
        has_paged = any(
            n.op_type == OT.OP_PAGED_INC_MULTIHEAD_ATTENTION
            for n in model.graph.topo_order())
        if has_paged:
            side.kv_block_size = int(model.config.serve_kv_block_size)
        upd_keys = {k for k in (model.executor.update_specs or {})} \
            if model.executor is not None else set()
        flat, _ = jtu.tree_flatten_with_path(model_state_tree(model))
        for path, leaf in flat:
            key = jtu.keystr(path)
            keys = tuple(k.key for k in path if isinstance(k, jtu.DictKey))
            wname = keys[-1] if keys else ""
            side.leaves[key] = LeafInfo(
                key=key,
                shape=tuple(getattr(leaf, "shape", ()) or ()),
                dtype=_np_dtype_name(leaf),
                assignment=_assignment_of_leaf(leaf),
                update_sharded=(len(keys) >= 2
                                and keys[-2:] in upd_keys),
                kv_pool=any(str(wname).startswith(p)
                            for p in _KV_CACHE_PREFIXES),
                topo_pos=topo_pos.get(keys[-2] if len(keys) >= 2 else "",
                                      1 << 30),
            )
        return side

    @staticmethod
    def from_checkpoint(flat_arrays: dict, manifest: dict,
                        label: str = "") -> "PlanSide":
        """Capture a committed checkpoint's state layout from its flat
        arrays + manifest alone: host-resident full logical arrays (the
        snapshot gathers shards), mesh/stage from the manifest extras —
        what the WRITER ran, recorded for the report."""
        extras = dict(manifest.get("extras") or {})
        upd = dict(extras.get("update_sharding") or {})
        side = PlanSide(
            axis_sizes={k: int(v)
                        for k, v in (extras.get("mesh_axes") or {}).items()},
            update_stage=int(upd.get("stage", 0)),
            plan_source="checkpoint",
            on_device=False,
            label=label or "checkpoint",
        )
        for key in sorted(flat_arrays):
            arr = flat_arrays[key]
            wname = key.rsplit("['", 1)[-1].rstrip("]'")
            side.leaves[key] = LeafInfo(
                key=key,
                shape=tuple(getattr(arr, "shape", ())),
                dtype=_np_dtype_name(arr),
                assignment=None,
                kv_pool=any(str(wname).startswith(p)
                            for p in _KV_CACHE_PREFIXES),
            )
        return side

    def to_json(self) -> dict:
        out = {
            "label": self.label,
            "mesh_axes": dict(self.axis_sizes),
            "update_stage": self.update_stage,
            "plan_source": self.plan_source,
            "on_device": self.on_device,
            "leaves": len(self.leaves),
        }
        if self.kv_block_size is not None:
            out["kv_block_size"] = self.kv_block_size
        return out


# ------------------------------------------------------------ derivation


def derive_transfer_collectives(leaf_src: LeafInfo, src_sizes: dict,
                                leaf_dst: LeafInfo, dst_sizes: dict,
                                el_bytes: int, src_on_device: bool,
                                same_mesh: bool) -> list[dict]:
    """The static GSPMD-style derivation: the collective list one leaf's
    source→dest re-placement lowers to. Each entry carries {kind, axis,
    bytes (wire bytes per chip), out_bytes} — seconds are priced
    separately so the derivation stays machine-independent. Kinds:

      all_gather  unwind a source-sharded axis (the REQUIRED gather path
                  out of a ZeRO at-rest layout)
      all_to_all  an axis moved between dims on one mesh
      slice       dest-side sharding taken as a free local slice
      host_hop    the full logical array crosses the host (checkpoint
                  restore, or meshes with no compatible device layout)
    """
    shape = leaf_src.shape
    logical = el_bytes * float(max(1, math.prod(shape)) if shape else 1)
    src_assign = leaf_src.assignment
    dst_assign = leaf_dst.assignment
    cols: list[dict] = []
    if not src_on_device:
        cols.append({"kind": "host_hop", "axis": "",
                     "bytes": logical, "out_bytes": logical})
    elif same_mesh:
        ndim = len(shape)
        sa = tuple(src_assign or ((),) * ndim)
        da = tuple(dst_assign or ((),) * ndim)
        removed, added = [], []
        for i in range(ndim):
            f = set(sa[i]) if i < len(sa) else set()
            t = set(da[i]) if i < len(da) else set()
            removed += [(i, ax) for ax in sorted(f - t)]
            added += [(i, ax) for ax in sorted(t - f)]
        moved = {ax for _, ax in removed} & {ax for _, ax in added}
        grown = _shard_bytes(shape, sa, src_sizes, el_bytes)
        for _i, ax in removed:
            n = src_sizes.get(ax, 1)
            if ax in moved:
                cols.append({"kind": "all_to_all", "axis": ax,
                             "bytes": (n - 1) / max(1, n) * grown,
                             "out_bytes": grown})
            else:
                grown *= n
                cols.append({"kind": "all_gather", "axis": ax,
                             "bytes": (n - 1) / max(1, n) * grown,
                             "out_bytes": grown})
        for _i, ax in added:
            if ax not in moved:
                cols.append({"kind": "slice", "axis": ax,
                             "bytes": 0.0, "out_bytes": 0.0})
    else:
        # cross-mesh: unwind every source-sharded axis to the full
        # logical array (gather path), then the dest layout is a free
        # local slice — the conservative program device_put realizes
        grown = _shard_bytes(shape, src_assign, src_sizes, el_bytes)
        for i, entry in enumerate(src_assign or ()):
            for ax in entry:
                n = src_sizes.get(ax, 1)
                if n <= 1:
                    continue
                grown *= n
                cols.append({"kind": "all_gather", "axis": ax,
                             "bytes": (n - 1) / max(1, n) * grown,
                             "out_bytes": grown})
        for i, entry in enumerate(dst_assign or ()):
            for ax in entry:
                if dst_sizes.get(ax, 1) > 1:
                    cols.append({"kind": "slice", "axis": ax,
                                 "bytes": 0.0, "out_bytes": 0.0})
    return cols


@dataclass
class TransitionPlan:
    """The static transfer program between two PlanSides, verified by
    `verify_transition` and executed by `resilience.migrate`."""

    src: PlanSide
    dst: PlanSide
    transfers: list = field(default_factory=list)
    predicted_s: float = 0.0
    bytes_on_wire: dict = field(default_factory=dict)
    hbm_cap_bytes: float = 0.0
    schedule_digest: str = ""

    def to_json(self, analysis: Optional[AnalysisResult] = None) -> dict:
        out = {
            "kind": "transition_plan",
            "src": self.src.to_json(),
            "dst": self.dst.to_json(),
            "transfers": [dict(t) for t in self.transfers],
            "predicted_s": self.predicted_s,
            "bytes_on_wire": dict(self.bytes_on_wire),
            "hbm_cap_bytes": self.hbm_cap_bytes,
            "schedule_digest": self.schedule_digest,
        }
        if analysis is not None:
            out["analysis"] = analysis.to_json()
        return out


def schedule_digest(transfers) -> str:
    """Canonical digest of the transfer program: computed over entries
    sorted by leaf key with only schedule-bearing fields, so every host
    that derives the plan from the same (old, new) pair lands on the
    SAME digest regardless of dict iteration order — the
    transfer_uniformity pass re-derives exactly this."""
    canon = []
    for t in sorted(transfers, key=lambda t: t["key"]):
        canon.append([
            t["key"], t["order"],
            [list(map(list, t.get("src_spec") or []))],
            [list(map(list, t.get("dst_spec") or []))],
            [[c["kind"], c["axis"]] for c in t["collectives"]],
        ])
    return hashlib.sha256(
        json.dumps(canon, sort_keys=True).encode()).hexdigest()[:16]


def transition_totals(transfers) -> tuple[float, dict]:
    """(predicted seconds, bytes-on-wire per axis) from the per-transfer
    entries ALONE — the documented aggregation rule: host hops serialize
    with everything (they drain through the host NIC), ICI collectives
    on the same mesh axis serialize against each other, and disjoint
    axes overlap. This is the makespan-identity function:
    `verify_transition_total` recomputes the plan's predicted_s through
    exactly this from the serialized JSON."""
    host_s = 0.0
    per_axis_s: dict[str, float] = {}
    wire: dict[str, float] = {}
    for t in transfers:
        for c in t["collectives"]:
            if c["kind"] == "slice":
                continue
            if c["kind"] == "host_hop":
                host_s += c.get("seconds", 0.0)
                wire["host"] = wire.get("host", 0.0) + c["bytes"]
            else:
                ax = c.get("axis") or ""
                per_axis_s[ax] = per_axis_s.get(ax, 0.0) \
                    + c.get("seconds", 0.0)
                wire[ax] = wire.get(ax, 0.0) + c["bytes"]
    return host_s + max(per_axis_s.values(), default=0.0), wire


def verify_transition_total(section: dict) -> float:
    """Recompute the transition section's predicted migration seconds
    from its own per-transfer entries under the aggregation rule —
    matches section["predicted_s"] by construction (the ffcheck-identity
    treatment; run_doctor --check gates on it)."""
    total, _ = transition_totals(section.get("transfers") or [])
    return total


def build_transition_plan(src: PlanSide, dst: PlanSide,
                          machine=None, hbm_cap_bytes: float = 0.0
                          ) -> TransitionPlan:
    """Derive + price the static transfer program for every dst leaf
    with a matching src leaf. Leaves missing on either side stay OFF the
    transfer list — that absence is exactly what the state_mapping pass
    reports (`dropped_state` / `unmapped_state`), so an incomplete
    mapping is a finding, not a crash."""
    from ..search.cost_model import price_transfer_collective
    import numpy as np

    same_mesh = (src.on_device and dst.on_device
                 and src.axis_sizes == dst.axis_sizes)
    plan = TransitionPlan(src=src, dst=dst, hbm_cap_bytes=hbm_cap_bytes)
    order_keys = sorted(
        dst.leaves,
        key=lambda k: (dst.leaves[k].topo_pos, k))
    for order, key in enumerate(order_keys):
        ld = dst.leaves[key]
        ls = src.leaves.get(key)
        if ls is None:
            continue
        el = int(np.dtype(ls.dtype).itemsize) if ls.dtype else 4
        cols = derive_transfer_collectives(
            ls, src.axis_sizes, ld, dst.axis_sizes, el,
            src.on_device, same_mesh)
        for c in cols:
            c["seconds"] = price_transfer_collective(
                c["kind"], c["bytes"], c["out_bytes"], c["axis"], machine)
        src_b = (_shard_bytes(ls.shape, ls.assignment, src.axis_sizes, el)
                 if src.on_device else 0.0)
        dst_b = _shard_bytes(ld.shape, ld.assignment, dst.axis_sizes, el)
        logical = el * float(max(1, math.prod(ls.shape))
                             if ls.shape else 1)
        # transfer buffer: an on-device gather materializes the full
        # logical array in HBM in flight; a host hop stages the full
        # array in HOST RAM and streams device-side shards in (the
        # place_like contract — its HBM footprint is the dest shard);
        # a pure same-mesh reshard carries at most the larger shard
        if any(c["kind"] == "all_gather" for c in cols):
            buf = logical
        elif any(c["kind"] == "host_hop" for c in cols):
            buf = dst_b
        else:
            buf = max(src_b, dst_b)
        plan.transfers.append({
            "key": key,
            "order": order,
            "shape": list(ls.shape),
            "dtype": ls.dtype,
            "dst_dtype": ld.dtype,
            "dst_shape": list(ld.shape),
            "src_spec": [list(e) for e in (ls.assignment or ())],
            "dst_spec": [list(e) for e in (ld.assignment or ())],
            "src_shard_bytes": src_b,
            "dst_shard_bytes": dst_b,
            "buffer_bytes": buf,
            "update_sharded": ls.update_sharded,
            "kv_pool": ls.kv_pool,
            "donate_src": True,
            "collectives": cols,
            "seconds": float(sum(c.get("seconds", 0.0) for c in cols)),
        })
    plan.predicted_s, plan.bytes_on_wire = transition_totals(plan.transfers)
    plan.schedule_digest = schedule_digest(plan.transfers)
    return plan


def plan_model_transition(old, new) -> TransitionPlan:
    """TransitionPlan between two compiled FFModels over the same
    logical PCG — the live re-planning / in-process migration entry
    (resilience.migrate executes it)."""
    from ..search.machine_model import machine_model_for_mesh

    machine = machine_model_for_mesh(
        old.mesh, num_hosts=old.config.num_nodes)
    cap = (new.config.device_mem if new.config.device_mem > 0
           else machine_model_for_mesh(
               new.mesh, num_hosts=new.config.num_nodes).chip.hbm_bytes)
    return build_transition_plan(
        PlanSide.from_model(old, label="old"),
        PlanSide.from_model(new, label="new"),
        machine=machine, hbm_cap_bytes=cap)


# ---------------------------------------------------------------- passes


def _check_state_mapping(plan: TransitionPlan) -> list[Finding]:
    findings: list[Finding] = []
    mapped_src = {t["key"] for t in plan.transfers}
    mapped_dst = {t["key"] for t in plan.transfers}
    for key in sorted(set(plan.src.leaves) - mapped_src):
        findings.append(Finding(
            SEV_ERROR, "dropped_state",
            f"old-plan leaf {key} has no mapping in the transition — its "
            f"state would be silently lost by the migration",
            where=key))
    for key in sorted(set(plan.dst.leaves) - mapped_dst):
        findings.append(Finding(
            SEV_ERROR, "unmapped_state",
            f"new-plan leaf {key} has no source in the old plan — the "
            f"migrated model would run on uninitialized state "
            f"(architecture mismatch?)",
            where=key))
    kv_flagged = False
    if (plan.src.kv_block_size is not None
            and plan.dst.kv_block_size is not None
            and plan.src.kv_block_size != plan.dst.kv_block_size):
        kv_flagged = True
        findings.append(Finding(
            SEV_ERROR, "kv_pool_mismatch",
            f"serving KV block size changes across the transition "
            f"({plan.src.kv_block_size} -> {plan.dst.kv_block_size}) — "
            f"block pools cannot be repacked by a reshard; drain the "
            f"engine and re-prefill instead",
            details={"src_block_size": plan.src.kv_block_size,
                     "dst_block_size": plan.dst.kv_block_size}))
    for t in plan.transfers:
        key = t["key"]
        if t.get("kv_pool") and tuple(t["shape"]) != tuple(t["dst_shape"]):
            if not kv_flagged:
                findings.append(Finding(
                    SEV_ERROR, "kv_pool_mismatch",
                    f"KV pool {key} geometry changes "
                    f"{tuple(t['shape'])} -> {tuple(t['dst_shape'])} — "
                    f"block pools/page tables cannot be repacked by a "
                    f"reshard", where=key,
                    details={"src_shape": t["shape"],
                             "dst_shape": t["dst_shape"]}))
            continue
        if tuple(t["shape"]) != tuple(t["dst_shape"]):
            findings.append(Finding(
                SEV_ERROR, "state_shape_change",
                f"leaf {key} has shape {tuple(t['shape'])} in the old "
                f"plan but {tuple(t['dst_shape'])} in the new — "
                f"architecture mismatch, not a re-placement",
                where=key,
                details={"src_shape": t["shape"],
                         "dst_shape": t["dst_shape"]}))
        if t["dtype"] != t["dst_dtype"]:
            findings.append(Finding(
                SEV_ERROR, "state_dtype_change",
                f"leaf {key} is {t['dtype']} in the old plan but "
                f"{t['dst_dtype']} in the new — a silent cast here is "
                f"dtype drift, not a re-placement",
                where=key,
                details={"src_dtype": t["dtype"],
                         "dst_dtype": t["dst_dtype"]}))
        # gather path: every source-sharded axis a transfer must unwind
        # (an axis the dest does not keep on the same dim — ALL source
        # axes cross-mesh) needs a recorded all_gather / host_hop; a
        # stage-3 at-rest shard re-placed replicated without one is the
        # corruption class that used to surface as garbage values
        required = _required_unwinds(plan, t)
        # an axis is unwound by its all_gather OR carried to its new dim
        # by an all_to_all (a same-mesh axis move is a legal transfer,
        # not a missing gather)
        got = {c["axis"] for c in t["collectives"]
               if c["kind"] in ("all_gather", "all_to_all")}
        hop = any(c["kind"] == "host_hop" for c in t["collectives"])
        missing = sorted(required - got) if not hop else []
        if missing:
            stage = plan.src.update_stage
            findings.append(Finding(
                SEV_ERROR, "missing_gather_path",
                f"leaf {key} leaves a sharded at-rest layout over "
                f"{missing}"
                + (f" (ZeRO stage {stage})" if t.get("update_sharded")
                   else "")
                + " but the transfer records no gather path — the "
                  "migration would re-place partial shards as whole "
                  "values", where=key,
                details={"missing_axes": missing,
                         "update_sharded": bool(t.get("update_sharded"))}))
    return findings


def _required_unwinds(plan: TransitionPlan, t: dict) -> set:
    if not plan.src.on_device:
        return set()
    same_mesh = (plan.dst.on_device
                 and plan.src.axis_sizes == plan.dst.axis_sizes)
    src_spec = t.get("src_spec") or []
    dst_spec = t.get("dst_spec") or []
    required = set()
    for i, entry in enumerate(src_spec):
        keep = set(dst_spec[i]) if same_mesh and i < len(dst_spec) else set()
        for ax in entry:
            if plan.src.axis_sizes.get(ax, 1) > 1 and ax not in keep:
                required.add(ax)
    return required


def _check_transition_memory(plan: TransitionPlan) -> list[Finding]:
    """Per-chip memory over the transfer schedule: every source shard is
    resident until its transfer lands (then donated), every dest shard
    from when it lands, plus the in-flight transfer buffer. Two-keyed
    like ffcheck's OOM gate: `transition_oom` is an ERROR only when the
    donation-scheduled peak AND the conservative both-layouts-resident
    bound both exceed the cap (the scheduled peak is always <= the
    bound, so an error means even perfect donation cannot fit);
    schedule-fits-only-via-donation is surfaced in the timeline
    details."""
    findings: list[Finding] = []
    transfers = sorted(plan.transfers, key=lambda t: t["order"])
    src_resident = sum(t["src_shard_bytes"] for t in transfers)
    # source leaves with no mapping still occupy their chips until the
    # old state is released — count them resident through the whole walk
    mapped = {t["key"] for t in transfers}
    src_resident += sum(
        _leaf_bytes(plan.src, k) for k in plan.src.leaves
        if k not in mapped and plan.src.on_device)
    dst_resident = 0.0
    peak, peak_at = src_resident, "(start)"
    max_buf = 0.0
    timeline = []
    for t in transfers:
        live = src_resident + dst_resident + t["buffer_bytes"]
        max_buf = max(max_buf, t["buffer_bytes"])
        timeline.append({"key": t["key"], "live_bytes": live})
        if live > peak:
            peak, peak_at = live, t["key"]
        src_resident -= t["src_shard_bytes"]
        dst_resident += t["dst_shard_bytes"]
    conservative = (
        sum(t["src_shard_bytes"] for t in transfers)
        + sum(t["dst_shard_bytes"] for t in transfers) + max_buf)
    cap = plan.hbm_cap_bytes
    details = {
        "peak_bytes": peak, "peak_at": peak_at,
        "conservative_bytes": conservative,
        "hbm_cap_bytes": cap,
        "donation_required": bool(cap and conservative > cap >= peak),
        "timeline": timeline[:_TIMELINE_CAP],
    }
    findings.append(Finding(
        SEV_INFO, "transition_memory_timeline",
        f"transition peak {peak / 2**20:.2f} MiB/chip at {peak_at} "
        f"(both-layouts bound {conservative / 2**20:.2f} MiB)",
        details=details))
    if cap and cap > 0 and peak > cap:
        over = [e for e in timeline if e["live_bytes"] > cap][:4]
        findings.append(Finding(
            SEV_ERROR, "transition_oom",
            f"transition-time per-chip peak {peak / 2**20:.2f} MiB "
            f"exceeds the {cap / 2**20:.2f} MiB cap at {peak_at} even "
            f"under the donation schedule (old shard + new shard + "
            f"transfer buffer)",
            details={"peak_bytes": peak, "cap_bytes": cap,
                     "peak_at": peak_at, "first_over_cap": over}))
    return findings


def _leaf_bytes(side: PlanSide, key: str) -> float:
    import numpy as np

    leaf = side.leaves[key]
    el = int(np.dtype(leaf.dtype).itemsize) if leaf.dtype else 4
    return _shard_bytes(leaf.shape, leaf.assignment, side.axis_sizes, el)


def _check_transfer_collectives(plan: TransitionPlan) -> list[Finding]:
    from ..parallel.ops import ring_permutation
    from .collectives import check_permutation

    findings: list[Finding] = []
    # ring bijectivity once per distinct ring size any transfer
    # collective runs over (the gathers/all_to_alls lower to the SAME
    # shared ring-schedule builder the runtime rings use)
    sizes = {}
    for t in plan.transfers:
        for c in t["collectives"]:
            if c["kind"] in ("all_gather", "all_to_all") and c["axis"]:
                n = plan.src.axis_sizes.get(
                    c["axis"], plan.dst.axis_sizes.get(c["axis"], 1))
                if n > 1:
                    sizes.setdefault(n, c["axis"])
    for n in sorted(sizes):
        for f in check_permutation(
                ring_permutation(n), n,
                where=f"transfer ring over {sizes[n]}={n}"):
            findings.append(Finding(
                SEV_ERROR, "bad_transfer_permutation", f.message,
                where=f.where, details=f.details))
    # topological transfer order: the schedule must follow the dst
    # graph's topo positions (ties broken by key) — a divergent order
    # breaks the donation schedule's memory accounting and, multihost,
    # the collective issue order
    order_sorted = sorted(plan.transfers, key=lambda t: t["order"])
    expected = sorted(
        plan.transfers,
        key=lambda t: (plan.dst.leaves[t["key"]].topo_pos
                       if t["key"] in plan.dst.leaves else 1 << 30,
                       t["key"]))
    got = [t["key"] for t in order_sorted]
    want = [t["key"] for t in expected]
    if got != want:
        first = next(i for i, (g, w) in enumerate(zip(got, want))
                     if g != w)
        findings.append(Finding(
            SEV_ERROR, "nontopological_transfer_order",
            f"transfer schedule departs from the topological order at "
            f"position {first} ({got[first]} before {want[first]}) — "
            f"the donation-schedule memory accounting and the multihost "
            f"collective issue order both key on it",
            details={"position": first, "got": got[first],
                     "want": want[first]}))
    return findings


def _check_migration_donation(plan: TransitionPlan) -> list[Finding]:
    findings: list[Finding] = []
    seen: dict[str, int] = {}
    for t in plan.transfers:
        if not t.get("donate_src"):
            continue
        if t["key"] in seen:
            findings.append(Finding(
                SEV_ERROR, "migration_donation_hazard",
                f"source leaf {t['key']} is donated by two transfers "
                f"(orders {seen[t['key']]} and {t['order']}) — the "
                f"second would read a dead buffer",
                where=t["key"]))
        seen[t["key"]] = t["order"]
    # the migrate apply path's own host code must be donated-reuse clean
    # (the executables it calls donate their inputs)
    findings.extend(_migrate_source_findings())
    return findings


_migrate_scan_cache: Optional[list] = None


def _migrate_source_findings() -> list[Finding]:
    """donated_reuse scan of resilience/migrate.py, cached per process
    (sources.py pattern — the apply path is host code the graph passes
    cannot see)."""
    global _migrate_scan_cache
    if _migrate_scan_cache is None:
        import os

        from .lint import lint_file
        from .sources import package_root

        path = os.path.join(package_root(), "resilience", "migrate.py")
        found: list[Finding] = []
        if os.path.exists(path):
            for f in lint_file(path, select=("donated_reuse",)):
                f.pass_name = ""
                found.append(f)
        _migrate_scan_cache = found
    return list(_migrate_scan_cache)


def _check_transfer_uniformity(plan: TransitionPlan) -> list[Finding]:
    want = schedule_digest(plan.transfers)
    if plan.schedule_digest != want:
        return [Finding(
            SEV_ERROR, "transfer_schedule_divergence",
            f"transfer schedule digest {plan.schedule_digest!r} does not "
            f"re-derive from the canonical sorted entries ({want!r}) — "
            f"hosts would build different transfer programs",
            details={"recorded": plan.schedule_digest, "derived": want})]
    return []


_PASS_RUNNERS = (
    ("state_mapping", _check_state_mapping),
    ("transition_memory", _check_transition_memory),
    ("transfer_collectives", _check_transfer_collectives),
    ("migration_donation", _check_migration_donation),
    ("transfer_uniformity", _check_transfer_uniformity),
)


def verify_transition(plan: TransitionPlan) -> AnalysisResult:
    """Run the transition pass pipeline. Same crash policy as
    run_analysis: a crashed pass reports analysis_crash at WARNING
    instead of taking the caller down with a verifier bug."""
    import time as _time

    from .findings import SEV_WARNING

    result = AnalysisResult()
    t0 = _time.perf_counter()
    for name, runner in _PASS_RUNNERS:
        try:
            result.extend(runner(plan), pass_name=name)
        except Exception as e:
            result.extend([Finding(
                SEV_WARNING, "analysis_crash",
                f"pass {name} crashed (its checks did NOT run): "
                f"{type(e).__name__}: {e}")], pass_name=name)
        result.passes_run.append(name)
    if result.ok:
        result.extend([Finding(
            SEV_INFO, "transition_clean",
            f"{len(plan.transfers)} transfer(s) map completely, "
            f"predicted {plan.predicted_s * 1e3:.3f} ms")],
            pass_name="state_mapping")
    result.elapsed_s = _time.perf_counter() - t0
    return result


def gate_transition(plan: TransitionPlan, config, label: str = "migration"
                    ) -> AnalysisResult:
    """Verify + enforce: raise PlanVerificationError on errors unless
    --no-verify-plan (errors downgrade to logged warnings, still
    recorded) — the one gate both the in-process migrate path and the
    checkpoint-restore path call before touching live state."""
    from .. import telemetry
    from ..telemetry import log as fflog

    result = verify_transition(plan)
    telemetry.event(
        "transition_verify", label=label,
        predicted_s=plan.predicted_s,
        transfers=len(plan.transfers), **result.summary())
    errs = result.errors()
    if errs:
        if getattr(config, "verify_plan", True):
            raise PlanVerificationError(result)
        fflog.warning(
            "%s: transition verification found %d error(s) "
            "(--no-verify-plan: applying anyway): %s", label, len(errs),
            "; ".join(str(f) for f in errs[:5]))
    return result
