"""Checkpoint / resume.

The reference has **no model checkpointing subsystem** (SURVEY §5: weights
only via set_tensor/get_tensor). This module exceeds the reference with real
sharded checkpointing via orbax: the full training state {params,
op state, optimizer slots, step, metric counters} saves/restores with each
array's NamedSharding preserved, so resume works on the same mesh layout
without gathering to host.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np


def save_checkpoint(ffmodel, path: str, step: Optional[int] = None):
    """Save the full training state under `path` (orbax PyTreeCheckpointer)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    state = {
        "params": ffmodel._params,
        "state": ffmodel._state or {},
        "opt_slots": ffmodel._opt_slots,
        "step": ffmodel._step,
        "counters": ffmodel._counters,
    }
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, state, force=True)
    return path


def restore_checkpoint(ffmodel, path: str):
    """Restore state saved by save_checkpoint into a compiled FFModel (must
    be compiled with the same architecture + mesh)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    template = {
        "params": ffmodel._params,
        "state": ffmodel._state or {},
        "opt_slots": ffmodel._opt_slots,
        "step": ffmodel._step,
        "counters": ffmodel._counters,
    }
    restored = ckptr.restore(path, item=template)
    # re-place leaves with the compiled model's shardings
    def place(new, old):
        sharding = getattr(old, "sharding", None)
        arr = jax.numpy.asarray(new, getattr(old, "dtype", None))
        return jax.device_put(arr, sharding) if sharding is not None else arr

    ffmodel._params = jax.tree.map(place, restored["params"],
                                   ffmodel._params)
    if ffmodel._state:
        ffmodel._state = jax.tree.map(place, restored["state"],
                                      ffmodel._state)
    ffmodel._opt_slots = jax.tree.map(place, restored["opt_slots"],
                                      ffmodel._opt_slots)
    ffmodel._step = jax.tree.map(place, restored["step"], ffmodel._step)
    ffmodel._counters = jax.tree.map(place, restored["counters"],
                                     ffmodel._counters)
    return ffmodel
