"""DEPRECATED compat wrapper over the resilience subsystem.

The original module was a blocking orbax wrapper with two defects this
shim's replacement fixes (resilience/):

- saves were not atomic: a kill mid-save corrupted the target path. The
  resilience checkpointer serializes into a tmp dir and commits via a
  single atomic rename, so a killed save never touches the latest-good
  checkpoint.
- restore built its template as `ffmodel._state or {}`, silently dropping
  restored op state whenever the compiled model's `_state` was falsy; the
  resilience restore path instead raises on any template/checkpoint leaf
  mismatch.
- restore required the *identical* mesh layout; the resilience path
  reshards every leaf onto the target compile's NamedSharding, so a
  checkpoint saved under dp=8 resumes under dp=4×tp=2.

Use `FFModel.save_checkpoint/load_checkpoint`, `FFModel.enable_checkpointing`
or `flexflow_tpu.resilience` directly; these wrappers remain for callers of
the old module-level API. NOTE the on-disk format changed with the
resilience subsystem (step_*/manifest.json + arrays.npz instead of an orbax
tree): checkpoints written by the old orbax path are not readable — restore
them with the release that wrote them and re-save.
"""

from __future__ import annotations

import warnings
from typing import Optional


def save_checkpoint(ffmodel, path: str, step: Optional[int] = None):
    """Deprecated: use FFModel.save_checkpoint (atomic, resilience-backed).
    Saves the full training state as a committed checkpoint under root
    `path`; returns the committed checkpoint directory."""
    warnings.warn(
        "flexflow_tpu.checkpoint.save_checkpoint is deprecated; use "
        "FFModel.save_checkpoint or flexflow_tpu.resilience",
        DeprecationWarning, stacklevel=2)
    return ffmodel.save_checkpoint(path)


def restore_checkpoint(ffmodel, path: str):
    """Deprecated: use FFModel.load_checkpoint (reshard-aware — the saving
    mesh may differ from this model's)."""
    warnings.warn(
        "flexflow_tpu.checkpoint.restore_checkpoint is deprecated; use "
        "FFModel.load_checkpoint or flexflow_tpu.resilience",
        DeprecationWarning, stacklevel=2)
    return ffmodel.load_checkpoint(path)
