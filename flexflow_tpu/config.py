"""FFConfig: runtime configuration + CLI flag parsing.

Parity with the reference's hand-rolled argv scan
(include/flexflow/config.h:92-160, src/runtime/model.cc:3500-3720): the same
flags are accepted (`-b`, `--epochs`, `-e`, `--budget`, `--alpha`,
`--only-data-parallel`, `--enable-parameter-parallel`, ...), plus TPU-native
knobs (mesh axis sizes, bf16 policy). Legion `-ll:gpu/-ll:cpu` flags map to
workers-per-node over the JAX device fleet.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass, field
from typing import Optional

import jax

from .fftype import CompMode, DataType
from .machine import DEFAULT_AXES, MeshShape

# Flags parsed for reference-CLI parity whose mechanics have no TPU analog;
# passing them warns loudly instead of silently doing nothing.
# (--search-overlap-backward-update is NOT here: it switches the cost
# model's gradient-sync overlap semantics, cost_model._MakespanAccum.)
_PARITY_ONLY_FLAGS = frozenset({
    "--simulator-workspace-size", "--segment-size", "--max-num-segments",
    "--enable-propagation",
})


@dataclass
class FFConfig:
    # training loop
    epochs: int = 1
    batch_size: int = 64
    print_freq: int = 10
    learning_rate: float = 0.01
    weight_decay: float = 0.0001
    # fleet description
    num_nodes: int = 1
    cpus_per_node: int = 4
    workers_per_node: int = 0  # 0 → all local devices
    device_mem: float = 0.0  # bytes of HBM per chip (0 → query)
    # search
    search_budget: int = 0
    search_alpha: float = 1.2
    search_overlap_backward_update: bool = False
    simulator_work_space_size: int = 2 * 1024 * 1024 * 1024
    search_num_nodes: Optional[int] = None
    search_num_workers: Optional[int] = None
    base_optimize_threshold: int = 10
    enable_propagation: bool = False
    perform_memory_search: bool = False
    # on-device cost-model calibration: measure the top-K distinct ops on
    # the local chip before searching (measure_operator_cost analog); 0=off
    search_calibrate: int = 0
    # also search over mesh factorizations of the chip count (the
    # MachineView grid-shape half of Unity — divisor degrees are reached by
    # re-factorizing the mesh, search/mesh_search.py); the searched shape
    # replaces the configured data/model split
    search_mesh_shapes: bool = False
    # overlap-capable collectives (ring attention's double-buffered
    # ppermute pipeline, the decomposed collective matmul): True prices
    # and schedules them overlapped with compute — max(compute, comm) in
    # the cost model, hop-before-compute in the runtime; False restores
    # the serial compute+comm pricing and schedule (the ablation
    # baseline, bench.py's ring legs)
    overlap_collectives: bool = True
    # flash attention layout: True (default) runs the packed relayout-free
    # kernels on the (b, s, h·d) projection layout; False forces the
    # head-transposed kernels — the (b,s,h,d)→(b,h,s,d) HBM relayout
    # ablation baseline (bench.py's seq-4096 kernel legs, PERF.md's
    # ~0.8 ms/step copies)
    flash_packed_layout: bool = True
    # weight-update sharding (ZeRO / Xu et al. 2020; FSDP, Zhao et al.
    # 2023): fp32 masters + optimizer slots sharded 1/dp along the
    # gradient-reduction axes (stage 2), and — stage 3 — the trainable
    # weights themselves sharded at rest with a just-in-time
    # double-buffered ring all-gather per layer (issued one layer ahead
    # on the overlappable channel, gathered copy dropped after last use,
    # backward re-gathers). None (default) = Unity decides by pricing
    # replicated vs stage 2 vs stage 3 — sharded is selected exactly
    # when the plan is memory- or grad-sync-bound, and stage 3 exactly
    # when stage 2's resident gathered copies are themselves over the
    # HBM cap (search/unity.choose_update_sharding).
    # `--weight-update-sharding[=stage3|stage2|off|on]` /
    # `--no-weight-update-sharding` force it (weight_update_stage: None
    # = auto among the enabled stages, 0/2/3 = forced). Bit-identical
    # trajectories at every stage (docs/performance.md).
    weight_update_sharding: Optional[bool] = None
    weight_update_stage: Optional[int] = None
    # parallelism gates (reference config.h:133-137)
    only_data_parallel: bool = False
    enable_sample_parallel: bool = False
    enable_parameter_parallel: bool = False
    enable_attribute_parallel: bool = False
    enable_inplace_optimizations: bool = False
    enable_control_replication: bool = True
    # substitution search: explore GraphXfer-rewritten PCGs (inserting
    # Repartition/Combine/Replicate/Reduction nodes) instead of only
    # assigning configs on the fixed graph; implied by --substitution-json
    enable_substitutions: bool = False
    # execution
    computation_mode: CompMode = CompMode.COMP_MODE_TRAINING
    profiling: bool = False
    perform_fusion: bool = False
    synthetic_input: bool = False
    # Mixed precision. allow_tensor_op_math_conversion is the reference's
    # cublas tensor-op flag recast for the MXU: fp32 matmul *inputs* are cast
    # to bf16 with fp32 accumulation (applies on TPU; force_tensor_op_math
    # extends it to CPU for tests). computation_dtype=DT_BFLOAT16 is the full
    # policy: bf16 activations end-to-end with fp32 master weights, optimizer
    # state, loss, and normalization statistics.
    allow_tensor_op_math_conversion: bool = True
    force_tensor_op_math: bool = False
    computation_dtype: Optional[DataType] = None  # None → fp32 activations
    # files / misc
    dataset_path: str = ""
    import_strategy_file: str = ""
    export_strategy_file: str = ""
    export_strategy_task_graph_file: str = ""
    export_strategy_computation_graph_file: str = ""
    substitution_json_path: Optional[str] = None
    machine_model_version: int = 0
    machine_model_file: str = ""
    simulator_segment_size: int = 16777216
    simulator_max_num_segments: int = 1
    python_data_loader_type: int = 2
    # TPU-native additions
    mesh_axis_sizes: Optional[tuple[int, ...]] = None  # (data, model, pipe, seq)
    mesh_axis_names: tuple[str, ...] = DEFAULT_AXES
    seed: int = 0
    # resilience (resilience/): async checkpointing + preemption-safe fit.
    # checkpoint_dir enables the subsystem; every-N-steps / every-T-seconds
    # gate the async saves; auto_resume restores the newest committed
    # checkpoint (resharding onto this run's mesh) before training.
    checkpoint_dir: str = ""
    checkpoint_every: int = 0
    checkpoint_every_seconds: float = 0.0
    checkpoint_keep: int = 3
    auto_resume: bool = False
    # observability (telemetry/): telemetry_dir enables the run-wide
    # tracer + JSONL metrics log (trace.json / metrics.jsonl under the
    # dir); xprof_dir additionally wraps fit in jax.profiler.trace for
    # device-level XProf timelines (docs/observability.md)
    telemetry_dir: str = ""
    xprof_dir: str = ""
    # ffpulse continuous export (telemetry/export.py, needs telemetry):
    # metrics_interval > 0 writes a rolling metrics_snapshot record +
    # metrics.prom every N seconds; metrics_port serves the latest
    # snapshot at /metrics and liveness at /healthz on 127.0.0.1
    # (coordinator-only; port 0 = off)
    metrics_interval: float = 0.0
    metrics_port: int = 0
    # diagnostics (diagnostics/): strategy explain report at compile,
    # online cost-model drift monitoring and run-health anomaly rules
    # during fit. Requires telemetry (the artifacts live in its dir).
    # drift_threshold is the EMA of |measured − predicted| / predicted
    # device step time above which a costmodel.drift advisory fires;
    # health_abort_on lists rule names ("nan_loss", "step_spike",
    # "data_wait_stall", "ckpt_stale") whose alerts abort training instead
    # of warning.
    diagnostics: bool = False
    drift_threshold: float = 0.5
    health_abort_on: tuple[str, ...] = ()
    # elastic re-planning (elastic/): the controller consumes drift
    # advisories and visible-device capacity deltas during fit (and the
    # serving step loop), re-searches online, and migrates in-process
    # when predicted_migration_s × fidelity < benefit/step × horizon.
    # cooldown spaces consecutive re-plan attempts (a capacity shrink
    # bypasses it); horizon is the step count the payoff rule amortizes
    # the migration over; dry-run decides + records but never migrates.
    # Drift triggers additionally need --diagnostics (the monitor lives
    # there); capacity triggers work with --elastic alone.
    elastic: bool = False
    replan_cooldown_steps: int = 50
    replan_horizon_steps: int = 1000
    elastic_dry_run: bool = False
    # pipelined execution engine (engine/): fit runs chunks of N train
    # steps as ONE donated lax.scan dispatch over batches prefetched by a
    # background thread; checkpoints/preemption land at chunk boundaries.
    # 1 = the eager per-step loop (default; bit-identical trajectories
    # either way — docs/performance.md).
    pipeline_steps: int = 1
    # warm start (warmstart/): persistent plan + calibration + executable
    # caching under one directory — the second compile of the same job
    # skips the Unity search (plan cache hit replayed through the
    # import-strategy machinery), calibration only measures misses, and
    # JAX's persistent compilation cache serves the XLA executables.
    # Invalidation is conservative: any change to the graph, mesh,
    # search-relevant config, device kind, or calibration data misses.
    warmstart_dir: str = ""
    # serving engine (serving/): defaults for model.serve() — the fixed
    # continuous-batching slot count, the KV-cache length (0 → the model's
    # training sequence length), and the prefill chunk width (prompts are
    # processed through the decode graph in power-of-two length buckets up
    # to this, each bucket one cached executable).
    serve_slots: int = 4
    serve_max_seq_len: int = 0
    serve_prefill_chunk: int = 16
    # KV-cache layout: "paged" (block pool + per-slot page tables with
    # copy-on-write prefix sharing, serving/paged.py — the default) or
    # "contiguous" ((slots, max_seq+1, embed) per slot — the ablation/
    # fallback). Block size is pool rows per block; blocks=0 sizes the
    # pool from the per-chip HBM budget, capped at capacity parity.
    # The layout is part of the warm-start plan fingerprint.
    serve_kv_layout: str = "paged"
    serve_kv_block_size: int = 16
    serve_kv_blocks: int = 0
    # Cross-request radix prefix cache (serving/radix.py): cached prompt
    # blocks outlive their residents under LRU eviction, so a recurring
    # system prompt hits warm KV after a full drain. 0 restores
    # live-residents-only sharing (the bench ablation).
    serve_prefix_cache: int = 1
    # Disaggregated serving (serving/disagg.py): prefill and decode run
    # as two separately searched Unity plans on disjoint sub-meshes of
    # the same device set (Orca / vLLM lineage: compute-bound prefill vs
    # memory-bound decode want different layouts). serve_prefill_chips
    # sizes the prefill sub-mesh (0 → half the devices); serve_role marks
    # which side a decode-graph compile is for — it joins the warm-start
    # plan fingerprint so the two plans cache independently.
    serve_disaggregate: bool = False
    serve_prefill_chips: int = 0
    serve_role: str = ""  # "" | "prefill" | "decode" | "draft"
    # Speculative decoding (serving/speculative.py): serve_draft_chips
    # places the drafter LM on its own trailing sub-mesh (0 → colocated
    # with the target); serve_spec_k caps the per-round draft length the
    # acceptance-calibrated payoff gate may choose.
    serve_draft_chips: int = 0
    serve_spec_k: int = 4
    # First device this mesh draws from jax.devices() — sub-meshes over
    # disjoint device subsets (disaggregated serving) set it per side.
    mesh_device_offset: int = 0
    # static plan verification (analysis/): the ffcheck pass pipeline —
    # sharding dataflow, memory liveness, collective uniformity,
    # donation/aliasing — runs at compile on EVERY plan source; errors
    # abort compile with the findings in strategy_report.json's analysis
    # section. --no-verify-plan is the escape hatch (findings downgrade
    # to logged warnings).
    verify_plan: bool = True
    # ffrules substitution-rule verification (analysis/rules.py): every
    # rule loaded from --substitution-json is verified at load — symbolic
    # shape/dtype transfer, parallel-state soundness, the semantic-
    # equivalence oracle, and boundary-precondition fuzz — before it can
    # inject rewrites into the search; an unsound rule raises a
    # structured RuleVerificationError naming the rule and finding
    # class. --no-verify-rules downgrades refusals to logged warnings
    # (the verdict still lands in strategy_report.json's analysis
    # section).
    verify_rules: bool = True
    # ffsan runtime half (flexflow_tpu/sanitize.py): instrument the
    # train/eval/decode step with per-op finiteness probes (forward
    # values AND backward cotangents) so a NaN/inf is attributed to the
    # exact (op, fwd|bwd, step) that produced it — the nan_loss health
    # alert then names the culprit instead of just declaring the run
    # dead. Zero-cost when off (no probes are traced); value-identical
    # when on (probes are effectful identities).
    sanitize_numerics: bool = False
    # SPMD fingerprint barrier (analysis/spmd.py): before the first
    # step, every process cross-checks a digest of its step-executable
    # ingredients (plan fingerprint, strategy, donation registry +
    # realized probe verdict, update-spec layout, numerics policy)
    # against the coordinator's over broadcast_json; a mismatch raises
    # SPMDDivergenceError on every process in lockstep. One small
    # broadcast when on; nothing when off.
    spmd_barrier: bool = False
    # eager-loop diagnostics loss fetch cadence: the per-step device_get
    # is a full device drain; K>1 samples it every K-th step and the
    # health/drift rules then see one K-step-AVERAGED record per window
    # (raw per-window timings are bimodal under async dispatch — the
    # sampled step absorbs the drain the others skipped). Pipelined mode
    # gets every step's loss from the per-chunk vector regardless.
    health_sample_every: int = 1
    # ffscope (flexflow_tpu/scope/): op-grain profiling plane, flight
    # recorder, hang watchdog. --profile-every K captures every K-th
    # step under jax.profiler and attributes device time back to PCG
    # ops (the report's `profile` section); 0 = off (model.profile_step()
    # still arms a one-shot). The watchdog fires when no step boundary
    # lands within max(timeout, step-EMA x multiplier); 0 timeout = off.
    profile_every: int = 0
    watchdog_timeout: float = 0.0
    watchdog_multiplier: float = 10.0
    watchdog_abort: bool = False
    # flight-recorder ring capacity (always on; 0 disables)
    flight_events: int = 256

    def __post_init__(self):
        argv = sys.argv[1:]
        self.parse_args(argv)
        try:
            if (self.num_nodes == 1
                    and not getattr(self, "_nodes_explicit", False)
                    and jax.process_count() > 1):
                # zero-config multi-controller runs (MULTIHOST.md): one
                # process per host, so the fleet's node count is the
                # process count; an explicit --nodes (even --nodes 1)
                # always wins
                self.num_nodes = jax.process_count()
        except Exception:
            pass
        if self.workers_per_node == 0:
            try:
                if jax.process_count() > 1:
                    # multi-controller: local_device_count is already the
                    # per-host chip count
                    self.workers_per_node = max(1, jax.local_device_count())
                else:
                    # single process (incl. virtual multi-host meshes):
                    # divide the one process's devices across the nodes
                    self.workers_per_node = max(
                        1, jax.local_device_count() // max(1, self.num_nodes)
                    )
            except Exception:
                self.workers_per_node = 1

    @property
    def num_devices(self) -> int:
        return self.num_nodes * self.workers_per_node

    def mesh_shape(self) -> MeshShape:
        from .machine import MULTIHOST_AXES

        if self.mesh_axis_sizes is not None:
            sizes = tuple(self.mesh_axis_sizes)
            names = self.mesh_axis_names
            if (len(sizes) == len(MULTIHOST_AXES)
                    and names == DEFAULT_AXES):
                # --mesh dcn,data,model,pipe,seq (5 entries): explicit
                # multi-host mesh with a leading DCN axis
                names = MULTIHOST_AXES
            elif self.num_nodes > 1 and len(sizes) == len(names):
                # --nodes N with a single-slice mesh: prepend the DCN axis
                sizes = (self.num_nodes,) + sizes
                names = MULTIHOST_AXES
            return MeshShape(sizes, names)
        if self.num_nodes > 1:
            sizes = (self.num_nodes, self.workers_per_node) + (1,) * (
                len(MULTIHOST_AXES) - 2)
            return MeshShape(sizes, MULTIHOST_AXES)
        sizes = [self.num_devices] + [1] * (len(self.mesh_axis_names) - 1)
        return MeshShape(tuple(sizes), self.mesh_axis_names)

    # flag table mirrors model.cc:3556-3720
    def parse_args(self, argv: list[str]):
        i = 0
        while i < len(argv):
            a = argv[i]

            def val():
                nonlocal i
                i += 1
                return argv[i]

            if a in _PARITY_ONLY_FLAGS:
                # accepted so reference scripts run unmodified, but loudly:
                # these knobs configure simulator/runtime mechanics that
                # have no analog in the TPU recast (XLA owns workspace
                # sizing; the analytic cost model doesn't segment
                # transfers; the jitted step already overlaps update comm)
                print(f"flexflow_tpu: flag {a} accepted for reference CLI "
                      f"parity but has no effect in this framework",
                      file=sys.stderr)
            if a in ("-e", "--epochs"):
                self.epochs = int(val())
            elif a in ("-b", "--batch-size"):
                self.batch_size = int(val())
            elif a == "--lr":
                self.learning_rate = float(val())
            elif a == "--wd":
                self.weight_decay = float(val())
            elif a == "--printFreq":
                self.print_freq = int(val())
            elif a == "--budget" or a == "--search-budget":
                self.search_budget = int(val())
            elif a == "--alpha" or a == "--search-alpha":
                self.search_alpha = float(val())
            elif a == "--simulator-workspace-size":
                self.simulator_work_space_size = int(val())
            elif a == "--only-data-parallel":
                self.only_data_parallel = True
            elif a == "--enable-parameter-parallel":
                self.enable_parameter_parallel = True
            elif a == "--enable-attribute-parallel":
                self.enable_attribute_parallel = True
            elif a == "--enable-sample-parallel":
                self.enable_sample_parallel = True
            elif a == "--enable-inplace-optimizations":
                self.enable_inplace_optimizations = True
            elif a == "--search-overlap-backward-update":
                self.search_overlap_backward_update = True
            elif a == "--no-overlap-collectives":
                self.overlap_collectives = False
            elif a == "--weight-update-sharding" or a.startswith(
                    "--weight-update-sharding="):
                # value forms: --weight-update-sharding=stage3 (or a
                # separate token); bare flag = legacy force-on with the
                # stage decided by pricing (memory-bound -> 3, else 2)
                if "=" in a:
                    v = a.split("=", 1)[1]
                elif (i + 1 < len(argv)
                      and argv[i + 1] in ("stage2", "stage3", "off", "on",
                                          "2", "3")):
                    v = val()
                else:
                    v = "on"
                if v in ("stage3", "3"):
                    self.weight_update_sharding = True
                    self.weight_update_stage = 3
                elif v in ("stage2", "2"):
                    self.weight_update_sharding = True
                    self.weight_update_stage = 2
                elif v == "off":
                    self.weight_update_sharding = False
                    self.weight_update_stage = 0
                elif v == "on":
                    self.weight_update_sharding = True
                    self.weight_update_stage = None
                else:
                    raise ValueError(
                        f"--weight-update-sharding={v!r}: expected "
                        f"stage2|stage3|off|on")
            elif a == "--no-weight-update-sharding":
                self.weight_update_sharding = False
                self.weight_update_stage = 0
            elif a == "--flash-transposed":
                self.flash_packed_layout = False
            elif a == "--fusion":
                self.perform_fusion = True
            elif a == "--profiling":
                self.profiling = True
            elif a == "--dataset":
                self.dataset_path = val()
            elif a == "--import-strategy" or a == "--import":
                self.import_strategy_file = val()
            elif a == "--export-strategy" or a == "--export":
                self.export_strategy_file = val()
            elif a == "--taskgraph":
                self.export_strategy_task_graph_file = val()
            elif a == "--compgraph":
                self.export_strategy_computation_graph_file = val()
            elif a == "--machine-model-version":
                self.machine_model_version = int(val())
            elif a == "--machine-model-file":
                self.machine_model_file = val()
            elif a == "--segment-size":
                self.simulator_segment_size = int(val())
            elif a == "--max-num-segments":
                self.simulator_max_num_segments = int(val())
            elif a == "--enable-propagation":
                self.enable_propagation = True
            elif a == "--memory-search":
                self.perform_memory_search = True
            elif a == "--search-num-nodes":
                self.search_num_nodes = int(val())
            elif a == "--search-num-workers":
                self.search_num_workers = int(val())
            elif a == "--base-optimize-threshold":
                self.base_optimize_threshold = int(val())
            elif a == "--calibrate":
                self.search_calibrate = int(val())
            elif a == "--search-mesh-shapes":
                self.search_mesh_shapes = True
            elif a == "--substitution-json":
                self.substitution_json_path = val()
            elif a == "--enable-substitutions":
                self.enable_substitutions = True
            elif a == "--nodes":
                self.num_nodes = int(val())
                self._nodes_explicit = True
            elif a == "-ll:gpu" or a == "-ll:tpu" or a == "--workers-per-node":
                self.workers_per_node = int(val())
            elif a == "-ll:cpu":
                self.cpus_per_node = int(val())
            elif a == "-ll:fsize":
                self.device_mem = float(val()) * 1024 * 1024
            elif a == "--mesh":
                # TPU-native: --mesh data,model,pipe,seq e.g. "8,4,1,1"
                self.mesh_axis_sizes = tuple(int(x) for x in val().split(","))
            elif a == "--seed":
                self.seed = int(val())
            elif a == "--checkpoint-dir":
                self.checkpoint_dir = val()
            elif a == "--checkpoint-every":
                self.checkpoint_every = int(val())
            elif a == "--checkpoint-every-seconds":
                self.checkpoint_every_seconds = float(val())
            elif a == "--checkpoint-keep":
                self.checkpoint_keep = int(val())
            elif a == "--auto-resume":
                self.auto_resume = True
            elif a == "--telemetry-dir":
                self.telemetry_dir = val()
            elif a == "--xprof-dir":
                self.xprof_dir = val()
            elif a == "--metrics-interval":
                self.metrics_interval = float(val())
            elif a == "--metrics-port":
                self.metrics_port = int(val())
            elif a == "--diagnostics":
                self.diagnostics = True
            elif a == "--drift-threshold":
                self.drift_threshold = float(val())
            elif a == "--elastic":
                self.elastic = True
            elif a == "--replan-cooldown-steps":
                self.replan_cooldown_steps = int(val())
            elif a == "--replan-horizon-steps":
                self.replan_horizon_steps = int(val())
            elif a == "--elastic-dry-run":
                self.elastic_dry_run = True
            elif a == "--health-abort-on":
                self.health_abort_on = tuple(
                    r.strip() for r in val().split(",") if r.strip())
            elif a == "--warmstart-dir":
                self.warmstart_dir = val()
            elif a == "--pipeline-steps":
                self.pipeline_steps = int(val())
            elif a == "--no-verify-plan":
                self.verify_plan = False
            elif a == "--no-verify-rules":
                self.verify_rules = False
            elif a == "--sanitize-numerics":
                self.sanitize_numerics = True
            elif a == "--spmd-barrier":
                self.spmd_barrier = True
            elif a == "--health-sample-every":
                self.health_sample_every = int(val())
            elif a == "--profile-every":
                self.profile_every = int(val())
            elif a == "--watchdog-timeout":
                self.watchdog_timeout = float(val())
            elif a == "--watchdog-multiplier":
                self.watchdog_multiplier = float(val())
            elif a == "--watchdog-abort":
                self.watchdog_abort = True
            elif a == "--flight-events":
                self.flight_events = int(val())
            elif a == "--serve-slots":
                self.serve_slots = int(val())
            elif a == "--serve-max-seq":
                self.serve_max_seq_len = int(val())
            elif a == "--serve-prefill-chunk":
                self.serve_prefill_chunk = int(val())
            elif a == "--serve-kv-layout":
                v = val()
                if v not in ("contiguous", "paged"):
                    raise ValueError(
                        f"--serve-kv-layout must be 'contiguous' or "
                        f"'paged', got {v!r}")
                self.serve_kv_layout = v
            elif a == "--serve-kv-block-size":
                self.serve_kv_block_size = int(val())
            elif a == "--serve-kv-blocks":
                self.serve_kv_blocks = int(val())
            elif a == "--serve-prefix-cache":
                self.serve_prefix_cache = int(val())
            elif a == "--serve-disaggregate":
                self.serve_disaggregate = True
            elif a == "--serve-prefill-chips":
                self.serve_prefill_chips = int(val())
            elif a == "--serve-draft-chips":
                self.serve_draft_chips = int(val())
            elif a == "--serve-spec-k":
                self.serve_spec_k = int(val())
            elif a == "--synthetic-input":
                self.synthetic_input = True
            elif a == "--allow-tensor-op-math-conversion":
                self.allow_tensor_op_math_conversion = True
            elif a == "--dtype":
                d = val().lower()
                table = {
                    "bf16": DataType.DT_BFLOAT16,
                    "bfloat16": DataType.DT_BFLOAT16,
                    "fp16": DataType.DT_HALF,
                    "half": DataType.DT_HALF,
                    "fp32": None,
                    "float32": None,
                }
                if d not in table:
                    raise ValueError(
                        f"--dtype {d!r}: expected one of {sorted(table)}")
                self.computation_dtype = table[d]
            # unknown flags are ignored, matching the reference's tolerant scan
            i += 1


class FFIterationConfig:
    """Per-iteration config (reference config.h:162-167): seq_length enables
    truncated-sequence batches."""

    def __init__(self):
        self.seq_length = -1

    def reset(self):
        self.seq_length = -1
