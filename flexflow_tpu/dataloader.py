"""Data loading.

Reference: SingleDataLoader (python/flexflow_dataloader.h:34-100 +
flexflow_dataloader.cc) — a two-stage path: the full numpy array is staged
into zero-copy host memory once, then a per-batch GPU index task copies each
shard's slice into framebuffer. TPU-native equivalent: the full array stays in
host RAM (numpy); each `next_batch` slices on host and `device_put`s with the
input's NamedSharding, so each chip receives exactly its shard over PCIe —
same data-movement shape, no task runtime. Batches are issued round-robin
with an epoch-stable order, matching reference semantics (sequential batches,
reset() to restart).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import NamedSharding

from . import telemetry


class SingleDataLoader:
    def __init__(self, ffmodel, batch_tensor, full_array: np.ndarray):
        self.ffmodel = ffmodel
        self.batch_tensor = batch_tensor
        self.full_array = np.ascontiguousarray(full_array)
        self.num_samples = int(full_array.shape[0])
        self.batch_size = batch_tensor.dims[0]
        self.next_index = 0
        # the input's device sharding, resolved once on first use: the
        # spec cannot change after compile, so the per-batch linear scan
        # of graph.sources() was pure overhead in the hot path
        self._sharding = None

    @property
    def num_batches(self) -> int:
        return self.num_samples // self.batch_size

    def reset(self):
        self.next_index = 0

    # ---- resumable cursor (resilience/): a checkpointed run restores the
    # loader mid-epoch and the next batch is exactly the one the killed run
    # would have issued
    def state_dict(self) -> dict:
        return {"next_index": int(self.next_index)}

    def load_state_dict(self, state: dict):
        idx = int(state["next_index"])
        if idx < 0 or idx > self.num_samples:
            raise ValueError(
                f"dataloader cursor {idx} out of range for "
                f"{self.num_samples} samples")
        self.next_index = idx

    def next_batch(self, ffmodel=None) -> np.ndarray:
        with telemetry.span("data.next_batch"):
            if self.next_index + self.batch_size > self.num_samples:
                self.next_index = 0
            sl = slice(self.next_index, self.next_index + self.batch_size)
            self.next_index += self.batch_size
            return self.full_array[sl]

    def _resolve_sharding(self):
        """The input node's NamedSharding, cached at first use (False
        when the tensor is not a graph input — plain device_put then)."""
        if self._sharding is None:
            ff = self.ffmodel
            spec = ff._input_partition_spec(self.batch_tensor.name)
            self._sharding = (NamedSharding(ff.mesh, spec)
                              if spec is not None else False)
        return self._sharding

    def next_batch_sharded(self):
        """Batch pre-placed on the mesh with the input's sharding. The
        data_wait span covers slice + device_put — the host-side stall a
        training step pays before dispatch (telemetry/)."""
        with telemetry.span("data_wait"):
            batch = self.next_batch()
            sharding = self._resolve_sharding()
            if sharding is not False:
                return jax.device_put(batch, sharding)
            return jax.device_put(batch)
