"""Diagnostics: strategy explain, cost-model drift, run-health anomalies.

Three coupled pieces on top of the telemetry substrate
(docs/observability.md → "Diagnostics & run doctor"):

1. **Strategy explain** (explain.py) — after compile, attribute the chosen
   plan's predicted makespan per op/segment (compute vs comm vs reshard)
   and report the runner-up plans with the margin by which they lost:
   `strategy_report.json` + `strategy_report.md`.
2. **Drift monitor** (drift.py) — during fit, compare predicted step
   makespan against measured device time, EMA the prediction error, emit
   `costmodel.drift` trace counters, and raise a structured advisory
   (optionally driving recompile.RecompileState re-calibration) when the
   cost model no longer matches reality.
3. **Health monitor** (health.py) — a rule engine over per-step records
   (NaN/inf loss, step-time spikes, data-wait stalls, checkpoint
   staleness) emitting leveled alerts into `alerts.jsonl` with
   configurable warn/abort actions.

Enable with `--diagnostics` (requires `--telemetry-dir`),
`model.enable_diagnostics()`, or the keras `Diagnostics` callback;
`scripts/run_doctor.py` renders a post-mortem from any telemetry dir.
"""

from .drift import DriftAdvisory, DriftMonitor, make_recalibration_state
from .explain import (
    build_strategy_report,
    render_markdown,
    verify_report_total,
    write_strategy_report,
)
from .health import (
    Alert,
    CheckpointStalenessRule,
    DataWaitStallRule,
    HealthAbort,
    HealthMonitor,
    NaNLossRule,
    Rule,
    StepSpikeRule,
    default_rules,
)
from .manager import DiagnosticsManager

__all__ = [
    "DiagnosticsManager",
    "DriftAdvisory", "DriftMonitor", "make_recalibration_state",
    "build_strategy_report", "render_markdown", "verify_report_total",
    "write_strategy_report",
    "Alert", "HealthAbort", "HealthMonitor", "Rule", "default_rules",
    "NaNLossRule", "StepSpikeRule", "DataWaitStallRule",
    "CheckpointStalenessRule",
]
