"""Run doctor: post-mortem report from a telemetry directory.

Reads whatever a run left behind — metrics.jsonl (tolerant of a torn final
line), alerts.jsonl, strategy_report.json, trace.json — and renders one
markdown report answering the post-mortem questions in order: did the run
die (alerts), was it slow (step/percentile stats + top trace spans), did
the input pipeline stall (data-wait fraction), did the cost model drift
(predicted vs measured), and is the trace complete (dropped events).

`scripts/run_doctor.py` is the CLI; `diagnose()` returns the structured
findings so tests and tooling can assert on them.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..telemetry.recorder import read_jsonl


def _load_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _load_jsonl(path: str) -> list[dict]:
    try:
        return read_jsonl(path)
    except OSError:
        return []
    except json.JSONDecodeError:
        # read_jsonl tolerates only a torn FINAL line; the doctor's job is
        # to explain damaged runs, so mid-file corruption degrades to
        # "every record that still parses" instead of crashing
        out = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError:
            pass
        return out


def diagnose(directory: str) -> dict:
    """Structured post-mortem of one telemetry dir. Every section is
    present (possibly empty) so renderers/tests need no existence
    checks."""
    directory = os.path.abspath(directory)
    metrics = _load_jsonl(os.path.join(directory, "metrics.jsonl"))
    alerts = _load_jsonl(os.path.join(directory, "alerts.jsonl"))
    report = _load_json(os.path.join(directory, "strategy_report.json"))
    trace = _load_json(os.path.join(directory, "trace.json"))

    by_kind: dict[str, list[dict]] = {}
    for r in metrics:
        by_kind.setdefault(r.get("kind", "?"), []).append(r)
    manifest = (by_kind.get("manifest") or [{}])[0]
    steps = by_kind.get("step", [])
    summary = (by_kind.get("summary") or [None])[-1]
    checkpoints = by_kind.get("checkpoint", [])
    searches = by_kind.get("search", [])
    compiles = by_kind.get("compile", [])
    replans = by_kind.get("replan", [])

    data_wait_frac = None
    if steps:
        tot = sum(s.get("step_time_s", 0.0) for s in steps)
        if tot > 0:
            data_wait_frac = (
                sum(s.get("data_wait_s", 0.0) for s in steps) / tot)

    drift = None
    if report is not None and steps:
        predicted = report.get("total_predicted_s")
        measured = [s.get("device_time_s") for s in steps[1:]
                    if s.get("device_time_s")]
        if predicted and measured:
            mean_meas = sum(measured) / len(measured)
            drift = {
                "predicted_s": predicted,
                "mean_measured_s": mean_meas,
                "error": abs(mean_meas - predicted) / predicted,
            }

    spans: dict[str, dict] = {}
    dropped_events = 0
    if trace is not None:
        for e in trace.get("traceEvents", []):
            if e.get("ph") == "X":
                s = spans.setdefault(e["name"], {"count": 0, "total_us": 0.0})
                s["count"] += 1
                s["total_us"] += e.get("dur", 0.0)
            elif e.get("name") == "tracer.dropped_events":
                dropped_events = int(e.get("args", {}).get("dropped", 0))

    # ffpulse: the LAST metrics_snapshot is the run's final merged
    # registry state; derive the latency/goodput/pool tables from it
    snapshots = by_kind.get("metrics_snapshot", [])
    metrics_plane = None
    if snapshots:
        from ..telemetry.metrics import percentile_from_hist

        snap = snapshots[-1].get("metrics", {}) or {}
        bounds_map = snap.get("bucket_bounds", {})
        latency = {}
        for key, h in (snap.get("histograms") or {}).items():
            if not h.get("count"):
                continue
            bounds = tuple(bounds_map.get(h.get("bounds_id"), ()))
            row = {"count": h["count"],
                   "mean_s": h["sum"] / h["count"]}
            for q in (50, 95, 99):
                row[f"p{q}_s"] = percentile_from_hist(
                    h, q, bounds=bounds or None)
            row["max_s"] = h.get("max")
            latency[key] = row
        metrics_plane = {
            "snapshots": len(snapshots),
            "reason": snapshots[-1].get("reason"),
            "latency": latency,
            "gauges": snap.get("gauges", {}),
            "counters": snap.get("counters", {}),
        }

    # ffscope: the report's profile section (either source) and the
    # flight record, when the run left one behind
    profile = report.get("profile") if report else None
    flight = _load_json(os.path.join(directory, "flight.json"))
    watchdog = None
    if flight is not None and flight.get("watchdog"):
        watchdog = flight["watchdog"]
    else:
        wd_alerts = [a for a in alerts
                     if a.get("rule") == "hang_watchdog"]
        if wd_alerts:
            watchdog = wd_alerts[-1]

    preempted = bool(by_kind.get("preempted"))
    resumed = bool(by_kind.get("resume"))
    errors = [a for a in alerts if a.get("level") == "error"]
    aborted = any(a.get("action") == "abort" for a in alerts)
    if aborted or errors:
        verdict = "dead"
    elif preempted:
        verdict = "preempted"
    elif alerts:
        verdict = "degraded"
    elif steps:
        verdict = "healthy"
    else:
        verdict = "no-steps"

    return {
        "directory": directory,
        "verdict": verdict,
        "manifest": manifest,
        "compile": (compiles or [None])[-1],
        "search": (searches or [None])[-1],
        "steps": len(steps),
        "summary": summary,
        "data_wait_frac": data_wait_frac,
        "alerts": alerts,
        "drift": drift,
        "checkpoints": {
            "count": len(checkpoints),
            "last_staleness_s": (checkpoints[-1].get("staleness_s")
                                 if checkpoints else None),
            "total_bytes": sum(c.get("bytes", 0) for c in checkpoints),
        },
        "preempted": preempted,
        "resumed": resumed,
        "metrics_plane": metrics_plane,
        "replans": replans,
        "trace_spans": spans,
        "trace_dropped_events": dropped_events,
        "strategy_report": report,
        "serving_disagg": (report or {}).get("serving_disagg"),
        "speculation": (report or {}).get("speculation"),
        "profile": profile,
        "flight": flight,
        "watchdog": watchdog,
    }


def render(d: dict) -> str:
    """Markdown post-mortem from a diagnose() result."""
    lines = [f"# Run doctor — `{d['directory']}`", "",
             f"**Verdict: {d['verdict'].upper()}**", ""]
    man = d["manifest"]
    if man:
        mesh = man.get("mesh_axes") or {}
        lines.append(
            "- mesh: `" + ", ".join(f"{k}={v}" for k, v in mesh.items())
            + f"`  ·  backend: {man.get('jax_backend', '?')}"
            + f"  ·  git: {man.get('git_sha', '?') or '?'}")
    if d["compile"]:
        lines.append(f"- compile: {d['compile'].get('duration_s', 0):.2f}s, "
                     f"{d['compile'].get('num_nodes', '?')} nodes")
    if d["search"]:
        s = d["search"]
        lines.append(f"- search: {s.get('evals', '?')} evals, "
                     f"best cost {s.get('best_cost_s', 0) * 1e3:.3f} ms, "
                     f"rewritten={s.get('rewritten')}")
    summ = d["summary"]
    if summ:
        lines.append(
            f"- steps: {d['steps']}  ·  p50 "
            f"{summ.get('p50_step_time_s', 0) * 1e3:.2f} ms  ·  p95 "
            f"{summ.get('p95_step_time_s', 0) * 1e3:.2f} ms  ·  "
            f"{summ.get('examples_per_sec', 0):.1f} examples/s")
    if d["data_wait_frac"] is not None:
        lines.append(f"- data-wait fraction: {d['data_wait_frac']:.1%}")
    ck = d["checkpoints"]
    if ck["count"]:
        lines.append(
            f"- checkpoints: {ck['count']} "
            f"({ck['total_bytes'] / 2**20:.1f} MiB total, last staleness "
            f"{(ck['last_staleness_s'] or 0):.1f}s)")
    if d["preempted"]:
        lines.append("- run was PREEMPTED (final snapshot committed)")
    if d["resumed"]:
        lines.append("- run auto-resumed from a checkpoint")
    if d["trace_dropped_events"]:
        lines.append(f"- ⚠ trace TRUNCATED: {d['trace_dropped_events']} "
                     f"events dropped at the buffer cap")

    lines += ["", "## Alerts", ""]
    if d["alerts"]:
        lines += ["| rule | level | step | action | message |",
                  "|---|---|---|---|---|"]
        for a in d["alerts"]:
            lines.append(
                f"| {a.get('rule')} | {a.get('level')} | {a.get('step')} "
                f"| {a.get('action', 'warn')} | {a.get('message')} |")
    else:
        lines.append("none")

    if d["replans"]:
        lines += ["", "## Elastic re-plans (ffelastic)", "",
                  "| step | trigger | decision | pay-off lhs (ms) "
                  "| rhs (ms) | migration (ms) |",
                  "|---|---|---|---|---|---|"]
        def _ms(v):
            return f"{v * 1e3:.3f}" if v is not None else "—"

        for r in d["replans"]:
            lines.append(
                f"| {r.get('step', '—')} | {r.get('trigger', '?')} "
                f"| {r.get('decision', '?')} | {_ms(r.get('lhs_s'))} "
                f"| {_ms(r.get('rhs_s'))} "
                f"| {_ms(r.get('migration_measured_s'))} |")

    mp = d.get("metrics_plane")
    if mp:
        lines += ["", "## Metrics plane (ffpulse)", "",
                  f"{mp['snapshots']} snapshot(s); last reason: "
                  f"`{mp['reason']}`"]
        if mp["latency"]:
            lines += ["", "### Latency (bucket-estimated percentiles)",
                      "",
                      "| series | count | p50 (ms) | p95 (ms) | p99 (ms) "
                      "| mean (ms) | max (ms) |",
                      "|---|---|---|---|---|---|---|"]
            for key, row in sorted(mp["latency"].items()):
                def _ms(v):
                    return f"{v * 1e3:.3f}" if v is not None else "—"

                lines.append(
                    f"| {key} | {row['count']} | {_ms(row['p50_s'])} "
                    f"| {_ms(row['p95_s'])} | {_ms(row['p99_s'])} "
                    f"| {_ms(row['mean_s'])} | {_ms(row['max_s'])} |")
        goodput = {k: v for k, v in mp["gauges"].items()
                   if k.startswith("train_") or k.endswith("_per_sec")}
        pool = {k: v for k, v in mp["gauges"].items()
                if k.startswith("serve_")}
        if goodput:
            lines += ["", "### Goodput", "", "| gauge | value |",
                      "|---|---|"]
            for k, v in sorted(goodput.items()):
                lines.append(f"| {k} | {v:.4g} |")
        if pool:
            lines += ["", "### Serving slots / block pool", "",
                      "| gauge | value |", "|---|---|"]
            for k, v in sorted(pool.items()):
                lines.append(f"| {k} | {v:.4g} |")
        hits = sum(v for k, v in mp["counters"].items()
                   if k.startswith("serve_prefix_cache_hits_total"))
        misses = sum(v for k, v in mp["counters"].items()
                     if k.startswith("serve_prefix_cache_misses_total"))
        if hits or misses:
            evict = sum(v for k, v in mp["counters"].items()
                        if k.startswith(
                            "serve_prefix_cache_evictions_total"))
            cached = {k: v for k, v in mp["gauges"].items()
                      if k.startswith("serve_prefix_cache_blocks")}
            lines += ["", "### Radix prefix cache", "",
                      f"- admissions: {hits + misses:.0f}  ·  hit rate "
                      f"{hits / max(1.0, hits + misses):.1%}  ·  "
                      f"evictions: {evict:.0f}"]
            for k, v in sorted(cached.items()):
                lines.append(f"- {k}: {v:.0f}")
        if mp["counters"]:
            lines += ["", "### Counters", "", "| counter | value |",
                      "|---|---|"]
            for k, v in sorted(mp["counters"].items()):
                lines.append(f"| {k} | {v:.0f} |")

    sd = d.get("serving_disagg")
    if sd:
        s = sd.get("summary") or {}
        lines += ["", "## Disaggregated serving (KV handoff plane)", "",
                  f"- chips: prefill {sd.get('prefill_chips', '?')} / "
                  f"decode {sd.get('decode_chips', '?')}",
                  f"- handoffs: {s.get('count', 0)} "
                  f"({s.get('fully_cached', 0)} landed fully "
                  f"radix-cached — zero rows moved)",
                  f"- transfer seconds: predicted "
                  f"{s.get('predicted_s', 0.0) * 1e3:.3f} ms, measured "
                  f"{s.get('measured_s', 0.0) * 1e3:.3f} ms",
                  f"- verified transfer programs: "
                  f"{len(sd.get('programs') or {})} (distinct block "
                  f"extents)"]
        if sd.get("rebalances"):
            last = sd["rebalances"][-1]
            lines.append(
                f"- last ratio decision: {last.get('decision')} "
                f"({last.get('old_prefill_chips')}→"
                f"{last.get('new_prefill_chips')} prefill chips, "
                f"lhs {last.get('lhs_s', 0.0) * 1e3:.3f} ms vs rhs "
                f"{last.get('rhs_s', 0.0) * 1e3:.3f} ms)")

    sp = d.get("speculation")
    if sp:
        drafted = sp.get("draft_tokens", 0)
        counts = sp.get("decision_counts") or {}
        place = ("colocated" if sp.get("colocated")
                 else f"{sp.get('draft_chips')} dedicated chip(s)")
        dplan = (sp.get("drafter") or {}).get("plan_source", "?")
        lines += ["", "## Speculative decoding", "",
                  f"- drafter: {place} (plan `{dplan}`)  ·  "
                  f"K max {sp.get('k_max', '?')}  ·  pair "
                  f"`{sp.get('pair_key', '?')}`",
                  f"- acceptance EMA: {sp.get('acceptance_ema', 0.0):.3f} "
                  f"({sp.get('acceptance_samples', 0)} samples)  ·  "
                  f"accepted {sp.get('accepted_tokens', 0)}/{drafted} "
                  f"drafted over {sp.get('rounds', 0)} round(s)",
                  f"- payoff gate: {counts.get('speculate', 0)} "
                  f"speculated / {counts.get('decode', 0)} plain-decode "
                  f"decision(s)"]
        last = next((x for x in reversed(sp.get("decisions") or [])
                     if x.get("reason") == "payoff"), None)
        if last:
            lines.append(
                f"- last payoff decision: {last.get('chosen')} at "
                f"K={last.get('k')} (lhs "
                f"{last.get('lhs_s', 0.0) * 1e3:.3f} ms vs rhs "
                f"{last.get('rhs_s', 0.0) * 1e3:.3f} ms, verify cost "
                f"{last.get('verify_cost_source', '?')})")

    prof = d.get("profile")
    if prof:
        # ONE measured-vs-predicted table for both sources: ffscope
        # xplane attribution and --profiling standalone kernels land in
        # the same section schema
        lines += ["", "## Op profile (ffscope)", "",
                  f"- source: `{prof.get('source', '?')}`  ·  step "
                  f"{prof.get('step', '?')}  ·  attributed "
                  f"{prof.get('attributed_s', 0.0) * 1e3:.3f} ms of "
                  f"{prof.get('device_time_s', 0.0) * 1e3:.3f} ms device "
                  f"time (parallelism x{prof.get('parallelism', 1)})",
                  "",
                  "| op | measured (ms) | predicted (ms) | fidelity |",
                  "|---|---|---|---|"]
        for o in sorted(prof.get("ops", []),
                        key=lambda r: -r.get("measured_s", 0.0))[:10]:
            pred = o.get("predicted_s")
            fid = o.get("fidelity")
            lines.append(
                f"| {o['name']} | {o['measured_s'] * 1e3:.3f} "
                + (f"| {pred * 1e3:.3f} " if pred is not None else "| — ")
                + (f"| {fid:.2f} |" if fid is not None else "| — |"))

    wd = d.get("watchdog")
    if wd:
        lines += ["", "## Hang watchdog (ffscope)", "",
                  f"- FIRED: no step-boundary progress for "
                  f"{wd.get('stalled_s', 0.0):.1f}s "
                  f"(deadline {wd.get('deadline_s', 0.0):.1f}s, last step "
                  f"{wd.get('last_step', '?')})",
                  f"- lagging host: {wd.get('lagging_host', '?')}"]
        for h in wd.get("hosts", []) or []:
            lines.append(f"  - host {h.get('host')}: step "
                         f"{h.get('step')} at t={h.get('time_unix')}")

    fl = d.get("flight")
    if fl:
        lines += ["", "## Flight record (ffscope)", "",
                  f"- reason: `{fl.get('reason', '?')}`  ·  "
                  f"{len(fl.get('events', []))} event(s) of "
                  f"{fl.get('total_recorded', 0)} recorded "
                  f"(ring capacity {fl.get('capacity', '?')})  ·  last "
                  f"step {fl.get('last_step', '?')}"]
        tail = fl.get("events", [])[-5:]
        if tail:
            lines.append("- last events: " + ", ".join(
                f"{e.get('kind')}:{e.get('name')}" for e in tail))

    if d["drift"]:
        dr = d["drift"]
        lines += ["", "## Cost-model drift", "",
                  f"- predicted step makespan: "
                  f"{dr['predicted_s'] * 1e3:.3f} ms",
                  f"- mean measured device time: "
                  f"{dr['mean_measured_s'] * 1e3:.3f} ms",
                  f"- relative error: {dr['error']:.2f}"]

    rep = d["strategy_report"]
    if rep:
        lines += ["", "## Strategy (top ops by predicted cost)", "",
                  "| op | config | compute (ms) | comm (ms) |",
                  "|---|---|---|---|"]
        ranked = sorted(rep.get("ops", []),
                        key=lambda o: -(o["compute_s"] + o["comm_s"]))[:8]
        for o in ranked:
            lines.append(f"| {o['name']} | {o['config']} "
                         f"| {o['compute_s'] * 1e3:.3f} "
                         f"| {o['comm_s'] * 1e3:.3f} |")
        if rep.get("runner_ups"):
            r0 = rep["runner_ups"][0]
            lines.append(
                f"\nchosen plan beat `{r0['label']}` by "
                f"{r0['margin_s'] * 1e3:.3f} ms")

    if d["trace_spans"]:
        lines += ["", "## Where the time went (host spans)", "",
                  "| span | count | total (ms) |", "|---|---|---|"]
        ranked = sorted(d["trace_spans"].items(),
                        key=lambda kv: -kv[1]["total_us"])[:10]
        for name, s in ranked:
            lines.append(f"| {name} | {s['count']} "
                         f"| {s['total_us'] / 1e3:.2f} |")
    lines.append("")
    return "\n".join(lines)
