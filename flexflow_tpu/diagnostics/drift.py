"""Online cost-model drift monitoring.

FlexFlow earns trust in its search by measuring ops on the real device;
this module keeps checking that trust *during training*: each step's
measured device time (metrics.jsonl already splits it out of wall time) is
compared against the search's predicted step makespan, an EMA of the
relative prediction error is maintained, every sample lands in the
telemetry trace as a `costmodel.drift` counter, and when the EMA crosses
the threshold a structured advisory fires — once per sustained excursion —
which can drive `recompile.RecompileState` re-calibration
(`make_recalibration_state` builds the canonical one).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional


@dataclass
class DriftAdvisory:
    """Structured drift advisory (also serialized into alerts.jsonl)."""

    step: int
    predicted_s: float
    measured_ema_s: float
    error_ema: float        # EMA of |measured − predicted| / predicted
    threshold: float
    message: str = ""

    def to_record(self) -> dict:
        return {
            "rule": "costmodel_drift", "level": "warning",
            "step": int(self.step),
            "predicted_s": float(self.predicted_s),
            "measured_ema_s": float(self.measured_ema_s),
            "error_ema": float(self.error_ema),
            "threshold": float(self.threshold),
            "message": self.message,
        }


@dataclass
class OpDriftAdvisory:
    """Op-grain drift advisory (ffscope): one profiled step's measured
    device time for ONE op deviated from its predicted cost beyond the
    threshold — the targeted-recalibration trigger, so the response
    refreshes exactly this op's calibration entry."""

    step: int
    op: str
    predicted_s: float
    measured_s: float
    fidelity: float          # measured_s / predicted_s
    threshold: float
    message: str = ""

    def to_record(self) -> dict:
        return {
            "rule": "costmodel_op_drift", "level": "warning",
            "step": int(self.step),
            "op": self.op,
            "predicted_s": float(self.predicted_s),
            "measured_s": float(self.measured_s),
            "fidelity": float(self.fidelity),
            "threshold": float(self.threshold),
            "message": self.message,
        }


class DriftMonitor:
    """EMA drift detector over per-step (predicted, measured) pairs.

    - warmup: the first `warmup` samples only feed the EMA (step 1 carries
      jit compile; early EMAs are noise);
    - hysteresis: after an advisory the monitor re-arms only when the EMA
      falls back under threshold/2 (or after a recalibration resets the
      prediction), so a sustained excursion yields ONE advisory, not one
      per step;
    - `recompile_state`: an optional recompile.RecompileState whose
      trigger/alter pair runs when an advisory fires — the reference's
      dynamic re-optimization hook (recompile_state.cc) pointed at
      cost-model re-calibration.
    """

    def __init__(self, predicted_s: float, threshold: float = 0.5,
                 warmup: int = 5, ema_alpha: float = 0.2,
                 recompile_state=None):
        self.predicted_s = float(predicted_s)
        self.threshold = float(threshold)
        self.warmup = int(warmup)
        self.ema_alpha = float(ema_alpha)
        self.recompile_state = recompile_state
        self.error_ema: Optional[float] = None
        self.measured_ema: Optional[float] = None
        self.samples = 0
        self.advisories: list[DriftAdvisory] = []
        self._armed = True
        # ffscope op-grain state: advisories from profiled steps and the
        # set of op names whose calibration entries await a targeted
        # refresh (consumed by recalibrate_model(ops=...))
        self.op_advisories: list[OpDriftAdvisory] = []
        self.pending_op_refresh: set = set()

    def set_prediction(self, predicted_s: float):
        """Point the monitor at a fresh prediction (post-recalibration);
        resets the error EMA so stale error doesn't instantly re-fire."""
        self.predicted_s = float(predicted_s)
        self.error_ema = None
        self.samples = 0
        self._armed = True

    def observe(self, step: int, measured_s: float
                ) -> Optional[DriftAdvisory]:
        """Feed one step's measured device time; returns an advisory when
        sustained drift crosses the threshold (else None)."""
        from .. import telemetry

        if (not math.isfinite(measured_s) or measured_s <= 0.0
                or self.predicted_s <= 0.0):
            return None
        err = abs(measured_s - self.predicted_s) / self.predicted_s
        a = self.ema_alpha
        self.error_ema = (err if self.error_ema is None
                          else (1 - a) * self.error_ema + a * err)
        self.measured_ema = (measured_s if self.measured_ema is None
                             else (1 - a) * self.measured_ema
                             + a * measured_s)
        self.samples += 1
        telemetry.counter("costmodel.drift", {
            "error_ema": self.error_ema,
            "predicted_ms": self.predicted_s * 1e3,
            "measured_ms": measured_s * 1e3,
        })
        if self.samples <= self.warmup:
            return None
        if not self._armed:
            if self.error_ema < self.threshold / 2:
                self._armed = True
            return None
        if self.error_ema <= self.threshold:
            return None
        self._armed = False
        adv = DriftAdvisory(
            step=step, predicted_s=self.predicted_s,
            measured_ema_s=self.measured_ema, error_ema=self.error_ema,
            threshold=self.threshold,
            message=(f"cost-model drift: EMA prediction error "
                     f"{self.error_ema:.2f} > {self.threshold:.2f} "
                     f"(predicted {self.predicted_s * 1e3:.3f} ms, "
                     f"measured EMA {self.measured_ema * 1e3:.3f} ms)"))
        self.advisories.append(adv)
        telemetry.instant("costmodel.drift.advisory", step=step,
                          error_ema=self.error_ema)
        if self.recompile_state is not None and self.recompile_state.trigger():
            self.recompile_state.alter()
        return adv

    def note_profile(self, section: dict) -> list:
        """Feed one profiled step's op-grain measurements (the report
        ``profile`` section) and return the op advisories it produced.

        An op drifts when its fidelity (measured/predicted) deviates
        from the step-level fidelity by more than the threshold — the
        step-level baseline absorbs the global measured-vs-predicted
        scale (a CPU mesh runs every op slower than the roofline by
        roughly the same factor; what matters for *targeted* refresh is
        the op whose ratio broke away from the pack). Drifted op names
        accumulate in `pending_op_refresh` until a recalibration
        consumes them."""
        from .. import telemetry

        step = int(section.get("step", 0))
        rows = [r for r in section.get("ops", [])
                if r.get("predicted_s") and r.get("measured_s", 0.0) > 0]
        if not rows:
            return []
        fids = [r["measured_s"] / r["predicted_s"] for r in rows]
        fids.sort()
        baseline = fids[len(fids) // 2]  # median fidelity
        if baseline <= 0:
            return []
        out = []
        for r in rows:
            fid = r["measured_s"] / r["predicted_s"]
            rel = abs(fid - baseline) / baseline
            if rel <= self.threshold:
                continue
            adv = OpDriftAdvisory(
                step=step, op=r["name"],
                predicted_s=float(r["predicted_s"]),
                measured_s=float(r["measured_s"]),
                fidelity=fid, threshold=self.threshold,
                message=(f"op-grain drift: {r['name']} fidelity "
                         f"{fid:.2f} vs step median {baseline:.2f} "
                         f"(rel dev {rel:.2f} > {self.threshold:.2f})"))
            self.op_advisories.append(adv)
            self.pending_op_refresh.add(r["name"])
            telemetry.instant("costmodel.op_drift.advisory",
                              step=step, op=r["name"], fidelity=fid)
            out.append(adv)
        return out


def recalibrate_model(model, top_k: int = 4, ops=None) -> Optional[float]:
    """Re-measure the plan's dominant ops on the local device
    (CostModel.calibrate_graph, remeasure=True) and refresh the model's
    predicted step makespan — the canonical drift response, shared by the
    recompile hook (make_recalibration_state) and the elastic controller's
    replan path. Persisting the refreshed readings into the warm-start DB
    happens HERE and only here (coordinator-only), so however the
    recalibration was triggered the entries land exactly once. Returns
    the refreshed prediction, or None when the model carries no search
    result to recalibrate."""
    # warm-started runs (plan cache / checkpoint / broadcast) carry no
    # search result; the explain report reconstructed an equivalent
    # (UnitySearch, choice) for the ADOPTED plan — use it, so drift
    # recalibration works exactly on the runs that reload persisted
    # calibration entries
    sr = (getattr(model, "_search_result", None)
          or getattr(model, "_replay_search", None))
    if sr is None:
        return None
    us, choice = sr
    diag = getattr(model, "_diagnostics", None)
    # ffscope targeted refresh: when the trigger was an op-grain
    # advisory, only the drifted ops' entries are re-measured and
    # persisted — undrifted ops keep their (still valid) measurements
    if ops is None and diag is not None and diag.drift is not None \
            and diag.drift.pending_op_refresh:
        ops = sorted(diag.drift.pending_op_refresh)
    refreshed_keys = None
    if ops:
        refreshed_keys = us.cm.calibrate_nodes(
            model.graph, ops, remeasure=True)
        if diag is not None and diag.drift is not None:
            diag.drift.pending_op_refresh.difference_update(ops)
    else:
        # remeasure: the monitor fired BECAUSE the cached measurements
        # no longer describe the device — refresh them, don't skip them
        us.cm.calibrate_graph(model.graph, top_k=top_k, remeasure=True)
    us.cm._cache.clear()
    warm = getattr(model, "_warmstart", None)
    if warm is not None:
        # persist the refreshed readings (coordinator-only inside
        # save_from's caller contract): the stale DB entries were
        # feeding the plan-cache fingerprint, so the next restart
        # would otherwise reload them and re-fire drift forever
        from ..distributed import is_coordinator

        if is_coordinator():
            if refreshed_keys is not None:
                warm.calibration_db.save_entries(us.cm, refreshed_keys)
            else:
                warm.calibration_db.save_from(us.cm)
    t, _ = us.evaluate(choice)
    model._predicted_step_s = t
    if diag is not None and diag.drift is not None:
        diag.drift.set_prediction(t)
    return t


def make_recalibration_state(model, top_k: int = 4):
    """A RecompileState whose alter() runs `recalibrate_model` — the
    drift response when NO elastic controller is attached. Attach it via
    DiagnosticsManager(..., recalibrate=True) or pass it to a
    DriftMonitor directly. (With --elastic the controller consumes the
    advisory instead and recalibrates inside its replan, so the manager
    does not arm this hook — one excursion, one trigger.)"""
    from ..recompile import RecompileState

    def _alter(ff):
        recalibrate_model(ff, top_k=top_k)

    return RecompileState(trigger_func=lambda ff: True,
                          alter_func=_alter, ffmodel=model)
