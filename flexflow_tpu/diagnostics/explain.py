"""Strategy explain: why compile chose the plan it chose.

Daydream (ATC '20, PAPERS.md) argues that optimization decisions become
auditable only when predictions are attributed at the dependency-graph
level. The Unity search already prices every op (CostModel.op_cost) and
evaluates whole plans under the makespan rule (graph_makespan); this module
re-runs ONE evaluation of the winning choice with per-node collection
turned on (UnitySearch.evaluate(collect=...)) and writes:

  <telemetry-dir>/strategy_report.json   machine-readable attribution
  <telemetry-dir>/strategy_report.md     the human-readable rendering

The JSON is self-contained: it carries per-op compute/comm seconds, the
ICI-axis tags, and the dependency edges *in report index space*, so
`verify_report_total` (and any external tool) can recompute the plan's
total predicted cost from the report alone — the acceptance property that
per-op costs sum, under the makespan rule, to the reported total.

Runner-up plans: the search keeps only the winner, so runner-ups are
re-derived the way `_refine` explores — the all-data-parallel baseline
plus single-node config flips of the chosen plan — each priced by the same
evaluator, ranked by penalized cost, and reported with the margin by which
they lost.
"""

from __future__ import annotations

import json
import os
from typing import Optional

_MAX_FLIP_EVALS = 48  # runner-up probing budget (compile-time cost bound)


def _detail_edges(us, detail):
    """Dependency edges in report index space — the same (idx, in_edges)
    walk _MakespanAccum.makespan performs, so graph_makespan over the
    collected arrays + these edges reproduces evaluate()'s task graph."""
    idx = {d["guid"]: i for i, d in enumerate(detail)}
    src, dst = [], []
    for d in detail:
        for e in us.graph.in_edges[d["guid"]]:
            j = idx.get(e.src)
            if j is not None:
                src.append(j)
                dst.append(idx[d["guid"]])
    return src, dst


def verify_report_total(report: dict) -> float:
    """Recompute the plan's total predicted cost from the report's own
    per-op entries and edges under the makespan rule — including, when the
    plan was costed with --search-overlap-backward-update
    (report["overlap_sync"]), the per-axis bound where overlapped gradient
    sync shares its ICI axis's links with path comm. Matches
    report["total_predicted_s"] by construction — the acceptance check."""
    from ..search.cost_model import graph_makespan

    ops = report["ops"]
    if not ops:
        return 0.0
    compute = [o["compute_s"] for o in ops]
    comm = [o["comm_s"] for o in ops]
    axis = [o["comm_axis_id"] for o in ops]
    src = [e[0] for e in report["edges"]]
    dst = [e[1] for e in report["edges"]]
    total = graph_makespan(compute, comm, src, dst, axis=axis)
    has_overlap = any(o.get("overlap_s", 0.0) > 0.0 for o in ops)
    if report.get("overlap_sync") or has_overlap:
        # the _MakespanAccum.makespan per-axis bounds: overlapped traffic
        # (ring-attention hops hidden behind compute, overlapped gradient
        # sync) still occupies its ICI axis's links, so same-axis serial +
        # overlapped (+ sync) comm serialize against each other
        sync_by_axis: dict[int, float] = {}
        comm_by_axis: dict[int, float] = {}
        for o in ops:
            if o["sync_s"] > 0.0:
                sync_by_axis[o["comm_axis_id"]] = (
                    sync_by_axis.get(o["comm_axis_id"], 0.0) + o["sync_s"])
            if o["comm_axis_id"] >= 0:
                comm_by_axis[o["comm_axis_id"]] = (
                    comm_by_axis.get(o["comm_axis_id"], 0.0)
                    + o["comm_s"] + o.get("overlap_s", 0.0))
        if has_overlap:
            for ax, c in comm_by_axis.items():
                total = max(total, c)
        if report.get("overlap_sync"):
            for ax, s in sync_by_axis.items():
                total = max(total, s + comm_by_axis.get(ax, 0.0))
    return total


def _segment_of(us):
    """{guid -> segment index}: ops grouped by the bottleneck cuts the
    sequence DP splits at (UnitySearch.bottlenecks)."""
    try:
        cuts = {n.guid for n in us.bottlenecks()}
    except Exception:
        cuts = set()
    seg, out = 0, {}
    for n in us.order:
        out[n.guid] = seg
        if n.guid in cuts:
            seg += 1
    return out


def _runner_ups(us, choice, chosen_cost: float, top_n: int = 3):
    """Re-derive the plans the winner beat: the all-dp baseline plus
    single-node flips of the chosen plan, each priced by the same
    evaluator. Returns (candidates ranked by cost, evals spent)."""
    cands = []
    baseline = {}
    for n in us.order:
        try:
            cfgs = us.node_configs(n)
        except ValueError:
            continue
        if cfgs:
            baseline[n.guid] = cfgs[0]
    # NodeConfigs are rebuilt per node_configs() call, so compare by value
    if baseline and any(baseline.get(g) != c for g, c in choice.items()):
        t, mem = us.evaluate(baseline)
        cands.append({
            "label": "all-" + next(iter(baseline.values())).name
            if len({c.name for c in baseline.values()}) == 1
            else "baseline (first configs)",
            "cost_s": us._memory_penalized(t, mem),
            "makespan_s": t, "memory_bytes": mem, "changes": []})
    evals = 0
    for n in us.order:
        if evals >= _MAX_FLIP_EVALS:
            break
        cur = choice.get(n.guid)
        if cur is None:
            continue
        try:
            alts = us.node_configs(n)
        except ValueError:
            continue
        for cfg in alts:
            if cfg is cur or cfg.name == cur.name:
                continue
            if evals >= _MAX_FLIP_EVALS:
                break
            cand = dict(choice)
            cand[n.guid] = cfg
            t, mem = us.evaluate(cand)
            evals += 1
            cands.append({
                "label": f"{n.name}: {cur.name} → {cfg.name}",
                "cost_s": us._memory_penalized(t, mem),
                "makespan_s": t, "memory_bytes": mem,
                "changes": [{"op": n.name, "from": cur.name,
                             "to": cfg.name}]})
    cands.sort(key=lambda c: c["cost_s"])
    for c in cands:
        c["margin_s"] = c["cost_s"] - chosen_cost
    return cands[:top_n], evals


def build_strategy_report(model) -> dict:
    """Attribution of the compiled plan's predicted cost. Uses the search
    state compile stashed (`model._search_result`); when the plan was not
    searched locally (pure data parallel, imported/broadcast strategy) the
    default-config assignment is evaluated instead and the report says so
    (`mode: "dp_fallback"`)."""
    from ..search.cost_model import CostModel
    from ..search.machine_model import machine_model_for_mesh

    upd = getattr(model, "_update_sharding", None) or {"enabled": False}

    sr = getattr(model, "_search_result", None)
    if sr is not None:
        us, choice = sr
        mode = "searched"
    else:
        from ..search.substitution import _logical_assignment
        from ..search.unity import UnitySearch

        machine = machine_model_for_mesh(
            model.mesh, num_hosts=model.config.num_nodes)
        opt_slots = (model.optimizer.num_slots
                     if model.optimizer is not None else 1)
        cm = CostModel(machine, opt_slots=opt_slots)
        warm = getattr(model, "_warmstart", None)
        if warm is not None:
            # price the reconstruction with the SAME persisted calibration
            # the cold search consumed — a roofline-only cm would arm the
            # drift monitor with a mispriced makespan and fire spurious
            # advisories on every warm restart of a --calibrate'd job
            warm.calibration_db.load_into(cm)
        us = UnitySearch(model.graph, model.mesh, model.config, cm,
                         refine=False)
        # a plan adopted WITHOUT a local search (warm-start cache,
        # checkpoint manifest, import, multi-host broadcast) left no
        # (UnitySearch, choice) behind — reconstruct the choice by
        # matching each node's candidate configs against the placements
        # the plan materialized onto the graph, so the report (and the
        # drift monitor's predicted makespan) describes the plan that is
        # actually RUNNING, not the data-parallel default
        applied = bool(getattr(model, "_strategy", None))

        def _sharded(specs: dict) -> dict:
            # drop fully-replicated entries: an absent weight spec and
            # PartitionSpec() mean the same placement
            return {k: tuple(v) for k, v in specs.items()
                    if any(e for e in tuple(v))}

        choice = {}
        matched = 0
        for n in us.order:
            try:
                cfgs = us.node_configs(n)
            except ValueError:
                cfgs = []
            if not cfgs:
                continue
            pick = cfgs[0]
            if applied and n.outputs:
                cur_out = tuple(_logical_assignment(n.outputs[0]))
                cur_w = _sharded(dict(n.weight_axes))
                best_score = 0
                for cfg in cfgs:
                    if tuple(cfg.out_assign) != cur_out:
                        continue
                    score = 1 + (_sharded(dict(cfg.weight_specs)) == cur_w)
                    if score > best_score:
                        best_score, pick = score, cfg
                if best_score:
                    matched += 1
            choice[n.guid] = pick
        mode = "replayed" if applied and matched else "dp_fallback"
        # stash the reconstructed evaluation for the drift-recalibration
        # hook (make_recalibration_state falls back to it): warm-started
        # runs have _search_result=None, and without this the remeasure +
        # DB-refresh path would be unreachable exactly on the runs that
        # reload persisted calibration. Kept SEPARATE from _search_result
        # so a second report build still labels the plan honestly.
        model._replay_search = (us, choice)

    # price the update mode that actually runs (unity.choose_update_
    # sharding's decision): sharded → the grad RS+AG rides the
    # overlappable channel and memory carries the 1/dp state; stage 3
    # additionally prices the just-in-time weight gathers and the
    # 1/shards-at-rest weights — so the drift monitor arms with the
    # running schedule's makespan
    us.cm.update_sharding = bool(upd.get("enabled"))
    us.cm.param_gather = upd.get("stage", 0) == 3
    us.cm.overlap_update = (bool(upd.get("enabled"))
                            and bool(model.config.overlap_collectives))

    detail: list[dict] = []
    makespan, mem = us.evaluate(choice, collect=detail)
    src, dst = _detail_edges(us, detail)
    seg_of = _segment_of(us)
    chosen_cost = us._memory_penalized(makespan, mem)
    runner_ups, flip_evals = _runner_ups(us, choice, chosen_cost)

    # axis id -> mesh axis name, from the accumulator's own id assignment
    # (the id is the node's first comm axis, in encounter order)
    axis_names: dict[int, str] = {}
    for d in detail:
        if d["comm_axis_id"] >= 0 and d["comm_axes"]:
            axis_names.setdefault(d["comm_axis_id"], d["comm_axes"][0])

    ops = []
    for d in detail:
        ops.append({
            "name": d["name"], "op_type": d["op_type"],
            "config": d["config"],
            "segment": seg_of.get(d["guid"], 0),
            "compute_s": d["compute_s"],
            "forward_s": d["forward_s"], "backward_s": d["backward_s"],
            "comm_s": d["comm_s"],
            "reshard_s": d["reshard_s"], "collective_s": d["collective_s"],
            "overlap_s": d.get("overlap_s", 0.0),
            "grad_sync_s": d.get("grad_sync_s", 0.0),
            "param_gather_s": d.get("param_gather_s", 0.0),
            "sync_s": d["sync_s"],
            "comm_axis_id": d["comm_axis_id"],
            "memory_bytes": d["memory_bytes"],
        })
    report = {
        "kind": "strategy_report",
        "mode": mode,
        # where the applied plan came from (search|cache|checkpoint|
        # import|manual|default|broadcast — warmstart/ — or replan, a
        # live ffelastic re-plan mid-run; _plan_origin then keeps the
        # underlying source): a cache/checkpoint source means this
        # compile ran ZERO search evaluations for it
        "plan_source": getattr(model, "_plan_source", "none"),
        "mesh_axes": {k: int(v) for k, v in
                      getattr(model.mesh, "shape", {}).items()},
        "overlap_sync": bool(us.config.search_overlap_backward_update),
        # weight-update sharding (ZeRO / Xu et al.; FSDP stage 3): the
        # running stage (0 replicated | 2 sharded optimizer | 3 params
        # sharded at rest), how many shards, the grad RS+AG seconds
        # priced on the overlappable channel, and — stage 3 — the
        # just-in-time weight-gather seconds (each op's share is its
        # grad_sync_s / param_gather_s, inside its overlap_s when
        # overlapped — the makespan identity covers both via the same
        # per-axis occupancy bound as the ring traffic)
        "update_sharding": bool(upd.get("enabled")),
        "update_stage": int(upd.get("stage", 0)),
        "update_shards": int(upd.get("shards", 1)),
        "grad_sync_s": 0.0,  # filled from the op entries below
        "param_gather_s": 0.0,
        "total_predicted_s": makespan,
        "penalized_cost_s": chosen_cost,
        "peak_memory_bytes": mem,
        "sum_compute_s": float(sum(o["compute_s"] for o in ops)),
        "sum_comm_s": float(sum(o["comm_s"] for o in ops)),
        "comm_axis_names": axis_names,
        "ops": ops,
        "edges": [[s, d] for s, d in zip(src, dst)],
        "runner_ups": runner_ups,
        "runner_up_evals": flip_evals,
    }
    report["grad_sync_s"] = float(sum(o["grad_sync_s"] for o in ops))
    report["param_gather_s"] = float(
        sum(o["param_gather_s"] for o in ops))
    analysis = getattr(model, "_analysis", None)
    if analysis is not None:
        # ffcheck results (analysis/): the compile gate's findings ride
        # the report so run_doctor / CI can audit the plan's static
        # verification next to the makespan identity
        report["analysis"] = analysis.to_json()
    # ffsan state: whether the compiled step carries the numerics
    # probes, and the SPMD fingerprint-barrier verdict — run_doctor
    # --check gates on these next to the analysis section
    report["sanitize_numerics"] = bool(
        getattr(model.config, "sanitize_numerics", False))
    report["spmd_barrier"] = (
        getattr(model, "_spmd_barrier", None) or {}).get("status", "off")
    transition = getattr(model, "_transition", None)
    if transition is not None:
        # fftrans (analysis/transition.py): the verified + priced
        # TransitionPlan of the restore/migration this model went
        # through — predicted_s reproduces from the per-transfer entries
        # alone (verify_transition_total, the makespan-identity
        # treatment), which is the datapoint the re-planner's pay-off
        # rule consumes
        report["transition"] = transition
    origin = getattr(model, "_plan_origin", None)
    if origin is not None:
        report["plan_origin"] = origin
    decisions = getattr(model, "_elastic_decisions", None)
    if decisions:
        # ffelastic (elastic/): every re-plan decision this run took,
        # each carrying BOTH sides of the pay-off inequality
        # (lhs = predicted_migration_s × fidelity_ratio,
        #  rhs = benefit_s_per_step × horizon_steps) so run_doctor
        # --check can reproduce the migrate/decline call from the
        # report alone
        report["elastic"] = {
            "decisions": list(decisions),
            "migrations": sum(1 for d in decisions
                              if d.get("decision") == "migrated"),
        }
    disagg = getattr(model, "_serving_disagg", None)
    if disagg is not None:
        # disaggregated serving's KV handoff plane: every handoff's
        # measured-vs-predicted plus the distinct verified fftrans
        # transfer programs they reference — run_doctor --check
        # recomputes each program's predicted_s from its own transfer
        # entries (the same makespan-identity treatment the migration
        # transition gets)
        report["serving_disagg"] = disagg
    return report


def render_markdown(report: dict) -> str:
    """Human-readable twin of the JSON report."""
    lines = ["# Strategy explain report", ""]
    mesh = ", ".join(f"{k}={v}" for k, v in report["mesh_axes"].items())
    lines += [
        f"- mesh: `{mesh}`  ·  mode: {report['mode']}"
        f"  ·  plan source: {report.get('plan_source', 'none')}",
        f"- **predicted step makespan: "
        f"{report['total_predicted_s'] * 1e3:.3f} ms** "
        f"(Σcompute {report['sum_compute_s'] * 1e3:.3f} ms, "
        f"Σcomm {report['sum_comm_s'] * 1e3:.3f} ms)",
        f"- peak per-chip memory: "
        f"{report['peak_memory_bytes'] / 2**20:.1f} MiB",
    ]
    if report.get("analysis"):
        a = report["analysis"]
        lines.append(
            f"- static verification (ffcheck): {a['errors']} error(s), "
            f"{a['warnings']} warning(s) across "
            f"{', '.join(a['passes_run'])}")
    lines.append(
        f"- ffsan: sanitizer "
        f"{'ON' if report.get('sanitize_numerics') else 'off'}"
        f"  ·  SPMD barrier: {report.get('spmd_barrier', 'off')}")
    if report.get("transition"):
        t = report["transition"]
        ta = t.get("analysis") or {}
        wire = sum((t.get("bytes_on_wire") or {}).values())
        lines.append(
            f"- plan transition (fftrans): {len(t.get('transfers', []))} "
            f"transfer(s), predicted {t.get('predicted_s', 0.0) * 1e3:.3f}"
            f" ms"
            + (f" (measured {t['measured_s'] * 1e3:.3f} ms)"
               if t.get("measured_s") is not None else "")
            + f", {wire / 2**20:.2f} MiB on wire — "
            f"{ta.get('errors', '?')} error(s), "
            f"{ta.get('warnings', '?')} warning(s)")
    if report.get("elastic"):
        e = report["elastic"]
        decs = e.get("decisions", [])
        lines.append(
            f"- elastic (ffelastic): {len(decs)} re-plan decision(s), "
            f"{e.get('migrations', 0)} migration(s)")
        for d in decs:
            side = ""
            if d.get("lhs_s") is not None and d.get("rhs_s") is not None:
                side = (f" — pay-off {d['lhs_s'] * 1e3:.3f} ms vs "
                        f"{d['rhs_s'] * 1e3:.3f} ms")
            lines.append(
                f"  - step {d.get('step', '?')}: {d.get('trigger', '?')}"
                f" → {d.get('decision', '?')}{side}")
    if report.get("serving_disagg"):
        sd = report["serving_disagg"]
        s = sd.get("summary") or {}
        lines.append(
            f"- disaggregated serving: prefill "
            f"{sd.get('prefill_chips', '?')} / decode "
            f"{sd.get('decode_chips', '?')} chips, "
            f"{s.get('count', 0)} KV handoff(s) "
            f"({s.get('fully_cached', 0)} fully radix-cached), "
            f"predicted {s.get('predicted_s', 0.0) * 1e3:.3f} ms vs "
            f"measured {s.get('measured_s', 0.0) * 1e3:.3f} ms, "
            f"{len(sd.get('programs') or {})} verified transfer "
            f"program(s)")
    if report.get("update_sharding"):
        stage = report.get("update_stage", 2)
        lines.append(
            f"- weight-update sharding: stage {stage} — masters + "
            f"optimizer slots"
            + (" + weights-at-rest" if stage == 3 else "")
            + f" 1/{report.get('update_shards', 1)} per chip, grad RS"
            + ("" if stage == 3 else "+AG")
            + f" {report.get('grad_sync_s', 0.0) * 1e3:.3f} ms on the "
            f"overlappable channel")
        if stage == 3:
            lines.append(
                f"- param gather (ZeRO-3/FSDP): just-in-time per-layer "
                f"ring all-gather, "
                f"{report.get('param_gather_s', 0.0) * 1e3:.3f} ms "
                f"issued one layer ahead (fwd + bwd re-gather)")
    if report.get("profile"):
        p = report["profile"]
        lines += [
            "",
            "## Measured profile (ffscope)",
            "",
            f"- source: {p.get('source', '?')}  ·  step "
            f"{p.get('step', '?')}  ·  device time "
            f"{p.get('device_time_s', 0.0) * 1e3:.3f} ms  ·  attributed "
            f"{p.get('attributed_s', 0.0) * 1e3:.3f} ms "
            f"(parallelism x{p.get('parallelism', 1)}, "
            f"slop {p.get('slop', 0.0):.2f})",
            "",
            "| op | measured (ms) | fwd (ms) | bwd (ms) "
            "| predicted (ms) | fidelity |",
            "|---|---|---|---|---|---|",
        ]
        for o in sorted(p.get("ops", []),
                        key=lambda r: -r.get("measured_s", 0.0)):
            pred = o.get("predicted_s")
            fid = o.get("fidelity")
            lines.append(
                f"| {o['name']} | {o['measured_s'] * 1e3:.3f} "
                f"| {o.get('fwd_s', 0.0) * 1e3:.3f} "
                f"| {o.get('bwd_s', 0.0) * 1e3:.3f} "
                + (f"| {pred * 1e3:.3f} " if pred is not None else "| — ")
                + (f"| {fid:.2f} |" if fid is not None else "| — |"))
        if p.get("extras"):
            lines += ["", "runtime scopes: " + ", ".join(
                f"{k} {v * 1e3:.3f} ms"
                for k, v in sorted(p["extras"].items()))]
    lines += [
        "",
        "## Per-op attribution",
        "",
        "| op | type | config | seg | fwd+bwd (ms) | reshard (ms) "
        "| collective (ms) | sync (ms) | mem (MiB) |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    ranked = sorted(report["ops"],
                    key=lambda o: -(o["compute_s"] + o["comm_s"]))
    for o in ranked:
        lines.append(
            f"| {o['name']} | {o['op_type']} | {o['config']} "
            f"| {o['segment']} "
            f"| {o['compute_s'] * 1e3:.3f} "
            f"| {o['reshard_s'] * 1e3:.3f} "
            f"| {o['collective_s'] * 1e3:.3f} "
            f"| {o['sync_s'] * 1e3:.3f} "
            f"| {o['memory_bytes'] / 2**20:.1f} |")
    segs: dict[int, dict] = {}
    for o in report["ops"]:
        s = segs.setdefault(o["segment"], {"compute": 0.0, "comm": 0.0,
                                           "n": 0})
        s["compute"] += o["compute_s"]
        s["comm"] += o["comm_s"]
        s["n"] += 1
    lines += ["", "## Per-segment totals (bottleneck cuts)", "",
              "| segment | ops | compute (ms) | comm (ms) |",
              "|---|---|---|---|"]
    for k in sorted(segs):
        s = segs[k]
        lines.append(f"| {k} | {s['n']} | {s['compute'] * 1e3:.3f} "
                     f"| {s['comm'] * 1e3:.3f} |")
    lines += ["", "## Runner-up plans", ""]
    if report["runner_ups"]:
        lines += ["| plan | cost (ms) | lost by (ms) |", "|---|---|---|"]
        for r in report["runner_ups"]:
            lines.append(f"| {r['label']} | {r['cost_s'] * 1e3:.3f} "
                         f"| +{r['margin_s'] * 1e3:.3f} |")
        lines += ["",
                  f"({report['runner_up_evals']} single-flip candidates "
                  f"re-priced by the search evaluator)"]
    else:
        lines.append("(no alternative configurations on this mesh)")
    lines.append("")
    return "\n".join(lines)


def write_strategy_report(model, directory: str) -> Optional[dict]:
    """Build + persist strategy_report.{json,md} under `directory`.
    Returns the report dict, or None when the model has no graph yet."""
    if getattr(model, "graph", None) is None or model.mesh is None:
        return None
    report = build_strategy_report(model)
    os.makedirs(directory, exist_ok=True)
    jpath = os.path.join(directory, "strategy_report.json")
    tmp = jpath + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1)
    os.replace(tmp, jpath)
    with open(os.path.join(directory, "strategy_report.md"), "w") as f:
        f.write(render_markdown(report))
    return report


def rewrite_strategy_report(report: dict, directory: str) -> None:
    """Atomically rewrite strategy_report.{json,md} from an updated
    report dict (e.g. after ffscope attached a `profile` section)."""
    jpath = os.path.join(directory, "strategy_report.json")
    tmp = jpath + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1)
    os.replace(tmp, jpath)
    with open(os.path.join(directory, "strategy_report.md"), "w") as f:
        f.write(render_markdown(report))
