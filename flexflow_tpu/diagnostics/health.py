"""Run-health anomaly detection: a rule engine over per-step records.

Dapper's lesson (PAPERS.md): always-on structured diagnostics must be
cheap enough to leave enabled. Each rule sees the same per-step record the
telemetry session writes to metrics.jsonl (plus the loss value, fetched
only when diagnostics is on) and emits leveled alerts:

  nan_loss          loss went NaN/inf — the run is dead, say so at the step
                    it died, not at the end of the epoch
  step_spike        step time spiked vs its own EMA (compile storms,
                    straggler hosts, thermal throttling)
  data_wait_stall   sustained input-pipeline stall: data-wait fraction of
                    wall time above threshold (the host, not the device,
                    is the bottleneck)
  ckpt_stale        no committed checkpoint for too long — the data-loss
                    window (CheckFreq's metric) is growing silently

Alerts flow through telemetry/log.py (leveled, multihost-aware), land in
<telemetry-dir>/alerts.jsonl, and rules named in `abort_on` raise
HealthAbort instead of warning — fit stops with artifacts flushed.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Optional


class HealthAbort(RuntimeError):
    """Raised when a rule listed in `abort_on` fires: training must stop.
    Carries the alert so callers can render it."""

    def __init__(self, alert: "Alert"):
        super().__init__(alert.message)
        self.alert = alert


@dataclass
class Alert:
    """One leveled health alert (the alerts.jsonl record)."""

    rule: str
    level: str          # "warning" | "error"
    step: int
    message: str
    value: float = 0.0
    threshold: float = 0.0
    action: str = "warn"  # "warn" | "abort"
    # structured context (e.g. the sanitizer's nan_loss localization:
    # op / phase / at_step) — serialized only when present
    details: dict = field(default_factory=dict)

    def to_record(self) -> dict:
        rec = {
            "rule": self.rule, "level": self.level, "step": int(self.step),
            "message": self.message, "value": float(self.value),
            "threshold": float(self.threshold), "action": self.action,
        }
        if self.details:
            rec["details"] = dict(self.details)
        return rec


class Rule:
    """Base rule: observe per-step records, return an Alert or None.
    `fire_once` rules latch after their first alert (a dead run needs one
    nan_loss alert, not one per remaining step)."""

    name = "rule"
    fire_once = False

    def __init__(self):
        self._fired = False

    def check(self, rec: dict) -> Optional[Alert]:
        if self._fired and self.fire_once:
            return None
        alert = self._check(rec)
        if alert is not None:
            self._fired = True
        return alert

    def _check(self, rec: dict) -> Optional[Alert]:
        raise NotImplementedError


class NaNLossRule(Rule):
    """Loss is NaN or inf: the run is numerically dead. With
    --sanitize-numerics the step record additionally carries the
    sanitizer's localization (nonfinite_op/phase/step, sanitize.py) and
    the one alert — fire-once semantics unchanged — names the exact op
    and pass that produced the first non-finite tensor."""

    name = "nan_loss"
    fire_once = True

    def _check(self, rec):
        loss = rec.get("loss")
        if loss is None:
            return None
        loss = float(loss)
        if math.isfinite(loss):
            return None
        details = {}
        origin = ""
        op = rec.get("nonfinite_op")
        if op:
            phase = ("backward" if rec.get("nonfinite_phase") == "bwd"
                     else "forward")
            at = rec.get("nonfinite_step")
            origin = (f" — first non-finite tensor: {op} ({phase}) "
                      f"at step {at}")
            details = {"op": op,
                       "phase": rec.get("nonfinite_phase"),
                       "at_step": at}
        return Alert(
            rule=self.name, level="error", step=int(rec.get("step", 0)),
            message=(f"non-finite loss ({loss}) at step "
                     f"{rec.get('step', '?')} — the model diverged"
                     f"{origin}"),
            value=loss if math.isnan(loss) else math.inf,
            details=details)


class StepSpikeRule(Rule):
    """Step wall time spiked vs the run's own EMA. Warmup skips the first
    steps (step 1 carries the jit compile and is ALWAYS a spike)."""

    name = "step_spike"

    def __init__(self, factor: float = 3.0, warmup: int = 5,
                 ema_alpha: float = 0.2, cooldown: int = 10):
        super().__init__()
        self.factor = float(factor)
        self.warmup = int(warmup)
        self.ema_alpha = float(ema_alpha)
        self.cooldown = int(cooldown)
        self._ema: Optional[float] = None
        self._n = 0
        self._last_fire = -10**9

    def _check(self, rec):
        t = rec.get("step_time_s")
        if t is None or not math.isfinite(float(t)):
            return None
        t = float(t)
        self._n += 1
        if self._n <= self.warmup:
            # warmup steps (jit compile, cache cold) neither alert NOR
            # seed the EMA — a compile-sized first step would inflate the
            # baseline and mask real spikes for the rest of the run
            return None
        if self._ema is not None and t > self.factor * self._ema:
            # ANY over-threshold sample is excluded from the baseline —
            # including ones the cooldown keeps from alerting; folding a
            # suppressed spike into the EMA would inflate the baseline a
            # still-ongoing incident (or the next one) is judged against
            if self._n - self._last_fire > self.cooldown:
                self._last_fire = self._n
                return Alert(
                    rule=self.name, level="warning",
                    step=int(rec.get("step", 0)),
                    message=(f"step time spike: {t * 1e3:.1f} ms > "
                             f"{self.factor:.1f}× EMA "
                             f"{self._ema * 1e3:.1f} ms"),
                    value=t, threshold=self.factor * self._ema)
            return None
        a = self.ema_alpha
        self._ema = t if self._ema is None else (1 - a) * self._ema + a * t
        return None


class DataWaitStallRule(Rule):
    """Sustained input-pipeline stall: EMA of data_wait/step_time above
    `ratio` — the device is idle waiting for the host."""

    name = "data_wait_stall"

    def __init__(self, ratio: float = 0.5, warmup: int = 5,
                 ema_alpha: float = 0.2, cooldown: int = 50):
        super().__init__()
        self.ratio = float(ratio)
        self.warmup = int(warmup)
        self.ema_alpha = float(ema_alpha)
        self.cooldown = int(cooldown)
        self._ema: Optional[float] = None
        self._n = 0
        self._last_fire = -10**9

    def _check(self, rec):
        t = rec.get("step_time_s")
        w = rec.get("data_wait_s")
        if not t or w is None:
            return None
        frac = max(0.0, min(1.0, float(w) / float(t)))
        a = self.ema_alpha
        self._ema = (frac if self._ema is None
                     else (1 - a) * self._ema + a * frac)
        self._n += 1
        if (self._n > self.warmup and self._ema > self.ratio
                and self._n - self._last_fire > self.cooldown):
            self._last_fire = self._n
            return Alert(
                rule=self.name, level="warning",
                step=int(rec.get("step", 0)),
                message=(f"input pipeline stall: data-wait is "
                         f"{self._ema:.0%} of step time (EMA) > "
                         f"{self.ratio:.0%} — the host, not the device, "
                         f"is the bottleneck"),
                value=self._ema, threshold=self.ratio)
        return None


class CheckpointStalenessRule(Rule):
    """The newest committed checkpoint is older than `max_age_s`: the
    data-loss window is growing. Fed the commit clock via
    `note_commit` (the manager reads the resilience checkpointer)."""

    name = "ckpt_stale"

    def __init__(self, max_age_s: float = 600.0, cooldown_s: float = 60.0):
        super().__init__()
        self.max_age_s = float(max_age_s)
        self.cooldown_s = float(cooldown_s)
        self._last_commit_t: Optional[float] = None
        self._last_fire_t = -10**12

    def note_commit(self, t: Optional[float]):
        if t is not None:
            self._last_commit_t = float(t)

    def _check(self, rec):
        if self._last_commit_t is None:
            return None
        now = rec.get("t", time.time())
        age = now - self._last_commit_t
        if age <= self.max_age_s or now - self._last_fire_t < self.cooldown_s:
            return None
        self._last_fire_t = now
        return Alert(
            rule=self.name, level="warning",
            step=int(rec.get("step", 0)),
            message=(f"checkpoint staleness: last commit {age:.0f}s ago "
                     f"> {self.max_age_s:.0f}s — a preemption now loses "
                     f"that much work"),
            value=age, threshold=self.max_age_s)


def default_rules(config=None) -> list[Rule]:
    """The standard rule set. `ckpt_stale` is always present so
    `--health-abort-on ckpt_stale` validates regardless of whether THIS
    run checkpoints — the rule stays dormant until a commit clock is fed
    (note_commit), which only happens when checkpointing is on."""
    every_s = (getattr(config, "checkpoint_every_seconds", 0.0) or 0.0
               if config is not None else 0.0)
    # stale = several missed periods; default 10 min when the policy is
    # step-based (no wall-clock period to scale from)
    max_age = max(5 * every_s, 600.0) if every_s else 600.0
    return [NaNLossRule(), StepSpikeRule(), DataWaitStallRule(),
            CheckpointStalenessRule(max_age_s=max_age)]


class HealthMonitor:
    """Runs every rule over each per-step record; routes alerts to the
    caller-supplied sink (DiagnosticsManager writes alerts.jsonl + the
    leveled log + a trace instant) and raises HealthAbort for rules listed
    in `abort_on`."""

    def __init__(self, rules: Optional[list[Rule]] = None,
                 abort_on: tuple = (), sink=None):
        self.rules = rules if rules is not None else default_rules()
        self.sink = sink
        self.alerts: list[Alert] = []
        self.abort_on: frozenset = frozenset()
        self.set_abort_on(abort_on)

    def set_abort_on(self, abort_on) -> None:
        """Replace the abort set (validated against the running rules) —
        lets a later enable_diagnostics(abort_on=...) upgrade rules from
        warn to abort mid-setup instead of being silently dropped."""
        abort_on = frozenset(abort_on)
        unknown = abort_on - {r.name for r in self.rules}
        if unknown:
            raise ValueError(
                f"--health-abort-on names unknown rules {sorted(unknown)}; "
                f"known: {sorted(r.name for r in self.rules)}")
        self.abort_on = abort_on

    def rule(self, name: str) -> Optional[Rule]:
        return next((r for r in self.rules if r.name == name), None)

    def observe_step(self, rec: dict) -> list[Alert]:
        """Run all rules over one step record. Returns the alerts fired;
        raises HealthAbort (after sinking the alert) when an abort-listed
        rule fires."""
        fired = []
        for r in self.rules:
            alert = r.check(rec)
            if alert is None:
                continue
            if r.name in self.abort_on:
                alert.action = "abort"
                alert.level = "error"
            self.alerts.append(alert)
            fired.append(alert)
            if self.sink is not None:
                self.sink(alert)
            if alert.action == "abort":
                raise HealthAbort(alert)
        return fired
