"""DiagnosticsManager: one run's explain + drift + health, wired to fit.

Owned by FFModel (`--diagnostics` / `model.enable_diagnostics()` / the
keras Diagnostics callback). Lifecycle:

  compile end  → write strategy_report.{json,md}; stash the plan's
                 predicted makespan for the drift monitor
  each step    → health rules over the step record (loss included — the
                 scalar fetch happens only with diagnostics on), drift
                 monitor over measured device time
  fit end      → drain alerts; summary counts into the metrics log

All artifacts live in the telemetry session's directory:

  strategy_report.json / strategy_report.md
  alerts.jsonl          one JSON record per alert/advisory
"""

from __future__ import annotations

import os
from typing import Optional

from ..telemetry import log as fflog
from ..telemetry.recorder import MetricsRecorder
from .drift import DriftMonitor, make_recalibration_state
from .explain import write_strategy_report
from .health import HealthMonitor, default_rules


class DiagnosticsManager:
    def __init__(self, model, session, drift_threshold: float = 0.5,
                 abort_on: tuple = (), recalibrate: bool = False,
                 rules=None):
        self.model = model
        self.session = session
        self.directory = session.directory
        self.alerts_path = os.path.join(self.directory, "alerts.jsonl")
        self._alerts = MetricsRecorder(self.alerts_path)
        self.health = HealthMonitor(
            rules if rules is not None
            else default_rules(getattr(model, "config", None)),
            abort_on=tuple(abort_on), sink=self._sink_alert)
        self.drift_threshold = float(drift_threshold)
        self._recalibrate = bool(recalibrate)
        self.drift: Optional[DriftMonitor] = None
        self.report: Optional[dict] = None
        # elastic controller (elastic/controller.py), set by
        # ElasticController.attach_diagnostics: when present it is the
        # single consumer of drift advisories (on_step forwards them) and
        # the monitor's own recompile hook stays disarmed — one sustained
        # excursion, one trigger
        self.elastic = None

    # ------------------------------------------------------------ compile

    def on_compile(self):
        """Write the strategy explain report and arm the drift monitor
        with the chosen plan's predicted makespan."""
        from .. import telemetry

        with telemetry.span("diagnostics.explain"):
            self.report = write_strategy_report(self.model, self.directory)
        if self.report is None:
            return
        predicted = self.report["total_predicted_s"]
        self.model._predicted_step_s = predicted
        rs = (make_recalibration_state(self.model)
              if self._recalibrate and self.elastic is None else None)
        self.drift = DriftMonitor(predicted,
                                  threshold=self.drift_threshold,
                                  recompile_state=rs)
        telemetry.event(
            "strategy_report", path=os.path.join(
                self.directory, "strategy_report.json"),
            total_predicted_s=predicted,
            mode=self.report["mode"],
            runner_ups=len(self.report["runner_ups"]))
        fflog.info(
            "diagnostics: strategy report written to %s "
            "(predicted step makespan %.3f ms, mode=%s)",
            os.path.join(self.directory, "strategy_report.md"),
            predicted * 1e3, self.report["mode"])

    # ------------------------------------------------------------ steps

    def on_step(self, rec: dict):
        """One per-step record (the metrics.jsonl step schema + loss).
        Raises health.HealthAbort when an abort-listed rule fires."""
        # health first: a NaN-loss abort should not be preceded by a
        # drift advisory computed from the same broken step
        self.health.observe_step(rec)
        if self.drift is not None:
            dev = rec.get("device_time_s")
            if dev is not None:
                adv = self.drift.observe(int(rec.get("step", 0)),
                                         float(dev))
                if adv is not None:
                    self._alerts.record("advisory", **adv.to_record())
                    fflog.warning("diagnostics: %s", adv.message)
                    if self.elastic is not None:
                        self.elastic.on_advisory(adv)

    def on_profile(self, section: dict):
        """One profiled step's op-grain attribution (ffscope): annotate
        with the plan's predictions, persist as the report's `profile`
        section, feed the ffpulse registry, and let the drift monitor
        derive op-grain advisories (the targeted-recalibration
        trigger). Also the landing path for profiling.py's standalone
        per-op numbers — one schema, two sources."""
        from .. import telemetry
        from ..scope.attribution import annotate_with_predictions
        from .explain import rewrite_strategy_report

        if self.report is not None:
            annotate_with_predictions(section, self.report)
            self.report["profile"] = section
            rewrite_strategy_report(self.report, self.directory)
        for row in section.get("ops", []):
            if row.get("measured_s", 0.0) > 0:
                telemetry.observe("op_time_s", row["measured_s"],
                                  op=row["name"])
        telemetry.event("profile", step=section.get("step"),
                        source=section.get("source"),
                        attributed_s=section.get("attributed_s"),
                        device_time_s=section.get("device_time_s"))
        # op-grain drift only from in-situ (xplane) captures: standalone
        # kernels are timed unfused, so their fidelity says nothing
        # about the entries the running plan was priced with
        if self.drift is not None and section.get("source") == "xplane":
            for adv in self.drift.note_profile(section):
                self._alerts.record("advisory", **adv.to_record())
                fflog.warning("diagnostics: %s", adv.message)

    def note_checkpoint_commit(self, t: Optional[float]):
        rule = self.health.rule("ckpt_stale")
        if rule is not None:
            rule.note_commit(t)

    # ------------------------------------------------------------ alerts

    def _sink_alert(self, alert):
        from .. import telemetry

        self._alerts.record("alert", **alert.to_record())
        telemetry.instant(f"alert.{alert.rule}", step=alert.step,
                          level=alert.level)
        emit = fflog.error if alert.level == "error" else fflog.warning
        emit("diagnostics[%s]: %s", alert.rule, alert.message)

    # ------------------------------------------------------------ lifecycle

    def on_fit_end(self):
        """Summarize into the metrics log; alerts.jsonl stays open for a
        later fit() on the same model (close() finalizes)."""
        from .. import telemetry

        n_alerts = len(self.health.alerts)
        n_adv = len(self.drift.advisories) if self.drift else 0
        telemetry.event("diagnostics_summary", alerts=n_alerts,
                        drift_advisories=n_adv,
                        drift_error_ema=(self.drift.error_ema
                                         if self.drift else None))
        if n_alerts or n_adv:
            fflog.warning(
                "diagnostics: %d health alert(s), %d drift advisory/ies — "
                "see %s", n_alerts, n_adv, self.alerts_path)

    def close(self):
        self._alerts.close()
