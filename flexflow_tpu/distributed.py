"""Multi-host (multi-controller) support.

Reference: FlexFlow runs multi-node via one Legion process per node under
mpirun, with the top-level task control-replicated so every node executes the
same program (src/mapper/mapper.cc:291-306, MULTI-NODE.md) and the strategy
search pinned to GPU0 with its result serialized to all nodes
(GRAPH_OPTIMIZE_TASK → deserialize, model.cc:2830-2872).

TPU recast: multi-controller JAX. `initialize()` wraps
`jax.distributed.initialize` (the mpirun/gasnet bootstrap analog); after it,
`jax.devices()` spans all hosts and one global Mesh with a leading `dcn`
axis (machine.MULTIHOST_AXES) covers the fleet — collectives on `dcn` ride
the data-center network, inboard axes stay on ICI. The Unity search runs on
process 0 only and the winning plan is broadcast as a serialized Strategy
(`run_search_on_host0`), mirroring the reference's search-on-GPU0 +
serialize pattern; every process then applies the identical plan, keeping
the SPMD programs in lockstep.

Launch recipe (the MULTI-NODE.md analog): see MULTIHOST.md at the repo root.
"""

from __future__ import annotations

import json
from typing import Callable, Optional

import jax
import numpy as np


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
):
    """Bootstrap multi-controller JAX (the mpirun + GASNet-Ex bootstrap
    analog). On TPU pods all arguments are discovered from the environment;
    on CPU/GPU fleets pass them explicitly. Safe to call once per process,
    before any other JAX use."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)


def is_coordinator() -> bool:
    return jax.process_index() == 0


def barrier(name: str = "barrier"):
    """Fleet-wide synchronization point (no-op single-process). The
    resilience subsystem brackets its checkpoint commit with this: every
    process must finish serializing before host 0 renames the tmp dir (a
    commit racing a still-writing process would publish a torn snapshot),
    and no process may move on believing the checkpoint durable before the
    rename happened."""
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


_ERR_KEY = "__broadcast_error__"


def broadcast_json(payload: Optional[dict], max_bytes: int = 1 << 20) -> dict:
    """Broadcast a JSON-serializable dict from process 0 to all processes
    (the strategy-serialization hop of GRAPH_OPTIMIZE_TASK). Single-process
    runs return the payload unchanged. The payload is framed as
    [length u32][utf-8 bytes][zero padding] in a fixed-size u8 buffer so
    every process contributes an identically-shaped array.

    Coordinator-side failures (oversized payload, serialization error) are
    broadcast as a small error marker instead of raised before the
    collective — otherwise the other processes would block in
    broadcast_one_to_all forever; every process then raises the same
    RuntimeError in lockstep."""
    if jax.process_count() <= 1:
        assert payload is not None
        return payload
    from jax.experimental import multihost_utils

    buf = np.zeros(max_bytes, dtype=np.uint8)
    if is_coordinator():
        try:
            raw = json.dumps(payload).encode()
            if len(raw) + 4 > max_bytes:
                raise ValueError(
                    f"payload {len(raw)}B exceeds broadcast buffer "
                    f"{max_bytes}B — pass a larger max_bytes")
        except Exception as e:  # keep the fleet in lockstep
            raw = json.dumps({_ERR_KEY: f"{type(e).__name__}: {e}"}).encode()
            raw = raw[:max_bytes - 4]
        buf[:4] = np.frombuffer(
            np.uint32(len(raw)).tobytes(), dtype=np.uint8)
        buf[4:4 + len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    out = multihost_utils.broadcast_one_to_all(buf)
    n = int(np.frombuffer(bytes(out[:4]), dtype=np.uint32)[0])
    data = json.loads(bytes(out[4:4 + n]).decode())
    if isinstance(data, dict) and _ERR_KEY in data:
        # the marker already carries the origin (framing error here, or a
        # caller-supplied failure like run_search_on_host0's)
        raise RuntimeError(data[_ERR_KEY])
    return data


def gather_json(payload: dict, max_bytes: int = 1 << 20) -> list:
    """All-gather one JSON-serializable dict per process; every process
    returns the list ordered by process index (single-process: [payload]).
    Same fixed-buffer framing as `broadcast_json` so every process
    contributes an identically-shaped array. This is the collective under
    ffpulse's coordinator-side metrics merge: each process gathers local
    registry snapshots, then `telemetry.metrics.merge_snapshots` folds
    them bucket-wise on the coordinator. A per-process serialization
    failure becomes an empty frame ({}), never a hang."""
    if jax.process_count() <= 1:
        return [payload]
    from jax.experimental import multihost_utils

    buf = np.zeros(max_bytes, dtype=np.uint8)
    try:
        raw = json.dumps(payload).encode()
        if len(raw) + 4 > max_bytes:
            raise ValueError("payload too large")
    except Exception:
        raw = b"{}"
    buf[:4] = np.frombuffer(np.uint32(len(raw)).tobytes(), dtype=np.uint8)
    buf[4:4 + len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    gathered = multihost_utils.process_allgather(buf)
    out = []
    for row in np.asarray(gathered).reshape(jax.process_count(), -1):
        n = int(np.frombuffer(bytes(row[:4]), dtype=np.uint32)[0])
        out.append(json.loads(bytes(row[4:4 + n]).decode()))
    return out


def gather_merged_snapshot(session) -> dict:
    """Fleet-merged metrics snapshot: every process contributes its
    session's local snapshot (collective — all processes must call);
    the merged result is identical everywhere, coordinator typically
    writes it. Single-process = the local merge."""
    from .telemetry.metrics import merge_snapshots

    return merge_snapshots(gather_json(session.collect_snapshot()))


def run_search_on_host0(search_fn: Callable[[], "object"]) -> dict:
    """Run `search_fn` (returning a Strategy) on process 0 only; everyone
    receives the serialized plan. Avoids divergent plans when on-device
    calibration measurements differ across hosts — the reference pins the
    search task to GPU0 for the same reason (mapper.cc select_task_options).
    A search failure on process 0 is broadcast as an error marker so every
    process raises together instead of the fleet hanging in the collective.
    Returns the Strategy's overrides dict."""
    from .parallel.strategies import Strategy

    payload = None
    if jax.process_count() <= 1 or is_coordinator():
        try:
            payload = search_fn().to_json()
        except Exception as e:
            if jax.process_count() <= 1:
                raise
            payload = {_ERR_KEY: f"search failed on process 0: "
                       f"{type(e).__name__}: {e}"}
    # broadcast_json raises the error marker on every process in lockstep
    data = broadcast_json(payload)
    return Strategy.from_json(data).overrides
