"""ffelastic: drift/capacity-triggered live re-planning (docs/elastic.md).

The subsystem that turns the verification layers and the migration engine
into behavior: an ElasticController wired into fit (and the serving
engine's step loop) consumes DriftMonitor advisories and visible-device
capacity deltas, re-runs the Unity search online against recalibrated
measurements, gates the winner through the full compile-time verifier
stack (plan_source "replan"), prices the move with fftrans, and fires
migrate_state exactly when

    predicted_migration_s x fidelity_ratio < benefit_s_per_step x horizon

recording every decision (both sides of the inequality) as a `replan`
telemetry event, an `elastic` strategy-report section, and run_doctor
alerts.
"""

from .apply import PlanSnapshot, replan
from .controller import ElasticController
from .payoff import evaluate_payoff, load_fidelity, record_fidelity
from .triggers import CapacityDelta, CapacityWatcher

__all__ = [
    "CapacityDelta",
    "CapacityWatcher",
    "ElasticController",
    "PlanSnapshot",
    "evaluate_payoff",
    "load_fidelity",
    "record_fidelity",
    "replan",
]
