"""The apply half of elastic re-planning: recompile in place, price, gate,
then migrate or roll back — one step boundary, no restart.

`replan(model, ...)` is the controller's workhorse. It snapshots the live
plan + training state, recompiles the SAME FFModel object through the
normal compile pipeline (warm-start cache consulted first, host-0 search +
broadcast in multihost runs, the full ffcheck/ffsan/ffrules verifier gate
— the new plan is a first-class plan source, labeled `replan`), prices the
old→new move with fftrans, evaluates the payoff inequality, and either
executes `migrate_state` (bit-exact, verified) or restores the snapshot as
if nothing happened. Every path — migrated, declined, dry-run, failed —
appends a decision record carrying both sides of the inequality to
`model._elastic_decisions`, emits a `replan` telemetry event, and lands in
strategy_report.json's `elastic` section.

Telemetry note: `model.compile()` and `migrate_state` both deactivate the
process-wide telemetry sink in their finallys (they assume they own the
session window). A mid-fit replan runs INSIDE fit's window, so this module
re-activates the saved session after each of those calls — otherwise the
rest of the fit would silently stop recording.
"""

from __future__ import annotations

import copy
import time
from typing import Optional

from ..telemetry import log as fflog
from .payoff import evaluate_payoff, load_fidelity

# everything a compile writes on the model, plus the live training state
# migrate_state moves: enough for the snapshot to satisfy the `old` model
# contract of PlanSide.from_model / model_state_tree / migrate_state, and
# for restore() to make a declined replan invisible
_SNAP_ATTRS = (
    "graph", "mesh", "executor", "optimizer", "loss_type", "metrics",
    "label_spec",
    "_strategy", "_plan_source", "_plan_fingerprint", "_plan_record",
    "_update_sharding", "_search_result", "_replay_search", "_analysis",
    "_spmd_barrier", "_transition", "_predicted_step_s",
    "_params", "_state", "_opt_slots", "_step", "_counters", "_rng",
)


class PlanSnapshot:
    """Frozen capture of a compiled model's plan and live state.

    Quacks like a compiled FFModel for fftrans's PlanSide.from_model and
    resilience.migrate_state's `old` argument (attribute surface: mesh,
    graph, executor, config, _update_sharding, _plan_source, the live
    state leaves), and restores every captured attribute for the
    rollback path."""

    def __init__(self, model):
        self._model_config = model.config  # shared object, never replaced
        for a in _SNAP_ATTRS:
            setattr(self, a, getattr(model, a, None))
        # config is copied so the snapshot keeps the OLD mesh_axis_sizes
        # (PlanSide reads config.num_nodes / serve_kv_block_size off it)
        self.config = copy.copy(model.config)
        self._compiled = True

    def restore(self, model):
        """Put every captured attribute back on the model; the config
        object is shared, so only the field replan mutates is reset."""
        for a in _SNAP_ATTRS:
            setattr(model, a, getattr(self, a))
        model.config.mesh_axis_sizes = self.config.mesh_axis_sizes
        model._compiled = True


def _reset_plan_state(model):
    """Clear plan residue so _compile_impl runs a fresh plan decision
    (plan source branches key off these; a stale _plan_source would
    short-circuit the search)."""
    model._strategy = None
    model._plan_source = "none"
    model._plan_fingerprint = None
    model._plan_record = None
    model._search_result = None
    model._replay_search = None
    model._transition = None


def replan(model, *, step: int, trigger: str,
           horizon_steps: int, new_mesh_axes: Optional[tuple] = None,
           measured_ema_s: Optional[float] = None, dry_run: bool = False,
           forced: bool = False, extra: Optional[dict] = None) -> dict:
    """One full re-plan attempt at a step boundary; returns the decision
    record (also appended to `model._elastic_decisions`).

    decision ∈ migrated | declined | dry_run | failed. The payoff rule:
    migrate iff predicted_migration_s × fidelity_ratio <
    benefit_s_per_step × horizon_steps, where benefit is the measured
    step-time EMA (falling back to the old plan's prediction) minus the
    new plan's predicted makespan. `forced` (capacity shrink) records
    the inequality but migrates regardless — the compiled mesh no
    longer exists. Declined/dry-run/failed paths restore the snapshot
    bit-exactly."""
    from .. import telemetry
    from ..analysis import transition as fftrans
    from ..diagnostics.drift import recalibrate_model
    from ..resilience.migrate import migrate_state

    session = telemetry.active_session()
    t0 = time.perf_counter()
    decision: dict = {
        "step": int(step), "trigger": str(trigger),
        "dry_run": bool(dry_run),
    }
    if extra:
        decision.update(extra)
    snap = PlanSnapshot(model)
    decision["old_mesh_axes"] = {k: int(v)
                                 for k, v in snap.mesh.shape.items()}
    decision["old_predicted_step_s"] = snap._predicted_step_s
    decision["measured_ema_s"] = measured_ema_s
    migrated = False
    rolled_back = False
    try:
        with telemetry.span("elastic.replan", trigger=trigger, step=step):
            if trigger == "drift":
                # the monitor fired BECAUSE the calibration no longer
                # describes the device: refresh it (and the warm-start
                # DB, coordinator-only) so the re-search prices real
                # costs — and so the plan-cache fingerprint moves off
                # the stale entries
                recalibrate_model(model)
            t_search0 = time.perf_counter()
            _reset_plan_state(model)
            if new_mesh_axes is not None:
                model.config.mesh_axis_sizes = tuple(new_mesh_axes)
            # relabel the recompile's outcome as plan_source "replan"
            # (the underlying origin — search/cache/broadcast — rides
            # the decision record as plan_origin)
            model._plan_source_hint = "replan"
            model.compile(
                optimizer=snap.optimizer, loss_type=snap.loss_type,
                metrics=getattr(model, "_metrics_arg", ()) or (),
                comp_mode=model.config.computation_mode)
        if session is not None:
            telemetry.activate(session)  # compile() deactivated it
        decision["research_s"] = time.perf_counter() - t_search0
        decision["plan_origin"] = getattr(model, "_plan_origin", None)
        decision["new_mesh_axes"] = {
            k: int(v) for k, v in model.mesh.shape.items()}
        decision["new_predicted_step_s"] = model._predicted_step_s
        plan = fftrans.plan_model_transition(snap, model)
        ratio, nsamples = load_fidelity(model)
        baseline = (float(measured_ema_s) if measured_ema_s
                    else float(snap._predicted_step_s or 0.0))
        benefit = max(0.0, baseline - float(model._predicted_step_s or 0.0))
        decision.update(evaluate_payoff(
            predicted_migration_s=plan.predicted_s, fidelity_ratio=ratio,
            benefit_s_per_step=benefit, horizon_steps=horizon_steps,
            forced=forced))
        decision["fidelity_samples"] = nsamples
        if (decision["would_migrate"] or forced) and not dry_run:
            # gate_transition runs inside migrate_state; a verification
            # failure raises and rolls back below
            migrate_state(snap, model, plan=plan)
            if session is not None:
                telemetry.activate(session)  # migrate_state deactivated it
            migrated = True
            decision["decision"] = "migrated"
            decision["migration_measured_s"] = (
                model._transition or {}).get("measured_s")
        else:
            decision["decision"] = "dry_run" if dry_run else "declined"
            snap.restore(model)
            rolled_back = True
    except Exception as e:
        snap.restore(model)
        rolled_back = True
        if session is not None:
            telemetry.activate(session)
        decision["decision"] = "failed"
        decision["error"] = f"{type(e).__name__}: {e}"
        fflog.error("elastic: replan failed (%s) — rolled back to the "
                    "running plan: %s", trigger, decision["error"])
    decision["total_s"] = time.perf_counter() - t0
    if not hasattr(model, "_elastic_decisions"):
        model._elastic_decisions = []
    model._elastic_decisions.append(decision)
    _finalize_artifacts(model, decision, rolled_back=rolled_back)
    return decision


def _finalize_artifacts(model, decision: dict, *, rolled_back: bool):
    """Record the decision everywhere run_doctor looks: a `replan`
    telemetry event, an alert record, and a strategy_report rewrite so
    the `elastic` section includes this decision (on rollback, the
    report also reverts to the restored plan and the drift monitor
    re-arms at its prediction)."""
    from .. import telemetry

    if telemetry.active_session() is not None:
        telemetry.inc("elastic_replan_decisions_total",
                      decision=str(decision.get("decision", "unknown")),
                      trigger=str(decision.get("trigger", "unknown")))
        if decision.get("research_s") is not None:
            telemetry.observe("elastic_research_s",
                              decision["research_s"])
        telemetry.event("replan", **decision)
    else:
        # direct replan() call outside a fit window: land the event in
        # the model's own session so run_doctor still sees it
        tel = getattr(model, "_telemetry", None)
        if tel is not None:
            tel.recorder.record("replan", **decision)
    diag = getattr(model, "_diagnostics", None)
    if diag is not None:
        msg = (f"elastic {decision['trigger']} trigger at step "
               f"{decision['step']}: {decision['decision']}"
               + (f" (lhs {decision['lhs_s'] * 1e3:.3f} ms vs rhs "
                  f"{decision['rhs_s'] * 1e3:.3f} ms)"
                  if "lhs_s" in decision else "")
               + (f" [{decision['error']}]"
                  if "error" in decision else ""))
        diag._alerts.record(
            "alert", rule="elastic_replan", level="warning",
            step=decision["step"], action=decision["decision"],
            message=msg)
        fflog.warning("diagnostics[elastic_replan]: %s", msg)
    if rolled_back:
        if diag is not None:
            # rewrite the report for the RESTORED plan (elastic section
            # included) and re-arm the drift monitor at its prediction
            diag.on_compile()
    else:
        session = getattr(model, "_telemetry", None)
        if session is not None:
            from ..diagnostics.explain import write_strategy_report

            try:
                write_strategy_report(model, session.directory)
            except Exception:  # pragma: no cover - report best-effort
                pass
