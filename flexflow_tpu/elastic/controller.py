"""ElasticController: drift/capacity-triggered live re-planning.

Closes the elastic loop the previous subsystems left open (ROADMAP item
1): the drift monitor (diagnostics/drift.py) detects when the cost model
no longer describes the device, warm start (warmstart/) makes an online
re-search cheap, and fftrans (analysis/transition.py +
resilience/migrate.py) makes any plan→plan move verified, priced, and
executable in-process — this controller decides WHEN to use them.
Payoff-gated live reconfiguration follows Gemini (Wang et al., SOSP '23,
PAPERS.md: reconfigure only when the modeled benefit over the remaining
horizon exceeds the modeled cost of moving), with the re-search run as a
fresh Unity joint optimization against recalibrated measurements (Unity,
OSDI '22).

Wiring: `FFModel.fit` calls `maybe_replan(step)` after each eager step
(the pipelined engine calls it at chunk boundaries; the serving engine
polls capacity between decode steps). Trigger streams:

- drift: the DiagnosticsManager forwards DriftMonitor advisories here
  (satellite dedupe: when a controller is attached the manager does NOT
  arm the monitor's own recompile hook, so one sustained excursion
  produces exactly one trigger — the monitor's re-arm at threshold/2
  stays the single source of hysteresis);
- capacity: CapacityWatcher compares the visible device set against the
  compiled mesh.

A step-count cooldown (`--replan-cooldown-steps`) spaces consecutive
re-plan attempts so the loop never flaps; a capacity SHRINK bypasses it
(the compiled mesh no longer physically exists). `--elastic-dry-run`
runs the full trigger → search → gate → price pipeline and records the
decision, but never migrates.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

from ..telemetry import log as fflog
from .apply import replan
from .triggers import CapacityDelta, CapacityWatcher


class ElasticController:
    def __init__(self, model, diag=None, *,
                 cooldown_steps: Optional[int] = None,
                 horizon_steps: Optional[int] = None,
                 dry_run: Optional[bool] = None,
                 visible_devices_fn: Optional[Callable[[], Sequence]] = None,
                 capacity_check_every: int = 8):
        cfg = model.config
        self.model = model
        self.diag = None
        self.cooldown_steps = int(
            cfg.replan_cooldown_steps if cooldown_steps is None
            else cooldown_steps)
        self.horizon_steps = int(
            cfg.replan_horizon_steps if horizon_steps is None
            else horizon_steps)
        self.dry_run = bool(
            cfg.elastic_dry_run if dry_run is None else dry_run)
        self.watcher = CapacityWatcher(
            model, visible_devices_fn, check_every=capacity_check_every)
        self._pending = None  # latest un-consumed DriftAdvisory
        # cooldown anchor: the step of the last re-plan ATTEMPT (any
        # outcome — a declined search is as expensive as a migrated one)
        self._anchor_step = int(model._py_step()) if getattr(
            model, "_compiled", False) else 0
        if not hasattr(model, "_elastic_decisions"):
            model._elastic_decisions = []
        self.decisions = model._elastic_decisions
        if diag is not None:
            self.attach_diagnostics(diag)

    # ------------------------------------------------------------ triggers

    def attach_diagnostics(self, diag):
        """Wire the drift stream: the manager forwards advisories here,
        and the monitor's own recompile hook is disarmed so one excursion
        yields one trigger (the controller replaces it as the drift
        response; recalibration runs inside the replan instead)."""
        self.diag = diag
        diag.elastic = self
        if diag.drift is not None:
            diag.drift.recompile_state = None

    def on_advisory(self, adv):
        """One DriftAdvisory from the monitor (hysteresis already
        applied there). Kept pending until the next maybe_replan call;
        advisories landing inside the cooldown are dropped."""
        if self._in_cooldown(int(adv.step)):
            fflog.debug("elastic: drift advisory at step %d dropped "
                        "(cooldown)", adv.step)
            return
        self._pending = adv

    def _in_cooldown(self, step: int) -> bool:
        return (step - self._anchor_step) < self.cooldown_steps

    def _measured_ema(self) -> Optional[float]:
        if self.diag is not None and self.diag.drift is not None:
            return self.diag.drift.measured_ema
        return None

    # ------------------------------------------------------------ decide

    def maybe_replan(self, step: int) -> bool:
        """The fit-loop hook: consume pending triggers and re-plan when
        warranted. Returns True when a migration happened (the caller's
        captured step function is stale and must be rebuilt from
        model.executor)."""
        step = int(step)
        adv, self._pending = self._pending, None
        cap = self.watcher.check(step)
        if cap is not None and cap.shrink:
            # forced: devices vanished from under the compiled mesh —
            # cooldown cannot apply, the old plan cannot run
            return self._on_capacity(step, cap)
        if self._in_cooldown(step):
            return False
        if cap is not None:
            return self._on_capacity(step, cap)
        if adv is not None:
            return self._on_drift(step, adv)
        return False

    def _on_drift(self, step: int, adv) -> bool:
        self._anchor_step = step
        d = replan(
            self.model, step=step, trigger="drift",
            horizon_steps=self.horizon_steps,
            measured_ema_s=adv.measured_ema_s, dry_run=self.dry_run,
            extra={"advisory": adv.to_record()})
        return d.get("decision") == "migrated"

    def _on_capacity(self, step: int, cap: CapacityDelta) -> bool:
        from .. import telemetry

        self._anchor_step = step
        if cap.new_axes is None:
            # visible count undividable by the fixed mesh axes (or a
            # multi-host mesh): record the decline — no search ran, so
            # the record carries no payoff sides
            decision = {
                "step": step, "trigger": "capacity",
                "decision": "declined", "dry_run": self.dry_run,
                "capacity": cap.to_record(),
                "reason": "no mesh factorization for visible device set",
            }
            self.decisions.append(decision)
            telemetry.inc("elastic_replan_decisions_total",
                          decision="declined", trigger="capacity")
            telemetry.event("replan", **decision)
            if self.diag is not None:
                self.diag._alerts.record(
                    "alert", rule="elastic_replan", level="warning",
                    step=step, action="declined",
                    message=(f"capacity delta ({cap.compiled} -> "
                             f"{cap.visible} devices) but no mesh "
                             f"factorization fits — staying put"))
            return False
        d = replan(
            self.model, step=step, trigger="capacity",
            horizon_steps=self.horizon_steps,
            new_mesh_axes=cap.new_axes,
            measured_ema_s=self._measured_ema(), dry_run=self.dry_run,
            forced=cap.shrink, extra={"capacity": cap.to_record()})
        return d.get("decision") == "migrated"
