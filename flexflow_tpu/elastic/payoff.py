"""Payoff rule + migration-fidelity calibration for elastic re-planning.

The controller migrates exactly when

    predicted_migration_s x fidelity_ratio  <  benefit_s_per_step x horizon

The left side is fftrans's statically priced TransitionPlan seconds scaled
by an online-calibrated *fidelity ratio* (measured / predicted migration
seconds): the transition cost model prices wire bytes and gather work, but
a real `migrate_state` also pays per-leaf dispatch overhead the static
price cannot see — the r18 bench `migration` leg measured ~45x on a CPU
mesh. Each completed migration feeds its own measured/predicted ratio back
in (EMA), and the ratio persists in the warm-start calibration DB under a
reserved per-device-kind key so it survives restarts instead of resetting
to the bench default every run (the same reserved-key idiom as the
collective-hop entries, cost_model._collective_key).
"""

from __future__ import annotations

from typing import Optional

from ..telemetry import log as fflog

# reserved calibration-DB key (never produced by _params_key: no real op
# carries this params repr). Value is stored in the [fwd, bwd] slots as
# [fidelity_ratio, sample_count].
_FIDELITY_PARAMS = "__migration_fidelity__"
_FIDELITY_SHAPES = ((1,),)

DEFAULT_FIDELITY = 1.0
_EMA_ALPHA = 0.5  # migrations are rare; weight fresh measurements heavily


def _fidelity_key():
    from ..fftype import OperatorType as OT

    return (OT.OP_NOOP, _FIDELITY_PARAMS, _FIDELITY_SHAPES)


def _calibration_db(model):
    warm = getattr(model, "_warmstart", None)
    if warm is not None:
        return warm.calibration_db
    directory = getattr(model.config, "warmstart_dir", "")
    if directory:
        from ..warmstart.calibration_db import CalibrationDB

        return CalibrationDB(directory)
    return None


def load_fidelity(model) -> tuple[float, int]:
    """The model's current (fidelity_ratio, samples): the in-process EMA
    when a migration already ran this process, else the persisted DB entry
    for this device kind, else (DEFAULT_FIDELITY, 0)."""
    mem = getattr(model, "_migration_fidelity", None)
    if mem is not None:
        return float(mem[0]), int(mem[1])
    db = _calibration_db(model)
    if db is not None:
        from ..warmstart.calibration_db import device_key, serialize_key

        entry = (db._read().get("devices", {}).get(device_key(), {})
                 .get(serialize_key(_fidelity_key())))
        if entry is not None:
            try:
                ratio, samples = float(entry[0]), int(entry[1])
                if ratio > 0:
                    model._migration_fidelity = (ratio, samples)
                    return ratio, samples
            except (TypeError, ValueError, IndexError):
                pass
    return DEFAULT_FIDELITY, 0


def record_fidelity(model, ratio: float) -> tuple[float, int]:
    """Fold one migration's measured/predicted ratio into the model's
    fidelity EMA and persist it (coordinator-only, fail-soft — a
    calibration write must never fail a migration). Returns the updated
    (ratio, samples)."""
    ratio = float(ratio)
    if not (ratio > 0):
        return load_fidelity(model)
    cur, samples = load_fidelity(model)
    if samples == 0:
        updated = ratio
    else:
        updated = (1 - _EMA_ALPHA) * cur + _EMA_ALPHA * ratio
    model._migration_fidelity = (updated, samples + 1)
    try:
        db = _calibration_db(model)
        if db is not None:
            from ..distributed import is_coordinator

            if is_coordinator():
                import types

                shim = types.SimpleNamespace(_calibration={
                    _fidelity_key(): (updated, float(samples + 1))})
                db.save_from(shim)
    except Exception as e:  # pragma: no cover - persistence is best-effort
        fflog.warning("elastic: could not persist migration fidelity: %s", e)
    return model._migration_fidelity


def evaluate_payoff(*, predicted_migration_s: float, fidelity_ratio: float,
                    benefit_s_per_step: float, horizon_steps: int,
                    forced: bool = False) -> dict:
    """Both sides of the payoff inequality, as the decision record carries
    them (run_doctor --check recomputes lhs/rhs from the factors and
    requires them to reproduce). `forced` (capacity shrink: the compiled
    mesh no longer exists) records the inequality without letting it
    gate."""
    lhs = float(predicted_migration_s) * float(fidelity_ratio)
    rhs = float(benefit_s_per_step) * int(horizon_steps)
    return {
        "predicted_migration_s": float(predicted_migration_s),
        "fidelity_ratio": float(fidelity_ratio),
        "benefit_s_per_step": float(benefit_s_per_step),
        "horizon_steps": int(horizon_steps),
        "lhs_s": lhs,
        "rhs_s": rhs,
        "would_migrate": bool(lhs < rhs),
        "forced": bool(forced),
    }
