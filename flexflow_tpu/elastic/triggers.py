"""Trigger streams for the elastic controller.

Two triggers feed `ElasticController.maybe_replan`:

- **drift** — sustained cost-model drift. The DriftMonitor already owns
  the hysteresis (advisory once per excursion, re-arm at threshold/2);
  the DiagnosticsManager forwards each advisory here instead of firing
  its own recompile hook, so one excursion produces ONE trigger.
- **capacity** — a delta between the visible device set and the compiled
  mesh (chips preempted away, or restored). `CapacityWatcher` polls the
  visible set (injectable for tests) every `check_every` controller
  calls and proposes a new mesh factorization by rescaling the data
  axis; a visible count the fixed model/pipe/seq axes cannot divide is
  reported with `new_axes=None` so the controller records a declined
  decision instead of compiling an impossible mesh.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence


@dataclass
class CapacityDelta:
    """One observed visible-vs-compiled device-set delta."""

    step: int
    visible: int            # devices visible now
    compiled: int           # devices in the compiled mesh
    new_axes: Optional[tuple]  # proposed mesh_axis_sizes (None: undividable)
    shrink: bool            # visible < compiled → forced migration

    def to_record(self) -> dict:
        return {
            "step": int(self.step), "visible": int(self.visible),
            "compiled": int(self.compiled),
            "new_axes": (list(self.new_axes)
                         if self.new_axes is not None else None),
            "shrink": bool(self.shrink),
        }


class CapacityWatcher:
    """Detects grow/shrink of the visible device set vs the compiled
    mesh. Stateless between checks except the poll cadence — the
    controller's cooldown owns anti-flap pacing for grows (a shrink is
    forced: the compiled mesh no longer physically exists)."""

    def __init__(self, model,
                 visible_devices_fn: Optional[Callable[[], Sequence]] = None,
                 check_every: int = 8):
        import jax

        self.model = model
        self._visible_fn = visible_devices_fn or jax.devices
        self.check_every = max(1, int(check_every))
        self._calls = 0

    def propose_axes(self, visible: int) -> Optional[tuple]:
        """mesh_axis_sizes for `visible` devices: rescale the data axis,
        keep every other axis fixed. None when the fixed axes don't
        divide the visible count (or the mesh is multi-host — capacity
        moves are single-controller scope, like serving)."""
        from ..machine import AXIS_DATA

        cfg = self.model.config
        if getattr(cfg, "num_nodes", 1) > 1:
            return None
        ms = cfg.mesh_shape()
        # the COMPILED mesh's sizes, in the config's axis order (a
        # mesh-shape search may have replaced the configured sizes)
        compiled = dict(self.model.mesh.shape)
        sizes = [int(compiled.get(a, s))
                 for a, s in zip(ms.axis_names, ms.axis_sizes)]
        if AXIS_DATA not in ms.axis_names:
            return None
        di = ms.axis_names.index(AXIS_DATA)
        fixed = 1
        for i, s in enumerate(sizes):
            if i != di:
                fixed *= s
        if visible < fixed or visible % fixed:
            return None
        sizes[di] = visible // fixed
        return tuple(sizes)

    def check(self, step: int) -> Optional[CapacityDelta]:
        """Poll the visible device set (every check_every-th call);
        returns a CapacityDelta when it no longer matches the compiled
        mesh."""
        self._calls += 1
        if (self._calls - 1) % self.check_every:
            return None
        try:
            visible = len(self._visible_fn())
        except Exception:
            return None
        compiled = int(self.model.mesh.devices.size)
        if visible == compiled:
            return None
        return CapacityDelta(
            step=int(step), visible=visible, compiled=compiled,
            new_axes=self.propose_axes(visible),
            shrink=visible < compiled)
