"""Pipelined execution engine (docs/performance.md).

`FFModel.fit` routes through PipelinedEngine when `--pipeline-steps N`
(or `fit(..., pipeline_steps=N)`) is > 1: chunks of N train steps run as
one donated `lax.scan` dispatch over batches a background thread staged
onto the mesh ahead of time, with per-step telemetry/diagnostics
reconstructed at chunk boundaries. Default stays the eager per-step
loop (`pipeline_steps=1`), which is bit-identical by construction.
"""

from .chunking import plan_chunks
from .pipelined import PipelinedEngine
from .prefetch import ChunkPrefetcher, PrefetchExhausted

__all__ = [
    "PipelinedEngine", "ChunkPrefetcher", "PrefetchExhausted",
    "plan_chunks",
]
