"""Chunk planning for the pipelined execution engine.

A chunk is a run of consecutive batches executed as ONE fused device
dispatch (`Executor.build_chunked_train_step`). Chunks are sub-epoch:
they never straddle an epoch boundary, so shuffle orders, RNG splits,
and step counters line up exactly with the eager loop's — the epoch is
simply covered by `ceil((num_batches - b0) / pipeline_steps)` dispatches
instead of `num_batches - b0`.

Checkpoint/preemption decisions happen only at chunk edges; the resume
cursor therefore always lands on one (docs/performance.md).
"""

from __future__ import annotations


def plan_chunks(b0: int, num_batches: int,
                pipeline_steps: int) -> list[tuple[int, int]]:
    """Cover batches [b0, num_batches) with chunks of up to
    `pipeline_steps` steps. Returns [(start_batch, n_steps), ...]; the
    final chunk absorbs the remainder (a shorter chunk costs one extra
    compile per distinct size, cached by the executor)."""
    if pipeline_steps < 1:
        raise ValueError(f"pipeline_steps must be >= 1, got {pipeline_steps}")
    if b0 < 0:
        raise ValueError(f"b0 must be >= 0, got {b0}")
    chunks = []
    b = b0
    while b < num_batches:
        n = min(pipeline_steps, num_batches - b)
        chunks.append((b, n))
        b += n
    return chunks
