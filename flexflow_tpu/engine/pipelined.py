"""Pipelined execution engine: fused multi-step dispatch for FFModel.fit.

PERF.md round 5 measured the gap this closes: the eager per-step fit loop
pays ~0.2-1.5 ms of per-dispatch overhead plus a synchronous host slice +
`device_put` inside every step window, while bench.py's single fused scan
loop (the TPU-native analog of the reference's Legion trace replay,
PAPER.md §3) runs the same math at full device throughput. The engine
brings `fit` onto the fused path without changing its semantics:

  - **fused multi-step dispatch** — chunks of `pipeline_steps` train steps
    compiled as one donated `lax.scan` over pre-staged batches
    (Executor.build_chunked_train_step). Chunks are sub-epoch, the RNG
    split sequence and step counters are identical to the eager loop's,
    and the per-step loss rides out of the scan as a vector — training is
    bit-identical to `pipeline_steps=1` (tested).
  - **async input pipeline** — a ChunkPrefetcher thread slices the next
    chunk's batches on host and `device_put`s them with the input's
    NamedSharding while the current chunk runs on device; `data_wait`
    collapses to a queue pop (Daydream's overlap what-if, PAPERS.md).
  - **deferred metrics/health sync** — ONE device fetch per chunk (the
    loss vector) replaces the per-step sync; telemetry gets per-step
    records reconstructed from the chunk window (device time attributed
    as chunk/N), and the diagnostics NaN/spike/drift rules evaluate per
    step from the fetched vector.

Periodic work (checkpoints, preemption drain, fault hooks) runs at chunk
boundaries only — CheckFreq's cadence riding along without giving the
overlap back — so the resume cursor always lands on a chunk edge.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .. import telemetry
from .chunking import plan_chunks
from .prefetch import ChunkPrefetcher


@partial(jax.jit, static_argnums=1)
def _split_chunk_rngs(rng, n: int):
    """The eager loop's per-step `rng, sub = jax.random.split(rng)`
    sequence, batched into one dispatch: returns (advanced rng, stacked
    subs) with bit-identical keys."""

    def body(r, _):
        r, sub = jax.random.split(r)
        return r, sub

    return jax.lax.scan(body, rng, None, length=n)


class PipelinedEngine:
    """Drives one model's fit epochs in fused chunks. Constructed per fit
    call (cheap: the chunked executables live in the executor's cache)."""

    def __init__(self, model, pipeline_steps: int, prefetch_depth: int = 2):
        if pipeline_steps < 2:
            raise ValueError(
                f"PipelinedEngine needs pipeline_steps >= 2, got "
                f"{pipeline_steps} (use the eager loop for 1)")
        self.model = model
        self.pipeline_steps = int(pipeline_steps)
        self.prefetch_depth = int(prefetch_depth)
        # input/label shardings resolved ONCE per name (the eager path's
        # per-batch graph.sources() scan, hoisted — via the same
        # model._input_partition_spec the eager loop uses, so placement
        # matches it exactly: unmatched names go mesh-REPLICATED, not to
        # the default device). Leading None is the chunk's scan axis —
        # batches stack along it unsharded.
        self._input_shardings: dict = {}
        self._mesh = model.mesh
        self._label_sharding = NamedSharding(
            model.mesh, PartitionSpec(None, *model.label_spec))

    def _sync_mesh(self):
        """Rebuild the cached shardings when an elastic re-plan swapped
        the model's mesh (staged inputs place onto the mesh the NEXT
        chunk's executable runs on, which is no longer the one these
        caches were resolved against)."""
        if self.model.mesh is not self._mesh:
            self._mesh = self.model.mesh
            self._input_shardings.clear()
            self._label_sharding = NamedSharding(
                self._mesh,
                PartitionSpec(None, *self.model.label_spec))

    def _sharding_for(self, name: str) -> NamedSharding:
        sh = self._input_shardings.get(name)
        if sh is None:
            spec = self.model._input_partition_spec(name)
            sh = NamedSharding(
                self.model.mesh,
                PartitionSpec(None, *spec) if spec is not None
                else PartitionSpec())
            self._input_shardings[name] = sh
        return sh

    # ------------------------------------------------------------ staging

    def _stage_chunk(self, x_dict: dict, y, order, start_b: int, n: int,
                     batch_size: int):
        """Host work for one chunk (runs on the prefetch thread): gather
        the chunk's samples in epoch order, stack per-step batches along
        the scan axis, and place them on the mesh."""
        with telemetry.span("prefetch.stage", steps=n, start_batch=start_b):
            lo = start_b * batch_size
            idx = order[lo: lo + n * batch_size]
            xs = {}
            for name, v in x_dict.items():
                arr = v[idx].reshape((n, batch_size) + v.shape[1:])
                xs[name] = jax.device_put(arr, self._sharding_for(name))
            yb = y[idx].reshape((n, batch_size) + y.shape[1:])
            return xs, jax.device_put(yb, self._label_sharding)

    # ------------------------------------------------------------ epoch

    def run_epoch(self, *, x_dict: dict, y, order, b0: int,
                  num_batches: int, batch_size: int, abs_e: int,
                  py_step: int, tel, diag, resil, preempt, fault_hook,
                  tokens_per_example: int) -> tuple[int, bool]:
        """Run batches [b0, num_batches) of one epoch in fused chunks.
        Mutates the model's training state in place (exactly like the
        eager loop) and returns (py_step, preempted). HealthAbort and
        SimulatedPreemption propagate to fit's handlers; the prefetch
        thread is shut down on every exit path."""
        model = self.model
        self._sync_mesh()  # an elastic re-plan may have swapped the mesh
        chunks = plan_chunks(b0, num_batches, self.pipeline_steps)
        if not chunks:
            return py_step, False
        stage = (lambda c: self._stage_chunk(
            x_dict, y, order, c[0], c[1], batch_size))
        prefetcher = ChunkPrefetcher(
            stage, chunks, depth=self.prefetch_depth)
        # the loss vector is fetched once per chunk only when something
        # consumes it (telemetry timing sync + diagnostics rules, both
        # synthesized under tel); a bare fit dispatches chunks
        # back-to-back with no host sync at all
        need_losses = tel is not None
        preempted = False
        pending = list(chunks)
        try:
            while pending:
                start_b, n = pending[0]
                t_chunk0 = time.perf_counter()
                staged = prefetcher.get()
                t_pop1 = time.perf_counter()
                # a cache miss means THIS chunk's wall time includes the
                # executable compile — its synthesized records must not
                # feed the timing-based health/drift rules (the eager
                # loop's step-1 compile is excluded by their warmup; a
                # tail-chunk compile mid-run would not be)
                compiled_now = n not in model.executor._chunk_steps
                chunk_fn = model.executor.build_chunked_train_step(n)
                model._rng, rngs = _split_chunk_rngs(model._rng, n)
                with telemetry.span("chunk", steps=n, step0=py_step + 1):
                    (
                        model._params,
                        model._state,
                        model._opt_slots,
                        model._step,
                        model._counters,
                        losses,
                    ) = chunk_fn(
                        model._params, model._state, model._opt_slots,
                        model._step, model._counters, rngs, staged,
                    )
                    loss_host = (np.asarray(jax.device_get(losses))
                                 if need_losses else None)
                t_run1 = time.perf_counter()
                py_step += n
                end_b = start_b + n
                # the cursor names the NEXT batch to run on resume —
                # always a chunk edge; epochs are ABSOLUTE (since compile)
                if end_b >= num_batches:
                    cursor = {"epoch": abs_e + 1, "batch": 0}
                else:
                    cursor = {"epoch": abs_e, "batch": end_b}
                if resil is not None:
                    if preempt is not None and preempt.preempted:
                        # preemption notice: the running chunk completed
                        # (a dispatched scan cannot be interrupted), so
                        # drain the in-flight async save and take the one
                        # final synchronous snapshot at this chunk edge
                        telemetry.instant("preempted", step=py_step)
                        resil.finalize(py_step, cursor, final_save=True)
                        preempted = True
                    elif resil.policy.should_save_range(py_step - n,
                                                        py_step):
                        resil.save(py_step, cursor, blocking=False)
                t_save1 = time.perf_counter()
                if tel is not None:
                    self._synthesize_step_records(
                        tel=tel, diag=diag, resil=resil, n=n,
                        step0=py_step - n + 1, abs_e=abs_e,
                        t_chunk0=t_chunk0, t_pop1=t_pop1, t_run1=t_run1,
                        t_save1=t_save1, loss_host=loss_host,
                        batch_size=batch_size,
                        tokens_per_example=tokens_per_example,
                        compiled_now=compiled_now)
                if fault_hook is not None:
                    for s in range(py_step - n + 1, py_step + 1):
                        fault_hook(s)
                pending.pop(0)
                elastic = getattr(model, "_elastic", None)
                if (elastic is not None and not preempted
                        and elastic.maybe_replan(py_step) and pending):
                    # the re-plan migrated executor + state at this
                    # chunk edge: chunks already staged on the OLD mesh
                    # are stale, so rebuild the prefetch pipeline over
                    # the remaining chunks with the new mesh's
                    # shardings (chunk_fn is re-fetched per chunk above,
                    # so the executable swap needs nothing here)
                    prefetcher.shutdown()
                    self._sync_mesh()
                    prefetcher = ChunkPrefetcher(
                        stage, list(pending), depth=self.prefetch_depth)
                if preempted:
                    telemetry.event("preempted", step=py_step)
                    return py_step, True
        finally:
            prefetcher.shutdown()
        return py_step, False

    # ------------------------------------------------------------ telemetry

    def _synthesize_step_records(self, *, tel, diag, resil, n: int,
                                 step0: int, abs_e: int, t_chunk0: float,
                                 t_pop1: float, t_run1: float,
                                 t_save1: float,
                                 loss_host: Optional[np.ndarray],
                                 batch_size: int, tokens_per_example: int,
                                 compiled_now: bool = False):
        """Reconstruct per-step telemetry/diagnostics records from one
        chunk's wall window so every downstream consumer (metrics.jsonl
        schema, drift windows, health rules, run_doctor) keeps working
        unchanged: device time is attributed as chunk_device/N, the queue
        pop as the chunk's data_wait, the boundary save as its
        save_latency — all spread evenly across the chunk's steps (their
        sum reproduces the chunk wall time exactly)."""
        data_wait = (t_pop1 - t_chunk0) / n
        save_lat = (t_save1 - t_run1) / n
        step_time = (t_save1 - t_chunk0) / n
        if diag is not None and resil is not None:
            # the staleness clock advances once per chunk (saves only
            # happen at boundaries)
            diag.note_checkpoint_commit(resil.last_commit_walltime())
        for i in range(n):
            step = step0 + i
            t0 = t_chunk0 + i * step_time
            # synthesized trace spans: Perfetto shows the same step/
            # data_wait lanes as the eager loop, sliced from the chunk
            tel.tracer.complete("step", t0, t0 + step_time, step=step,
                                synthesized=True)
            tel.tracer.complete("data_wait", t0, t0 + data_wait,
                                synthesized=True)
            tel.record_step(step, abs_e, step_time, data_wait, save_lat,
                            batch_size, tokens_per_example)
            if diag is not None:
                # HealthAbort propagates from here mid-chunk: earlier
                # steps of the chunk are already recorded, exactly like
                # the eager loop stopping at the aborting step. A chunk
                # that just compiled its executable reports loss only —
                # its timings are compile-dominated and would seed the
                # spike/drift baselines wrong (every timing rule skips
                # None fields; the telemetry records above stay honest
                # wall time, like the eager loop's step-1 record).
                loss_i = (float(loss_host[i])
                          if loss_host is not None else None)
                rec = {
                    "step": step, "epoch": abs_e, "t": time.time(),
                    "step_time_s": None if compiled_now else step_time,
                    "data_wait_s": None if compiled_now else data_wait,
                    "save_latency_s": None if compiled_now else save_lat,
                    "device_time_s": None if compiled_now else max(
                        0.0, step_time - data_wait - save_lat),
                    "loss": loss_i,
                }
                # sanitizer attribution rides the same record shape as
                # the eager loop's (the scan's probes carried the real
                # per-iteration step value, so localization still names
                # the exact step inside the chunk)
                rec.update(self.model._nonfinite_localization(loss_i))
                diag.on_step(rec)
