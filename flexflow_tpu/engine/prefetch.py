"""Background input pipeline: stage the NEXT chunk while this one runs.

The eager fit loop pays a synchronous host slice + `device_put` inside
every step window. The prefetcher moves that work onto a daemon thread:
for each planned chunk it fancy-indexes the epoch's sample order, stacks
the batches along a leading scan axis, and `device_put`s them with the
input's NamedSharding — while the device is still executing the previous
chunk. The queue is bounded (double-buffered by default) so host memory
holds at most `depth` staged chunks; the consumer's `data_wait` collapses
to a queue pop.

Shutdown contract (tested): `shutdown()` always leaves the thread dead —
on normal completion, on consumer-side aborts (HealthAbort, injected
preemptions), and on staging errors, which are re-raised at the next
`get()` rather than vanishing on the worker thread.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Optional


class PrefetchExhausted(RuntimeError):
    """get() was called more times than there were chunks to stage."""


class ChunkPrefetcher:
    """Stages `stage_fn(chunk)` for each chunk on a background thread.

    `get()` returns staged payloads in chunk order; a staging exception
    is re-raised there (the training loop, not the worker, owns error
    handling). `shutdown()` is idempotent and safe from any state —
    including a worker blocked on a full queue."""

    def __init__(self, stage_fn: Callable, chunks: Iterable,
                 depth: int = 2, name: str = "ff-prefetch"):
        self._stage_fn = stage_fn
        self._chunks = list(chunks)
        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, name=name, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ worker

    def _put(self, item) -> bool:
        """Stop-aware blocking put: a consumer that aborted mid-epoch
        would otherwise leave the worker blocked on a full queue
        forever."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            for chunk in self._chunks:
                if self._stop.is_set():
                    return
                staged = self._stage_fn(chunk)
                if not self._put(("ok", staged)):
                    return
            self._put(("done", None))
        except BaseException as e:  # noqa: BLE001 - must cross threads
            self._put(("error", e))

    # ------------------------------------------------------------ consumer

    def get(self, timeout: Optional[float] = None):
        """Next staged chunk payload (blocks while the worker stages).
        Raises the worker's exception if staging failed, and
        PrefetchExhausted past the last chunk."""
        kind, payload = self._q.get(timeout=timeout)
        if kind == "error":
            raise payload
        if kind == "done":
            raise PrefetchExhausted(
                "prefetcher exhausted: more get() calls than chunks")
        return payload

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def shutdown(self, timeout: float = 10.0) -> bool:
        """Stop the worker and join it. Idempotent; drains the queue so a
        blocked put wakes up. Called in the engine's finally — no path
        (normal, HealthAbort, SimulatedPreemption, staging error) leaks
        the thread. Returns False (and says so in the log) when the
        worker is wedged past `timeout` — e.g. a device_put stuck
        against a dead backend — instead of silently breaking the
        no-leak contract."""
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout)
        if self._thread.is_alive():
            from ..telemetry import log as fflog

            fflog.warning(
                "prefetcher: staging thread did not exit within %.0fs of "
                "shutdown (wedged device transfer?) — daemon thread left "
                "behind", timeout)
            return False
        return True
