"""Executor: lowers a PCG to one jitted SPMD training/eval step.

This replaces the reference's entire L0-L2 stack (Legion index tasks + FFMapper
+ per-op CUDA kernels, SURVEY §1): the topo-ordered PCG becomes a single pure
function traced under `jax.jit`; each node's searched placement is pinned with
`with_sharding_constraint` (the GSPMD analog of tagging region requirements
with `machine_view.hash()`, src/ops/linear.cc:352-359), so the plan the search
chose is the plan XLA runs, and re-sharding between differently-placed ops is
compiled into ICI collectives exactly where the reference would launch
parallel-op copy tasks.

Autodiff (`jax.value_and_grad`) replaces all hand-written backward tasks;
Legion tracing (`begin_trace/end_trace` around each iteration) is subsumed by
the jit compilation cache; the optimizer update runs sharded in the same
program, so the whole training iteration is one XLA executable — the same
"single traced hot loop" property the reference gets from Legion trace replay.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .config import FFConfig
from .fftype import CompMode, DataType, LossType, OperatorType as OT, dtype_to_jnp
from .initializer import initializer_by_name
from .loss import loss_terms
from .metrics import Metrics
from .ops.base import OpContext
from .optimizer import Optimizer
from .pcg.graph import Graph, OpNode


def _stable_fold(key, name: str):
    h = int.from_bytes(hashlib.md5(name.encode()).digest()[:4], "little")
    return jax.random.fold_in(key, h)


# stage-3 (ZeRO-3 / FSDP) residual policy: the jax.checkpoint regions in
# _forward_gathered save every intermediate EXCEPT the gathered weight
# copies tagged with this name — so the backward re-gathers them instead
# of keeping a full per-layer copy live across the whole fwd+bwd, and
# nothing else is recomputed. Older jax without named policies degrades
# to full-region remat (still bit-identical, just recomputes the op).
_GATHER_NAME = "fsdp_gather"
_FSDP_SAVE_POLICY = (
    jax.checkpoint_policies.save_anything_except_these_names(_GATHER_NAME)
    if hasattr(jax.checkpoint_policies, "save_anything_except_these_names")
    else None)


class Executor:
    def __init__(
        self,
        graph: Graph,
        mesh: Mesh,
        config: FFConfig,
        loss_type: LossType,
        metrics: Metrics,
        optimizer: Optimizer,
        logits_node: OpNode,
        label_spec: PartitionSpec,
        update_sharding: Optional[dict] = None,
    ):
        self.graph = graph
        self.mesh = mesh
        self.config = config
        self.loss_type = loss_type
        self.metrics = metrics
        self.optimizer = optimizer
        self.order = graph.topo_order()
        self.logits_node = logits_node
        self.label_spec = label_spec
        # weight-update sharding (ZeRO / Xu et al.; decided by
        # unity.choose_update_sharding): fp32 masters + optimizer slots of
        # each shardable trainable weight live 1/dp-sharded along its
        # gradient-reduction axes. update_specs[(node, weight)] = (spec,
        # shape): the at-rest PartitionSpec init_variables places with and
        # the train step pins grads / updated params / slots to — GSPMD
        # then lowers the grad psum into a reduce-scatter in layer order
        # and defers the updated-param all-gather into each consumer's
        # first use next step (it fuses with the _cast_compute downcast at
        # that seam). The update math is element-wise on the same reduced
        # gradient values, so the trajectory is bit-identical to the
        # replicated update.
        self.update_sharding = update_sharding or {"enabled": False}
        self.update_specs: dict[tuple[str, str], tuple] = {}
        # ZeRO-3 / FSDP stage 3 (choose_update_sharding stage == 3): the
        # trainable weights themselves live sharded at rest in the SAME
        # update_specs layout, and _apply gathers each layer's params
        # just-in-time with a double-buffered ring all-gather
        # (parallel/ops.ring_all_gather) issued one layer ahead on the
        # overlappable channel, the gathered copy dropped after last use
        # (the backward re-gathers under jax.checkpoint). gather_specs
        # holds, per sharded weight, what the gather needs: the compute
        # placement it restores, the update axes it unwinds, and the dim
        # they shard. gather_schedule is the per-layer prefetch schedule
        # derived from the PCG topological order: entry k's gather is
        # issued behind entry k-1's compute (XLA's latency-hiding
        # scheduler realizes the overlap from the ring hops'
        # data-independence).
        self.update_stage = int(self.update_sharding.get(
            "stage", 2 if self.update_sharding.get("enabled") else 0))
        self.gather_specs: dict[tuple[str, str], tuple] = {}
        self.gather_schedule: list[tuple[str, Optional[str]]] = []
        # custom-VJP gather callables keyed by (owner, wname); built once
        # per weight at first trace (the overlap flag is read inside
        # _gather_param at trace time — config is fixed for the compile)
        self._gather_fns: dict[tuple[str, str], Any] = {}
        if self.update_sharding.get("enabled"):
            self._build_update_specs()
        # A substitution rewrite may have interposed Combine/Repartition/...
        # nodes between the real softmax and the marked logits node; walk
        # back through value-preserving parallel ops so the loss doesn't
        # re-apply log-softmax to probabilities after such a rewrite.
        terminal = _terminal_compute_op(graph, logits_node)
        self.last_op_is_softmax = terminal.op_type == OT.OP_SOFTMAX
        # AggregateSpec emits per-token-copy rows (k*b, dim) in copy-major
        # order; labels must be replicated k× to score every expert's
        # prediction (the reference replicates the label tensor at compile
        # when the final op is OP_AGG_SPEC, model.cc:2875). A trailing
        # softmax doesn't change the row count — look through it.
        self.label_replication = 1
        spec_probe = terminal
        if spec_probe.op_type == OT.OP_SOFTMAX:
            edges = graph.in_edges[spec_probe.guid]
            if edges:
                e = sorted(edges, key=lambda e: e.dst_idx)[0]
                spec_probe = _terminal_compute_op(graph, graph.nodes[e.src])
        if spec_probe.op_type == OT.OP_AGG_SPEC and spec_probe.inputs:
            self.label_replication = (
                spec_probe.inputs[0].shape.logical_shape[1])
        # Mixed precision (config.py): compute_dtype != None → bf16/fp16
        # activations with fp32 master weights; matmul_dtype → MXU input cast
        # for fp32 matmuls (tensor-op math analog).
        self.compute_dtype = (
            dtype_to_jnp(config.computation_dtype)
            if config.computation_dtype is not None else None
        )
        self.matmul_dtype = (
            jnp.bfloat16
            if config.allow_tensor_op_math_conversion
            and (jax.default_backend() == "tpu" or config.force_tensor_op_math)
            else None
        )
        self._train_step = None
        self._eval_step = None
        self._forward_fn = None
        self._decode_step = None  # serving decode executable (serving/)
        # chunked (lax.scan) train steps keyed by chunk length — the
        # pipelined engine's fused multi-step dispatch (engine/)
        self._chunk_steps: dict[int, Any] = {}
        # ffsan runtime sanitizer (--sanitize-numerics, sanitize.py):
        # when on, _apply wraps every op output in finiteness probes
        # (fwd value + bwd cotangent) that localize the first non-finite
        # tensor to (op, phase, step). Off → no probes traced, the step
        # is byte-identical to the uninstrumented one.
        self.sanitize_numerics = bool(
            getattr(config, "sanitize_numerics", False))
        # test/debug fault injection: (op_name | "loss", "fwd"|"bwd",
        # step) — poisons exactly that tensor from that step on
        self._numeric_fault: Optional[tuple] = None

    def _build_update_specs(self):
        """Resolve the per-weight update shardings through the SAME
        helpers the cost model prices with (parallel/ops): for every
        trainable, non-tied weight, the gradient-reduction axes (consumer
        activation axes minus the weight's own) extend the plan's compute
        spec on the first divisible dim. Non-shardable weights stay
        replicated — their update is the replicated baseline (still
        bit-identical). Emits the weight_update telemetry event plus one
        grad_sync bytes counter per layer-order bucket (= param-owning
        node) so the drift monitor sees the new comm channel."""
        from . import telemetry
        from .parallel.ops import (
            _spec_assignment, choose_update_dim, grad_sync_axes,
            weight_update_spec,
        )

        axis_sizes = {k: int(v) for k, v in dict(self.mesh.shape).items()}
        total_bytes = 0
        buckets = 0
        used_axes: set = set()
        max_shards = 1
        for node in self.order:
            if getattr(node, "weight_source", None):
                continue
            out_axes = set()
            if node.outputs:
                for entry in node.outputs[0].partition_spec():
                    if entry is None:
                        continue
                    out_axes.update(entry if isinstance(entry, tuple)
                                    else (entry,))
            bucket_bytes = 0
            for ws in node.weight_specs:
                if not ws.trainable:
                    continue
                base = node.weight_axes.get(ws.name, PartitionSpec())
                w_axes = set()
                for entry in base:
                    if entry is None:
                        continue
                    w_axes.update(entry if isinstance(entry, tuple)
                                  else (entry,))
                axes = tuple(ax for ax in grad_sync_axes(out_axes, w_axes)
                             if axis_sizes.get(ax, 1) > 1)
                if not axes:
                    continue
                spec = weight_update_spec(ws.shape, base, axes, axis_sizes)
                if spec is None:
                    continue
                self.update_specs[(node.name, ws.name)] = (
                    spec, tuple(ws.shape))
                if self.update_stage >= 3:
                    # stage 3: record what the just-in-time gather needs
                    # — the compute placement it restores (base), the
                    # update axes it unwinds, and the dim they shard
                    dim = choose_update_dim(
                        ws.shape, _spec_assignment(base, len(ws.shape)),
                        axes, axis_sizes)
                    self.gather_specs[(node.name, ws.name)] = (
                        base, spec, tuple(axes), dim)
                used_axes.update(axes)
                deg = 1
                for ax in axes:
                    deg *= axis_sizes.get(ax, 1)
                max_shards = max(max_shards, deg)
                nbytes = int(np.prod(ws.shape)) * 4
                bucket_bytes += nbytes
                total_bytes += nbytes
            if bucket_bytes:
                buckets += 1
                telemetry.counter("grad_sync", {
                    "bucket": buckets, "bytes": bucket_bytes})
        self.update_sharding = dict(self.update_sharding,
                                    buckets=buckets,
                                    sharded_weights=len(self.update_specs),
                                    bytes=total_bytes)
        if self.gather_specs:
            # one-layer-ahead prefetch schedule from the PCG topological
            # order: entry k's fwd gather is issued behind entry k-1's
            # compute (None = the first gather, nothing to hide behind);
            # the backward walks it in reverse. The ring hops carry no
            # data dependence on the neighbouring compute, which is what
            # lets the latency-hiding scheduler realize this schedule.
            owners = []
            for node in self.order:
                if getattr(node, "weight_source", None):
                    continue
                if any((node.name, ws.name) in self.gather_specs
                       for ws in node.weight_specs):
                    owners.append(node.name)
            self.gather_schedule = [
                (name, owners[i - 1] if i > 0 else None)
                for i, name in enumerate(owners)]
            gathered_bytes = sum(
                int(np.prod(shape)) * 4
                for key, (_spec, shape) in self.update_specs.items()
                if key in self.gather_specs)
            telemetry.event(
                "param_gather",
                layers=len(owners),
                sharded_weights=len(self.gather_specs),
                bytes=gathered_bytes,
                overlap=bool(self.config.overlap_collectives))
        if self.update_specs:
            # the REALIZED layout can exceed the decision's dp-default
            # guess (a seq-sharded consumer adds `seq` to a weight's
            # reduction axes): record what actually runs — the manifest,
            # the weight_update event, and strategy_report all read this
            self.update_sharding["axes"] = sorted(used_axes)
            self.update_sharding["shards"] = max_shards
        else:
            # decided (or forced) sharded but no weight had a divisible
            # dim: nothing runs sharded, so the record — and everything
            # downstream that prices or audits it — must say replicated
            self.update_sharding.update(
                enabled=False, stage=0, shards=1, axes=[],
                reason=self.update_sharding.get("reason", "")
                + "+no_shardable_weight")
            self.update_stage = 0
            self.gather_specs.clear()
        if self.update_specs:
            telemetry.event(
                "weight_update",
                stage=self.update_stage,
                shards=int(self.update_sharding.get("shards", 1)),
                buckets=buckets, sharded_weights=len(self.update_specs),
                bytes=total_bytes)

    def _map_update_leaves(self, tree, fn):
        """Apply `fn(leaf, NamedSharding)` to every leaf carrying an
        update sharding (no-op when disabled). Leaves are matched by the
        (node, weight) tail of their tree path — the same two keys for
        params/grads ({node: {w}}) and slot trees ({m: {node: {w}}}) —
        and only when the leaf has the weight's full shape (SGD's
        momentum-off scalar slots pass through)."""
        if not self.update_specs:
            return tree
        import jax.tree_util as jtu

        flat, treedef = jtu.tree_flatten_with_path(tree)
        out = []
        for path, leaf in flat:
            keys = tuple(k.key for k in path if isinstance(k, jtu.DictKey))
            entry = (self.update_specs.get(keys[-2:])
                     if len(keys) >= 2 else None)
            if entry is not None and tuple(
                    getattr(leaf, "shape", ())) == entry[1]:
                leaf = fn(leaf, NamedSharding(self.mesh, entry[0]))
            out.append(leaf)
        return jtu.tree_unflatten(treedef, out)

    def _pin_update_sharding(self, tree):
        """Constrain grads / updated params / optimizer slots to their
        update shardings inside the jitted step."""
        return self._map_update_leaves(
            tree, jax.lax.with_sharding_constraint)

    def place_update_sharded(self, tree):
        """device_put leaves onto their update shardings (outside jit) —
        compile-time placement of optimizer slots built by zeros_like, and
        insurance that params/slots restored or constructed elsewhere land
        at rest in the sharded layout."""
        return self._map_update_leaves(tree, jax.device_put)

    # -------------------------------------------------- stage-3 gathers

    def _gather_param(self, owner: str, wname: str, arr):
        """Ring all-gather one stage-3 weight from its at-rest update
        layout back to its compute placement — exact data movement, so
        the gathered value is bit-identical to a replicated weight.
        Multi-axis updates unwind one ring per axis, minor axis first
        (weight_update_spec appends the update axes onto the dim, so
        chunks concatenate in ring order within each outer shard). Hops
        are double-buffered (hop-before-use) when overlap_collectives is
        on; --no-overlap-collectives is the serial hop-then-write
        ablation — bit-identical either way."""
        from .parallel.ops import _spec_assignment, ring_all_gather

        base, upd, axes, dim = self.gather_specs[(owner, wname)]
        overlap = bool(self.config.overlap_collectives)
        cur = list(_spec_assignment(upd, arr.ndim))

        def to_spec(assignment):
            return PartitionSpec(*(
                None if not e else (e[0] if len(e) == 1 else tuple(e))
                for e in assignment))

        with jax.named_scope(f"param_gather/{owner}.{wname}"):
            for ax in reversed(axes):
                nxt = list(cur)
                entry = list(nxt[dim])
                entry.remove(ax)
                nxt[dim] = tuple(entry)
                arr = ring_all_gather(
                    arr, mesh=self.mesh, axis_name=ax, dim=dim,
                    overlap=overlap,
                    in_spec=to_spec(cur), out_spec=to_spec(nxt))
                cur = nxt
        return arr

    def _gather_with_vjp(self, owner: str, wname: str):
        """The stage-3 gather as a custom-VJP callable (built once per
        weight): forward = the explicit ring all-gather; backward = the
        gathered copy's cotangent pinned to the compute placement
        (replicated over the update axes) — the exact stage-2 gradient
        path, so GSPMD lowers the dp psum into the same reduce-scatter
        and the trajectory stays bit-identical to the replicated
        baseline; _pin_update_sharding then slices the owner's shard.
        (Autodiff THROUGH the ring would accumulate the grad chunks in
        ring-arrival order, which is NOT the allreduce's ULP order —
        measured as ~1e-7 drift on the CI mesh.)"""
        key = (owner, wname)
        fn = self._gather_fns.get(key)
        if fn is not None:
            return fn
        base = self.gather_specs[key][0]
        base_sh = NamedSharding(
            self.mesh, base if base is not None else PartitionSpec())

        @jax.custom_vjp
        def gather(w):
            return self._gather_param(owner, wname, w)

        def fwd(w):
            return gather(w), None

        def bwd(_, ct):
            return (jax.lax.with_sharding_constraint(ct, base_sh),)

        gather.defvjp(fwd, bwd)
        self._gather_fns[key] = gather
        return gather

    def _forward_gathered(self, node, wsrc, gathered, p_own, new_state,
                          ins, op_state, ctx):
        """Stage-3 forward of one op: gather its sharded-at-rest weights
        just-in-time inside a jax.checkpoint region whose policy refuses
        to save the gathered copies — they are DROPPED after the op's
        last use and the backward re-gathers them (ZeRO-3; the ASPLOS'23
        decomposition pattern applied to the forward). Everything else
        the VJP needs (the op's inputs, its saveable internals) is
        stored as usual, so the only recompute is the re-gather itself.
        The compute-dtype cast sits inside the region too, so it fuses
        with the gather exactly as it fused with the implicit stage-2
        all-gather."""
        shard_p = {k: p_own[k] for k in gathered}
        plain_p = {k: v for k, v in p_own.items() if k not in gathered}
        state_w = new_state.get(wsrc, {})

        def run(shard_p, plain_p, ins_t, op_state_in, state_w):
            full = {
                k: checkpoint_name(self._gather_with_vjp(wsrc, k)(v),
                                   _GATHER_NAME)
                for k, v in shard_p.items()}
            weights = {}
            weights.update(self._cast_compute({**plain_p, **full}))
            weights.update(state_w)
            # runs under the forward loop's `with jax.named_scope
            # (node.name)` — the remat closure is invoked from inside
            # that scope, so its trace events already carry the label
            return node.op_def.forward(  # fflint: ok unnamed_op_scope
                node.params, list(ins_t), weights, op_state_in, ctx)

        # prevent_cse=False: these regions only ever run inside jit
        # (the documented-safe case), and the CSE barriers would pin the
        # ring hops behind region boundaries — defeating the one-ahead
        # overlap the schedule exists for
        remat = jax.checkpoint(run, policy=_FSDP_SAVE_POLICY,
                               prevent_cse=False)
        return remat(shard_p, plain_p, tuple(ins), op_state, state_w)

    def _cast_compute(self, tree):
        """Cast float leaves to the compute dtype (inside jit; the VJP of the
        cast accumulates gradients back into the fp32 master leaves)."""
        cd = self.compute_dtype
        if cd is None:
            return tree
        return jax.tree.map(
            lambda x: x.astype(cd)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            tree,
        )

    def set_numeric_fault(self, op: Optional[str], phase: str = "fwd",
                          step: int = 0):
        """Install (or clear, op=None) a numeric fault: the named op's
        output (or its cotangent, phase="bwd"; op "loss" targets the
        scalar loss) goes NaN from global step `step` on. Test/debug
        hook for the sanitizer's localization matrix — the cached step
        executables are dropped so the next dispatch retraces with the
        fault baked in."""
        if op is not None:
            if phase not in ("fwd", "bwd"):
                raise ValueError(f"phase must be fwd|bwd, got {phase!r}")
            if op != "loss" and all(n.name != op for n in self.order):
                raise ValueError(f"no op named {op!r} in the graph")
        self._numeric_fault = (
            None if op is None else (op, phase, int(step)))
        self._train_step = None
        self._eval_step = None
        self._forward_fn = None
        self._decode_step = None
        self._chunk_steps.clear()

    def _maybe_poison(self, x, name: str, step, phase: str):
        """Apply the installed numeric fault to tensor `name`, for the
        given phase only. Wrap order vs the sanitizer probe matters: a
        fwd fault is applied BEFORE the probe (so the probe sees the
        poisoned value), a bwd fault AFTER it (so the probe's backward
        sees the poisoned cotangent — bwd composition reverses the
        forward wrap order)."""
        fault = self._numeric_fault
        if fault is None or fault[0] != name or fault[1] != phase:
            return x
        from . import sanitize

        _op, _phase, at = fault
        if phase == "fwd":
            return sanitize.inject_nonfinite(x, step, at)
        return sanitize.inject_grad_nonfinite(
            x, step if step is not None else jnp.int32(-1), at)

    def make_loss_fn(self, state, x_inputs, labels, rng, step=None):
        """Shared mixed-precision loss closure for the fused train step and
        the granular FFModel.backward: bf16 compute casts on params/inputs
        (state is passed uncast — ops own their fp32-statistics handling).
        Params are passed UNCAST into `_apply`, which casts each node's
        weights at their first use — the cast fuses into the consumer's
        matmul prologue instead of materializing a full bf16 parameter
        copy through HBM every step (PERF.md "remaining headroom": the
        per-step fp32-master downcast traffic). The VJP is unchanged (a
        per-leaf astype either way), so gradients still accumulate into
        the fp32 masters bit-identically.
        Logits stay in the compute dtype — the loss reduces them with f32
        accumulation internally (loss.py), so no logits-sized f32 tensor is
        materialized. aux carries (logits, new_state, ce_sum): ce_sum is the
        reusable sparse-CE sum for Metrics (None for non-SCCE losses)."""
        xc = self._cast_compute(x_inputs)
        labels = self.expand_labels(labels)

        def loss_fn(p):
            logits, new_state, aux = self._apply(
                p, state, xc, training=True, rng=rng, step=step
            )
            l, ce_sum = loss_terms(
                self.loss_type, logits, labels, self.last_op_is_softmax
            )
            total = l + aux
            total = self._maybe_poison(total, "loss", step, "fwd")
            if self.sanitize_numerics:
                from . import sanitize

                # the loss sits one past the last graph op in topo space
                total = sanitize.probe(total, step, "loss",
                                       len(self.order))
            total = self._maybe_poison(total, "loss", step, "bwd")
            return total, (logits, new_state, ce_sum)

        return loss_fn

    def expand_labels(self, labels):
        """Replicate labels k× for an AggregateSpec terminal (copy-major,
        matching _agg_spec_forward's (k*b, dim) row order) — the
        model.cc:2875 label replication."""
        k = self.label_replication
        if k <= 1:
            return labels
        reps = (k,) + (1,) * (labels.ndim - 1)
        return jnp.tile(labels, reps)

    def _restore_state_dtypes(self, new_state):
        """Non-trainable state (running stats) is kept fp32 across steps so
        its dtype — and therefore the jitted step signature — is stable."""
        if self.compute_dtype is None:
            return new_state
        return jax.tree.map(
            lambda x: x.astype(jnp.float32)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            new_state,
        )

    # ------------------------------------------------------------ variables

    def init_variables(self, rng):
        """Initialize params (trainable) and state (non-trainable weights),
        each placed with its searched sharding (replaces weight-region mapping
        in model.cc map_weight + initializer tasks)."""
        params, state = {}, {}
        for node in self.order:
            if getattr(node, "weight_source", None):
                continue  # tied weights live under the source node's name
            p, s = {}, {}
            for i, ws in enumerate(node.weight_specs):
                init = node.initializers.get(
                    ws.name, initializer_by_name(ws.initializer)
                )
                key = _stable_fold(rng, f"{node.name}/{ws.name}")
                arr = init(key, ws.shape, dtype_to_jnp(ws.dtype))
                spec = node.weight_axes.get(ws.name, PartitionSpec())
                upd = self.update_specs.get((node.name, ws.name))
                if upd is not None:
                    # at-rest layout under weight-update sharding: the
                    # fp32 master lives 1/dp-sharded. Stage 2: consumers
                    # all-gather at first use (GSPMD, fused with their
                    # compute-dtype cast). Stage 3: _apply gathers
                    # just-in-time with the explicit ring all-gather and
                    # drops the copy after last use.
                    spec = upd[0]
                arr = jax.device_put(arr, NamedSharding(self.mesh, spec))
                (p if ws.trainable else s)[ws.name] = arr
            if p:
                params[node.name] = p
            if s:
                state[node.name] = s
        return params, state

    # ------------------------------------------------------------ apply

    def _apply(self, params, state, inputs, *, training, rng,
               seq_length=-1, step=None):
        """Run the PCG forward. Returns (logits, new_state, aux_loss).
        `step` (traced int or None) feeds the sanitizer probes and the
        fault injector so localization carries the exact step inside
        chunked lax.scan dispatches too."""
        if self.sanitize_numerics:
            from . import sanitize
        vals: dict[tuple[int, int], Any] = {}
        new_state = {k: dict(v) for k, v in state.items()}
        aux_loss = 0.0
        for topo_idx, node in enumerate(self.order):
            if node.op_type in (OT.OP_INPUT, OT.OP_WEIGHT, OT.OP_NOOP):
                if node.op_type == OT.OP_INPUT:
                    x = inputs[node.name]
                    spec = node.outputs[0].partition_spec()
                    if _spec_nontrivial(spec):
                        x = jax.lax.with_sharding_constraint(
                            x, NamedSharding(self.mesh, spec)
                        )
                    vals[(node.guid, 0)] = x
                elif self.graph.in_edges[node.guid]:
                    src, sidx = self.graph.producer(node, 0)
                    vals[(node.guid, 0)] = vals[(src.guid, sidx)]
                continue

            ins = [None] * len(self.graph.in_edges[node.guid])
            for e in self.graph.in_edges[node.guid]:
                ins[e.dst_idx] = vals[(e.src, e.src_idx)]

            # tied weights read the source node's parameter set; autodiff
            # then sums every use's gradient into that one set
            wsrc = getattr(node, "weight_source", None) or node.name
            p_own = params.get(wsrc, {})
            # stage 3 (ZeRO-3/FSDP): this node's sharded-at-rest weights
            # are ring-gathered just-in-time inside a remat region that
            # drops the gathered copies after last use (bwd re-gathers)
            gathered = ([k for k in p_own
                         if (wsrc, k) in self.gather_specs]
                        if self.update_stage >= 3 else [])
            ctx = OpContext(
                training=training,
                rng=_stable_fold(rng, node.name) if rng is not None else None,
                seq_length=seq_length,
                profiling=self.config.profiling,
                mesh=self.mesh,
                matmul_dtype=self.matmul_dtype,
                overlap_collectives=self.config.overlap_collectives,
                flash_packed=self.config.flash_packed_layout,
            )
            op_state = new_state.get(node.name)
            # named_scope labels the op in XLA profiles (the analog of the
            # reference's per-op profiling prints, linear_kernels.cu:95-117)
            with jax.named_scope(node.name):
                if gathered:
                    outs, op_state = self._forward_gathered(
                        node, wsrc, gathered, p_own, new_state, ins,
                        op_state, ctx)
                else:
                    weights = {}
                    # bf16 cast at the consumer: each node casts only its
                    # own weights, so XLA fuses the downcast into the
                    # first use instead of writing a model-sized bf16
                    # copy to HBM up front (state stays uncast — ops own
                    # their fp32-statistics handling)
                    weights.update(self._cast_compute(p_own))
                    weights.update(new_state.get(wsrc, {}))
                    outs, op_state = node.op_def.forward(
                        node.params, ins, weights, op_state, ctx
                    )
            if op_state:
                op_state = dict(op_state)
                aux = op_state.pop("aux_loss", None)
                if aux is not None:
                    aux_loss = aux_loss + aux
                if op_state:
                    cur = new_state.setdefault(node.name, {})
                    cur.update(op_state)

            for i, out in enumerate(outs):
                if i < len(node.outputs):
                    spec = node.outputs[i].partition_spec()
                    if _spec_nontrivial(spec):
                        out = jax.lax.with_sharding_constraint(
                            out, NamedSharding(self.mesh, spec)
                        )
                if i == 0 and self._numeric_fault is not None:
                    out = self._maybe_poison(out, node.name, step, "fwd")
                if self.sanitize_numerics:
                    label = (node.name if i == 0
                             else f"{node.name}#out{i}")
                    out = sanitize.probe(out, step, label, topo_idx)
                if i == 0 and self._numeric_fault is not None:
                    out = self._maybe_poison(out, node.name, step, "bwd")
                vals[(node.guid, i)] = out

        logits = vals[(self.logits_node.guid, 0)]
        return logits, new_state, aux_loss

    # ------------------------------------------------------------ steps

    def _train_step_body(self, params, state, opt_slots, step, counters,
                         rng, batch):
        """One iteration's math: fwd + loss + bwd + optimizer + metrics.
        Shared verbatim between the eager per-step jit and the chunked
        lax.scan body, so the pipelined engine is bit-identical to the
        eager loop by construction."""
        x_inputs, labels = batch
        loss_fn = self.make_loss_fn(state, x_inputs, labels, rng,
                                    step=step)
        (lval, (logits, new_state, ce_sum)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        new_state = self._restore_state_dtypes(new_state)
        if self.update_specs:
            # sharded weight update (ZeRO / Xu et al.): pin each bucket's
            # gradient to the 1/dp update layout, so GSPMD lowers the dp
            # psum into a reduce-scatter per layer-order bucket — the hop
            # for bucket k free to overlap the backward compute producing
            # bucket k+1 (no data dependence between them; the same
            # latency-hiding the ring bodies exploit). The sharded update
            # below then touches only this replica's shard; the updated
            # params stay sharded at rest and each consumer's first use
            # next step all-gathers them, fused with its compute cast.
            # Bit-identical: the same reduced gradient elements feed the
            # same element-wise update — each replica just owns a slice.
            # (The span fires at trace time — one per compile, labelling
            # the executable that carries the RS/AG schedule.)
            from . import telemetry

            with telemetry.span(
                    "grad_sync",
                    shards=int(self.update_sharding.get("shards", 1)),
                    buckets=int(self.update_sharding.get("buckets", 0))):
                with jax.named_scope("grad_sync"):
                    grads = self._pin_update_sharding(grads)
        # named for ffscope attribution: optimizer math that belongs to
        # no single PCG node lands in the profile section's extras map
        with jax.named_scope("weight_update"):
            new_params, new_slots = self.optimizer.update(
                grads, params, opt_slots, step
            )
        if self.update_specs:
            with jax.named_scope("weight_update_shard"):
                new_params = self._pin_update_sharding(new_params)
                new_slots = self._pin_update_sharding(new_slots)
        with jax.named_scope("metrics"):
            counters = self.metrics.compute(
                counters, logits, self.expand_labels(labels),
                from_logits=not self.last_op_is_softmax, scce_sum=ce_sum,
            )
        return new_params, new_state, new_slots, step + 1, counters, lval

    def build_train_step(self):
        """One fused iteration: fwd + loss + bwd + optimizer + metrics.
        Mirrors the traced loop of FFModel::fit (flexflow_cffi.py:2058-2100)
        collapsed into a single XLA executable."""
        self._train_step = jax.jit(
            self._train_step_body,
            donate_argnums=_donate_argnums((0, 1, 2, 3, 4)))
        return self._train_step

    def build_chunked_train_step(self, num_steps: int):
        """`num_steps` train iterations fused into ONE donated executable:
        a lax.scan over pre-staged batches (leading scan axis) and
        pre-split per-step RNG keys, carrying the full training state and
        emitting the per-step loss vector — the TPU-native analog of the
        reference's Legion trace replay batching N iterations per runtime
        round-trip. Cached per chunk length (an epoch tail shorter than
        the pipeline depth costs one extra compile, once)."""
        num_steps = int(num_steps)
        if num_steps < 1:
            raise ValueError(f"num_steps must be >= 1, got {num_steps}")
        cached = self._chunk_steps.get(num_steps)
        if cached is not None:
            return cached

        def chunk_step(params, state, opt_slots, step, counters, rngs,
                       batches):
            def body(carry, inp):
                rng, batch = inp
                out = self._train_step_body(*carry, rng, batch)
                return tuple(out[:5]), out[5]

            carry, losses = jax.lax.scan(
                body, (params, state, opt_slots, step, counters),
                (rngs, batches), length=num_steps)
            return carry + (losses,)

        fn = jax.jit(chunk_step,
                     donate_argnums=_donate_argnums((0, 1, 2, 3, 4)))
        self._chunk_steps[num_steps] = fn
        return fn

    def build_eval_step(self):
        def eval_step(params, state, counters, batch):
            x_inputs, labels = batch
            logits, _, _ = self._apply(
                params, state,
                self._cast_compute(x_inputs), training=False, rng=None,
            )
            counters = self.metrics.compute(
                counters, logits, self.expand_labels(labels),
                from_logits=not self.last_op_is_softmax,
            )
            return counters

        self._eval_step = jax.jit(eval_step, donate_argnums=_donate_argnums((2,)))
        return self._eval_step

    def build_decode_step(self):
        """ONE serving iteration as a donated executable: forward the
        decode graph (incremental attention reads+writes the KV-cache
        state threaded through `state`), then sample the next token per
        slot from the logits row `read_idx` names — argmax where
        `temperature[slot] == 0`, Gumbel sampling otherwise, in the same
        program so only the (slots,) token vector crosses the host
        boundary. Donating `state` updates the cache in place on backends
        that support donation (the TPU serving hot loop allocates nothing
        per token). Distinct q_len values (decode=1, prefill buckets)
        retrace into their own cached executables — the length-bucketed
        executable set falls out of jit's shape specialization."""

        def decode_step(params, state, x_inputs, read_idx, rng, temperature):
            logits, new_state, _ = self._apply(
                params, state,
                self._cast_compute(x_inputs), training=False, rng=None,
            )
            slots = logits.shape[0]
            sel = logits[jnp.arange(slots), read_idx]  # (slots, vocab)
            sel = sel.astype(jnp.float32)
            t = temperature.astype(jnp.float32)[:, None]
            gumbel = jax.random.gumbel(rng, sel.shape, jnp.float32)
            noisy = jnp.where(t > 0.0,
                              sel / jnp.maximum(t, 1e-6) + gumbel, sel)
            next_tok = jnp.argmax(noisy, axis=-1).astype(jnp.int32)
            return self._restore_state_dtypes(new_state), next_tok

        self._decode_step = jax.jit(
            decode_step, donate_argnums=_donate_argnums((1,)))
        return self._decode_step

    def build_verify_step(self):
        """Speculative-decoding verification as a donated executable:
        forward q = K+1 tokens per slot through the decode graph (the
        incremental-attention ops already take (slots, q) positions —
        the chunked-prefill multi-token path) and return EVERY row's
        greedy argmax, (slots, q) int32 — row j is the target's token
        for position `positions[s, j] + 1`. The host compares the
        drafter's proposals against this vector to accept the longest
        matching prefix + one correction token (serving/speculative.py);
        greedy-only by construction, which is what keeps speculative
        streams bit-identical to plain decode. Distinct draft lengths
        retrace into their own cached executables — the draft-length
        bucket set falls out of jit's shape specialization, like the
        prefill buckets. Donating `state` updates the KV cache in place;
        rejected rows need no device-side rollback — the host rewinds
        its position cursor and the next call's writes land over them
        before any masked read can see them."""

        def verify_step(params, state, x_inputs):
            logits, new_state, _ = self._apply(
                params, state,
                self._cast_compute(x_inputs), training=False, rng=None,
            )
            toks = jnp.argmax(logits.astype(jnp.float32),
                              axis=-1).astype(jnp.int32)  # (slots, q)
            return self._restore_state_dtypes(new_state), toks

        self._verify_step = jax.jit(
            verify_step, donate_argnums=_donate_argnums((1,)))
        return self._verify_step

    def build_block_copy(self):
        """Copy-on-write support for the paged KV layout: duplicate pool
        blocks src[i] → dst[i] across EVERY layer's pool_k/pool_v in one
        donated dispatch (the block ids are layer-uniform, so one (src,
        dst) vector serves the whole stack). The serving engine pads the
        vectors to a power-of-two width with (scratch → scratch) no-op
        pairs, so the executable set stays O(log slots·chunk) like the
        prefill buckets. Donating `state` updates the pools in place on
        backends with donation — a COW costs one block-sized DMA per
        layer, never a pool-sized allocation."""

        def copy_blocks(state, src, dst):
            new_state = {}
            for name, ws in state.items():
                nw = dict(ws)
                for pool in ("pool_k", "pool_v"):
                    buf = nw.get(pool)
                    if buf is not None:
                        nw[pool] = buf.at[dst].set(buf[src])
                new_state[name] = nw
            return new_state

        self._copy_fn = jax.jit(
            copy_blocks, donate_argnums=_donate_argnums((0,)))
        return self._copy_fn

    def build_kv_inject(self):
        """Disaggregated-serving handoff landing: write externally
        computed KV rows (the prefill pool's blocks, host-staged by the
        coordinator) into this engine's pool blocks in one donated
        dispatch. `blocks` is the (B,) physical destination vector,
        `rows_k`/`rows_v` are (layers, B, block_size, embed) stacked in
        sorted pool-layer-name order — the same order the extraction
        side reads, so layer i's rows land in layer i's pool. The engine
        pads B to a power of two with (scratch, zero-rows) pairs, so the
        executable set stays O(log blocks-per-prompt) like the COW copy
        buckets. Donating `state` updates the pools in place on backends
        with donation — a handoff costs block-sized DMAs, never a
        pool-sized allocation."""

        def inject_blocks(state, blocks, rows_k, rows_v):
            new_state = {}
            i = 0
            for name in sorted(state):
                nw = dict(state[name])
                if "pool_k" in nw:
                    nw["pool_k"] = nw["pool_k"].at[blocks].set(
                        rows_k[i].astype(nw["pool_k"].dtype))
                    nw["pool_v"] = nw["pool_v"].at[blocks].set(
                        rows_v[i].astype(nw["pool_v"].dtype))
                    i += 1
                new_state[name] = nw
            return new_state

        self._inject_fn = jax.jit(
            inject_blocks, donate_argnums=_donate_argnums((0,)))
        return self._inject_fn

    def build_param_gather(self):
        """The stage-3 params' full gather as ONE donated executable:
        every sharded-at-rest leaf ring-gathered back to its compute
        placement (replicated over the update axes) in a single
        dispatch; non-stage-3 leaves pass through. Consume-point
        semantics: the input tree is donated, so callers REBIND
        (`tree = gather_fn(tree)`) — the carry pattern the donated-reuse
        lint enforces. Used by the bench's param-sharding legs and the
        fsdp smoke to read/verify the gathered model without one host
        round-trip per weight; a no-op identity dispatch below stage 3."""

        def gather_params(params):
            out = {}
            for name, ws in params.items():
                nw = dict(ws)
                for k in ws:
                    if (name, k) in self.gather_specs:
                        nw[k] = self._gather_param(name, k, ws[k])
                out[name] = nw
            return out

        self._gather_fn = jax.jit(
            gather_params, donate_argnums=_donate_argnums((0,)))
        return self._gather_fn

    def build_forward(self):
        def forward(params, state, x_inputs, training):
            logits, new_state, _ = self._apply(
                params, state,
                self._cast_compute(x_inputs), training=training,
                rng=jax.random.key(0),
            )
            return logits, self._restore_state_dtypes(new_state)

        self._forward_fn = jax.jit(forward, static_argnums=(3,))
        return self._forward_fn

    # ------------------------------------------------------------ data placement

    def replicate(self, tree):
        """Place leaves on the mesh (replicated) unless already mesh-placed.
        All training state must live on the mesh before the first donated
        step: donating a buffer that needs an implicit placement change
        cannot reuse it and deadlocks XLA:CPU's in-process collectives.
        Leaves that already carry a NamedSharding on this mesh (e.g. optimizer
        slots built with zeros_like over sharded params) keep their sharding."""
        repl = NamedSharding(self.mesh, PartitionSpec())

        def place(x):
            sh = getattr(x, "sharding", None)
            if isinstance(sh, NamedSharding) and sh.mesh.shape == self.mesh.shape:
                return x
            return jax.device_put(x, repl)

        return jax.tree.map(place, tree)

    def shard_batch(self, arrays: dict, specs: dict):
        out = {}
        for name, arr in arrays.items():
            spec = specs.get(name, PartitionSpec())
            out[name] = jax.device_put(arr, NamedSharding(self.mesh, spec))
        return out


# Reduction and FusedParallelOp are deliberately excluded: a (fused)
# Reduction sums partial results, changing the value.
_VALUE_PRESERVING = frozenset({
    OT.OP_REPARTITION, OT.OP_COMBINE, OT.OP_REPLICATE,
    OT.OP_PIPELINE, OT.OP_NOOP, OT.OP_IDENTITY,
})


def _terminal_compute_op(graph: Graph, node: OpNode) -> OpNode:
    """Walk back through parallel/identity ops that only re-place (not
    transform) their input, to the op that actually computed the value.
    (Reduction is excluded: it sums partial results, changing the value.)"""
    seen = set()
    while node.op_type in _VALUE_PRESERVING and node.guid not in seen:
        seen.add(node.guid)
        edges = graph.in_edges[node.guid]
        if not edges:
            break
        src = min(edges, key=lambda e: e.dst_idx)
        node = graph.nodes[src.src]
    return node


def _spec_nontrivial(spec: PartitionSpec) -> bool:
    return any(entry is not None for entry in spec)


_DONATION_OK: Optional[bool] = None


def _donation_supported() -> bool:
    """Probe whether the backend honors donated buffers. XLA:CPU (the
    virtual-mesh test backend) deadlocks in-process collectives on donated
    aliases; tunneled TPU backends (axon) reject donation with
    INVALID_ARGUMENT while presenting themselves as plain 'tpu' — so probe
    once with a tiny donated jit instead of trusting the platform name."""
    global _DONATION_OK
    if _DONATION_OK is None:
        if jax.default_backend() == "cpu":
            _DONATION_OK = False
        else:
            try:
                f = jax.jit(lambda x: x + 1, donate_argnums=(0,))
                out = f(jnp.zeros((8,), jnp.float32))
                jax.block_until_ready(out)
                np.asarray(out)
                _DONATION_OK = True
            except Exception:
                _DONATION_OK = False
    return _DONATION_OK


def _donate_argnums(nums: tuple[int, ...]) -> tuple[int, ...]:
    """Buffer donation saves HBM on TPU when the backend supports it."""
    return nums if _donation_supported() else ()
