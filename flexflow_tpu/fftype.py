"""Core enums and type constants for the TPU-native FlexFlow framework.

Mirrors the *surface* of the reference's constant vocabulary
(/root/reference/include/flexflow/ffconst.h) so user code written against the
reference's Python API maps one-to-one, while the values behind them drive a
JAX/XLA execution model instead of Legion tasks.
"""

from __future__ import annotations

import enum

import jax.numpy as jnp


class ActiMode(enum.IntEnum):
    AC_MODE_NONE = 10
    AC_MODE_RELU = 11
    AC_MODE_SIGMOID = 12
    AC_MODE_TANH = 13
    AC_MODE_GELU = 14


class RegularizerMode(enum.IntEnum):
    REG_MODE_NONE = 17
    REG_MODE_L1 = 18
    REG_MODE_L2 = 19


class AggrMode(enum.IntEnum):
    AGGR_MODE_NONE = 20
    AGGR_MODE_SUM = 21
    AGGR_MODE_AVG = 22


class PoolType(enum.IntEnum):
    POOL_MAX = 30
    POOL_AVG = 31


class DataType(enum.IntEnum):
    DT_BOOLEAN = 40
    DT_INT32 = 41
    DT_INT64 = 42
    DT_HALF = 43
    DT_BFLOAT16 = 46  # TPU-native addition: bf16 is the MXU's home dtype
    DT_FLOAT = 44
    DT_DOUBLE = 45
    DT_NONE = 49


_DTYPE_TO_JNP = {
    DataType.DT_BOOLEAN: jnp.bool_,
    DataType.DT_INT32: jnp.int32,
    DataType.DT_INT64: jnp.int64,
    DataType.DT_HALF: jnp.float16,
    DataType.DT_BFLOAT16: jnp.bfloat16,
    DataType.DT_FLOAT: jnp.float32,
    DataType.DT_DOUBLE: jnp.float64,
}

_JNP_TO_DTYPE = {
    jnp.dtype("bool"): DataType.DT_BOOLEAN,
    jnp.dtype("int32"): DataType.DT_INT32,
    jnp.dtype("int64"): DataType.DT_INT64,
    jnp.dtype("float16"): DataType.DT_HALF,
    jnp.dtype("bfloat16"): DataType.DT_BFLOAT16,
    jnp.dtype("float32"): DataType.DT_FLOAT,
    jnp.dtype("float64"): DataType.DT_DOUBLE,
}


def dtype_to_jnp(dt: DataType):
    return _DTYPE_TO_JNP[DataType(dt)]


def jnp_to_dtype(dt) -> DataType:
    return _JNP_TO_DTYPE[jnp.dtype(dt)]


def size_of_datatype(dt: DataType) -> int:
    return jnp.dtype(dtype_to_jnp(dt)).itemsize


class LossType(enum.IntEnum):
    LOSS_CATEGORICAL_CROSSENTROPY = 50
    LOSS_SPARSE_CATEGORICAL_CROSSENTROPY = 51
    LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE = 52
    LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE = 53
    LOSS_IDENTITY = 54


class CompMode(enum.IntEnum):
    COMP_MODE_TRAINING = 70
    COMP_MODE_INFERENCE = 71


class ParameterSyncType(enum.IntEnum):
    """Kept for API parity (reference: include/flexflow/ffconst.h:52-56).
    On TPU NCCL-mode sync lowers to an XLA psum over the data axes, chosen
    by GSPMD from shardings. PS (hub-and-spoke parameter server,
    optimizer_kernel.cu:48-76) is rejected at tensor construction: a psum
    riding ICI strictly dominates it on TPU (SURVEY §7)."""

    NONE = 80
    PS = 81
    NCCL = 82


class MetricsType(enum.IntEnum):
    METRICS_ACCURACY = 1001
    METRICS_CATEGORICAL_CROSSENTROPY = 1002
    METRICS_SPARSE_CATEGORICAL_CROSSENTROPY = 1004
    METRICS_MEAN_SQUARED_ERROR = 1008
    METRICS_ROOT_MEAN_SQUARED_ERROR = 1016
    METRICS_MEAN_ABSOLUTE_ERROR = 1032


class OperatorType(enum.IntEnum):
    """Operator vocabulary (reference: include/flexflow/ffconst.h:69-162)."""

    OP_INPUT = enum.auto()
    OP_WEIGHT = enum.auto()
    OP_NOOP = enum.auto()
    OP_CONV2D = enum.auto()
    OP_DROPOUT = enum.auto()
    OP_LINEAR = enum.auto()
    OP_BATCHMATMUL = enum.auto()
    OP_POOL2D = enum.auto()
    OP_SCALAR_MULTIPLY = enum.auto()
    OP_SCALAR_ADD = enum.auto()
    OP_SCALAR_FLOOR_DIV = enum.auto()
    OP_SCALAR_TRUE_DIV = enum.auto()
    OP_SCALAR_SUB = enum.auto()
    OP_RELU = enum.auto()
    OP_IDENTITY = enum.auto()
    OP_SIGMOID = enum.auto()
    OP_TANH = enum.auto()
    OP_ELU = enum.auto()
    OP_FLAT = enum.auto()
    OP_SOFTMAX = enum.auto()
    OP_BATCHNORM = enum.auto()
    OP_CONCAT = enum.auto()
    OP_SPLIT = enum.auto()
    OP_EMBEDDING = enum.auto()
    OP_GROUP_BY = enum.auto()
    OP_CACHE = enum.auto()
    OP_AGGREGATE = enum.auto()
    OP_AGG_SPEC = enum.auto()
    # TPU-native addition: stacked-experts op enabling expert-axis sharding
    OP_EXPERTS = enum.auto()
    # TPU-native addition: stacked transformer blocks runnable as a
    # ppermute pipeline over the `pipe` mesh axis (parallel/pipeline.py)
    OP_PIPE_BLOCKS = enum.auto()
    OP_RESHAPE = enum.auto()
    OP_REVERSE = enum.auto()
    OP_TRANSPOSE = enum.auto()
    OP_EW_ADD = enum.auto()
    OP_EW_MUL = enum.auto()
    OP_MATMUL = enum.auto()
    OP_MUL = enum.auto()
    OP_ENLARGE = enum.auto()
    OP_SQUEEZE = enum.auto()
    OP_UNSQUEEZE = enum.auto()
    OP_EW_SUB = enum.auto()
    OP_EW_DIV = enum.auto()
    OP_EW_EQUAL = enum.auto()
    OP_EW_GREATER = enum.auto()
    OP_EW_LESS = enum.auto()
    OP_EW_MAX = enum.auto()
    OP_EW_MIN = enum.auto()
    OP_REDUCE_ARGMAX = enum.auto()
    OP_REDUCE_ARGMIN = enum.auto()
    OP_REDUCE_MAX = enum.auto()
    OP_REDUCE_MEAN = enum.auto()
    OP_REDUCE_MIN = enum.auto()
    OP_REDUCE_PROD = enum.auto()
    OP_REDUCE_SUM = enum.auto()
    OP_PAD = enum.auto()
    OP_SHAPE = enum.auto()
    OP_SIZE = enum.auto()
    OP_TOPK = enum.auto()
    OP_WHERE = enum.auto()
    OP_CEIL = enum.auto()
    OP_CAST = enum.auto()
    OP_EXP = enum.auto()
    OP_ROUND = enum.auto()
    OP_LOG = enum.auto()
    OP_LOGICAL_NOT = enum.auto()
    OP_SQRT = enum.auto()
    OP_SIN = enum.auto()
    OP_COS = enum.auto()
    OP_LEAKYRELU = enum.auto()
    OP_SLICE = enum.auto()
    OP_RESIZE = enum.auto()
    OP_PRELU = enum.auto()
    OP_GELU = enum.auto()
    OP_MULTIHEAD_ATTENTION = enum.auto()
    # incremental (decode-phase) self-attention over a stateful KV cache —
    # the serving-engine op the reference snapshot predates (its later
    # serving rewrite added IncMultiHeadSelfAttention; PAPER.md §0)
    OP_INC_MULTIHEAD_ATTENTION = enum.auto()
    # paged variant: the KV cache is a shared block pool + per-slot page
    # tables (vLLM/PagedAttention, SOSP '23) instead of a contiguous
    # per-slot region — the serving memory lever (docs/serving.md)
    OP_PAGED_INC_MULTIHEAD_ATTENTION = enum.auto()
    OP_FUSED = enum.auto()
    OP_RSQRT = enum.auto()
    OP_POW = enum.auto()
    OP_MEAN = enum.auto()
    OP_LAYERNORM = enum.auto()
    OP_GATHER = enum.auto()
    # Parallelization operators — first-class PCG nodes
    # (reference: src/parallel_ops/*)
    OP_REPARTITION = enum.auto()
    OP_COMBINE = enum.auto()
    OP_REPLICATE = enum.auto()
    OP_REDUCTION = enum.auto()
    OP_PIPELINE = enum.auto()
    OP_FUSED_PARALLEL = enum.auto()
    OP_INVALID = enum.auto()


PARALLEL_OP_TYPES = frozenset(
    {
        OperatorType.OP_REPARTITION,
        OperatorType.OP_COMBINE,
        OperatorType.OP_REPLICATE,
        OperatorType.OP_REDUCTION,
        OperatorType.OP_PIPELINE,
        OperatorType.OP_FUSED_PARALLEL,
    }
)


# guid ranges (reference: ffconst.h:230-239) — kept so tooling that keys on
# guid ranges (e.g. layer-vs-op discrimination) behaves identically.
LAYER_GUID_FIRST_VALID = 1000000
LAYER_GUID_LAST_VALID = 1999999
OP_GUID_FIRST_VALID = 2000000
OP_GUID_LAST_VALID = 2999999
TENSOR_GUID_FIRST_VALID = 3000000
TENSOR_GUID_LAST_VALID = 3999999
PARALLEL_TENSOR_GUID_FIRST_VALID = 4000000
NODE_GUID_FIRST_VALID = 5000000
