"""Weight initializers.

Reference: include/flexflow/initializer.h + initializer_kernel.cu — each a
Legion task over the weight's index space using curand. Here each initializer
is a pure function of a PRNG key; the executor gives every weight a distinct
key folded from the op/weight name, so results are reproducible regardless of
mesh shape or evaluation order (stronger determinism than the reference's
per-device curand streams).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp


class Initializer:
    def __call__(self, key, shape, dtype):
        raise NotImplementedError


@dataclass
class GlorotUniformInitializer(Initializer):
    seed: int = 0

    def __call__(self, key, shape, dtype):
        if len(shape) >= 2:
            fan_in, fan_out = shape[-2], shape[-1]
            receptive = math.prod(shape[:-2]) if len(shape) > 2 else 1
            fan_in *= receptive
            fan_out *= receptive
        else:
            fan_in = fan_out = shape[0] if shape else 1
        scale = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -scale, scale)


@dataclass
class ZeroInitializer(Initializer):
    def __call__(self, key, shape, dtype):
        return jnp.zeros(shape, dtype)


@dataclass
class ConstantInitializer(Initializer):
    value: float = 0.0

    def __call__(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype)


@dataclass
class UniformInitializer(Initializer):
    seed: int = 0
    min_val: float = 0.0
    max_val: float = 1.0

    def __call__(self, key, shape, dtype):
        return jax.random.uniform(key, shape, dtype, self.min_val, self.max_val)


@dataclass
class NormInitializer(Initializer):
    seed: int = 0
    mean: float = 0.0
    stddev: float = 1.0

    def __call__(self, key, shape, dtype):
        return self.mean + self.stddev * jax.random.normal(key, shape, dtype)


_BY_NAME = {
    "glorot_uniform": GlorotUniformInitializer(),
    "zeros": ZeroInitializer(),
    "ones": ConstantInitializer(1.0),
    "normal": NormInitializer(stddev=0.02),
    "uniform": UniformInitializer(),
}


def initializer_by_name(name: str) -> Initializer:
    return _BY_NAME[name]
