"""Keras frontend (reference python/flexflow/keras — SURVEY §2.5).

Same surface: `Input`, layer classes (Dense/Conv2D/MaxPooling2D/.../merge
layers), `Sequential` and functional `Model` with `compile(optimizer, loss,
metrics)` / `fit` / `evaluate`, string-named optimizers/losses/metrics. The
layer DAG is recorded symbolically and lowered onto an `FFModel` at compile,
exactly like the reference's BaseModel._create_flexflow_layers.
"""

from .layers import (
    Activation,
    Add,
    AveragePooling2D,
    BatchNormalization,
    Concatenate,
    Conv2D,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    Input,
    InputLayer,
    Layer,
    Maximum,
    Minimum,
    MaxPooling2D,
    Multiply,
    Permute,
    Reshape,
    Subtract,
    add,
    concatenate,
    subtract,
)
from . import callbacks, datasets
from .callbacks import (
    Callback,
    EpochVerifyMetrics,
    LearningRateScheduler,
    ModelCheckpoint,
    VerifyMetrics,
)
from .models import Model, Sequential
from .optimizers import SGD, Adam
