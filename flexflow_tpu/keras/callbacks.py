"""Keras callbacks (reference python/flexflow/keras/callbacks.py:1-90).

Same surface and semantics: `Callback` hook base, `LearningRateScheduler`
(epoch → rate, applied via the optimizer's set_learning_rate),
`VerifyMetrics` (train-end accuracy gate) and `EpochVerifyMetrics`
(per-epoch gate with early stop). Wired into `Model.fit(callbacks=...)` —
train and epoch hooks fire; an `on_epoch_end` returning truthy stops
training (the reference's early-stop contract)."""

from __future__ import annotations

import numpy as np


def _gate_value(accuracy) -> float:
    """A plain float is a fraction (this API's get_accuracy convention);
    the reference's ModelAccuracy-style enums (anything with a .value)
    carry percents."""
    if hasattr(accuracy, "value"):
        return float(accuracy.value) / 100.0
    return float(accuracy)


class Callback:
    def __init__(self):
        self.validation_data = None
        self.params = None
        self.model = None

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, batch, logs=None):
        pass

    def on_batch_end(self, batch, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass


class LearningRateScheduler(Callback):
    """schedule(epoch) -> float, applied before each epoch."""

    def __init__(self, schedule):
        super().__init__()
        self.schedule = schedule

    def on_epoch_begin(self, epoch, logs=None):
        if not hasattr(self.model.optimizer, "lr"):
            raise ValueError('Optimizer must have a "lr" attribute.')
        lr = self.schedule(epoch)
        if not isinstance(lr, (float, np.float32, np.float64)):
            raise ValueError(
                'The output of the "schedule" function should be float.')
        # through the FFModel so the jitted step's cached executable is
        # invalidated (the rate is a trace-time constant)
        self.model.ffmodel.set_learning_rate(lr)
        print("set learning rate ", self.model.optimizer.lr)


class ModelCheckpoint(Callback):
    """Checkpoint during keras-style training, backed by the resilience
    subsystem (atomic commits, reshard-aware restore — resilience/).

    - periodic: every `every_n_epochs` epochs (default 1);
    - save-best-on-metric: with save_best_only=True, only epochs improving
      the monitored metric are saved. monitor="accuracy" (mode max, from
      PerfMetrics.get_accuracy) or "loss" (mode min, the mean monitored
      loss from the perf counters).

    Restore with `model.ffmodel.load_checkpoint(directory)` — onto any
    mesh/Strategy.
    """

    def __init__(self, directory: str, monitor: str = "accuracy",
                 save_best_only: bool = False, every_n_epochs: int = 1,
                 keep: int = 3, verbose: bool = False):
        super().__init__()
        if monitor not in ("accuracy", "loss"):
            raise ValueError(
                f"monitor must be 'accuracy' or 'loss', got {monitor!r}")
        if every_n_epochs < 1:
            raise ValueError("every_n_epochs must be >= 1")
        self.directory = directory
        self.monitor = monitor
        self.save_best_only = save_best_only
        self.every_n_epochs = every_n_epochs
        self.keep = keep
        self.verbose = verbose
        self.best = None
        self.last_saved = None
        self._manager = None

    def _metric(self) -> float:
        pm = self.model.ffmodel.get_perf_metrics()
        if self.monitor == "accuracy":
            return float(pm.get_accuracy())
        return float(pm.get_mean_loss())

    def _improved(self, value: float) -> bool:
        if self.best is None:
            return True
        return (value > self.best if self.monitor == "accuracy"
                else value < self.best)

    def on_train_begin(self, logs=None):
        from ..resilience import ResilienceManager

        ff = self.model.ffmodel
        assert ff is not None, "compile() before fit with ModelCheckpoint"
        self._manager = ResilienceManager(ff, self.directory, keep=self.keep)

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.every_n_epochs != 0:
            return False
        value = self._metric()
        if self.save_best_only and not self._improved(value):
            return False
        if self._improved(value):
            self.best = value
        ff = self.model.ffmodel
        step = ff._py_step()
        # cursor epochs are ABSOLUTE since compile (fit's convention):
        # the inner fit already advanced _epoch_base past this epoch, and
        # the keras-relative `epoch` restarts at 0 on a second fit call
        abs_epoch = int(getattr(ff, "_epoch_base", epoch + 1))
        # async: serialization overlaps the next epoch; commit is atomic
        self._manager.save(step, cursor={"epoch": abs_epoch, "batch": 0})
        self.last_saved = step
        if self.verbose:
            print(f"ModelCheckpoint: saved step {step} "
                  f"({self.monitor}={value:.4f})")
        return False  # never early-stop training

    def on_train_end(self, logs=None):
        if self._manager is not None:
            self._manager.finalize()  # drain the in-flight async save


class Telemetry(Callback):
    """Enable the observability subsystem (telemetry/) for keras-style
    training: Chrome-trace timeline + JSONL metrics under `directory`,
    with one `epoch` record per keras epoch carrying the monitored
    accuracy/loss. The callback twin of --telemetry-dir.

    Artifacts are flushed at every epoch end (live tailing) and finalized
    at train end; the session stays attached to the model, so
    `model.ffmodel.get_telemetry()` reads it back afterwards.
    """

    def __init__(self, directory: str):
        super().__init__()
        self.directory = directory
        self.session = None

    def on_train_begin(self, logs=None):
        ff = self.model.ffmodel
        assert ff is not None, "compile() before fit with Telemetry"
        self.session = ff.enable_telemetry(self.directory)
        self.session.write_manifest(ff)

    def on_epoch_end(self, epoch, logs=None):
        pm = self.model.ffmodel.get_perf_metrics()
        self.session.recorder.record(
            "keras_epoch", epoch=int(epoch),
            accuracy=float(pm.get_accuracy()),
            mean_loss=float(pm.get_mean_loss()))
        self.session.flush()
        return False  # never early-stop training

    def on_train_end(self, logs=None):
        if self.session is not None:
            self.session.write_summary()
            self.session.flush()


class Diagnostics(Callback):
    """Enable the diagnostics subsystem (diagnostics/) for keras-style
    training: strategy explain report, cost-model drift monitoring, and
    run-health anomaly alerts, with artifacts (strategy_report.json/md,
    alerts.jsonl) under `directory` next to the telemetry files. The
    callback twin of --diagnostics; implies telemetry in the same
    directory when no session exists yet.

    `abort_on` lists rule names ("nan_loss", "step_spike",
    "data_wait_stall", "ckpt_stale") that stop training (HealthAbort)
    instead of warning. Both settings default to None — leave unset to
    inherit whatever --drift-threshold / --health-abort-on configured
    (passing values here overrides the flags).
    """

    def __init__(self, directory: str, drift_threshold=None,
                 abort_on=None):
        super().__init__()
        self.directory = directory
        self.drift_threshold = drift_threshold
        self.abort_on = abort_on if abort_on is None else tuple(abort_on)
        self.manager = None

    def on_train_begin(self, logs=None):
        ff = self.model.ffmodel
        assert ff is not None, "compile() before fit with Diagnostics"
        self.manager = ff.enable_diagnostics(
            self.directory, drift_threshold=self.drift_threshold,
            abort_on=self.abort_on)

    def on_train_end(self, logs=None):
        if self.manager is not None:
            session = self.model.ffmodel.get_telemetry()
            if session is not None:
                session.flush()


class VerifyMetrics(Callback):
    """Assert the final train accuracy clears a gate (AE scripts' check)."""

    def __init__(self, accuracy):
        super().__init__()
        self.accuracy = _gate_value(accuracy)

    def on_train_end(self, logs=None):
        got = self.model.ffmodel.get_perf_metrics().get_accuracy()
        assert got >= self.accuracy, (
            f"accuracy gate failed: {got:.4f} < {self.accuracy:.4f}")


class EpochVerifyMetrics(Callback):
    """Per-epoch accuracy gate; returning True from on_epoch_end stops
    training early once the gate is cleared."""

    def __init__(self, accuracy, early_stop: bool = True):
        super().__init__()
        self.accuracy = _gate_value(accuracy)
        self.early_stop = early_stop

    def on_epoch_end(self, epoch, logs=None):
        if not self.early_stop:
            return False
        got = self.model.ffmodel.get_perf_metrics().get_accuracy()
        return got >= self.accuracy
