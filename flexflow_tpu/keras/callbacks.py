"""Keras callbacks (reference python/flexflow/keras/callbacks.py:1-90).

Same surface and semantics: `Callback` hook base, `LearningRateScheduler`
(epoch → rate, applied via the optimizer's set_learning_rate),
`VerifyMetrics` (train-end accuracy gate) and `EpochVerifyMetrics`
(per-epoch gate with early stop). Wired into `Model.fit(callbacks=...)` —
train and epoch hooks fire; an `on_epoch_end` returning truthy stops
training (the reference's early-stop contract)."""

from __future__ import annotations

import numpy as np


def _gate_value(accuracy) -> float:
    """A plain float is a fraction (this API's get_accuracy convention);
    the reference's ModelAccuracy-style enums (anything with a .value)
    carry percents."""
    if hasattr(accuracy, "value"):
        return float(accuracy.value) / 100.0
    return float(accuracy)


class Callback:
    def __init__(self):
        self.validation_data = None
        self.params = None
        self.model = None

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, batch, logs=None):
        pass

    def on_batch_end(self, batch, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass


class LearningRateScheduler(Callback):
    """schedule(epoch) -> float, applied before each epoch."""

    def __init__(self, schedule):
        super().__init__()
        self.schedule = schedule

    def on_epoch_begin(self, epoch, logs=None):
        if not hasattr(self.model.optimizer, "lr"):
            raise ValueError('Optimizer must have a "lr" attribute.')
        lr = self.schedule(epoch)
        if not isinstance(lr, (float, np.float32, np.float64)):
            raise ValueError(
                'The output of the "schedule" function should be float.')
        # through the FFModel so the jitted step's cached executable is
        # invalidated (the rate is a trace-time constant)
        self.model.ffmodel.set_learning_rate(lr)
        print("set learning rate ", self.model.optimizer.lr)


class VerifyMetrics(Callback):
    """Assert the final train accuracy clears a gate (AE scripts' check)."""

    def __init__(self, accuracy):
        super().__init__()
        self.accuracy = _gate_value(accuracy)

    def on_train_end(self, logs=None):
        got = self.model.ffmodel.get_perf_metrics().get_accuracy()
        assert got >= self.accuracy, (
            f"accuracy gate failed: {got:.4f} < {self.accuracy:.4f}")


class EpochVerifyMetrics(Callback):
    """Per-epoch accuracy gate; returning True from on_epoch_end stops
    training early once the gate is cleared."""

    def __init__(self, accuracy, early_stop: bool = True):
        super().__init__()
        self.accuracy = _gate_value(accuracy)
        self.early_stop = early_stop

    def on_epoch_end(self, epoch, logs=None):
        if not self.early_stop:
            return False
        got = self.model.ffmodel.get_perf_metrics().get_accuracy()
        return got >= self.accuracy
