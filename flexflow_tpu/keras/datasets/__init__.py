"""Keras dataset loaders (reference python/flexflow/keras/datasets/:
mnist.py, cifar10.py). Same `load_data()` surface; this environment has no
network egress, so loaders read a local archive when present (the standard
keras cache or $FLEXFLOW_DATASET_DIR) and otherwise fall back to a
deterministic synthetic set with the real shapes/dtypes (clearly labeled —
pass synthetic=False to require real data)."""

from . import cifar10, mnist
