"""CIFAR-10 loader (reference python/flexflow/keras/datasets/cifar10.py +
cifar.py's batch unpickling). `load_data()` returns ((x_train, y_train),
(x_test, y_test)): x uint8 NCHW (N, 3, 32, 32) — the reference's
channels-first convention its CNN examples consume — y uint8 (N, 1).
Resolution mirrors mnist.py: a local `cifar10.npz` archive, else a
deterministic synthetic fallback (no network egress here)."""

from __future__ import annotations

import numpy as np

from .mnist import _local_archive, _synthetic


def load_data(path: str = "cifar10.npz", synthetic: bool | None = None,
              n_train: int = 8192, n_test: int = 1024):
    local = _local_archive(path)
    if local is not None:
        with np.load(local, allow_pickle=True) as f:
            return ((f["x_train"], f["y_train"]),
                    (f["x_test"], f["y_test"]))
    if synthetic is False:
        raise FileNotFoundError(
            f"{path} not found in $FLEXFLOW_DATASET_DIR or "
            f"~/.keras/datasets and synthetic=False; this environment has "
            f"no network egress to download it")
    (xtr, ytr), (xte, yte) = _synthetic((3, 32, 32), 10, n_train, n_test,
                                        seed=1)
    return (xtr, ytr.reshape(-1, 1)), (xte, yte.reshape(-1, 1))
