"""MNIST loader (reference python/flexflow/keras/datasets/mnist.py).

`load_data()` returns ((x_train, y_train), (x_test, y_test)) with the real
shapes/dtypes: x uint8 (N, 28, 28), y uint8 (N,). Resolution order:
  1. an `mnist.npz` in $FLEXFLOW_DATASET_DIR or ~/.keras/datasets (the
     standard keras cache layout: arrays x_train/y_train/x_test/y_test);
  2. with synthetic=True (default — this environment has no network
     egress), a DETERMINISTIC synthetic set: 10 fixed class-template
     images + per-sample noise, linearly separable so training gates
     (≥90% accuracy) are meaningful. Pass synthetic=False to require the
     real archive."""

from __future__ import annotations

import os

import numpy as np


def _local_archive(name: str):
    candidates = []
    env = os.environ.get("FLEXFLOW_DATASET_DIR")
    if env:
        candidates.append(os.path.join(env, name))
    candidates.append(os.path.expanduser(f"~/.keras/datasets/{name}"))
    for p in candidates:
        if os.path.exists(p):
            return p
    return None


def _synthetic(shape, num_classes, n_train, n_test, seed):
    rs = np.random.RandomState(seed)
    templates = rs.randint(0, 256, (num_classes,) + shape).astype(np.float32)

    def split(n, seed2):
        r = np.random.RandomState(seed2)
        y = r.randint(0, num_classes, n).astype(np.uint8)
        noise = r.randn(n, *shape).astype(np.float32) * 32.0
        x = np.clip(templates[y] * 0.5 + noise + 64.0, 0, 255)
        return x.astype(np.uint8), y

    return split(n_train, seed + 1), split(n_test, seed + 2)


def load_data(path: str = "mnist.npz", synthetic: bool | None = None,
              n_train: int = 8192, n_test: int = 1024):
    local = _local_archive(path)
    if local is not None:
        with np.load(local, allow_pickle=True) as f:
            return ((f["x_train"], f["y_train"]),
                    (f["x_test"], f["y_test"]))
    if synthetic is False:
        raise FileNotFoundError(
            f"{path} not found in $FLEXFLOW_DATASET_DIR or "
            f"~/.keras/datasets and synthetic=False; this environment has "
            f"no network egress to download it")
    return _synthetic((28, 28), 10, n_train, n_test, seed=0)
