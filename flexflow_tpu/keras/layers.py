"""Keras layer classes (reference python/flexflow/keras/layers/*).

Each layer is a symbolic node: `__call__` records connectivity on KTensor
handles and computes output shapes; `materialize(ff, inputs)` emits the
FFModel builder call at compile time.
"""

from __future__ import annotations

import itertools
from typing import Optional, Sequence

from ..fftype import ActiMode, DataType, PoolType

_uid = itertools.count()


class KTensor:
    """Symbolic keras tensor: batch-inclusive shape + the producing layer
    call. `call_inputs` records this specific call's inputs so a layer
    invoked multiple times (shared layer) keeps every edge."""

    def __init__(self, shape, dtype="float32", layer=None, idx=0,
                 name=None, call_inputs=()):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.layer = layer
        self.idx = idx
        self.name = name or f"ktensor_{next(_uid)}"
        self.call_inputs: tuple = tuple(call_inputs)

    @property
    def batch_shape(self):
        return self.shape


class Layer:
    def __init__(self, name=None, **kwargs):
        self.name = name or f"{type(self).__name__.lower()}_{next(_uid)}"
        self.input_tensors: list[KTensor] = []
        self.output_tensors: list[KTensor] = []
        self._num_calls = 0

    def __call__(self, inputs):
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        # last-call views, kept for Sequential and summary()
        self.input_tensors = list(ins)
        out_shape = self.compute_output_shape([t.shape for t in ins])
        self._num_calls += 1
        out = KTensor(out_shape, ins[0].dtype, layer=self,
                      name=f"{self.name}_out{self._num_calls}",
                      call_inputs=ins)
        self.output_tensors = [out]
        return out

    def compute_output_shape(self, in_shapes):
        return tuple(in_shapes[0])

    def materialize(self, ff, inputs):  # -> output Tensor
        raise NotImplementedError


class InputLayer(Layer):
    def __init__(self, shape=None, batch_size=None, dtype="float32",
                 name=None):
        super().__init__(name)
        self.batch_size = batch_size
        self.shape = tuple(shape or ())
        t = KTensor((batch_size,) + self.shape, dtype, layer=self,
                    name=self.name)
        self.output_tensors = [t]


def Input(shape=None, batch_size=None, dtype="float32", name=None):
    """Reference input_layer.py:43."""
    return InputLayer(shape, batch_size, dtype, name).output_tensors[0]


_ACTIVATIONS = {
    None: ActiMode.AC_MODE_NONE,
    "relu": ActiMode.AC_MODE_RELU,
    "sigmoid": ActiMode.AC_MODE_SIGMOID,
    "tanh": ActiMode.AC_MODE_TANH,
    "gelu": ActiMode.AC_MODE_GELU,
    "softmax": "softmax",
}


class Dense(Layer):
    def __init__(self, units, input_shape=None, activation=None,
                 use_bias=True, name=None, **kwargs):
        super().__init__(name)
        self.units = units
        self.activation = activation
        self.use_bias = use_bias
        self.input_shape_arg = input_shape

    def compute_output_shape(self, in_shapes):
        return tuple(in_shapes[0][:-1]) + (self.units,)

    def materialize(self, ff, inputs, shared_op=None):
        """`shared_op` is a sharing FLAG from BaseModel.compile (truthy on
        re-calls of the same layer object): the tie anchors to the first
        call's dense op recorded on the layer, since the externally visible
        output may be a trailing softmax tensor."""
        act = _ACTIVATIONS.get(self.activation, ActiMode.AC_MODE_NONE)
        softmax_after = act == "softmax"
        tie = (getattr(self, "_ff_dense_out", None) if shared_op else None)
        t = ff.dense(inputs[0], self.units,
                     ActiMode.AC_MODE_NONE if softmax_after else act,
                     use_bias=self.use_bias, name=self.name,
                     shared_op=tie)
        if getattr(self, "_ff_dense_out", None) is None:
            self._ff_dense_out = t
        if softmax_after:
            t = ff.softmax(t, name=f"{self.name}_softmax")
        return t


class Conv2D(Layer):
    def __init__(self, filters, kernel_size, strides=(1, 1), padding="valid",
                 activation=None, use_bias=True, input_shape=None,
                 groups=1, name=None, **kwargs):
        super().__init__(name)
        self.filters = filters
        self.kernel = (kernel_size,) * 2 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.strides = (strides,) * 2 if isinstance(strides, int) \
            else tuple(strides)
        self.padding = padding
        self.activation = activation
        self.use_bias = use_bias
        self.groups = groups
        self.input_shape_arg = input_shape

    def _pads(self, in_shape):
        if self.padding == "same":
            return self.kernel[0] // 2, self.kernel[1] // 2
        if self.padding == "valid":
            return 0, 0
        p = self.padding
        return (p, p) if isinstance(p, int) else tuple(p)

    def compute_output_shape(self, in_shapes):
        n, c, h, w = in_shapes[0]
        ph, pw = self._pads(in_shapes[0])
        oh = (h + 2 * ph - self.kernel[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.kernel[1]) // self.strides[1] + 1
        return (n, self.filters, oh, ow)

    def materialize(self, ff, inputs):
        ph, pw = self._pads(None)
        act = _ACTIVATIONS.get(self.activation, ActiMode.AC_MODE_NONE)
        softmax_after = act == "softmax"
        t = ff.conv2d(inputs[0], self.filters, *self.kernel, *self.strides,
                      ph, pw,
                      ActiMode.AC_MODE_NONE if softmax_after else act,
                      groups=self.groups, use_bias=self.use_bias,
                      name=self.name)
        if softmax_after:
            t = ff.softmax(t, name=f"{self.name}_softmax")
        return t


class Pooling2D(Layer):
    pool_type = PoolType.POOL_MAX

    def __init__(self, pool_size=(2, 2), strides=None, padding="valid",
                 name=None, **kwargs):
        super().__init__(name)
        self.pool = (pool_size,) * 2 if isinstance(pool_size, int) \
            else tuple(pool_size)
        strides = strides if strides is not None else self.pool
        self.strides = (strides,) * 2 if isinstance(strides, int) \
            else tuple(strides)
        self.padding = padding

    def _pads(self):
        if self.padding == "same":
            return self.pool[0] // 2, self.pool[1] // 2
        return 0, 0

    def compute_output_shape(self, in_shapes):
        n, c, h, w = in_shapes[0]
        ph, pw = self._pads()
        oh = (h + 2 * ph - self.pool[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.pool[1]) // self.strides[1] + 1
        return (n, c, oh, ow)

    def materialize(self, ff, inputs):
        ph, pw = self._pads()
        return ff.pool2d(inputs[0], *self.pool, *self.strides, ph, pw,
                         self.pool_type, name=self.name)


class MaxPooling2D(Pooling2D):
    pool_type = PoolType.POOL_MAX


class AveragePooling2D(Pooling2D):
    pool_type = PoolType.POOL_AVG


class Flatten(Layer):
    def compute_output_shape(self, in_shapes):
        s = in_shapes[0]
        n = 1
        for d in s[1:]:
            n *= d
        return (s[0], n)

    def materialize(self, ff, inputs):
        return ff.flat(inputs[0], name=self.name)


class Embedding(Layer):
    def __init__(self, input_dim, output_dim, input_length=None, name=None,
                 **kwargs):
        super().__init__(name)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def compute_output_shape(self, in_shapes):
        return tuple(in_shapes[0]) + (self.output_dim,)

    def materialize(self, ff, inputs):
        return ff.embedding(inputs[0], self.input_dim, self.output_dim,
                            name=self.name)


class Activation(Layer):
    def __init__(self, activation, name=None, **kwargs):
        super().__init__(name)
        self.activation = activation

    def materialize(self, ff, inputs):
        x = inputs[0]
        if self.activation == "softmax":
            return ff.softmax(x, name=self.name)
        fn = {"relu": ff.relu, "sigmoid": ff.sigmoid, "tanh": ff.tanh,
              "gelu": ff.gelu, "elu": ff.elu}[self.activation]
        return fn(x, name=self.name)


class Dropout(Layer):
    def __init__(self, rate, seed=0, name=None, **kwargs):
        super().__init__(name)
        self.rate = rate
        self.seed = seed

    def materialize(self, ff, inputs):
        return ff.dropout(inputs[0], self.rate, self.seed, name=self.name)


class Reshape(Layer):
    def __init__(self, target_shape, name=None, **kwargs):
        super().__init__(name)
        self.target_shape = tuple(target_shape)

    def compute_output_shape(self, in_shapes):
        return (in_shapes[0][0],) + self.target_shape

    def materialize(self, ff, inputs):
        return ff.reshape(
            inputs[0], (inputs[0].dims[0],) + self.target_shape,
            name=self.name)


class Permute(Layer):
    def __init__(self, dims, name=None, **kwargs):
        super().__init__(name)
        self.dims = tuple(dims)  # keras: 1-indexed, excludes batch

    def compute_output_shape(self, in_shapes):
        s = in_shapes[0]
        return (s[0],) + tuple(s[d] for d in self.dims)

    def materialize(self, ff, inputs):
        perm = (0,) + self.dims
        return ff.transpose(inputs[0], perm, name=self.name)


class BatchNormalization(Layer):
    def __init__(self, relu=False, name=None, **kwargs):
        super().__init__(name)
        self.relu = relu

    def materialize(self, ff, inputs):
        return ff.batch_norm(inputs[0], relu=self.relu, name=self.name)


class _Merge(Layer):
    def compute_output_shape(self, in_shapes):
        return tuple(in_shapes[0])


class Add(_Merge):
    def materialize(self, ff, inputs):
        return ff.add(inputs[0], inputs[1], name=self.name)


class Subtract(_Merge):
    def materialize(self, ff, inputs):
        return ff.subtract(inputs[0], inputs[1], name=self.name)


class Multiply(_Merge):
    def materialize(self, ff, inputs):
        return ff.multiply(inputs[0], inputs[1], name=self.name)


class Maximum(_Merge):
    def materialize(self, ff, inputs):
        return ff.max(inputs[0], inputs[1], name=self.name)


class Minimum(_Merge):
    def materialize(self, ff, inputs):
        return ff.min(inputs[0], inputs[1], name=self.name)


class Concatenate(_Merge):
    def __init__(self, axis=1, name=None, **kwargs):
        super().__init__(name)
        self.axis = axis

    def compute_output_shape(self, in_shapes):
        s = list(in_shapes[0])
        ax = self.axis % len(s)
        s[ax] = sum(x[ax] for x in in_shapes)
        return tuple(s)

    def materialize(self, ff, inputs):
        return ff.concat(list(inputs), self.axis, name=self.name)


def concatenate(input_tensors, _axis=1):
    return Concatenate(axis=_axis)(input_tensors)


def add(input_tensors):
    return Add()(input_tensors)


def subtract(input_tensors):
    return Subtract()(input_tensors)
