"""Keras Sequential + functional Model (reference keras/models/*.py).

compile() lowers the symbolic layer DAG onto a fresh FFModel (the reference's
BaseModel.compile → _create_flexflow_layers, base_model.py:128-197); fit/
evaluate delegate to FFModel.fit/eval (same trace loop semantics,
base_model.py:198-376).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..config import FFConfig
from ..fftype import DataType, LossType, MetricsType
from ..model import FFModel
from .layers import InputLayer, KTensor, Layer
from . import optimizers as _optim

_LOSSES = {
    "categorical_crossentropy": LossType.LOSS_CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy":
        LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
    "mse": LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
}

_METRICS = {
    "accuracy": MetricsType.METRICS_ACCURACY,
    "categorical_crossentropy": MetricsType.METRICS_CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy":
        MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": MetricsType.METRICS_MEAN_SQUARED_ERROR,
    "mean_absolute_error": MetricsType.METRICS_MEAN_ABSOLUTE_ERROR,
}


class BaseModel:
    def __init__(self, name=None):
        self.name = name
        self.ffmodel: Optional[FFModel] = None
        self.ffconfig = FFConfig()
        self._output_tensor = None
        self.optimizer = None  # the core optimizer, set by compile()

    # ---- provided by subclasses: producing KTensors in topological order
    def _topo_calls(self):
        raise NotImplementedError

    def _input_ktensors(self):
        raise NotImplementedError

    def compile(self, optimizer, loss=None, metrics=None, **kwargs):
        ff = FFModel(self.ffconfig)
        mapping = {}
        for kt in self._input_ktensors():
            shape = list(kt.shape)
            if shape[0] is None:
                shape[0] = self.ffconfig.batch_size
            dtype = (DataType.DT_INT32 if "int" in str(kt.dtype)
                     else DataType.DT_FLOAT)
            mapping[kt.name] = ff.create_tensor(shape, dtype, name=kt.name)
        import inspect

        call_counts: dict = {}
        for kt in self._topo_calls():
            layer = kt.layer
            # stale anchors from a previous compile must not leak into this
            # FFModel
            if call_counts.get(id(layer), 0) == 0:
                layer._ff_dense_out = None
            ins = [mapping[t.name] for t in kt.call_inputs]
            n = call_counts.get(id(layer), 0)
            call_counts[id(layer)] = n + 1
            if n > 0:
                # shared layer called again: materialize under a unique name
                # tied to the first call's parameters (Keras layer-sharing
                # semantics; reference dense/embedding shared_op). Layer
                # types whose materialize has no shared_op parameter fall
                # back to per-call weights — a real limitation for weighted
                # layers other than Dense, kept visible here rather than
                # swallowed by a broad except.
                saved = layer.name
                layer.name = f"{saved}_call{n}"
                sig = inspect.signature(layer.materialize)
                if "shared_op" in sig.parameters:
                    out = layer.materialize(ff, ins, shared_op=True)
                else:
                    out = layer.materialize(ff, ins)
                layer.name = saved
            else:
                out = layer.materialize(ff, ins)
            mapping[kt.name] = out
        loss_type = _LOSSES[loss] if isinstance(loss, str) else (
            loss or LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        mtypes = [_METRICS[m] if isinstance(m, str) else m
                  for m in (metrics or [])]
        ff.compile(optimizer=_optim.get(optimizer), loss_type=loss_type,
                   metrics=mtypes)
        self.ffmodel = ff
        self.optimizer = ff.optimizer  # scheduler-settable (callbacks.py)
        return ff

    def fit(self, x, y, epochs=1, batch_size=-1, callbacks=None,
            shuffle=True, verbose=True):
        """Reference base_model.py:198-376 semantics: train/epoch callback
        hooks fire around the per-epoch FFModel.fit loop; an on_epoch_end
        returning truthy stops training early (EpochVerifyMetrics)."""
        assert self.ffmodel is not None, "call compile() first"
        if isinstance(x, (list, tuple)):
            names = [t.name for t in self._input_ktensors()]
            x = dict(zip(names, x))
        y = np.asarray(y)
        callbacks = list(callbacks or [])
        for cb in callbacks:
            cb.set_model(self)
            cb.set_params({"epochs": epochs, "batch_size": batch_size})
            cb.on_train_begin()
        for epoch in range(epochs):
            # per-epoch metrics, like the reference's reset at epoch start
            # (base_model.py:397): gates read THIS epoch's accuracy, not a
            # running average over all epochs
            self.ffmodel.reset_metrics()
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            self.ffmodel.fit(x, y, epochs=1, batch_size=batch_size,
                             shuffle=shuffle, verbose=verbose)
            # evaluate EVERY callback's hook before deciding to stop — a
            # short-circuiting any() would starve callbacks after the
            # first truthy one of their final-epoch hook
            stops = [cb.on_epoch_end(epoch) for cb in callbacks]
            if any(stops):
                break
        for cb in callbacks:
            cb.on_train_end()

    def evaluate(self, x, y, batch_size=-1):
        assert self.ffmodel is not None
        if isinstance(x, (list, tuple)):
            names = [t.name for t in self._input_ktensors()]
            x = dict(zip(names, x))
        return self.ffmodel.eval(x, np.asarray(y), batch_size=batch_size)

    def summary(self):
        for kt in self._topo_calls():
            print(f"{kt.layer.name}: "
                  f"{[t.shape for t in kt.call_inputs]} -> [{kt.shape}]")


class Sequential(BaseModel):
    """reference keras/models/sequential.py."""

    def __init__(self, layers=None, name=None):
        super().__init__(name)
        self._layers: list[Layer] = []
        self._input_kt = None
        for l in layers or []:
            self.add(l)

    def add(self, layer: Layer):
        if isinstance(layer, InputLayer):
            self._input_kt = layer.output_tensors[0]
            return
        if self._input_kt is None:
            shape = getattr(layer, "input_shape_arg", None)
            assert shape is not None, (
                "first layer needs input_shape= or add an InputLayer"
            )
            self._input_kt = KTensor((None,) + tuple(shape))
        prev = (self._layers[-1].output_tensors[0] if self._layers
                else self._input_kt)
        layer(prev)
        self._layers.append(layer)

    def _topo_calls(self):
        return [l.output_tensors[0] for l in self._layers]

    def _input_ktensors(self):
        return [self._input_kt]


class Model(BaseModel):
    """Functional model (reference keras/models/model.py): walk back from
    outputs to inputs to topologically order the recorded layer DAG."""

    def __init__(self, inputs, outputs, name=None):
        super().__init__(name)
        self.inputs = inputs if isinstance(inputs, (list, tuple)) \
            else [inputs]
        self.outputs = outputs if isinstance(outputs, (list, tuple)) \
            else [outputs]
        self._order = self._toposort()

    def _toposort(self):
        """DFS over KTensors (per-call edges, so shared layers keep every
        invocation)."""
        order, visited = [], set()

        def visit(kt: KTensor):
            if kt.layer is None or isinstance(kt.layer, InputLayer):
                return
            if kt.name in visited:
                return
            visited.add(kt.name)
            for t in kt.call_inputs:
                visit(t)
            order.append(kt)

        for out in self.outputs:
            visit(out)
        return order

    def _topo_calls(self):
        return list(self._order)

    def _input_ktensors(self):
        return list(self.inputs)
