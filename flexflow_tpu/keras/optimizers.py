"""Keras optimizers (reference python/flexflow/keras/optimizers.py)."""

from __future__ import annotations

from ..optimizer import AdamOptimizer, SGDOptimizer


class SGD:
    def __init__(self, learning_rate=0.01, momentum=0.0, nesterov=False,
                 weight_decay=0.0, **kwargs):
        self.core = SGDOptimizer(lr=learning_rate, momentum=momentum,
                                 nesterov=nesterov,
                                 weight_decay=weight_decay)


class Adam:
    def __init__(self, learning_rate=0.001, beta_1=0.9, beta_2=0.999,
                 epsilon=1e-8, **kwargs):
        self.core = AdamOptimizer(alpha=learning_rate, beta1=beta_1,
                                  beta2=beta_2, epsilon=epsilon)


def get(name_or_opt):
    if isinstance(name_or_opt, (SGD, Adam)):
        return name_or_opt.core
    if isinstance(name_or_opt, str):
        return {"sgd": SGD(), "adam": Adam()}[name_or_opt.lower()].core
    return name_or_opt  # already a core Optimizer
