"""Pallas TPU kernels for the hot ops.

The reference's L2 is hand-written CUDA/cuDNN kernels (SURVEY §1). On TPU,
XLA emits MXU-tiled code for nearly everything; Pallas kernels exist only
where fusion across the softmax (attention) or data-dependent routing (MoE)
beats XLA's default lowering.
"""

from .flash_attention import flash_attention
