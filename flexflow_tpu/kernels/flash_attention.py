"""Fused (flash) attention as a Pallas TPU kernel.

Replaces the reference's cuDNN `cudnnMultiHeadAttnForward` call
(src/ops/attention.cu:35) as the fast attention path. Design follows the
standard flash-attention blocking for TPU: grid over (batch*heads, q-blocks,
kv-blocks) with the kv axis innermost and sequential ("arbitrary"), a
(block_q, block_k) logits tile living in VMEM, and online-softmax running
max/denominator carried in VMEM scratch across kv steps. The MXU sees two
large matmuls per tile; HBM traffic is O(s*d) instead of the O(s^2)
materialized-probabilities tensor XLA would allocate at long sequence.

Backward currently recomputes attention under autodiff via the XLA einsum
path (correct, memory O(s^2) per block pair at trace level but XLA re-tiles
it); a dedicated Pallas backward is a planned optimization.

On non-TPU backends (the 8-device CPU test mesh) the kernel runs in Pallas
interpret mode so tests exercise the same code path.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _attn_reference(q, k, v, causal: bool, scale: float):
    """XLA-path attention (ops.attention.sdpa_xla): the small-shape fallback
    and the custom-VJP backward reference — one source of truth for attention
    numerics. Lazy import avoids a cycle (ops.attention lazily imports this
    module for impl="flash")."""
    from ..ops.attention import sdpa_xla

    return sdpa_xla(q, k, v, causal=causal, scale=scale)


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, scale: float, causal: bool, block_q: int, block_k: int, seq_k: int,
    causal_offset: int,
):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # with causal masking, kv blocks strictly above the diagonal contribute
    # nothing — skip them entirely (halves the work, like the reference's
    # unmasked cuDNN op cannot). Diagonal is bottom-right aligned
    # (offset = seq_k - seq_q), matching sdpa_xla's tril(k=s_k-s_q).
    live = (
        (j * block_k <= i * block_q + block_q - 1 + causal_offset)
        if causal else True
    )

    @pl.when(live)
    def _step():
        q = q_ref[0]  # (block_q, d)
        k = k_ref[0]  # (block_k, d)
        v = v_ref[0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        k_pos = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        ) + j * block_k
        # mask the padded tail of the last kv block, plus the causal triangle
        mask = k_pos < seq_k
        if causal:
            q_pos = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            ) + i * block_q
            mask = mask & (q_pos + causal_offset >= k_pos)
        logits = jnp.where(mask, logits, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, logits.max(axis=-1))
        p = jnp.exp(logits - m_new[:, None])
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1)
        # zero padded V rows: OOB block rows hold garbage (NaN in interpret
        # mode) and 0·NaN would poison the contraction
        v_valid = jax.lax.broadcasted_iota(
            jnp.int32, v.shape, 0
        ) + j * block_k < seq_k
        v = jnp.where(v_valid, v, 0.0)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new

    @pl.when(j == nj - 1)
    def _finish():
        o_ref[0] = (acc_ref[...] / l_ref[...][:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal: bool, scale: float,
               block_q: int, block_k: int):
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    bq = min(block_q, s_q)
    bk = min(block_k, s_k)
    qf = q.reshape(b * h, s_q, d)
    kf = k.reshape(b * h, s_k, d)
    vf = v.reshape(b * h, s_k, d)
    grid = (b * h, pl.cdiv(s_q, bq), pl.cdiv(s_k, bk))
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_k=s_k, causal_offset=s_k - s_q,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=jax.default_backend() != "tpu",
        name="flash_attention_fwd",
    )(qf, kf, vf)
    return out.reshape(b, h, s_q, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k)


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k):
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k), (q, k, v)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _attn_reference(q_, k_, v_, causal, scale), q, k, v
    )
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q, k, v, *, causal: bool = False, scale: float | None = None,
    block_q: int = 512, block_k: int = 512,
):
    """Fused attention. q,k,v: (batch, heads, seq, head_dim)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s_q, s_k, d = q.shape[2], k.shape[2], q.shape[3]
    # shape gate: tiny/ragged shapes go to the XLA path (still fused by XLA)
    if s_q < 128 or s_k < 128 or d % 8 != 0:
        return _attn_reference(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, block_q, block_k)
