"""Fused (flash) attention as a Pallas TPU kernel.

Replaces the reference's cuDNN `cudnnMultiHeadAttnForward` call
(src/ops/attention.cu:35) as the fast attention path. Design follows the
standard flash-attention blocking for TPU: grid over (batch*heads, q-blocks,
kv-blocks) with the kv axis innermost and sequential ("arbitrary"), a
(block_q, block_k) logits tile living in VMEM, and online-softmax running
max/denominator carried in VMEM scratch across kv steps. The MXU sees two
large matmuls per tile; HBM traffic is O(s*d) instead of the O(s^2)
materialized-probabilities tensor XLA would allocate at long sequence.

At short head_dim the kernel is VPU-bound (exp/mask/select passes over the
(block_q, block_k) tile dominate the two small MXU matmuls), so the tile
body is specialized three ways to do the minimum vector work:
  - dead tiles (strictly above the causal diagonal) are skipped entirely —
    with block < seq this halves the softmax work for causal attention;
  - interior tiles (strictly below the diagonal, no key tail) run with no
    iota/compare/select at all;
  - only diagonal / ragged-tail tiles pay for mask construction, and the
    masks that are statically all-true (seq divisible by block) are never
    built.
When the kv axis fits one block, the online-softmax scratch, init and
rescale passes are statically elided (one-pass softmax).

Backward is the FlashAttention-2 scheme as two Pallas kernels: the forward
saves per-row logsumexp; `delta = rowsum(dO*O)` is a cheap XLA elementwise
precompute; the dq kernel iterates kv-blocks per q-block and the dk/dv
kernel iterates q-blocks per kv-block, both recomputing the probability
tile from (q, k, lse) with the same three-way tile specialization.

On non-TPU backends (the 8-device CPU test mesh) the kernel runs in Pallas
interpret mode so tests exercise the same code path.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Older jax spells pltpu.CompilerParams as TPUCompilerParams (same
# dimension_semantics field); resolve once so the kernels — and the
# interpret-mode CPU test suite — run on both.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

NEG_INF = -1e30
# Minor dim of the (seq,) row-stat tensors (lse/delta): Mosaic wants
# 128-lane minor blocks for f32 (the in-tree jax flash kernel's
# MIN_BLOCK_SIZE); measured faster than an 8-lane layout on v5e despite the
# 16x larger residual, because every row-stat read in the bwd kernels is a
# lane-aligned block load.
LSE_LANES = 128


def _attn_reference(q, k, v, causal: bool, scale: float):
    """XLA-path attention (ops.attention.sdpa_xla): the small-shape fallback
    and the custom-VJP backward reference — one source of truth for attention
    numerics. Lazy import avoids a cycle (ops.attention lazily imports this
    module for impl="flash")."""
    from ..ops.attention import sdpa_xla

    return sdpa_xla(q, k, v, causal=causal, scale=scale)


def _tile_classes(i, j, *, causal, block_q, block_k, causal_offset,
                  even_k, nj):
    """(live, needs_mask) predicates for tile (q-block i, kv-block j).

    A tile is live unless it lies strictly above the causal diagonal. It
    needs a mask if it straddles the diagonal or covers a ragged key tail;
    interior tiles run the unmasked fast path. Predicates are traced scalars
    (grid indices are dynamic) but the *structure* — whether a mask could
    ever be needed — is static Python, so fully-regular shapes compile no
    mask code at all."""
    if causal:
        live = j * block_k <= i * block_q + block_q - 1 + causal_offset
        # interior ⇔ the tile's top-right element (min q row, max k col) is
        # still on/below the diagonal
        interior = i * block_q + causal_offset >= j * block_k + block_k - 1
        needs_mask = jnp.logical_not(interior)
    else:
        live = True
        needs_mask = False
    if not even_k:
        tail = j == nj - 1
        needs_mask = jnp.logical_or(needs_mask, tail) if causal else tail
    return live, needs_mask


def _tile_mask(i, j, *, causal, block_q, block_k, seq_k, causal_offset,
               even_k):
    """Boolean (block_q, block_k) mask for a diagonal/tail tile. Only the
    statically-possible components are built."""
    mask = None
    if not even_k:
        k_pos = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        ) + j * block_k
        mask = k_pos < seq_k
    if causal:
        q_pos = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        ) + i * block_q
        k_pos = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        ) + j * block_k
        tri = q_pos + causal_offset >= k_pos
        mask = tri if mask is None else jnp.logical_and(mask, tri)
    return mask


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, *refs,
    scale: float, causal: bool, block_q: int, block_k: int, seq_k: int,
    causal_offset: int, save_lse: bool, nj: int,
    i_dim: int = 1, j_dim: int = 2,
):
    even_k = seq_k % block_k == 0
    single_kv = nj == 1
    if save_lse:
        lse_ref = refs[0]
        refs = refs[1:]
    else:
        lse_ref = None
    if not single_kv:
        m_ref, l_ref, acc_ref = refs
    i = pl.program_id(i_dim)
    j = pl.program_id(j_dim)

    def step(masked: bool):
        q = q_ref[0]  # (block_q, d)
        k = k_ref[0]  # (block_k, d)
        v = v_ref[0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        if masked:
            mask = _tile_mask(
                i, j, causal=causal, block_q=block_q, block_k=block_k,
                seq_k=seq_k, causal_offset=causal_offset, even_k=even_k,
            )
            logits = jnp.where(mask, logits, NEG_INF)
            # Masked logits underflow to p == 0 exactly, so no second
            # probability mask is needed. A row with zero live keys (only
            # possible when causal and s_q > s_k) gets uniform p — the same
            # value sdpa_xla's softmax-of-constant-row produces, so the two
            # impls agree on that degenerate case.
            if not even_k:
                # zero padded V rows: OOB block rows hold garbage (NaN in
                # interpret mode) and 0·NaN would poison the contraction.
                v_valid = jax.lax.broadcasted_iota(
                    jnp.int32, v.shape, 0
                ) + j * block_k < seq_k
                v = jnp.where(v_valid, v, 0.0)

        if single_kv:
            # one-pass softmax: no scratch, no init/rescale passes
            m = logits.max(axis=-1)
            p = jnp.exp(logits - m[:, None])
            l = p.sum(axis=-1)
            acc = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
            if save_lse:
                lse = m + jnp.log(l)
                lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref[0].shape)
        else:
            m_prev = m_ref[...]
            m_new = jnp.maximum(m_prev, logits.max(axis=-1))
            p = jnp.exp(logits - m_new[:, None])
            alpha = jnp.exp(m_prev - m_new)
            l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1)
            acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[...] = m_new

    if single_kv:
        # masked-ness is static: exactly one body is compiled
        masked = causal or not even_k
        step(masked)
        return

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    live, needs_mask = _tile_classes(
        i, j, causal=causal, block_q=block_q, block_k=block_k,
        causal_offset=causal_offset,
        even_k=seq_k % block_k == 0, nj=nj,
    )
    if causal or seq_k % block_k != 0:
        live_masked = jnp.logical_and(live, needs_mask)
        live_clear = jnp.logical_and(live, jnp.logical_not(needs_mask))
        pl.when(live_masked)(lambda: step(True))
        pl.when(live_clear)(lambda: step(False))
    else:
        step(False)

    @pl.when(j == nj - 1)
    def _finish():
        o_ref[0] = (acc_ref[...] / l_ref[...][:, None]).astype(o_ref.dtype)
        if save_lse:
            # row stats carry a minor dim of LSE_LANES so the block is
            # tile-legal on TPU (same trick as jax's in-tree flash kernel)
            lse = m_ref[...] + jnp.log(l_ref[...])
            lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref[0].shape)


def _flash_fwd(q, k, v, causal: bool, scale: float,
               block_q: int, block_k: int, save_lse: bool = True):
    """save_lse=False (the primal / inference path) skips computing and
    writing the logsumexp residual entirely."""
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    bq = min(block_q, s_q)
    bk = min(block_k, s_k)
    qf = q.reshape(b * h, s_q, d)
    kf = k.reshape(b * h, s_k, d)
    vf = v.reshape(b * h, s_k, d)
    nj = pl.cdiv(s_k, bk)
    grid = (b * h, pl.cdiv(s_q, bq), nj)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_k=s_k, causal_offset=s_k - s_q, save_lse=save_lse, nj=nj,
    )
    out_specs = [pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype)]
    if save_lse:
        out_specs.append(
            pl.BlockSpec((1, bq, LSE_LANES), lambda bh, i, j: (bh, i, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b * h, s_q, LSE_LANES), jnp.float32))
    scratch_shapes = []
    if nj > 1:
        scratch_shapes = [
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ]
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=jax.default_backend() != "tpu",
        name="flash_attention_fwd",
    )(qf, kf, vf)
    if save_lse:
        out, lse = res
    else:
        (out,), lse = res, None
    return out.reshape(b, h, s_q, d), lse


def _bwd_tile_math(
    q, k, v, do, lse, delta, i, j, masked,
    *, scale: float, causal: bool, block_q: int, block_k: int,
    seq_q: int, seq_k: int, causal_offset: int, mask_q_rows: bool,
):
    """Shared backward tile recompute on plain arrays: rebuild the
    probability tile p from (q, k, lse) and form ds = p*(dp - delta)*scale.
    Shared between the per-head ref-loading wrapper (`_bwd_tile`) and the
    grouped narrow-head kernels, which load lane sub-slices per head.

    Padded-row handling is static: q-row zeroing only exists when seq_q is
    ragged against block_q (garbage rows are NaN in interpret mode and
    0*NaN would poison contractions), kv-row zeroing only when seq_k is
    ragged against block_k. mask_q_rows additionally joins q-row validity
    into the probability mask: padded q rows have p == exp(0-0) == 1 and
    must not leak into reductions over the q axis (dk/dv); reductions over
    the kv axis (dq) don't need it because their padded output rows are
    discarded on write."""
    even_q = seq_q % block_q == 0
    even_k = seq_k % block_k == 0
    q_valid = None
    if not even_q:
        q_valid = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0
        ) + i * block_q < seq_q
        q = jnp.where(q_valid, q, 0.0)
        do = jnp.where(q_valid, do, 0.0)
        lse = jnp.where(q_valid[:, 0], lse, 0.0)
        delta = jnp.where(q_valid[:, 0], delta, 0.0)
    if not even_k:
        kv_valid = jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0
        ) + j * block_k < seq_k
        k = jnp.where(kv_valid, k, 0.0)
        v = jnp.where(kv_valid, v, 0.0)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    mask = None
    if masked:
        mask = _tile_mask(
            i, j, causal=causal, block_q=block_q, block_k=block_k,
            seq_k=seq_k, causal_offset=causal_offset, even_k=even_k,
        )
    if mask_q_rows and q_valid is not None:
        mask = q_valid if mask is None else jnp.logical_and(mask, q_valid)
    p = jnp.exp(s - lse[:, None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta[:, None]) * scale
    return q, k, v, do, p, ds


def _bwd_tile(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, i, j, masked,
    *, scale: float, causal: bool, block_q: int, block_k: int,
    seq_q: int, seq_k: int, causal_offset: int, mask_q_rows: bool,
):
    """Ref-loading wrapper around `_bwd_tile_math` for the per-head
    kernels (one head per block; leading singleton block dim)."""
    return _bwd_tile_math(
        q_ref[0], k_ref[0], v_ref[0], do_ref[0],
        lse_ref[0][:, 0], delta_ref[0][:, 0], i, j, masked,
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        seq_q=seq_q, seq_k=seq_k, causal_offset=causal_offset,
        mask_q_rows=mask_q_rows)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, scale: float, causal: bool, block_q: int, block_k: int,
    seq_q: int, seq_k: int, causal_offset: int, nj: int,
    i_dim: int = 1, j_dim: int = 2,
):
    i = pl.program_id(i_dim)
    j = pl.program_id(j_dim)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def step(masked: bool):
        q, k, _, do, p, ds = _bwd_tile(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, i, j, masked,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            seq_q=seq_q, seq_k=seq_k, causal_offset=causal_offset,
            mask_q_rows=False,  # padded dq rows are discarded on write
        )
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    live, needs_mask = _tile_classes(
        i, j, causal=causal, block_q=block_q, block_k=block_k,
        causal_offset=causal_offset, even_k=seq_k % block_k == 0, nj=nj,
    )
    if causal or seq_k % block_k != 0:
        pl.when(jnp.logical_and(live, needs_mask))(lambda: step(True))
        pl.when(jnp.logical_and(live, jnp.logical_not(needs_mask)))(
            lambda: step(False))
    else:
        step(False)

    @pl.when(j == nj - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale: float, causal: bool, block_q: int, block_k: int,
    seq_q: int, seq_k: int, causal_offset: int, ni: int, nj: int,
    i_dim: int = 2, j_dim: int = 1,
):
    j = pl.program_id(j_dim)  # kv block
    i = pl.program_id(i_dim)  # q block (innermost, sequential)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def step(masked: bool):
        q, _, _, do, p, ds = _bwd_tile(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, i, j, masked,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            seq_q=seq_q, seq_k=seq_k, causal_offset=causal_offset,
            mask_q_rows=True,  # padded q rows would leak p==1 into dk/dv
        )
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    live, needs_mask = _tile_classes(
        i, j, causal=causal, block_q=block_q, block_k=block_k,
        causal_offset=causal_offset, even_k=seq_k % block_k == 0, nj=nj,
    )
    # (a ragged q tail needs no masked-path forcing here: _bwd_tile joins
    # q-row validity into the probability mask independently of `masked`)
    if causal or seq_k % block_k != 0:
        pl.when(jnp.logical_and(live, needs_mask))(lambda: step(True))
        pl.when(jnp.logical_and(live, jnp.logical_not(needs_mask)))(
            lambda: step(False))
    else:
        step(False)

    @pl.when(i == ni - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_single_tile_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dk_ref, dv_ref,
    *, scale: float, causal: bool, block_q: int, block_k: int,
    seq_q: int, seq_k: int, causal_offset: int,
):
    """Whole-sequence backward in ONE kernel (seq fits a single tile): the
    probability tile and ds are computed once and reused for dq, dk, AND
    dv — the split dq/dkv FA2 kernels each recompute them, costing a
    second exp pass over the logits tile. At short-to-medium sequence this
    is the dominant backward cost (the kernels are VPU-bound, like the
    forward)."""
    zero = jnp.zeros((), jnp.int32)
    q, k, v, do, p, ds = _bwd_tile(
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, zero, zero,
        True,  # single tile is always the diagonal tile under causal
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        seq_q=seq_q, seq_k=seq_k, causal_offset=causal_offset,
        # invariant of this kernel: the caller fixes block == seq, so
        # there are never padded q rows to mask
        mask_q_rows=False,
    )
    dq_ref[0] = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dq_ref.dtype)
    dk_ref[0] = jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dk_ref.dtype)
    dv_ref[0] = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dv_ref.dtype)


def _flash_bwd_single_tile(qf, kf, vf, gf, lse, delta, causal, scale,
                           s_q, s_k, d, bh):
    spec = pl.BlockSpec((1, s_q, d), lambda i: (i, 0, 0))
    kspec = pl.BlockSpec((1, s_k, d), lambda i: (i, 0, 0))
    rowspec = pl.BlockSpec((1, s_q, LSE_LANES), lambda i: (i, 0, 0))
    return pl.pallas_call(
        functools.partial(
            _bwd_single_tile_kernel, scale=scale, causal=causal,
            block_q=s_q, block_k=s_k, seq_q=s_q, seq_k=s_k,
            causal_offset=s_k - s_q,
        ),
        grid=(bh,),
        in_specs=[spec, kspec, kspec, spec, rowspec, rowspec],
        out_specs=[spec, kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), qf.dtype),
            jax.ShapeDtypeStruct((bh, s_k, d), kf.dtype),
            jax.ShapeDtypeStruct((bh, s_k, d), vf.dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",),
        ),
        interpret=jax.default_backend() != "tpu",
        name="flash_attention_bwd_fused",
    )(qf, kf, vf, gf, lse, delta)


def _flash_bwd(q, k, v, out, lse, g, causal, scale, block_q, block_k,
               delta_adj=None):
    """`delta_adj` (b, h, s_q), when given, is SUBTRACTED from delta before
    the kernels run: the lse cotangent of the with-lse forward. Derivation:
    ∂lse_i/∂s_ij = p_ij, so a g_lse cotangent adds p·g_lse to ds — i.e.
    ds = p·(dp − (delta − g_lse)), a pure delta shift. dv = pᵀ·do is
    unaffected, so the same dq/dkv kernels serve both VJPs."""
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    bq = min(block_q, s_q)
    bk = min(block_k, s_k)
    qf = q.reshape(b * h, s_q, d)
    kf = k.reshape(b * h, s_k, d)
    vf = v.reshape(b * h, s_k, d)
    gf = g.reshape(b * h, s_q, d)
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise XLA precompute
    delta = jnp.sum(
        gf.astype(jnp.float32) * out.reshape(b * h, s_q, d).astype(jnp.float32),
        axis=-1,
    )
    if delta_adj is not None:
        delta = delta - delta_adj.reshape(b * h, s_q).astype(jnp.float32)
    delta = jnp.broadcast_to(delta[..., None], (b * h, s_q, LSE_LANES))
    interpret = jax.default_backend() != "tpu"
    ni = pl.cdiv(s_q, bq)
    nj = pl.cdiv(s_k, bk)
    if ni == 1 and nj == 1:
        dq, dk, dv = _flash_bwd_single_tile(
            qf, kf, vf, gf, lse, delta, causal, scale, s_q, s_k, d, b * h)
        return (
            dq.reshape(b, h, s_q, d),
            dk.reshape(b, h, s_k, d),
            dv.reshape(b, h, s_k, d),
        )
    common = dict(
        scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_q=s_q, seq_k=s_k, causal_offset=s_k - s_q,
    )
    qspec = pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0))
    kspec = pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0))
    rowspec = pl.BlockSpec((1, bq, LSE_LANES), lambda bh, i, j: (bh, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, nj=nj, **common),
        grid=(b * h, ni, nj),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
        name="flash_attention_bwd_dq",
    )(qf, kf, vf, gf, lse, delta)
    # kv-grid kernel: block index maps take (bh, kv_j, q_i)
    qspec2 = pl.BlockSpec((1, bq, d), lambda bh, j, i: (bh, i, 0))
    kspec2 = pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0))
    rowspec2 = pl.BlockSpec((1, bq, LSE_LANES), lambda bh, j, i: (bh, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, ni=ni, nj=nj, **common),
        grid=(b * h, nj, ni),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s_k, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, s_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
        name="flash_attention_bwd_dkv",
    )(qf, kf, vf, gf, lse, delta)
    return (
        dq.reshape(b, h, s_q, d),
        dk.reshape(b, h, s_k, d),
        dv.reshape(b, h, s_k, d),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                        save_lse=False)
    return out


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, g, causal, scale, block_q, block_k)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ----------------------------------------------------- (out, lse) variant
# Ring attention combines per-block partial softmaxes across K/V rotations
# (parallel/ring_attention.py): each block contributes (out_blk, lse_blk)
# and the online merge is out = Σ out_blk·exp(lse_blk − lse) with
# lse = logaddexp over blocks. Both outputs carry gradients (the merge
# weights depend on lse), so this variant's VJP folds the lse cotangent
# into delta (see _flash_bwd) instead of inventing a second backward.


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, causal, scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    b, h, s_q, _ = q.shape
    return out, lse[:, :, 0].reshape(b, h, s_q)


def _flash_lse_vjp_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    b, h, s_q, _ = q.shape
    return ((out, lse[:, :, 0].reshape(b, h, s_q)),
            (q, k, v, out, lse))


def _flash_lse_vjp_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    g_out, g_lse = g
    return _flash_bwd(q, k, v, out, lse, g_out, causal, scale,
                      block_q, block_k, delta_adj=g_lse)


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def _attn_reference_lse(q, k, v, causal: bool, scale: float):
    """XLA-path (out, lse) with sdpa_xla's exact masking convention — the
    small-shape fallback of flash_attention_with_lse. lse over masked
    (-1e30) logits matches the kernel's live-keys logsumexp to f32 eps."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v), lse


def flash_attention_with_lse(
    q, k, v, *, causal: bool = False, scale: float | None = None,
    block_q: int = 512, block_k: int = 512,
):
    """Fused attention returning (out, lse). q,k,v: (b, h, s, d); lse:
    (b, h, s_q) float32 row logsumexp of the scaled (masked) logits.
    Differentiable in BOTH outputs (the lse cotangent folds into delta in
    the shared FA2 backward). Same shape gates as flash_attention."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s_q, s_k, d = q.shape[2], k.shape[2], q.shape[3]
    if s_q < 128 or s_k < 128 or d % 8 != 0 or (causal and s_q > s_k):
        return _attn_reference_lse(q, k, v, causal, scale)
    return _flash_lse(q, k, v, causal, scale, block_q, block_k)


# --------------------------------------------------------- packed layout
# (b, s, h·dh) activations end to end: the qkv projection's natural output
# layout. Heads are selected by BlockSpec lane-offset index maps — block
# index h on the last (h·dh)-wide dim — so NO head transpose/relayout ever
# touches HBM (PERF.md measured the (b,s,h,d)→(b,h,s,d) copies at ~0.8 ms
# per flagship step). The kernel bodies are shared with the bhsd path; only
# the grids ((b, h, qi, kj)) and index maps differ.
#
# NARROW HEADS (head_dim < 128): Mosaic requires a lane block be a multiple
# of 128 lanes (or the full array width), so a single head_dim-64 head
# cannot be its own block — the old gate routed those models through the
# transposed layout and paid the relayout. The grouped path below removes
# that: blocks take a GROUP of `hpb` consecutive heads per 128-lane stripe
# (hpb = 128/dh when dh | 128, else all heads — full array width, legal for
# any dh), the grid gains a head-GROUP dimension, and the kernel bodies
# loop statically over the group's heads via lane sub-slices — the same
# (b, s, h, d) block semantics as a 4-D BlockSpec with a head grid dim,
# expressed on the 3-D packed array so no reshape/relayout ever runs.


def _packed_heads_per_block(head_dim: int, num_heads: int) -> int:
    """Heads per lane block for the packed path. 1 = the classic one-head
    lane-offset blocks (head_dim % 128 == 0); >1 = the grouped narrow-head
    path. Always yields a Mosaic-legal lane width: hpb·dh is either a
    multiple of 128 or the full (h·dh) array width."""
    if head_dim % 128 == 0:
        return 1
    if 128 % head_dim == 0 and num_heads % (128 // head_dim) == 0:
        return 128 // head_dim
    return num_heads


def _flash_kernel_grouped(
    q_ref, k_ref, v_ref, o_ref, *refs,
    scale: float, causal: bool, block_q: int, block_k: int, seq_k: int,
    causal_offset: int, save_lse: bool, nj: int, hpb: int, head_dim: int,
):
    """Forward tile for a HEAD GROUP: same online-softmax math as
    _flash_kernel, looped statically over the hpb heads of the block's
    lane stripe. Row stats live per head ((hpb, bq) scratch); the
    accumulator shares the block's (bq, hpb·dh) lane layout."""
    even_k = seq_k % block_k == 0
    single_kv = nj == 1
    if save_lse:
        lse_ref = refs[0]
        refs = refs[1:]
    else:
        lse_ref = None
    if not single_kv:
        m_ref, l_ref, acc_ref = refs
    i = pl.program_id(2)
    j = pl.program_id(3)

    def step(masked: bool):
        mask = v_valid = None
        if masked:
            mask = _tile_mask(
                i, j, causal=causal, block_q=block_q, block_k=block_k,
                seq_k=seq_k, causal_offset=causal_offset, even_k=even_k,
            )
            if not even_k:
                v_valid = jax.lax.broadcasted_iota(
                    jnp.int32, (block_k, head_dim), 0
                ) + j * block_k < seq_k
        for hh in range(hpb):
            sl = slice(hh * head_dim, (hh + 1) * head_dim)
            q = q_ref[0][:, sl]
            k = k_ref[0][:, sl]
            v = v_ref[0][:, sl]
            logits = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            if masked:
                logits = jnp.where(mask, logits, NEG_INF)
                if not even_k:
                    v = jnp.where(v_valid, v, 0.0)
            if single_kv:
                m = logits.max(axis=-1)
                p = jnp.exp(logits - m[:, None])
                l = p.sum(axis=-1)
                acc = jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                o_ref[0, :, sl] = (acc / l[:, None]).astype(o_ref.dtype)
                if save_lse:
                    lse_ref[hh] = jnp.broadcast_to(
                        (m + jnp.log(l))[:, None], lse_ref.shape[1:])
            else:
                m_prev = m_ref[hh]
                m_new = jnp.maximum(m_prev, logits.max(axis=-1))
                p = jnp.exp(logits - m_new[:, None])
                alpha = jnp.exp(m_prev - m_new)
                l_ref[hh] = l_ref[hh] * alpha + p.sum(axis=-1)
                acc_ref[:, sl] = (acc_ref[:, sl] * alpha[:, None]
                                  + jax.lax.dot_general(
                                      p.astype(v.dtype), v,
                                      (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32))
                m_ref[hh] = m_new

    if single_kv:
        step(causal or not even_k)
        return

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    live, needs_mask = _tile_classes(
        i, j, causal=causal, block_q=block_q, block_k=block_k,
        causal_offset=causal_offset, even_k=even_k, nj=nj,
    )
    if causal or not even_k:
        pl.when(jnp.logical_and(live, needs_mask))(lambda: step(True))
        pl.when(jnp.logical_and(live, jnp.logical_not(needs_mask)))(
            lambda: step(False))
    else:
        step(False)

    @pl.when(j == nj - 1)
    def _finish():
        for hh in range(hpb):
            sl = slice(hh * head_dim, (hh + 1) * head_dim)
            o_ref[0, :, sl] = (acc_ref[:, sl]
                               / l_ref[hh][:, None]).astype(o_ref.dtype)
            if save_lse:
                lse_ref[hh] = jnp.broadcast_to(
                    (m_ref[hh] + jnp.log(l_ref[hh]))[:, None],
                    lse_ref.shape[1:])


def _bwd_dq_kernel_grouped(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, scale: float, causal: bool, block_q: int, block_k: int,
    seq_q: int, seq_k: int, causal_offset: int, nj: int,
    hpb: int, head_dim: int,
):
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def step(masked: bool):
        for hh in range(hpb):
            sl = slice(hh * head_dim, (hh + 1) * head_dim)
            _, k, _, _, _, ds = _bwd_tile_math(
                q_ref[0][:, sl], k_ref[0][:, sl], v_ref[0][:, sl],
                do_ref[0][:, sl], lse_ref[hh][:, 0], delta_ref[hh][:, 0],
                i, j, masked,
                scale=scale, causal=causal, block_q=block_q,
                block_k=block_k, seq_q=seq_q, seq_k=seq_k,
                causal_offset=causal_offset,
                mask_q_rows=False,  # padded dq rows are discarded on write
            )
            dq_acc[:, sl] += jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    live, needs_mask = _tile_classes(
        i, j, causal=causal, block_q=block_q, block_k=block_k,
        causal_offset=causal_offset, even_k=seq_k % block_k == 0, nj=nj,
    )
    if causal or seq_k % block_k != 0:
        pl.when(jnp.logical_and(live, needs_mask))(lambda: step(True))
        pl.when(jnp.logical_and(live, jnp.logical_not(needs_mask)))(
            lambda: step(False))
    else:
        step(False)

    @pl.when(j == nj - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_grouped(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale: float, causal: bool, block_q: int, block_k: int,
    seq_q: int, seq_k: int, causal_offset: int, ni: int, nj: int,
    hpb: int, head_dim: int,
):
    j = pl.program_id(2)  # kv block
    i = pl.program_id(3)  # q block (innermost, sequential)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def step(masked: bool):
        for hh in range(hpb):
            sl = slice(hh * head_dim, (hh + 1) * head_dim)
            q, _, _, do, p, ds = _bwd_tile_math(
                q_ref[0][:, sl], k_ref[0][:, sl], v_ref[0][:, sl],
                do_ref[0][:, sl], lse_ref[hh][:, 0], delta_ref[hh][:, 0],
                i, j, masked,
                scale=scale, causal=causal, block_q=block_q,
                block_k=block_k, seq_q=seq_q, seq_k=seq_k,
                causal_offset=causal_offset,
                mask_q_rows=True,  # padded q rows would leak p==1 into dk/dv
            )
            dv_acc[:, sl] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dk_acc[:, sl] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    live, needs_mask = _tile_classes(
        i, j, causal=causal, block_q=block_q, block_k=block_k,
        causal_offset=causal_offset, even_k=seq_k % block_k == 0, nj=nj,
    )
    if causal or seq_k % block_k != 0:
        pl.when(jnp.logical_and(live, needs_mask))(lambda: step(True))
        pl.when(jnp.logical_and(live, jnp.logical_not(needs_mask)))(
            lambda: step(False))
    else:
        step(False)

    @pl.when(i == ni - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_fwd_packed_grouped(q, k, v, num_heads, causal, scale,
                              block_q, block_k, hpb, save_lse=True):
    """Narrow-head forward: head-GROUP lane blocks (hpb heads per block,
    width hpb·d = 128-multiple or full array width) over the 3-D packed
    array, grid (b, head-groups, q-blocks, kv-blocks)."""
    b, s_q, e = q.shape
    s_k = k.shape[1]
    h = num_heads
    d = e // h
    ng = h // hpb
    bq = min(block_q, s_q)
    bk = min(block_k, s_k)
    nj = pl.cdiv(s_k, bk)
    grid = (b, ng, pl.cdiv(s_q, bq), nj)
    kernel = functools.partial(
        _flash_kernel_grouped, scale=scale, causal=causal, block_q=bq,
        block_k=bk, seq_k=s_k, causal_offset=s_k - s_q, save_lse=save_lse,
        nj=nj, hpb=hpb, head_dim=d,
    )
    w = hpb * d
    qspec = pl.BlockSpec((1, bq, w), lambda bi, gi, i, j: (bi, i, gi))
    kspec = pl.BlockSpec((1, bk, w), lambda bi, gi, i, j: (bi, j, gi))
    out_specs = [qspec]
    out_shape = [jax.ShapeDtypeStruct((b, s_q, e), q.dtype)]
    if save_lse:
        # per-head row stats in the (b·h, s, LANES) layout; the group's
        # hpb consecutive head rows form one block
        out_specs.append(pl.BlockSpec(
            (hpb, bq, LSE_LANES),
            lambda bi, gi, i, j: (bi * ng + gi, i, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b * h, s_q, LSE_LANES), jnp.float32))
    scratch_shapes = []
    if nj > 1:
        scratch_shapes = [
            pltpu.VMEM((hpb, bq), jnp.float32),
            pltpu.VMEM((hpb, bq), jnp.float32),
            pltpu.VMEM((bq, w), jnp.float32),
        ]
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[qspec, kspec, kspec],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=jax.default_backend() != "tpu",
        name="flash_attention_fwd_packed_grouped",
    )(q, k, v)
    if save_lse:
        return res[0], res[1]
    return res[0], None


def _flash_fwd_packed(q, k, v, num_heads, causal, scale,
                      block_q, block_k, save_lse=True):
    b, s_q, e = q.shape
    s_k = k.shape[1]
    h = num_heads
    d = e // h
    hpb = _packed_heads_per_block(d, h)
    if hpb > 1:
        return _flash_fwd_packed_grouped(q, k, v, num_heads, causal, scale,
                                         block_q, block_k, hpb, save_lse)
    bq = min(block_q, s_q)
    bk = min(block_k, s_k)
    nj = pl.cdiv(s_k, bk)
    grid = (b, h, pl.cdiv(s_q, bq), nj)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_k=s_k, causal_offset=s_k - s_q, save_lse=save_lse, nj=nj,
        i_dim=2, j_dim=3,
    )
    qspec = pl.BlockSpec((1, bq, d), lambda bi, hi, i, j: (bi, i, hi))
    kspec = pl.BlockSpec((1, bk, d), lambda bi, hi, i, j: (bi, j, hi))
    out_specs = [qspec]
    out_shape = [jax.ShapeDtypeStruct((b, s_q, e), q.dtype)]
    if save_lse:
        # row stats stay in the (b·h, s, LANES) layout the shared kernel
        # bodies index; the flat block row is computed from (bi, hi)
        out_specs.append(pl.BlockSpec(
            (1, bq, LSE_LANES), lambda bi, hi, i, j: (bi * h + hi, i, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b * h, s_q, LSE_LANES), jnp.float32))
    scratch_shapes = []
    if nj > 1:
        scratch_shapes = [
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ]
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[qspec, kspec, kspec],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=jax.default_backend() != "tpu",
        name="flash_attention_fwd_packed",
    )(q, k, v)
    if save_lse:
        return res[0], res[1]
    return res[0], None


def _flash_bwd_packed_grouped(q, k, v, g, lse, delta, num_heads, causal,
                              scale, block_q, block_k, hpb):
    """Narrow-head dq + dkv kernels on head-group lane blocks (the
    single-tile fused specialization is per-head-only; grouped shapes
    route through the split FA2 pair even at one tile)."""
    b, s_q, e = q.shape
    s_k = k.shape[1]
    h = num_heads
    d = e // h
    ng = h // hpb
    w = hpb * d
    bq = min(block_q, s_q)
    bk = min(block_k, s_k)
    ni = pl.cdiv(s_q, bq)
    nj = pl.cdiv(s_k, bk)
    interpret = jax.default_backend() != "tpu"
    common = dict(
        scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_q=s_q, seq_k=s_k, causal_offset=s_k - s_q, hpb=hpb, head_dim=d,
    )
    qspec = pl.BlockSpec((1, bq, w), lambda bi, gi, i, j: (bi, i, gi))
    kspec = pl.BlockSpec((1, bk, w), lambda bi, gi, i, j: (bi, j, gi))
    rowspec = pl.BlockSpec((hpb, bq, LSE_LANES),
                           lambda bi, gi, i, j: (bi * ng + gi, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_grouped, nj=nj, **common),
        grid=(b, ng, ni, nj),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, s_q, e), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, w), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
        name="flash_attention_bwd_dq_packed_grouped",
    )(q, k, v, g, lse, delta)
    # kv-grid kernels: block index maps take (b, group, kv_j, q_i)
    qspec2 = pl.BlockSpec((1, bq, w), lambda bi, gi, j, i: (bi, i, gi))
    kspec2 = pl.BlockSpec((1, bk, w), lambda bi, gi, j, i: (bi, j, gi))
    rowspec2 = pl.BlockSpec((hpb, bq, LSE_LANES),
                            lambda bi, gi, j, i: (bi * ng + gi, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_grouped, ni=ni, nj=nj, **common),
        grid=(b, ng, nj, ni),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct((b, s_k, e), k.dtype),
            jax.ShapeDtypeStruct((b, s_k, e), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, w), jnp.float32),
            pltpu.VMEM((bk, w), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
        name="flash_attention_bwd_dkv_packed_grouped",
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


def _flash_bwd_packed(q, k, v, out, lse, g, num_heads, causal, scale,
                      block_q, block_k):
    b, s_q, e = q.shape
    s_k = k.shape[1]
    h = num_heads
    d = e // h
    bq = min(block_q, s_q)
    bk = min(block_k, s_k)
    # delta = rowsum(dO·O) per head: reduce dh inside each head, then a
    # tiny (b, s, h) transpose — no (·, d)-sized relayout
    delta = jnp.sum(
        (g.astype(jnp.float32) * out.astype(jnp.float32))
        .reshape(b, s_q, h, d),
        axis=-1,
    ).transpose(0, 2, 1).reshape(b * h, s_q)
    delta = jnp.broadcast_to(delta[..., None], (b * h, s_q, LSE_LANES))
    hpb = _packed_heads_per_block(d, h)
    if hpb > 1:
        return _flash_bwd_packed_grouped(q, k, v, g, lse, delta, num_heads,
                                         causal, scale, block_q, block_k,
                                         hpb)
    interpret = jax.default_backend() != "tpu"
    ni = pl.cdiv(s_q, bq)
    nj = pl.cdiv(s_k, bk)
    common = dict(
        scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_q=s_q, seq_k=s_k, causal_offset=s_k - s_q,
    )
    if ni == 1 and nj == 1:
        spec = pl.BlockSpec((1, s_q, d), lambda bi, hi: (bi, 0, hi))
        kspec = pl.BlockSpec((1, s_k, d), lambda bi, hi: (bi, 0, hi))
        rowspec = pl.BlockSpec((1, s_q, LSE_LANES),
                               lambda bi, hi: (bi * h + hi, 0, 0))
        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_single_tile_kernel, scale=scale, causal=causal,
                block_q=s_q, block_k=s_k, seq_q=s_q, seq_k=s_k,
                causal_offset=s_k - s_q,
            ),
            grid=(b, h),
            in_specs=[spec, kspec, kspec, spec, rowspec, rowspec],
            out_specs=[spec, kspec, kspec],
            out_shape=[
                jax.ShapeDtypeStruct((b, s_q, e), q.dtype),
                jax.ShapeDtypeStruct((b, s_k, e), k.dtype),
                jax.ShapeDtypeStruct((b, s_k, e), v.dtype),
            ],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel"),
            ),
            interpret=interpret,
            name="flash_attention_bwd_fused_packed",
        )(q, k, v, g, lse, delta)
        return dq, dk, dv
    qspec = pl.BlockSpec((1, bq, d), lambda bi, hi, i, j: (bi, i, hi))
    kspec = pl.BlockSpec((1, bk, d), lambda bi, hi, i, j: (bi, j, hi))
    rowspec = pl.BlockSpec((1, bq, LSE_LANES),
                           lambda bi, hi, i, j: (bi * h + hi, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, nj=nj, i_dim=2, j_dim=3, **common),
        grid=(b, h, ni, nj),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, s_q, e), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
        name="flash_attention_bwd_dq_packed",
    )(q, k, v, g, lse, delta)
    # kv-grid kernels: block index maps take (b, h, kv_j, q_i)
    qspec2 = pl.BlockSpec((1, bq, d), lambda bi, hi, j, i: (bi, i, hi))
    kspec2 = pl.BlockSpec((1, bk, d), lambda bi, hi, j, i: (bi, j, hi))
    rowspec2 = pl.BlockSpec((1, bq, LSE_LANES),
                            lambda bi, hi, j, i: (bi * h + hi, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, ni=ni, nj=nj, i_dim=3, j_dim=2,
                          **common),
        grid=(b, h, nj, ni),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct((b, s_k, e), k.dtype),
            jax.ShapeDtypeStruct((b, s_k, e), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
        name="flash_attention_bwd_dkv_packed",
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_packed(q, k, v, num_heads, causal, scale, block_q, block_k):
    out, _ = _flash_fwd_packed(q, k, v, num_heads, causal, scale,
                               block_q, block_k, save_lse=False)
    return out


def _flash_packed_vjp_fwd(q, k, v, num_heads, causal, scale,
                          block_q, block_k):
    out, lse = _flash_fwd_packed(q, k, v, num_heads, causal, scale,
                                 block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_packed_vjp_bwd(num_heads, causal, scale, block_q, block_k,
                          res, g):
    q, k, v, out, lse = res
    return _flash_bwd_packed(q, k, v, out, lse, g, num_heads, causal,
                             scale, block_q, block_k)


_flash_packed.defvjp(_flash_packed_vjp_fwd, _flash_packed_vjp_bwd)


def flash_attention_packed(
    q, k, v, *, num_heads: int, causal: bool = False,
    scale: float | None = None, block_q: int = 512, block_k: int = 512,
):
    """Fused attention on (batch, seq, heads·head_dim) activations — the
    qkv projection's natural layout, so no head transpose is ever
    materialized. Numerics identical to flash_attention on the transposed
    layout (same kernel bodies). Shapes the kernel can't tile fall back to
    the XLA path via an explicit (cheap at those sizes) transpose."""
    b, s_q, e = q.shape
    s_k = k.shape[1]
    d = e // num_heads
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if e % num_heads != 0:
        raise ValueError(f"embed dim {e} % heads {num_heads} != 0")
    # Mosaic requires the LAST block dim be a multiple of 128 or the full
    # array width (lowering.py _check_block_mappings). head_dim % 128 == 0
    # satisfies it with one head per block; NARROWER heads now satisfy it
    # too via head-GROUP blocks (hpb heads per 128-lane stripe, or the
    # full array width) with an in-kernel static head loop — so head_dim
    # 64 models run relayout-free where they previously paid the
    # transposed-layout copies (PERF.md ~0.8 ms/step). Only sub-sublane
    # head dims (d % 8 != 0) still fall back to the transposed path.
    if s_q < 128 or s_k < 128 or (causal and s_q > s_k) or d % 8 != 0:
        def split(t, s):
            return t.reshape(b, s, num_heads, d).transpose(0, 2, 1, 3)

        out = flash_attention(split(q, s_q), split(k, s_k), split(v, s_k),
                              causal=causal, scale=scale,
                              block_q=block_q, block_k=block_k)
        return out.transpose(0, 2, 1, 3).reshape(b, s_q, e)
    return _flash_packed(q, k, v, num_heads, causal, scale,
                         block_q, block_k)


# --------------------------------------------------------- decode (q_len=1)
# Serving's hot path: ONE new query row per slot attending over that slot's
# KV cache rows [0, length). The kernel is a degenerate flash forward —
# grid (slots, heads, kv-blocks), a (1, block_k) logits stripe, online
# softmax carried in VMEM — with the causal mask replaced by a per-slot
# LENGTH mask (key_pos < length), since cache rows past the slot's cursor
# hold stale garbage from earlier residents of the slot. Dead kv blocks
# (entirely past the cursor) are skipped, so a nearly-empty cache costs
# O(length), not O(max_seq). Like the packed training kernel, q/k/v stay in
# the (slots, seq, heads·head_dim) projection layout — heads are selected
# by lane-offset block index maps, no head transpose touches HBM.


def _decode_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, *refs,
                   scale: float, block_k: int, seq_k: int, nj: int):
    if nj == 1:
        m_ref = l_ref = acc_ref = None
    else:
        m_ref, l_ref, acc_ref = refs
    j = pl.program_id(2)
    length = len_ref[0, 0]

    @pl.when(j == 0)
    def _init():
        if nj > 1:
            m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

    def step():
        q = q_ref[0]  # (1, d)
        k = k_ref[0]  # (block_k, d)
        v = v_ref[0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (1, block_k)
        key_pos = jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1) + j * block_k
        mask = key_pos < length
        logits = jnp.where(mask, logits, NEG_INF)
        # zero masked V rows: stale cache rows can hold anything (NaN in
        # interpret mode) and 0·NaN would poison the contraction
        v = jnp.where(
            jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
            + j * block_k < length, v, 0.0)
        if nj == 1:
            m = logits.max(axis=-1)
            p = jnp.exp(logits - m[:, None])
            l = p.sum(axis=-1)
            acc = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            # length == 0 (empty slot) ⇒ l == 0; clamp keeps the dead row
            # finite (its output is never consumed) without touching live
            # rows, whose l >= exp(0) = 1
            o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(
                o_ref.dtype)
        else:
            m_prev = m_ref[...]
            m_new = jnp.maximum(m_prev, logits.max(axis=-1))
            p = jnp.exp(logits - m_new[:, None])
            alpha = jnp.exp(m_prev - m_new)
            l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1)
            acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[...] = m_new

    if nj == 1:
        step()
        return
    # live ⇔ the block's first key is inside [0, length)
    pl.when(j * block_k < length)(step)

    @pl.when(j == nj - 1)
    def _finish():
        o_ref[0] = (acc_ref[...]
                    / jnp.maximum(l_ref[...], 1e-30)[:, None]).astype(
                        o_ref.dtype)


def decode_attention_reference(q, k, v, positions, *, num_heads: int,
                               scale: float | None = None):
    """Reference einsum attention over a KV cache — the CPU serving path
    and the decode kernel's numerics oracle. q: (slots, q_len, H·hd) new
    queries, k/v: (slots, S, H·hd) cache (new rows already written),
    positions: (slots, q_len) int32 absolute position of each query row.
    Query row i attends cache rows [0, positions[s, i]] — intra-chunk
    causality during prefill falls out of the per-row positions. Same
    where(-1e30)/softmax convention as sdpa_xla, so greedy decode is
    token-identical to the teacher-forced training forward. The
    speculative verify call (serving/speculative.py) rides the SAME
    multi-query path at q_len=K+1 — each proposal row's logits equal
    what plain decode would compute after the rows before it, which is
    the whole bit-identity argument; the Pallas kernels below stay
    q_len=1, so multi-query calls (prefill chunks and verify alike)
    take this einsum on every backend — a multi-query Pallas decode
    kernel is the ROADMAP item that would close the gap."""
    slots, q_len, e = q.shape
    s_k = k.shape[1]
    h = num_heads
    d = e // h
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    def split(t, s):
        return t.reshape(slots, s, h, d).transpose(0, 2, 1, 3)

    qh, kh, vh = split(q, q_len), split(k, s_k), split(v, s_k)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32)
    logits = logits * scale
    key_pos = jnp.arange(s_k, dtype=jnp.int32)
    mask = key_pos[None, None, None, :] <= positions[:, None, :, None]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return out.transpose(0, 2, 1, 3).reshape(slots, q_len, e)


def flash_decode_attention(
    q, k, v, lengths, *, num_heads: int, scale: float | None = None,
    block_k: int = 512, interpret: bool | None = None,
):
    """Single-query decode attention on the packed layout. q: (slots, 1,
    H·hd), k/v: (slots, S, H·hd) cache, lengths: (slots,) int32 live-key
    counts (query at position p attends p+1 keys). Shapes the kernel can't
    tile on hardware (head_dim not lane-aligned, tiny caches) fall back to
    the reference einsum — the serving op routes CPU meshes there
    directly, so tier-1 exercises serving without Pallas."""
    slots, q_len, e = q.shape
    if q_len != 1:
        raise ValueError(f"decode kernel is single-query (got q_len={q_len})")
    s_k = k.shape[1]
    d = e // num_heads
    if e % num_heads != 0:
        raise ValueError(f"embed dim {e} % heads {num_heads} != 0")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # Mosaic lane rule (see flash_attention_packed): head selection by lane
    # offset needs head_dim % 128 == 0 on hardware; small caches aren't
    # worth a kernel launch anywhere
    lane_ok = d % 128 == 0 or num_heads == 1 or interpret
    if s_k < 128 or not lane_ok:
        positions = (lengths.astype(jnp.int32) - 1)[:, None]
        return decode_attention_reference(q, k, v, positions,
                                          num_heads=num_heads, scale=scale)
    bk = min(block_k, s_k)
    nj = pl.cdiv(s_k, bk)
    # scalar per-slot length rides a lane-aligned stripe, like the row
    # stats in the training kernels (LSE_LANES trick)
    len_b = jnp.broadcast_to(
        lengths.astype(jnp.int32)[:, None], (slots, LSE_LANES))
    qspec = pl.BlockSpec((1, 1, d), lambda s, h, j: (s, 0, h))
    kspec = pl.BlockSpec((1, bk, d), lambda s, h, j: (s, j, h))
    lspec = pl.BlockSpec((1, LSE_LANES), lambda s, h, j: (s, 0))
    scratch_shapes = []
    if nj > 1:
        scratch_shapes = [
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ]
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, block_k=bk,
                          seq_k=s_k, nj=nj),
        grid=(slots, num_heads, nj),
        in_specs=[qspec, kspec, kspec, lspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((slots, 1, e), q.dtype),
        scratch_shapes=scratch_shapes,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
        name="flash_attention_decode",
    )(q, k, v, len_b)
    return out


# ------------------------------------------------------- paged decode
# Serving's paged hot path (vLLM/PagedAttention): the KV cache is a block
# pool (num_blocks, block_size, H·hd) shared by every slot, and each slot
# reads its cache THROUGH a page table (slots, blocks_per_slot) int32. The
# kernel is the single-query decode kernel with the kv grid axis walking
# the page table instead of a contiguous cache: the K/V BlockSpec index
# maps read the physical block id from the scalar-prefetched table
# (PrefetchScalarGridSpec), so the gather costs nothing beyond the DMA the
# contiguous kernel already issues — and the dead-block skip is preserved
# (logical blocks past the slot's cursor are never fetched; their table
# entries point at the scratch block and the `pl.when` guard skips them).


def _paged_decode_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         *refs, scale: float, block_size: int, nj: int):
    if nj == 1:
        m_ref = l_ref = acc_ref = None
    else:
        m_ref, l_ref, acc_ref = refs
    j = pl.program_id(2)
    s = pl.program_id(0)
    length = len_ref[s]

    @pl.when(j == 0)
    def _init():
        if nj > 1:
            m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

    def step():
        q = q_ref[0]  # (1, d)
        k = k_ref[0]  # (block_size, d) — physical block tbl[s, j]
        v = v_ref[0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (1, block_size)
        # LOGICAL key position of row r in this block is j*block_size + r
        # (the table maps logical→physical; the logical axis is what the
        # per-slot length masks)
        key_pos = jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1) + j * block_size
        logits = jnp.where(key_pos < length, logits, NEG_INF)
        # zero masked V rows: rows past the cursor in a partially-filled
        # block hold stale pool state (NaN in interpret mode)
        v = jnp.where(
            jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
            + j * block_size < length, v, 0.0)
        if nj == 1:
            m = logits.max(axis=-1)
            p = jnp.exp(logits - m[:, None])
            l = p.sum(axis=-1)
            acc = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(
                o_ref.dtype)
        else:
            m_prev = m_ref[...]
            m_new = jnp.maximum(m_prev, logits.max(axis=-1))
            p = jnp.exp(logits - m_new[:, None])
            alpha = jnp.exp(m_prev - m_new)
            l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1)
            acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[...] = m_new

    if nj == 1:
        step()
        return
    # dead-block skip: a logical block entirely past the cursor is never
    # computed (its physical block — usually scratch — may still DMA; the
    # table keeps unallocated entries at scratch so that DMA is one hot
    # block, not a cold pool walk)
    pl.when(j * block_size < length)(step)

    @pl.when(j == nj - 1)
    def _finish():
        o_ref[0] = (acc_ref[...]
                    / jnp.maximum(l_ref[...], 1e-30)[:, None]).astype(
                        o_ref.dtype)


def paged_decode_attention_reference(q, pool_k, pool_v, page_table,
                                     positions, *, num_heads: int,
                                     scale: float | None = None):
    """Einsum oracle for the paged decode kernel (and the CPU serving
    path, via ops/inc_attention.py): gather each slot's logical cache
    view from the pool through its page table, then run the contiguous
    reference. q: (slots, q_len, H·hd); pool_k/v: (num_blocks, bs, H·hd);
    page_table: (slots, W) int32; positions: (slots, q_len) int32 (query
    row i attends logical rows [0, positions[s, i]]; negative = dead)."""
    slots = q.shape[0]
    W = page_table.shape[1]
    bs = pool_k.shape[1]
    e = pool_k.shape[-1]
    kc = pool_k[page_table].reshape(slots, W * bs, e).astype(q.dtype)
    vc = pool_v[page_table].reshape(slots, W * bs, e).astype(q.dtype)
    return decode_attention_reference(q, kc, vc, positions,
                                      num_heads=num_heads, scale=scale)


def paged_flash_decode_attention(
    q, pool_k, pool_v, page_table, lengths, *, num_heads: int,
    scale: float | None = None, interpret: bool | None = None,
):
    """Single-query decode attention over a paged KV pool. q: (slots, 1,
    H·hd); pool_k/v: (num_blocks, block_size, H·hd); page_table: (slots,
    W) int32 logical→physical block map; lengths: (slots,) int32 live-key
    counts. The kv grid walks the page table via scalar prefetch — one
    (1, block_size, head) K/V block DMA per live logical block, dead
    blocks skipped. Shapes the kernel can't tile on hardware fall back to
    the gather + einsum reference (the CPU serving path routes there
    directly)."""
    slots, q_len, e = q.shape
    if q_len != 1:
        raise ValueError(f"decode kernel is single-query (got q_len={q_len})")
    bs = pool_k.shape[1]
    W = page_table.shape[1]
    d = e // num_heads
    if e % num_heads != 0:
        raise ValueError(f"embed dim {e} % heads {num_heads} != 0")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # Mosaic gates (see flash_decode_attention) + the paged-specific one:
    # a block must be a legal (sublane, lane) tile, so tiny block sizes
    # route to the reference
    lane_ok = d % 128 == 0 or num_heads == 1 or interpret
    if W * bs < 128 or bs % 8 != 0 or not lane_ok:
        positions = (lengths.astype(jnp.int32) - 1)[:, None]
        return paged_decode_attention_reference(
            q, pool_k, pool_v, page_table, positions,
            num_heads=num_heads, scale=scale)
    nj = W
    lengths = lengths.astype(jnp.int32)
    table = page_table.astype(jnp.int32)
    qspec = pl.BlockSpec((1, 1, d), lambda s, h, j, tbl, ln: (s, 0, h))
    # the paged gather: the physical block row comes from the prefetched
    # table, not the grid index
    kspec = pl.BlockSpec(
        (1, bs, d), lambda s, h, j, tbl, ln: (tbl[s, j], 0, h))
    scratch_shapes = []
    if nj > 1:
        scratch_shapes = [
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1,), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, num_heads, nj),
        in_specs=[qspec, kspec, kspec],
        out_specs=qspec,
        scratch_shapes=scratch_shapes,
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale=scale,
                          block_size=bs, nj=nj),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, 1, e), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
        name="flash_attention_paged_decode",
    )(table, lengths, q, pool_k, pool_v)
    return out


def flash_attention(
    q, k, v, *, causal: bool = False, scale: float | None = None,
    block_q: int = 512, block_k: int = 512,
):
    """Fused attention. q,k,v: (batch, heads, seq, head_dim).

    Default 512-blocks: measured on v5e, one 512-wide kv block per q block
    (the one-pass-softmax specialization) beats smaller causal-skipping
    tilings — grid-iteration overhead outweighs the skipped exp work at
    short-to-medium sequence. At seq > 512 the kv axis tiles at 512 and the
    online-softmax path takes over."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s_q, s_k, d = q.shape[2], k.shape[2], q.shape[3]
    # shape gate: tiny/ragged shapes go to the XLA path (still fused by XLA).
    # causal with s_q > s_k also routes there: rows with zero live keys
    # (q_pos + offset < 0) would read m = -inf and p = exp(0) = 1 in the
    # multi-kv online softmax — averaging V over live tiles only and
    # emitting a bogus lse — instead of sdpa_xla's uniform-over-all-keys
    # convention for that degenerate shape.
    if s_q < 128 or s_k < 128 or d % 8 != 0 or (causal and s_q > s_k):
        return _attn_reference(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, block_q, block_k)
