"""Fused (flash) attention as a Pallas TPU kernel.

Replaces the reference's cuDNN `cudnnMultiHeadAttnForward` call
(src/ops/attention.cu:35) as the fast attention path. Design follows the
standard flash-attention blocking for TPU: grid over (batch*heads, q-blocks,
kv-blocks) with the kv axis innermost and sequential ("arbitrary"), a
(block_q, block_k) logits tile living in VMEM, and online-softmax running
max/denominator carried in VMEM scratch across kv steps. The MXU sees two
large matmuls per tile; HBM traffic is O(s*d) instead of the O(s^2)
materialized-probabilities tensor XLA would allocate at long sequence.

Backward is the FlashAttention-2 scheme as two Pallas kernels: the forward
saves per-row logsumexp; `delta = rowsum(dO*O)` is a cheap XLA elementwise
precompute; the dq kernel iterates kv-blocks per q-block and the dk/dv
kernel iterates q-blocks per kv-block, both recomputing the probability
tile from (q, k, lse) so nothing O(s^2) ever touches HBM. Causal block
skipping applies on both sides of the diagonal.

On non-TPU backends (the 8-device CPU test mesh) the kernel runs in Pallas
interpret mode so tests exercise the same code path.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# Minor dim of the (seq,) row-stat tensors (lse/delta): Mosaic requires
# 128-lane minor blocks for f32 (the in-tree jax flash kernel's
# MIN_BLOCK_SIZE), so 8 lanes would mis-tile or fail to lower on real TPU.
LSE_LANES = 128


def _attn_reference(q, k, v, causal: bool, scale: float):
    """XLA-path attention (ops.attention.sdpa_xla): the small-shape fallback
    and the custom-VJP backward reference — one source of truth for attention
    numerics. Lazy import avoids a cycle (ops.attention lazily imports this
    module for impl="flash")."""
    from ..ops.attention import sdpa_xla

    return sdpa_xla(q, k, v, causal=causal, scale=scale)


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, *refs,
    scale: float, causal: bool, block_q: int, block_k: int, seq_k: int,
    causal_offset: int, save_lse: bool,
):
    if save_lse:
        lse_ref, m_ref, l_ref, acc_ref = refs
    else:
        lse_ref = None
        m_ref, l_ref, acc_ref = refs
    i = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # with causal masking, kv blocks strictly above the diagonal contribute
    # nothing — skip them entirely (halves the work, like the reference's
    # unmasked cuDNN op cannot). Diagonal is bottom-right aligned
    # (offset = seq_k - seq_q), matching sdpa_xla's tril(k=s_k-s_q).
    live = (
        (j * block_k <= i * block_q + block_q - 1 + causal_offset)
        if causal else True
    )

    @pl.when(live)
    def _step():
        q = q_ref[0]  # (block_q, d)
        k = k_ref[0]  # (block_k, d)
        v = v_ref[0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        k_pos = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        ) + j * block_k
        # mask the padded tail of the last kv block, plus the causal triangle
        mask = k_pos < seq_k
        if causal:
            q_pos = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            ) + i * block_q
            mask = mask & (q_pos + causal_offset >= k_pos)
        logits = jnp.where(mask, logits, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, logits.max(axis=-1))
        p = jnp.exp(logits - m_new[:, None])
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1)
        # zero padded V rows: OOB block rows hold garbage (NaN in interpret
        # mode) and 0·NaN would poison the contraction
        v_valid = jax.lax.broadcasted_iota(
            jnp.int32, v.shape, 0
        ) + j * block_k < seq_k
        v = jnp.where(v_valid, v, 0.0)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new

    @pl.when(j == nj - 1)
    def _finish():
        o_ref[0] = (acc_ref[...] / l_ref[...][:, None]).astype(o_ref.dtype)
        if save_lse:
            # row stats carry a minor dim of LSE_LANES so the block is
            # tile-legal on TPU (same trick as jax's in-tree flash kernel,
            # which uses MIN_BLOCK_SIZE lanes)
            lse = m_ref[...] + jnp.log(l_ref[...])
            lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref[0].shape)


def _flash_fwd(q, k, v, causal: bool, scale: float,
               block_q: int, block_k: int, save_lse: bool = True):
    """save_lse=False (the primal / inference path) skips computing and
    writing the logsumexp residual entirely."""
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    bq = min(block_q, s_q)
    bk = min(block_k, s_k)
    qf = q.reshape(b * h, s_q, d)
    kf = k.reshape(b * h, s_k, d)
    vf = v.reshape(b * h, s_k, d)
    grid = (b * h, pl.cdiv(s_q, bq), pl.cdiv(s_k, bk))
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_k=s_k, causal_offset=s_k - s_q, save_lse=save_lse,
    )
    out_specs = [pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype)]
    if save_lse:
        out_specs.append(
            pl.BlockSpec((1, bq, LSE_LANES), lambda bh, i, j: (bh, i, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b * h, s_q, LSE_LANES), jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=jax.default_backend() != "tpu",
        name="flash_attention_fwd",
    )(qf, kf, vf)
    if save_lse:
        out, lse = res
    else:
        (out,), lse = res, None
    return out.reshape(b, h, s_q, d), lse


def _bwd_tile(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, i, j,
    *, scale: float, causal: bool, block_q: int, block_k: int,
    seq_q: int, seq_k: int, causal_offset: int, mask_q_rows: bool,
):
    """Shared backward tile recompute: zero garbage padded rows (NaN in
    interpret mode, 0*NaN poisons contractions), rebuild the probability
    tile p from (q, k, lse), and form ds = p*(dp - delta)*scale.

    mask_q_rows additionally joins q-row validity into the probability mask:
    padded q rows have p == exp(0-0) == 1 and must not leak into reductions
    over the q axis (dk/dv); reductions over the kv axis (dq) don't need it
    because their padded output rows are discarded on write."""
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0][:, 0]
    delta = delta_ref[0][:, 0]
    q_valid = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0
    ) + i * block_q < seq_q
    q = jnp.where(q_valid, q, 0.0)
    do = jnp.where(q_valid, do, 0.0)
    lse = jnp.where(q_valid[:, 0], lse, 0.0)
    delta = jnp.where(q_valid[:, 0], delta, 0.0)
    kv_valid = jax.lax.broadcasted_iota(
        jnp.int32, (block_k, 1), 0
    ) + j * block_k < seq_k
    k = jnp.where(kv_valid, k, 0.0)
    v = jnp.where(kv_valid, v, 0.0)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    k_pos = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    ) + j * block_k
    mask = k_pos < seq_k
    if mask_q_rows:
        mask = mask & q_valid
    if causal:
        q_pos = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        ) + i * block_q
        mask = mask & (q_pos + causal_offset >= k_pos)
    p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta[:, None]) * scale
    return q, k, v, do, p, ds


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, scale: float, causal: bool, block_q: int, block_k: int,
    seq_q: int, seq_k: int, causal_offset: int,
):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    live = (
        (j * block_k <= i * block_q + block_q - 1 + causal_offset)
        if causal else True
    )

    @pl.when(live)
    def _step():
        q, k, _, do, p, ds = _bwd_tile(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            seq_q=seq_q, seq_k=seq_k, causal_offset=causal_offset,
            mask_q_rows=False,  # padded dq rows are discarded on write
        )
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nj - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale: float, causal: bool, block_q: int, block_k: int,
    seq_q: int, seq_k: int, causal_offset: int,
):
    j = pl.program_id(1)  # kv block
    i = pl.program_id(2)  # q block (innermost, sequential)
    ni = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # a q block contributes to this kv block unless it lies entirely above
    # the causal diagonal
    live = (
        (i * block_q + block_q - 1 + causal_offset >= j * block_k)
        if causal else True
    )

    @pl.when(live)
    def _step():
        q, _, _, do, p, ds = _bwd_tile(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            seq_q=seq_q, seq_k=seq_k, causal_offset=causal_offset,
            mask_q_rows=True,  # padded q rows would leak p==1 into dk/dv
        )
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == ni - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, causal, scale, block_q, block_k):
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    bq = min(block_q, s_q)
    bk = min(block_k, s_k)
    qf = q.reshape(b * h, s_q, d)
    kf = k.reshape(b * h, s_k, d)
    vf = v.reshape(b * h, s_k, d)
    gf = g.reshape(b * h, s_q, d)
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise XLA precompute
    delta = jnp.sum(
        gf.astype(jnp.float32) * out.reshape(b * h, s_q, d).astype(jnp.float32),
        axis=-1,
    )
    delta = jnp.broadcast_to(delta[..., None], (b * h, s_q, LSE_LANES))
    interpret = jax.default_backend() != "tpu"
    common = dict(
        scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_q=s_q, seq_k=s_k, causal_offset=s_k - s_q,
    )
    qspec = pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0))
    kspec = pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0))
    rowspec = pl.BlockSpec((1, bq, LSE_LANES), lambda bh, i, j: (bh, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(b * h, pl.cdiv(s_q, bq), pl.cdiv(s_k, bk)),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
        name="flash_attention_bwd_dq",
    )(qf, kf, vf, gf, lse, delta)
    # kv-grid kernel: block index maps take (bh, kv_j, q_i)
    qspec2 = pl.BlockSpec((1, bq, d), lambda bh, j, i: (bh, i, 0))
    kspec2 = pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0))
    rowspec2 = pl.BlockSpec((1, bq, LSE_LANES), lambda bh, j, i: (bh, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(b * h, pl.cdiv(s_k, bk), pl.cdiv(s_q, bq)),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s_k, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, s_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
        name="flash_attention_bwd_dkv",
    )(qf, kf, vf, gf, lse, delta)
    return (
        dq.reshape(b, h, s_q, d),
        dk.reshape(b, h, s_k, d),
        dv.reshape(b, h, s_k, d),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                        save_lse=False)
    return out


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, g, causal, scale, block_q, block_k)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q, k, v, *, causal: bool = False, scale: float | None = None,
    block_q: int = 512, block_k: int = 512,
):
    """Fused attention. q,k,v: (batch, heads, seq, head_dim)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s_q, s_k, d = q.shape[2], k.shape[2], q.shape[3]
    # shape gate: tiny/ragged shapes go to the XLA path (still fused by XLA)
    if s_q < 128 or s_k < 128 or d % 8 != 0:
        return _attn_reference(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, block_q, block_k)
