"""Fused LayerNorm as Pallas TPU kernels.

XLA lowers layer-norm forward to a convert+reduce fusion that runs ~9x off
the HBM roofline at transformer shapes (measured 190µs for a 16.8MB
read+write on v5e — the cross-lane row reductions don't pipeline well), and
the affine epilogue in the naive jnp spelling promotes bf16 activations to
f32. These kernels do the whole thing in one VMEM pass per row block:

- forward: row mean/variance (f32), normalize, affine, cast — one HBM read
  + one write of the activation.
- backward: recomputes row stats from x (cheaper than spilling residuals),
  emits dx in one pass plus per-block partial dscale/dbias reduced by one
  tiny XLA sum outside (the reduction over rows is lane-parallel, unlike
  the forward's within-row reductions).

Reference: layer_norm.cu's Welford kernels play the same role. On non-TPU
backends the kernels run in Pallas interpret mode so tests exercise the
same path. Shapes that don't tile (ragged rows / tiny feature dims /
non-last-axis normalization) fall back to the jnp path in ops/core.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_ROW_BLOCK = 256


def _row_block(n: int, d: int) -> int:
    """Row-block size capped so the kernels' f32 temporaries (~8 live
    (rb, d) buffers in the backward) stay inside Mosaic's 16MB scoped
    vmem: rb·d·4 ≤ 1MB keeps the worst case ≈8MB. The cap rounds DOWN to a
    power of two so it still divides the power-of-two-ish row counts
    transformers produce (a multiple-of-8 cap like 168 at d=1536 would
    fail n % rb for every power-of-two n and silently disable the fusion).
    d=1024 keeps the tuned rb=256; rb=256 at d=2048 overflowed scoped vmem
    on v5e (caught by scripts/cost_model_fidelity.py)."""
    cap = max(8, 262144 // max(1, d))
    cap = 1 << (cap.bit_length() - 1)  # floor to a power of two
    return min(_ROW_BLOCK, cap, n)


def _fwd_kernel(x_ref, s_ref, b_ref, y_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)  # (rb, d)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd * s_ref[...].astype(jnp.float32) + b_ref[...].astype(
        jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)


def _bwd_kernel(x_ref, s_ref, dy_ref, dx_ref, ds_ref, db_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    s = s_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    dyh = dy * s
    m1 = jnp.mean(dyh * xhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dyh, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (dyh - m2 - xhat * m1)).astype(dx_ref.dtype)
    # partial reductions broadcast over 8 sublanes (Mosaic's minimum block
    # sublane count); the caller reads row 0 of each block
    ds = jnp.sum(dy * xhat, axis=0, keepdims=True)
    db = jnp.sum(dy, axis=0, keepdims=True)
    ds_ref[0] = jnp.broadcast_to(ds, ds_ref[0].shape)
    db_ref[0] = jnp.broadcast_to(db, db_ref[0].shape)


def _call_fwd(x2, scale2, bias2, eps):
    n, d = x2.shape
    rb = _row_block(n, d)
    grid = (n // rb,)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rb, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((rb, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x2.dtype),
        interpret=jax.default_backend() != "tpu",
        name="layer_norm_fwd",
    )(x2, scale2, bias2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_ln(x2, scale, bias, eps):
    return _call_fwd(x2, scale.reshape(1, -1), bias.reshape(1, -1), eps)


def _fused_ln_fwd(x2, scale, bias, eps):
    return _fused_ln(x2, scale, bias, eps), (x2, scale)


def _fused_ln_bwd(eps, res, dy):
    x2, scale = res
    n, d = x2.shape
    rb = _row_block(n, d)
    grid = (n // rb,)
    dx, ds_part, db_part = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rb, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((rb, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((rb, d), lambda i: (i, 0)),
            pl.BlockSpec((1, 8, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 8, d), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2.dtype),
            jax.ShapeDtypeStruct((grid[0], 8, d), jnp.float32),
            jax.ShapeDtypeStruct((grid[0], 8, d), jnp.float32),
        ],
        interpret=jax.default_backend() != "tpu",
        name="layer_norm_bwd",
    )(x2, scale.reshape(1, -1), dy)
    return dx, ds_part[:, 0].sum(axis=0), db_part[:, 0].sum(axis=0)


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def fused_layer_norm_or_none(x, scale, bias, axes, eps):
    """Fused path when the shape tiles: last-axis-only normalization,
    feature dim a multiple of 128, rows divisible by the row block.
    Returns None when the caller should use the jnp fallback."""
    ndim = x.ndim
    if tuple(a % ndim for a in axes) != (ndim - 1,):
        return None
    d = x.shape[-1]
    n = 1
    for s in x.shape[:-1]:
        n *= s
    # rows must divide into 8-sublane-aligned blocks: `n % rb` alone is
    # vacuous for n < rb (n % n == 0) and a 12-row or 100-row block would
    # fail Mosaic's 8-sublane tiling on real TPU (interpret-mode CPU tests
    # can't catch that)
    rb = _row_block(n, d)
    if d % 128 != 0 or n < 8 or rb % 8 != 0 or n % rb != 0:
        return None
    y2 = _fused_ln(x.reshape(n, d), scale, bias, float(eps))
    return y2.reshape(x.shape)
