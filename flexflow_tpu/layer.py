"""Layer: the lazy frontend IR record.

Reference: include/flexflow/layer.h:10 — untyped layer records created by
FFModel builder calls before compile(); compile's
create_operators_from_layers (src/runtime/model.cc:2785,2605) turns them into
operators with ParallelTensors. Same two-phase life here.
"""

from __future__ import annotations

import itertools
from typing import Any, Optional

from .fftype import DataType, OperatorType

_layer_guid = itertools.count(1000000)  # LAYER_GUID_FIRST_VALID


class Layer:
    def __init__(
        self,
        op_type: OperatorType,
        params: Any,
        inputs: list,
        name: str = "",
        data_type: DataType = DataType.DT_FLOAT,
        initializers: Optional[dict] = None,
    ):
        self.layer_guid = next(_layer_guid)
        self.op_type = op_type
        self.params = params
        self.inputs = list(inputs)
        self.outputs = []
        self.data_type = data_type
        self.name = name or f"{op_type.name.lower()}_{self.layer_guid}"
        # per-weight Initializer overrides, name → Initializer
        self.initializers = initializers or {}
        # tied weights: guid of the layer whose parameters this one reads
        # (reference shared_op; -1 = owns its own weights)
        self.shared_layer_guid = -1

    @property
    def num_inputs(self) -> int:
        return len(self.inputs)

    @property
    def num_outputs(self) -> int:
        return len(self.outputs)

    def __repr__(self):
        return f"Layer({self.name}, {self.op_type.name})"
