"""Loss functions.

Reference: include/flexflow/loss_functions.h:27-88 + src/loss_functions/
loss_functions.cu. The reference implements loss as a single backward task
writing logit gradients scaled by 1/batch (`scale_factor`); here the loss is
a scalar-valued pure function and autodiff produces the same gradients — the
CCE-after-softmax case yields the identical fused (probs - onehot)/batch
gradient the reference hand-codes (loss_functions.cu:24-50).

Auxiliary losses accumulated by ops (MoE load-balance) are added to the
objective so their gradients flow, mirroring aggregate.cu's hand-injected
balance gradient.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .fftype import LossType

_EPS = 1e-8


@jax.custom_vjp
def _softmax_xent_sum(logits2d, labels1d):
    """Sum over rows of (logsumexp(row) - row[label]), f32.

    Fused softmax-cross-entropy from logits: the forward reduces the (possibly
    bf16) logits with f32 accumulation without materializing an f32 copy, and
    the hand-written backward emits (softmax - onehot)·g directly in the
    logits dtype — so nothing logits-sized ever hits HBM in f32. This is the
    TPU analog of the reference's fused loss backward kernel
    (loss_functions.cu:24-50), which likewise writes scaled logit gradients
    in one pass."""
    lse = jax.scipy.special.logsumexp(logits2d.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(
        logits2d, labels1d[:, None], axis=-1
    )[:, 0].astype(jnp.float32)
    return jnp.sum(lse - ll)


def _softmax_xent_sum_fwd(logits2d, labels1d):
    lse = jax.scipy.special.logsumexp(logits2d.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(
        logits2d, labels1d[:, None], axis=-1
    )[:, 0].astype(jnp.float32)
    return jnp.sum(lse - ll), (logits2d, labels1d, lse)


def _softmax_xent_sum_bwd(res, g):
    logits2d, labels1d, lse = res
    # onehot via iota-compare so exp/sub/scale/cast fuse into one pass
    col = jax.lax.broadcasted_iota(jnp.int32, logits2d.shape, 1)
    p = jnp.exp(logits2d.astype(jnp.float32) - lse[:, None])
    d = (p - (col == labels1d[:, None]).astype(jnp.float32)) * g
    return d.astype(logits2d.dtype), None


_softmax_xent_sum.defvjp(_softmax_xent_sum_fwd, _softmax_xent_sum_bwd)


def loss_terms(loss_type: LossType, logits, labels, last_op_is_softmax: bool):
    """(scalar loss, reusable sparse-CE sum or None).

    The CE sum (f32, pre-averaging) is handed to Metrics so the scce counter
    doesn't re-reduce the full logits tensor a second time per step."""
    lt = LossType(loss_type)
    b = logits.shape[0]
    if lt == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY:
        # every leading position is a sample (LM case: (b, s, vocab) logits
        # with (b, s, 1) labels), matching the reference kernel's per-sample
        # flattening (loss_functions.cu sparse_categorical_crossentropy)
        num_classes = logits.shape[-1]
        flat = logits.reshape(-1, num_classes)
        lab = labels.reshape(-1).astype(jnp.int32)
        if last_op_is_softmax:
            logp2 = jnp.log(flat.astype(jnp.float32) + _EPS)
            ce_sum = -jnp.sum(
                jnp.take_along_axis(logp2, lab[:, None], axis=-1)
            )
        else:
            ce_sum = _softmax_xent_sum(flat, lab)
        return ce_sum / flat.shape[0], ce_sum
    return _loss_value_rest(lt, logits, labels, last_op_is_softmax, b), None


def loss_value(loss_type: LossType, logits, labels, last_op_is_softmax: bool):
    """Scalar loss. `logits` is the final op output — probabilities if the
    graph ends in softmax (the reference's convention for CCE losses)."""
    return loss_terms(loss_type, logits, labels, last_op_is_softmax)[0]


def _loss_value_rest(lt, logits, labels, last_op_is_softmax, b):
    # legacy paths reduce in f32; the cast fuses into the reductions
    logits = logits.astype(jnp.float32)
    if lt == LossType.LOSS_CATEGORICAL_CROSSENTROPY:
        logp = jnp.log(logits + _EPS) if last_op_is_softmax else jax.nn.log_softmax(logits, -1)
        return -jnp.sum(labels * logp) / b
    if lt == LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE:
        return jnp.mean(jnp.sum((logits - labels) ** 2, axis=tuple(range(1, logits.ndim))))
    if lt == LossType.LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE:
        return jnp.sum((logits - labels) ** 2) / b
    if lt == LossType.LOSS_IDENTITY:
        # pass-through: gradient of ones/batch (loss_functions.cu identity_loss)
        return jnp.sum(logits) / b
    raise ValueError(f"unknown loss {lt}")
