"""Loss functions.

Reference: include/flexflow/loss_functions.h:27-88 + src/loss_functions/
loss_functions.cu. The reference implements loss as a single backward task
writing logit gradients scaled by 1/batch (`scale_factor`); here the loss is
a scalar-valued pure function and autodiff produces the same gradients — the
CCE-after-softmax case yields the identical fused (probs - onehot)/batch
gradient the reference hand-codes (loss_functions.cu:24-50).

Auxiliary losses accumulated by ops (MoE load-balance) are added to the
objective so their gradients flow, mirroring aggregate.cu's hand-injected
balance gradient.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .fftype import LossType

_EPS = 1e-8


def loss_value(loss_type: LossType, logits, labels, last_op_is_softmax: bool):
    """Scalar loss. `logits` is the final op output — probabilities if the
    graph ends in softmax (the reference's convention for CCE losses)."""
    lt = LossType(loss_type)
    b = logits.shape[0]
    if lt == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY:
        # every leading position is a sample (LM case: (b, s, vocab) logits
        # with (b, s, 1) labels), matching the reference kernel's per-sample
        # flattening (loss_functions.cu sparse_categorical_crossentropy)
        num_classes = logits.shape[-1]
        logp2 = logits.reshape(-1, num_classes)
        lab = labels.reshape(-1).astype(jnp.int32)
        if last_op_is_softmax:
            logp2 = jnp.log(logp2 + _EPS)
        else:
            logp2 = jax.nn.log_softmax(logp2, axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp2, lab[:, None], axis=-1))
    if lt == LossType.LOSS_CATEGORICAL_CROSSENTROPY:
        logp = jnp.log(logits + _EPS) if last_op_is_softmax else jax.nn.log_softmax(logits, -1)
        return -jnp.sum(labels * logp) / b
    if lt == LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE:
        return jnp.mean(jnp.sum((logits - labels) ** 2, axis=tuple(range(1, logits.ndim))))
    if lt == LossType.LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE:
        return jnp.sum((logits - labels) ** 2) / b
    if lt == LossType.LOSS_IDENTITY:
        # pass-through: gradient of ones/batch (loss_functions.cu identity_loss)
        return jnp.sum(logits) / b
    raise ValueError(f"unknown loss {loss_type}")
