"""Machine abstraction: device fleets, machine views, and the JAX mesh bridge.

The reference models placement with `MachineView` — a strided view of a flat
device grid assigned per PCG node (include/flexflow/machine_view.h:14-96) that
the Legion mapper turns into task→GPU routing. On TPU the analogous object is
an assignment of *parallel tensor dims to named mesh axes* over one global
`jax.sharding.Mesh`: XLA/GSPMD then routes data movement over ICI/DCN instead
of a task mapper. We keep `MachineView` (same fields, same hash role: it is
the cost-model cache key and the identity of a placement) and add the bridge
to `PartitionSpec`.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


@dataclass(frozen=True)
class MachineView:
    """Strided view over a flat device id space; parity with
    machine_view.h:14-96. `dims[i]` = number of devices along view dim i."""

    ndims: int
    dims: tuple[int, ...]
    strides: tuple[int, ...]
    start_device_id: int = 0
    device_type: str = "TPU"

    @staticmethod
    def make_1d(num_devices: int, start: int = 0, stride: int = 1) -> "MachineView":
        return MachineView(1, (num_devices,), (stride,), start)

    @property
    def num_parts(self) -> int:
        return int(np.prod(self.dims)) if self.dims else 1

    def device_ids(self) -> list[int]:
        ids = []
        for idx in np.ndindex(*self.dims) if self.dims else [()]:
            off = sum(i * s for i, s in zip(idx, self.strides))
            ids.append(self.start_device_id + off)
        return ids

    def hash(self) -> int:
        h = 17
        for v in (self.ndims, self.start_device_id, *self.dims, *self.strides):
            h = (h * 31 + v) & 0xFFFFFFFFFFFFFFFF
        return h

    def __repr__(self) -> str:
        return (
            f"MachineView(start={self.start_device_id}, dims={self.dims}, "
            f"strides={self.strides})"
        )


@dataclass(frozen=True)
class MachineResource:
    """Resource slice the DP search splits (reference machine_view.h: the
    MachineResource carried through graph_cost)."""

    num_nodes: int
    all_devices_per_node: int
    available_devices_per_node: int
    start_device_id: int = 0

    @property
    def num_devices(self) -> int:
        return self.num_nodes * self.available_devices_per_node


# Canonical mesh axis names. One global mesh; per-op placements are
# PartitionSpecs over these axes. Degree-1 axes are harmless.
AXIS_DCN = "dcn"        # cross-host (multislice) data parallel over DCN
AXIS_DATA = "data"      # batch / sample parallel
AXIS_MODEL = "model"    # tensor/attribute/parameter parallel
AXIS_PIPE = "pipe"      # pipeline stages
AXIS_SEQ = "seq"        # sequence/context parallel (ring attention)
AXIS_EXPERT = "expert"  # expert parallel (alias of model by default)

DEFAULT_AXES = (AXIS_DATA, AXIS_MODEL, AXIS_PIPE, AXIS_SEQ)
# multi-host meshes lead with a DCN axis: collectives on it cross the
# data-center network, everything inboard stays on ICI (the reference runs
# one Legion process per node over GASNet/MPI; here the outer mesh axis IS
# the host boundary, mapper.cc:291-306 / MULTI-NODE.md analog)
MULTIHOST_AXES = (AXIS_DCN,) + DEFAULT_AXES


def batch_axes_for(axis_sizes: dict) -> tuple[str, ...]:
    """Mesh axes the batch dim rides under the data-parallel default: the
    DCN axis (outer, when present) composed with `data`."""
    axes = []
    if axis_sizes.get(AXIS_DCN, 1) > 1:
        axes.append(AXIS_DCN)
    if axis_sizes.get(AXIS_DATA, 1) > 1 or not axes:
        axes.append(AXIS_DATA)
    return tuple(axes)


@dataclass(frozen=True)
class MeshShape:
    """Declarative description of the global device mesh."""

    axis_sizes: tuple[int, ...]
    axis_names: tuple[str, ...] = DEFAULT_AXES

    def __post_init__(self):
        if len(self.axis_sizes) != len(self.axis_names):
            raise ValueError(
                f"axis_sizes {self.axis_sizes} and axis_names {self.axis_names} "
                "must have equal rank"
            )

    @property
    def num_devices(self) -> int:
        return math.prod(self.axis_sizes)

    @staticmethod
    def data_parallel(num_devices: int) -> "MeshShape":
        return MeshShape((num_devices, 1, 1, 1))


def build_mesh(shape: MeshShape, devices: Optional[Sequence] = None) -> Mesh:
    """Build the global mesh. Uses the classic `Mesh` constructor so axes are
    Auto-typed (required for `with_sharding_constraint` pinning under GSPMD)."""
    if devices is None:
        devices = jax.devices()
    n = shape.num_devices
    if n > len(devices):
        raise ValueError(
            f"mesh needs {n} devices but only {len(devices)} available"
        )
    grid = np.array(devices[:n]).reshape(shape.axis_sizes)
    return Mesh(grid, shape.axis_names)


def spec_num_shards(mesh: Mesh, spec: PartitionSpec) -> int:
    n = 1
    for entry in spec:
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            n *= mesh.shape[ax]
    return n


def named_sharding(mesh: Mesh, spec: PartitionSpec) -> NamedSharding:
    return NamedSharding(mesh, spec)
