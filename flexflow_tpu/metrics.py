"""Metrics: PerfMetrics accumulation.

Reference: include/flexflow/metrics_functions.h:44 + src/metrics_functions/ —
per-shard GPU accumulation folded through a Legion future reduction
(METRICS_COMP_TASK_ID / UPDATE_METRICS_TASK_ID). On TPU the per-shard compute
+ cross-replica reduction is a jnp reduction inside the jitted step (GSPMD
inserts the psum); accumulation across iterations happens in a small on-device
pytree, read back only when the user asks (get_metrics), so the train loop
stays free of host syncs.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import math

import jax
import jax.numpy as jnp

from .fftype import LossType, MetricsType


@dataclass
class Metrics:
    loss_type: LossType
    measure_accuracy: bool = False
    measure_categorical_crossentropy: bool = False
    measure_sparse_categorical_crossentropy: bool = False
    measure_mean_squared_error: bool = False
    measure_root_mean_squared_error: bool = False
    measure_mean_absolute_error: bool = False

    @staticmethod
    def from_list(loss_type: LossType, metrics: list) -> "Metrics":
        m = Metrics(loss_type)
        for mt in metrics:
            mt = MetricsType(mt)
            if mt == MetricsType.METRICS_ACCURACY:
                m.measure_accuracy = True
            elif mt == MetricsType.METRICS_CATEGORICAL_CROSSENTROPY:
                m.measure_categorical_crossentropy = True
            elif mt == MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY:
                m.measure_sparse_categorical_crossentropy = True
            elif mt == MetricsType.METRICS_MEAN_SQUARED_ERROR:
                m.measure_mean_squared_error = True
            elif mt == MetricsType.METRICS_ROOT_MEAN_SQUARED_ERROR:
                m.measure_root_mean_squared_error = True
            elif mt == MetricsType.METRICS_MEAN_ABSOLUTE_ERROR:
                m.measure_mean_absolute_error = True
        return m

    def zero_counters(self):
        # distinct buffers per counter: sharing one zeros() array across all
        # keys makes buffer donation alias the same buffer 7 times, which
        # XLA rejects (INVALID_ARGUMENT)
        return {
            k: jnp.zeros((), jnp.float32)
            for k in (
                "train_all", "train_correct", "cce_loss", "sparse_cce_loss",
                "mse_loss", "rmse_loss", "mae_loss",
            )
        }

    def compute(self, counters, logits, labels, *, from_logits=False,
                scce_sum=None):
        """One batch's contribution (metrics_functions.cu update kernels).

        Classification metrics treat every leading position as a sample —
        (b, classes) classifiers and (b, s, vocab) LMs both work (matching
        loss.py's sparse-CE flattening); sample count follows suit.

        `from_logits` says the final op is not a softmax, so CE metrics go
        through log_softmax instead of log(probs). `scce_sum`, when given, is
        the loss pass's already-reduced CE sum (loss.loss_terms) — reusing it
        avoids a second full reduction over the logits tensor per step."""
        classification = (
            self.measure_accuracy
            or self.measure_sparse_categorical_crossentropy
            or self.measure_categorical_crossentropy
        )
        if classification:
            n = math.prod(logits.shape[:-1])
            flat = logits.reshape(n, logits.shape[-1])
        else:
            n = logits.shape[0]
        new = dict(counters)
        new["train_all"] = counters["train_all"] + n
        eps = 1e-8
        if self.measure_accuracy or self.measure_sparse_categorical_crossentropy:
            sparse = labels.reshape(-1).astype(jnp.int32)
        if self.measure_accuracy:
            pred = jnp.argmax(flat, axis=-1).astype(jnp.int32)
            new["train_correct"] = counters["train_correct"] + jnp.sum(
                (pred == sparse).astype(jnp.float32)
            )
        if self.measure_sparse_categorical_crossentropy:
            if scce_sum is not None:
                contrib = scce_sum
            else:
                f32 = flat.astype(jnp.float32)
                logp = (jax.nn.log_softmax(f32, axis=-1) if from_logits
                        else jnp.log(f32 + eps))
                contrib = -jnp.sum(
                    jnp.take_along_axis(logp, sparse[:, None], axis=-1)
                )
            new["sparse_cce_loss"] = counters["sparse_cce_loss"] + contrib
        if self.measure_categorical_crossentropy:
            f32 = logits.astype(jnp.float32)
            logp = (jax.nn.log_softmax(f32, axis=-1) if from_logits
                    else jnp.log(f32 + eps))
            new["cce_loss"] = counters["cce_loss"] - jnp.sum(labels * logp)
        if (self.measure_mean_squared_error or self.measure_root_mean_squared_error
                or self.measure_mean_absolute_error):
            # reduce in f32: the bf16 compute path hands bf16 logits in, and
            # an 8-bit-mantissa accumulation over the batch is garbage
            err = logits.astype(jnp.float32) - labels.astype(jnp.float32)
        if self.measure_mean_squared_error or self.measure_root_mean_squared_error:
            new["mse_loss"] = counters["mse_loss"] + jnp.sum(err ** 2)
        if self.measure_mean_absolute_error:
            new["mae_loss"] = counters["mae_loss"] + jnp.sum(jnp.abs(err))
        return new


class PerfMetrics:
    """Host-side view of accumulated counters (reference PerfMetrics struct)."""

    def __init__(self, counters, metrics: Metrics):
        self._c = {k: float(v) for k, v in counters.items()}
        self._m = metrics

    @property
    def train_all(self) -> int:
        return int(self._c["train_all"])

    @property
    def train_correct(self) -> int:
        return int(self._c["train_correct"])

    def get_accuracy(self) -> float:
        return self.train_correct / max(1, self.train_all)

    def get_mean_loss(self) -> float:
        n = max(1, self.train_all)
        if self._m.measure_sparse_categorical_crossentropy:
            return self._c["sparse_cce_loss"] / n
        if self._m.measure_categorical_crossentropy:
            return self._c["cce_loss"] / n
        return self._c["mse_loss"] / n

    def __repr__(self):
        n = max(1, self.train_all)
        parts = [f"train_all={self.train_all}"]
        if self._m.measure_accuracy:
            parts.append(f"accuracy={100.0 * self.get_accuracy():.2f}%")
        if self._m.measure_sparse_categorical_crossentropy:
            parts.append(f"sparse_cce={self._c['sparse_cce_loss'] / n:.4f}")
        if self._m.measure_categorical_crossentropy:
            parts.append(f"cce={self._c['cce_loss'] / n:.4f}")
        if self._m.measure_mean_squared_error:
            parts.append(f"mse={self._c['mse_loss'] / n:.4f}")
        if self._m.measure_mean_absolute_error:
            parts.append(f"mae={self._c['mae_loss'] / n:.4f}")
        return "[" + " ".join(parts) + "]"
