"""FFModel: the layer-builder API, compile pipeline, and training loop.

Reference: include/flexflow/model.h:326-958 + src/runtime/model.cc. The
builder surface (dense/conv2d/multihead_attention/..., model.h:336-553) is
reproduced method-for-method; `compile()` mirrors the reference pipeline
(model.cc:2803-3168):

  reference                               TPU-native
  ─────────────────────────────────────   ─────────────────────────────────
  create_operators_from_layers            Layer list → PCG OpNodes
  GRAPH_OPTIMIZE_TASK (Unity search)      search/ (DP+substitutions) or
                                          default data-parallel strategy
  deserialize optimal (graph, views)      per-node PartitionSpec assignment
  ParallelOp::create_input_partition      resharding constraints in executor
  apply_fusion (--fusion)                 XLA fusion (inherent)
  label tensor creation                   label PartitionSpec
  optimizer->init(); NCCL comms           optimizer slots; GSPMD collectives

`fit()` reproduces the cffi fit loop (flexflow_cffi.py:2058-2100): per
iteration {next_batch; forward; zero_gradients; backward; update} — fused
into one jitted step, with the granular forward()/backward()/update() API
also available for parity with C++ examples (transformer.cc:183-197).
"""

from __future__ import annotations

import math
import time
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from .config import FFConfig, FFIterationConfig
from .executor import Executor
from .fftype import (
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OperatorType as OT,
    ParameterSyncType,
    PoolType,
    RegularizerMode,
)
from .initializer import Initializer
from .layer import Layer
from .loss import loss_value
from .machine import AXIS_DATA, AXIS_MODEL, AXIS_PIPE, MachineView, build_mesh
from .metrics import Metrics, PerfMetrics
from .optimizer import Optimizer, SGDOptimizer
from .ops import (
    AggregateParams,
    AggregateSpecParams,
    BatchMatmulParams,
    BatchNormParams,
    CacheParams,
    CastParams,
    ConcatParams,
    Conv2DParams,
    DropoutParams,
    ElementBinaryParams,
    ElementUnaryParams,
    EmbeddingParams,
    GatherParams,
    GroupByParams,
    LayerNormParams,
    LinearParams,
    MultiHeadAttentionParams,
    Pool2DParams,
    ReduceParams,
    ReshapeParams,
    ReverseParams,
    SoftmaxParams,
    SplitParams,
    TopKParams,
    TransposeParams,
)
from .ops.base import get_op_def
from .pcg.graph import Graph, OpNode
from .tensor import ParallelTensor, ParallelTensorShape, Tensor


class FFModel:
    def __init__(self, config: Optional[FFConfig] = None):
        self.config = config or FFConfig()
        self.layers: list[Layer] = []
        self._input_tensors: list[Tensor] = []
        self.graph: Optional[Graph] = None
        self.mesh = None
        self.executor: Optional[Executor] = None
        self.optimizer: Optional[Optimizer] = None
        self.loss_type: Optional[LossType] = None
        self.metrics: Optional[Metrics] = None
        self.label_tensor: Optional[Tensor] = None
        self.iter_config = FFIterationConfig()
        self._params = None
        self._state = None
        self._opt_slots = None
        self._step = None
        self._counters = None
        self._rng = None
        self._current_batch = None
        self._cached_logits = None
        self._grads = None
        self._compiled = False
        self._strategy = None  # node name -> dict of spec overrides
        self._resilience = None  # ResilienceManager (resilience/manager.py)
        self._fault_hook = None  # step -> None; test-only failure injection
        self._epoch_base = 0  # absolute epochs completed across fit() calls
        self._auto_resumed = False  # auto-resume fires at most once
        self._resume_cursor = None  # (absolute epoch, batch) to resume at
        self._telemetry = None  # TelemetrySession (telemetry/session.py)
        self._diagnostics = None  # DiagnosticsManager (diagnostics/)
        # (UnitySearch, choice) of the winning plan — kept after compile so
        # diagnostics/explain can attribute the predicted makespan per op
        # and re-rank runner-up plans without re-running the search
        self._search_result = None
        self._predicted_step_s = None  # chosen plan's predicted makespan
        # warm start (warmstart/): where the applied plan came from
        # (search|cache|checkpoint|import|manual|default), the structural
        # plan fingerprint, the WarmStartManager when --warmstart-dir is
        # set, and the manifest-ready plan record checkpoints embed so
        # --auto-resume can restore the plan without searching
        self._plan_source = "none"
        self._plan_fingerprint = None
        self._warmstart = None
        self._plan_record = None
        # weight-update sharding decision (unity.choose_update_sharding):
        # whether fp32 masters + optimizer slots run ZeRO-sharded 1/dp
        # with the grad sync as an overlappable reduce-scatter; recorded
        # in checkpoint manifests + strategy_report.json
        self._update_sharding = None
        # ffcheck result (analysis.AnalysisResult) of the compile gate —
        # strategy_report.json surfaces it as its `analysis` section
        self._analysis = None
        # SPMD fingerprint-barrier verdict ({status, fingerprint} or
        # None when --spmd-barrier is off) — recorded at compile,
        # surfaced in the compile metrics record + strategy_report.json
        self._spmd_barrier = None
        # elastic re-planning (elastic/): the controller (--elastic /
        # enable_elastic) and its decision records — every replan attempt
        # (migrated/declined/dry_run/failed, both sides of the payoff
        # inequality) appends here and rides strategy_report.json's
        # `elastic` section
        self._elastic = None
        self._elastic_decisions = []

    # ================================================== tensor creation

    def create_tensor(
        self,
        dims: Sequence[int],
        dtype: DataType = DataType.DT_FLOAT,
        create_grad: bool = True,
        name: str = "",
    ) -> Tensor:
        t = Tensor(tuple(dims), dtype, name=name or f"input_{len(self._input_tensors)}",
                   create_gradients=create_grad)
        self._input_tensors.append(t)
        return t

    def create_constant(self, dims, value: float, data_type: DataType) -> Tensor:
        t = self.create_tensor(dims, data_type, create_grad=False,
                               name=f"const_{len(self._input_tensors)}")
        t.constant_value = value
        return t

    # ================================================== internal builder

    def _add_layer(
        self,
        op_type: OT,
        params,
        inputs: list[Tensor],
        name: str = "",
        initializers: Optional[dict] = None,
        data_type: DataType = DataType.DT_FLOAT,
        shared_op=None,
    ) -> Layer:
        layer = Layer(op_type, params, inputs, name=name, data_type=data_type,
                      initializers=initializers)
        if shared_op is not None:
            # tied weights (reference dense/embedding shared_op, model.h):
            # this layer reads the shared layer's parameters; autodiff sums
            # the gradients of every use into the one parameter set
            src = getattr(shared_op, "owner_layer", shared_op)
            if not isinstance(src, Layer):
                raise TypeError(
                    f"shared_op must be a Layer or one of its output "
                    f"tensors, got {type(shared_op).__name__}")
            if src.op_type != op_type:
                raise ValueError(
                    f"shared_op ties a {op_type.name} layer to a "
                    f"{src.op_type.name} layer")
            layer.shared_layer_guid = src.layer_guid
        op_def = get_op_def(op_type)
        in_shapes = [t.dims for t in inputs]
        out_shapes = op_def.infer_shapes(params, in_shapes)
        for i, s in enumerate(out_shapes):
            layer.outputs.append(
                Tensor(s, data_type, owner_layer=layer, owner_idx=i,
                       name=f"{layer.name}_out{i}")
            )
        self.layers.append(layer)
        return layer

    def _unary(self, op_type: OT, x: Tensor, name: str = "", inplace: bool = True,
               scalar: float = 0.0) -> Tensor:
        p = ElementUnaryParams(op_type, inplace, scalar)
        return self._add_layer(op_type, p, [x], name, data_type=x.dtype).outputs[0]

    def _binary(self, op_type: OT, x: Tensor, y: Tensor, name: str = "",
                inplace_a: bool = False) -> Tensor:
        p = ElementBinaryParams(op_type, inplace_a)
        return self._add_layer(op_type, p, [x, y], name, data_type=x.dtype).outputs[0]

    # ================================================== ops (model.h:336-553)

    def exp(self, x, name=""):
        return self._unary(OT.OP_EXP, x, name)

    def sin(self, x, name=""):
        return self._unary(OT.OP_SIN, x, name)

    def cos(self, x, name=""):
        return self._unary(OT.OP_COS, x, name)

    def add(self, x, y, inplace_a=False, name=""):
        return self._binary(OT.OP_EW_ADD, x, y, name, inplace_a)

    def subtract(self, x, y, inplace_a=False, name=""):
        return self._binary(OT.OP_EW_SUB, x, y, name, inplace_a)

    def multiply(self, x, y, inplace_a=False, name=""):
        return self._binary(OT.OP_EW_MUL, x, y, name, inplace_a)

    def divide(self, x, y, inplace_a=False, name=""):
        return self._binary(OT.OP_EW_DIV, x, y, name, inplace_a)

    def max(self, x, y, inplace_a=False, name=""):
        return self._binary(OT.OP_EW_MAX, x, y, name, inplace_a)

    def min(self, x, y, inplace_a=False, name=""):
        return self._binary(OT.OP_EW_MIN, x, y, name, inplace_a)

    def rsqrt(self, x, inplace=True, name=""):
        return self._unary(OT.OP_RSQRT, x, name, inplace)

    def pow(self, x, exponent: float, inplace=True, name=""):
        return self._unary(OT.OP_POW, x, name, inplace, scalar=exponent)

    def scalar_multiply(self, x, scalar: float, inplace=True, name=""):
        return self._unary(OT.OP_SCALAR_MULTIPLY, x, name, inplace, scalar)

    def scalar_add(self, x, scalar: float, inplace=True, name=""):
        return self._unary(OT.OP_SCALAR_ADD, x, name, inplace, scalar)

    def scalar_sub(self, x, scalar: float, inplace=True, name=""):
        return self._unary(OT.OP_SCALAR_SUB, x, name, inplace, scalar)

    def scalar_true_divide(self, x, scalar: float, inplace=True, name=""):
        return self._unary(OT.OP_SCALAR_TRUE_DIV, x, name, inplace, scalar)

    def relu(self, x, inplace=True, name=""):
        return self._unary(OT.OP_RELU, x, name, inplace)

    def identity(self, x, name=""):
        return self._unary(OT.OP_IDENTITY, x, name)

    def gelu(self, x, name=""):
        return self._unary(OT.OP_GELU, x, name)

    def sigmoid(self, x, name=""):
        return self._unary(OT.OP_SIGMOID, x, name)

    def tanh(self, x, name=""):
        return self._unary(OT.OP_TANH, x, name)

    def elu(self, x, inplace=True, name=""):
        return self._unary(OT.OP_ELU, x, name, inplace)

    def dense(
        self,
        input: Tensor,
        out_dim: int,
        activation: ActiMode = ActiMode.AC_MODE_NONE,
        use_bias: bool = True,
        data_type: DataType = DataType.DT_FLOAT,
        shared_op=None,
        kernel_initializer: Optional[Initializer] = None,
        bias_initializer: Optional[Initializer] = None,
        kernel_regularizer: RegularizerMode = RegularizerMode.REG_MODE_NONE,
        name: str = "",
    ) -> Tensor:
        p = LinearParams(out_dim, use_bias, ActiMode(activation), data_type)
        inits = {}
        if kernel_initializer is not None:
            inits["kernel"] = kernel_initializer
        if bias_initializer is not None:
            inits["bias"] = bias_initializer
        return self._add_layer(OT.OP_LINEAR, p, [input], name, inits,
                               data_type, shared_op=shared_op).outputs[0]

    def conv2d(
        self,
        input: Tensor,
        out_channels: int,
        kernel_h: int,
        kernel_w: int,
        stride_h: int,
        stride_w: int,
        padding_h: int,
        padding_w: int,
        activation: ActiMode = ActiMode.AC_MODE_NONE,
        groups: int = 1,
        use_bias: bool = True,
        shared_op=None,
        kernel_initializer: Optional[Initializer] = None,
        bias_initializer: Optional[Initializer] = None,
        name: str = "",
    ) -> Tensor:
        p = Conv2DParams(out_channels, kernel_h, kernel_w, stride_h, stride_w,
                         padding_h, padding_w, groups, use_bias, ActiMode(activation))
        inits = {}
        if kernel_initializer is not None:
            inits["kernel"] = kernel_initializer
        if bias_initializer is not None:
            inits["bias"] = bias_initializer
        return self._add_layer(OT.OP_CONV2D, p, [input], name, inits).outputs[0]

    def pool2d(
        self,
        input: Tensor,
        kernel_h: int,
        kernel_w: int,
        stride_h: int,
        stride_w: int,
        padding_h: int,
        padding_w: int,
        pool_type: PoolType = PoolType.POOL_MAX,
        activation: ActiMode = ActiMode.AC_MODE_NONE,
        name: str = "",
    ) -> Tensor:
        p = Pool2DParams(kernel_h, kernel_w, stride_h, stride_w, padding_h,
                         padding_w, PoolType(pool_type), ActiMode(activation))
        return self._add_layer(OT.OP_POOL2D, p, [input], name).outputs[0]

    def batch_norm(self, input: Tensor, relu: bool = True, name: str = "") -> Tensor:
        p = BatchNormParams(relu)
        return self._add_layer(OT.OP_BATCHNORM, p, [input], name).outputs[0]

    def layer_norm(
        self,
        input: Tensor,
        axes: Sequence[int],
        elementwise_affine: bool = True,
        eps: float = 1e-5,
        name: str = "",
    ) -> Tensor:
        p = LayerNormParams(tuple(axes), elementwise_affine, eps)
        return self._add_layer(OT.OP_LAYERNORM, p, [input], name,
                               data_type=input.dtype).outputs[0]

    def batch_matmul(
        self,
        A: Tensor,
        B: Tensor,
        a_seq_length_dim: int = -1,
        b_seq_length_dim: int = -1,
        name: str = "",
    ) -> Tensor:
        p = BatchMatmulParams(a_seq_length_dim, b_seq_length_dim)
        return self._add_layer(OT.OP_BATCHMATMUL, p, [A, B], name,
                               data_type=A.dtype).outputs[0]

    def dropout(self, input: Tensor, rate: float, seed: int = 0, name: str = "") -> Tensor:
        p = DropoutParams(rate, seed)
        return self._add_layer(OT.OP_DROPOUT, p, [input], name,
                               data_type=input.dtype).outputs[0]

    def embedding(
        self,
        input: Tensor,
        num_entries: int,
        out_dim: int,
        aggr: AggrMode = AggrMode.AGGR_MODE_NONE,
        dtype: DataType = DataType.DT_FLOAT,
        shared_op=None,
        kernel_initializer: Optional[Initializer] = None,
        name: str = "",
    ) -> Tensor:
        p = EmbeddingParams(num_entries, out_dim, AggrMode(aggr), dtype)
        inits = {"kernel": kernel_initializer} if kernel_initializer else {}
        return self._add_layer(OT.OP_EMBEDDING, p, [input], name, inits,
                               dtype, shared_op=shared_op).outputs[0]

    def gather(self, input: Tensor, index: Tensor, dim: int = 0, name: str = "") -> Tensor:
        p = GatherParams(dim)
        return self._add_layer(OT.OP_GATHER, p, [input, index], name,
                               data_type=input.dtype).outputs[0]

    def multihead_attention(
        self,
        query: Tensor,
        key: Tensor,
        value: Tensor,
        embed_dim: int,
        num_heads: int,
        kdim: int = 0,
        vdim: int = 0,
        dropout: float = 0.0,
        bias: bool = True,
        add_bias_kv: bool = False,
        add_zero_attn: bool = False,
        kernel_initializer: Optional[Initializer] = None,
        causal: bool = False,
        impl: str = "xla",
        name: str = "",
    ) -> Tensor:
        if impl not in ("xla", "flash", "ring"):
            raise ValueError(
                f"multihead_attention impl must be xla|flash|ring, got {impl!r}"
            )
        p = MultiHeadAttentionParams(embed_dim, num_heads, kdim, vdim, dropout,
                                     bias, add_bias_kv, add_zero_attn, causal,
                                     impl)
        inits = {}
        if kernel_initializer is not None:
            for w in ("wq", "wk", "wv", "wo"):
                inits[w] = kernel_initializer
        return self._add_layer(OT.OP_MULTIHEAD_ATTENTION, p, [query, key, value],
                               name, inits, query.dtype).outputs[0]

    def inc_multihead_attention(
        self,
        input: Tensor,
        positions: Tensor,
        embed_dim: int,
        num_heads: int,
        max_seq_len: int,
        use_bias: bool = True,
        impl: str = "auto",
        name: str = "",
    ) -> Tensor:
        """Decode-phase self-attention over a per-layer KV cache (serving/):
        `input` carries q_len new tokens per slot, `positions` their
        absolute sequence positions (scratch-row convention for padding —
        ops/inc_attention.py). The cache is a non-trainable stateful
        weight, placed by the plan like any parameter. Weight names match
        multihead_attention's, so trained parameters transfer by name."""
        from .ops import IncMultiHeadAttentionParams

        p = IncMultiHeadAttentionParams(embed_dim, num_heads, max_seq_len,
                                        use_bias, impl)
        return self._add_layer(OT.OP_INC_MULTIHEAD_ATTENTION, p,
                               [input, positions], name,
                               data_type=input.dtype).outputs[0]

    def paged_inc_multihead_attention(
        self,
        input: Tensor,
        positions: Tensor,
        page_table: Tensor,
        embed_dim: int,
        num_heads: int,
        max_seq_len: int,
        block_size: int,
        num_blocks: int,
        use_bias: bool = True,
        impl: str = "auto",
        name: str = "",
    ) -> Tensor:
        """Decode-phase self-attention over a PAGED KV cache (serving/,
        vLLM-style): per-layer block pools `pool_k`/`pool_v` of shape
        (num_blocks, block_size, embed_dim) — block 0 reserved as scratch
        — addressed through the shared `page_table` input ((slots,
        ceil(max_seq_len/block_size)) int32, logical→physical). Pools are
        non-trainable stateful weights placed by the plan; weight names
        match multihead_attention's, so trained parameters transfer by
        name exactly like the contiguous decode op's."""
        from .ops import PagedIncMultiHeadAttentionParams

        p = PagedIncMultiHeadAttentionParams(
            embed_dim, num_heads, max_seq_len, block_size, num_blocks,
            use_bias, impl)
        return self._add_layer(OT.OP_PAGED_INC_MULTIHEAD_ATTENTION, p,
                               [input, positions, page_table], name,
                               data_type=input.dtype).outputs[0]

    def concat(self, tensors: Sequence[Tensor], axis: int, name: str = "") -> Tensor:
        p = ConcatParams(axis, len(tensors))
        return self._add_layer(OT.OP_CONCAT, p, list(tensors), name,
                               data_type=tensors[0].dtype).outputs[0]

    def split(self, input: Tensor, sizes: Union[int, Sequence[int]], axis: int,
              name: str = "") -> list[Tensor]:
        if isinstance(sizes, int):
            # torch.split-style: n equal chunks
            total = input.dims[axis % len(input.dims)]
            if total % sizes != 0:
                raise ValueError(f"cannot split dim {total} into {sizes} equal parts")
            sizes = [total // sizes] * sizes
        p = SplitParams(tuple(sizes), axis)
        return self._add_layer(OT.OP_SPLIT, p, [input], name,
                               data_type=input.dtype).outputs

    def flat(self, input: Tensor, name: str = "") -> Tensor:
        return self._add_layer(OT.OP_FLAT, None, [input], name).outputs[0]

    def softmax(self, input: Tensor, dim: int = -1, name: str = "") -> Tensor:
        p = SoftmaxParams(dim)
        return self._add_layer(OT.OP_SOFTMAX, p, [input], name,
                               data_type=input.dtype).outputs[0]

    def transpose(self, input: Tensor, perm: Sequence[int], name: str = "") -> Tensor:
        p = TransposeParams(tuple(perm))
        return self._add_layer(OT.OP_TRANSPOSE, p, [input], name,
                               data_type=input.dtype).outputs[0]

    def reduce_sum(self, input: Tensor, axes: Sequence[int], keepdims: bool = False,
                   name: str = "") -> Tensor:
        p = ReduceParams(OT.OP_REDUCE_SUM, tuple(axes), keepdims)
        return self._add_layer(OT.OP_REDUCE_SUM, p, [input], name,
                               data_type=input.dtype).outputs[0]

    def mean(self, input: Tensor, dims: Sequence[int], keepdims: bool = False,
             name: str = "") -> Tensor:
        p = ReduceParams(OT.OP_MEAN, tuple(dims), keepdims)
        return self._add_layer(OT.OP_MEAN, p, [input], name,
                               data_type=input.dtype).outputs[0]

    def reshape(self, input: Tensor, shape: Sequence[int], name: str = "") -> Tensor:
        p = ReshapeParams(tuple(shape))
        return self._add_layer(OT.OP_RESHAPE, p, [input], name,
                               data_type=input.dtype).outputs[0]

    def reverse(self, input: Tensor, axis: int, name: str = "") -> Tensor:
        p = ReverseParams(axis)
        return self._add_layer(OT.OP_REVERSE, p, [input], name,
                               data_type=input.dtype).outputs[0]

    def top_k(self, input: Tensor, k: int, sorted: bool = True,
              name: str = "") -> tuple[Tensor, Tensor]:
        p = TopKParams(k, sorted)
        outs = self._add_layer(OT.OP_TOPK, p, [input], name,
                               data_type=input.dtype).outputs
        return outs[0], outs[1]

    def cast(self, input: Tensor, dtype: DataType, name: str = "") -> Tensor:
        p = CastParams(DataType(dtype))
        return self._add_layer(OT.OP_CAST, p, [input], name,
                               data_type=DataType(dtype)).outputs[0]

    # ------------------------------------------------ MoE family

    def group_by(self, data: Tensor, assign: Tensor, n: int, alpha: float,
                 name: str = "") -> list[Tensor]:
        p = GroupByParams(n, alpha)
        return self._add_layer(OT.OP_GROUP_BY, p, [data, assign], name,
                               data_type=data.dtype).outputs

    def aggregate(self, inputs: Sequence[Tensor], n: int, lambda_bal: float = 0.0,
                  name: str = "") -> Tensor:
        p = AggregateParams(n, lambda_bal)
        return self._add_layer(OT.OP_AGGREGATE, p, list(inputs), name,
                               data_type=inputs[4].dtype).outputs[0]

    def aggregate_spec(self, inputs: Sequence[Tensor], n: int,
                       lambda_bal: float = 0.0, name: str = "") -> Tensor:
        p = AggregateSpecParams(n, lambda_bal)
        return self._add_layer(OT.OP_AGG_SPEC, p, list(inputs), name,
                               data_type=inputs[4].dtype).outputs[0]

    def cache(self, input: Tensor, num_batches: int, name: str = "") -> Tensor:
        p = CacheParams(num_batches, input.dtype)
        return self._add_layer(OT.OP_CACHE, p, [input], name,
                               data_type=input.dtype).outputs[0]

    def experts(
        self,
        input: Tensor,
        gate_values: Tensor,
        gate_assign: Tensor,
        num_experts: int,
        hidden_size: int,
        alpha: float = 1.0,
        lambda_bal: float = 0.0,
        use_bias: bool = True,
        activation: str = "relu",
        name: str = "",
    ) -> Tensor:
        """Fused stacked-experts op (TPU-native MoE fast path; shard its
        kernel dim 0 over the expert mesh axis for expert parallelism)."""
        from .ops import ExpertsParams

        p = ExpertsParams(num_experts, hidden_size, alpha, lambda_bal,
                          use_bias, activation)
        return self._add_layer(OT.OP_EXPERTS, p,
                               [input, gate_values, gate_assign], name,
                               data_type=input.dtype).outputs[0]

    def moe(
        self,
        input: Tensor,
        num_exp: int,
        num_select: int,
        expert_hidden_size: int,
        alpha: float,
        lambda_bal: float,
        fused: bool = False,
    ) -> Tensor:
        """MoE composite (reference src/ops/moe.cc:20-50): gate dense → topk →
        group_by → per-expert dense → aggregate. With fused=True the
        group_by/expert/aggregate trio is the single stacked Experts op."""
        gate_preds = self.dense(input, num_exp, ActiMode.AC_MODE_RELU)
        gate_probs = self.softmax(gate_preds)
        topk_values, topk_assign = self.top_k(gate_probs, num_select)
        if fused:
            return self.experts(input, topk_values, topk_assign, num_exp,
                                expert_hidden_size, alpha, lambda_bal)
        expert_inputs = self.group_by(input, topk_assign, num_exp, alpha)
        expert_outputs = []
        for ei in expert_inputs:
            h = self.dense(ei, expert_hidden_size, ActiMode.AC_MODE_RELU)
            expert_outputs.append(h)
        agg_inputs = [topk_values, topk_assign, topk_assign, gate_probs] + expert_outputs
        return self.aggregate(agg_inputs, num_exp, lambda_bal)

    def pipeline_blocks(
        self,
        input: Tensor,
        num_layers: int,
        num_heads: int,
        mlp_ratio: int = 4,
        num_microbatches: int = 0,
        causal: bool = True,
        attention_impl: str = "xla",
        name: str = "",
    ) -> Tensor:
        """L stacked pre-LN transformer blocks as one op whose layer dim
        shards over the `pipe` mesh axis — working pipeline parallelism
        (ppermute fill/drain schedule, parallel/pipeline.py), exceeding the
        reference's enum-only OP_PIPELINE (ffconst.h:159)."""
        from .ops import PipelineBlocksParams

        p = PipelineBlocksParams(num_layers, num_heads, mlp_ratio,
                                 num_microbatches, causal, attention_impl)
        return self._add_layer(OT.OP_PIPE_BLOCKS, p, [input], name,
                               data_type=input.dtype).outputs[0]

    # ------------------------------------------------ parallel ops
    # (reference src/parallel_ops/*; inserted explicitly or by Unity search)

    def repartition(self, input: Tensor, dim: int, degree: int,
                    name: str = "") -> Tensor:
        from .parallel import RepartitionParams

        p = RepartitionParams(dim, degree)
        return self._add_layer(OT.OP_REPARTITION, p, [input], name,
                               data_type=input.dtype).outputs[0]

    def combine(self, input: Tensor, dim: int, degree: int,
                name: str = "") -> Tensor:
        from .parallel import CombineParams

        p = CombineParams(dim, degree)
        return self._add_layer(OT.OP_COMBINE, p, [input], name,
                               data_type=input.dtype).outputs[0]

    def replicate(self, input: Tensor, degree: int, name: str = "") -> Tensor:
        from .parallel import ReplicateParams

        p = ReplicateParams(degree)
        return self._add_layer(OT.OP_REPLICATE, p, [input], name,
                               data_type=input.dtype).outputs[0]

    def reduction(self, input: Tensor, degree: int, name: str = "") -> Tensor:
        from .parallel import ReductionParams

        p = ReductionParams(degree)
        return self._add_layer(OT.OP_REDUCTION, p, [input], name,
                               data_type=input.dtype).outputs[0]

    # ================================================== strategy

    def set_strategy(self, strategy):
        """Install a parallelization strategy (a parallel.Strategy or raw
        override dict) applied on top of the data-parallel default at
        compile. The `--import-strategy` analog (model.cc:3599-3608)."""
        self._strategy = getattr(strategy, "overrides", strategy)

    # ================================================== compile

    def compile(
        self,
        optimizer: Optional[Optimizer] = None,
        loss_type: LossType = LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics: Sequence[MetricsType] = (),
        comp_mode: CompMode = CompMode.COMP_MODE_TRAINING,
    ):
        """Lower layers → PCG, choose a parallelization strategy, build the
        executor (pipeline parity: model.cc:2803-3168)."""
        from . import telemetry

        if self._telemetry is None and self.config.telemetry_dir:
            self.enable_telemetry(self.config.telemetry_dir)
        tel = self._telemetry
        try:
            if tel is not None:
                # the global sink is active only while ITS model is inside
                # an instrumented operation — another model compiled in the
                # same process must not write into this model's artifacts
                telemetry.activate(tel)
                # manifest FIRST — before any search events the body emits
                tel.write_manifest(self)
            t_compile0 = time.perf_counter()
            if tel is not None:
                # time-to-first-step accounting: the fit summary reports
                # first-step completion relative to this instant
                tel.note_compile_start(t_compile0)
            with telemetry.span("compile"):
                self._compile_impl(optimizer, loss_type, metrics, comp_mode)
            if tel is not None:
                # the COMPILED outcome (a mesh-shape search may have
                # replaced the configured mesh; strategy_nodes = ops
                # deviating from pure data parallel)
                tel.recorder.record(
                    "compile",
                    duration_s=time.perf_counter() - t_compile0,
                    num_nodes=len(self.graph.topo_order()),
                    mesh_axes={k: int(v)
                               for k, v in self.mesh.shape.items()},
                    strategy_nodes=sorted(self._strategy)
                    if self._strategy else [],
                    plan_source=self._plan_source,
                    plan_fingerprint=self._plan_fingerprint,
                    # ffsan state: whether this compile's step carries
                    # the numerics probes, and the fingerprint-barrier
                    # verdict (run_doctor --check gates on both)
                    sanitize_numerics=bool(
                        self.config.sanitize_numerics),
                    spmd_barrier=(self._spmd_barrier or {}).get(
                        "status", "off"),
                )
                diag = self._maybe_enable_diagnostics()
                if diag is not None:
                    # strategy explain + drift-monitor arming, inside the
                    # active-session window so its spans/events land here
                    diag.on_compile()
        finally:
            if tel is not None:
                # flush in the finally: a compile/search crash is exactly
                # when the buffered spans are wanted on disk
                tel.flush()
                telemetry.deactivate(tel)

    def _compile_impl(self, optimizer, loss_type, metrics, comp_mode):
        from . import telemetry

        self.optimizer = optimizer or SGDOptimizer(lr=self.config.learning_rate)
        self.loss_type = LossType(loss_type)
        self.metrics = Metrics.from_list(self.loss_type, list(metrics))
        # the raw metrics argument, kept so an elastic replan can drive
        # this same compile pipeline again with identical arguments
        self._metrics_arg = tuple(metrics)
        self.config.computation_mode = comp_mode

        # --- create_operators_from_layers
        g = Graph()
        tensor_to_out = {}  # Tensor guid -> (OpNode, out idx)
        for t in self._input_tensors:
            node = OpNode(OT.OP_INPUT, None, name=t.name)
            shape = ParallelTensorShape.from_shape(t.dims, t.dtype)
            pt = ParallelTensor(shape, name=t.name)
            node.outputs = [pt]
            g.add_node(node)
            tensor_to_out[t.tensor_guid] = (node, 0)

        guid_to_node: dict[int, OpNode] = {}
        self._weight_alias: dict[str, str] = {}  # tied node name -> owner
        for layer in self.layers:
            node = OpNode(layer.op_type, layer.params, name=layer.name,
                          layer_guid=layer.layer_guid,
                          initializers=layer.initializers)
            g.add_node(node)
            guid_to_node[layer.layer_guid] = node
            for dst_idx, t_in in enumerate(layer.inputs):
                src_node, src_idx = tensor_to_out[t_in.tensor_guid]
                g.add_edge(src_node, node, src_idx, dst_idx)
                node.inputs.append(src_node.outputs[src_idx])
            in_shapes = [t.dims for t in layer.inputs]
            node.weight_specs = node.op_def.weights(layer.params, in_shapes)
            if layer.shared_layer_guid >= 0:
                # tied weights: this node reads the source node's parameter
                # set; the executor creates no variables for it and autodiff
                # sums gradients across all uses (reference shared_op)
                src = guid_to_node.get(layer.shared_layer_guid)
                if src is None:
                    raise ValueError(
                        f"{layer.name}: shared_op layer must be built "
                        f"before the layer sharing it")
                src_shapes = {ws.name: ws.shape for ws in src.weight_specs}
                for ws in node.weight_specs:
                    if src_shapes.get(ws.name) != ws.shape:
                        raise ValueError(
                            f"{layer.name}: shared weight {ws.name!r} shape "
                            f"{ws.shape} != source {src.name}'s "
                            f"{src_shapes.get(ws.name)}")
                node.weight_source = src.name
                self._weight_alias[node.name] = src.name
            for i, t_out in enumerate(layer.outputs):
                shape = ParallelTensorShape.from_shape(t_out.dims, t_out.dtype)
                pt = ParallelTensor(shape, name=t_out.name)
                pt.owner_op = node
                pt.owner_idx = i
                node.outputs.append(pt)
                tensor_to_out[t_out.tensor_guid] = (node, i)
        self.graph = g

        # --- mesh + strategy
        self.mesh = self._build_mesh(self.config.mesh_shape())
        used_substitutions = False
        search_cost_model = None  # set by the search branch (calibrated)
        if self.config.warmstart_dir and self._warmstart is None:
            # attach the warm-start subsystem early: pointing JAX's
            # persistent compilation cache under the warm-start dir must
            # precede the first jit of this compile (executor build,
            # init_variables) so those executables land in / load from it
            from .warmstart import WarmStartManager

            self._warmstart = WarmStartManager(
                self, self.config.warmstart_dir)
        if self._strategy is not None:
            self._plan_source = "manual"  # set_strategy()
        elif self.config.import_strategy_file:
            # replay a previously searched/exported plan instead of
            # re-searching (--import-strategy, model.cc:3599-3608) —
            # validated against THIS graph and mesh first, so a stale
            # plan fails loudly instead of silently degrading node by
            # node to data parallel
            from .parallel.strategies import Strategy

            imported = Strategy.load(self.config.import_strategy_file)
            try:
                imported.validate(g, self.mesh)
            except ValueError as e:
                raise ValueError(
                    f"--import-strategy "
                    f"{self.config.import_strategy_file}: {e}") from e
            self._strategy = imported.overrides
            self._plan_source = "import"
        n_devices = 1
        for v in self.mesh.shape.values():
            n_devices *= v
        do_search = (
            self._strategy is None
            and not self.config.only_data_parallel
            and n_devices > 1
            and (
                self.config.search_budget > 0
                or self.config.enable_parameter_parallel
                or self.config.enable_attribute_parallel
                or self.config.enable_substitutions
                or bool(self.config.substitution_json_path)
            )
        )
        if do_search:
            # ONE joint Unity search (GRAPH_OPTIMIZE_TASK analog): GraphXfer
            # rewrites and per-node placements optimized together — every
            # rewritten candidate is costed by the placement DP
            # (substitution.cc:2229-2311 + graph.cc:1742-1843). The winning
            # graph (possibly rewritten, with explicit parallel ops) replaces
            # the layer-built one and arrives with every tensor's mesh axes +
            # weight shardings materialized; the searched placements are also
            # kept as a Strategy for --export-strategy.
            from .search.cost_model import CostModel
            from .search.joint import joint_graph_optimize
            from .search.machine_model import (
                machine_model_for_mesh,
                machine_model_from_file,
            )

            machine = (
                machine_model_from_file(
                    self.config.machine_model_file, self.mesh)
                if self.config.machine_model_file
                else machine_model_for_mesh(
                    self.mesh, num_hosts=self.config.num_nodes)
            )
            cost_model = CostModel(
                machine, opt_slots=self.optimizer.num_slots)
            if (self.config.weight_update_sharding
                    and self.config.computation_mode
                    == CompMode.COMP_MODE_TRAINING):
                # forced sharded update: the placement search itself must
                # price sync as the overlappable RS+AG + 1/dp state (auto
                # mode decides after the placements are materialized —
                # choose_update_sharding below); a forced stage 3 also
                # prices weights 1/shards-at-rest + the just-in-time
                # gather pair. Inference compiles — a serving replay
                # inherits the trainer's config — have no grad sync or
                # optimizer state to price.
                cost_model.update_sharding = True
                cost_model.param_gather = (
                    self.config.weight_update_stage == 3)
                cost_model.overlap_update = bool(
                    self.config.overlap_collectives)
            search_cost_model = cost_model

            _calibrated = [False]

            def _calibrate():
                # measure the dominant ops on the local chip so the search
                # costs candidates from measurements, not the mfu guess
                # (Simulator::measure_operator_cost, model.cu:38-75).
                # Idempotent: the warm-start fingerprinting runs it before
                # the search branches do, and it must not emit two spans.
                if _calibrated[0] or self.config.search_calibrate <= 0:
                    return
                _calibrated[0] = True
                with telemetry.span("compile.calibrate"):
                    cost_model.calibrate_graph(
                        g, top_k=self.config.search_calibrate)
                    # ring-capable axes: measure the real ppermute hop so
                    # the overlap-aware sp pricing (and the warm-start DB)
                    # uses the chip's hop, not the datasheet guess
                    from .machine import AXIS_SEQ

                    ring_axes = [
                        ax for ax in (AXIS_SEQ,)
                        if dict(self.mesh.shape).get(ax, 1) > 1]
                    if ring_axes:
                        hops = cost_model.calibrate_collectives(
                            self.mesh, ring_axes)
                        telemetry.event("calibrate_collectives",
                                        axes=ring_axes, measured=hops)
                    stats = getattr(cost_model, "calib_stats", None)
                    if stats is not None:
                        # measured-vs-cache-hit split (the calibration
                        # twin of the search evals/cache_hits counters):
                        # with a warm calibration DB, measured → 0 and
                        # cache_hits → candidates — drift in that reuse
                        # is visible per compile in metrics.jsonl
                        telemetry.event(
                            "calibrate",
                            top_k=self.config.search_calibrate, **stats)

            tensor_to_out[self.layers[-1].outputs[0].tensor_guid][0]._is_logits = True
            restored = None
            if jax.process_count() == 1:
                # warm start: adopt a cached/checkpointed plan when its
                # fingerprint matches everything this search would consume
                # — a hit replays through the same strategy machinery
                # --import-strategy uses, with ZERO search evaluations
                from .warmstart import restore_plan

                restored = restore_plan(self, g, cost_model, _calibrate)
            if restored is not None:
                overrides, plan_mesh_axes, source = restored
                cur_axes = {k: int(v) for k, v in self.mesh.shape.items()}
                if plan_mesh_axes and plan_mesh_axes != cur_axes:
                    # a mesh-shape-searched plan carries its winning
                    # factorization — rebuild the mesh it was found for
                    from .machine import MeshShape

                    ms = self.config.mesh_shape()
                    sizes = {a: 1 for a in ms.axis_names}
                    sizes.update(plan_mesh_axes)
                    self.mesh = self._build_mesh(MeshShape(
                        tuple(sizes[a] for a in ms.axis_names),
                        ms.axis_names))
                self._strategy = overrides
                self._plan_source = source
                self._search_result = None  # plan replayed, not searched
                self._assign_strategy()
            elif jax.process_count() > 1:
                # multi-host: search on process 0 only, broadcast the plan,
                # and apply it to the ORIGINAL graph on every process (the
                # reference's search-on-GPU0 + serialize pattern,
                # mapper.cc:291-306 / model.cc:2830-2872) — rewritten-graph
                # materialization is skipped because the broadcast Strategy
                # expresses the same placements in logical-rank form
                from .distributed import run_search_on_host0

                def _search():
                    # calibration only where its measurements are consumed
                    # (process 0) — the other hosts' device time is not
                    # wasted on benchmarks whose results get discarded.
                    # Warm start also lives entirely on process 0: only
                    # host 0 reads/writes the shared warm-start dir, and a
                    # plan-cache hit reaches the other hosts through the
                    # same broadcast a searched plan would
                    from .parallel.strategies import Strategy
                    from .telemetry import log as fflog
                    from .warmstart import restore_plan, store_plan

                    warm = restore_plan(self, g, cost_model, _calibrate)
                    if warm is not None:
                        cur = {k: int(v)
                               for k, v in self.mesh.shape.items()}
                        if warm[1] and warm[1] != cur:
                            # the fleet's mesh is already built on every
                            # process — a plan for a different
                            # factorization cannot be adopted here; treat
                            # as a miss rather than mis-apply it
                            fflog.warning(
                                "warmstart: cached plan's mesh %s != "
                                "fleet mesh %s — re-searching",
                                warm[1], cur)
                            warm = None
                    if warm is not None:
                        self._plan_source = warm[2]
                        return Strategy(warm[0])
                    _calibrate()
                    orig_names = {n.name for n in g.topo_order()}
                    _, choice, us = joint_graph_optimize(
                        g, self.mesh, self.config, cost_model)
                    strategy = us.to_strategy(choice)
                    self._strategy = strategy.overrides
                    store_plan(self, meta={"mode": "multihost",
                                           "evals": us.evals},
                               replay_names=orig_names)
                    return strategy

                with telemetry.span("compile.search", mode="multihost"):
                    self._strategy = run_search_on_host0(_search)
                if self._plan_source == "none":
                    # host 0 knows whether the plan was searched or served
                    # warm; the other hosts only know it arrived over the
                    # broadcast — label it that way rather than guessing
                    from .distributed import is_coordinator

                    self._plan_source = ("search" if is_coordinator()
                                         else "broadcast")
                self._assign_strategy()
                self._search_result = None  # plan arrived as a broadcast
            elif self.config.search_mesh_shapes:
                # also search the mesh factorization itself (the MachineView
                # grid-shape half of Unity, search/mesh_search.py): divisor
                # degrees — a 2×4 hybrid on 8 chips — are reached by
                # re-factorizing the data/model split, then the joint search
                # runs per candidate shape. Calibration transfers: the
                # measurements are per-op, mesh-independent.
                from .machine import AXIS_SEQ, MeshShape
                from .search.mesh_search import search_mesh_shapes

                # a PIPE_BLOCKS stack makes the pipe axis searchable too:
                # the dp-vs-pp decision is taken ACROSS factorizations
                # (each candidate's costing matches its execution)
                search_axes = (AXIS_DATA, AXIS_MODEL)
                if any(n.op_type == OT.OP_PIPE_BLOCKS
                       for n in g.topo_order()):
                    search_axes = search_axes + (AXIS_PIPE,)
                ms = self.config.mesh_shape()
                fixed = {a: s for a, s in zip(ms.axis_names, ms.axis_sizes)
                         if s > 1 and a not in search_axes}
                if fixed:
                    # factorizing around a pinned dcn/seq axis is not
                    # modeled — refuse loudly rather than silently collapse
                    # the configured axes to 1
                    raise ValueError(
                        f"--search-mesh-shapes factorizes the chip count "
                        f"over {search_axes} on a single slice; drop the "
                        f"flag or the extra mesh axes {sorted(fixed)}")
                machine_factory = None
                if self.config.machine_model_file:
                    # candidate machines must keep the file's topology/
                    # congestion fidelity, not fall back to the analytic
                    # defaults
                    from .search.machine_model import machine_model_from_file

                    machine_factory = lambda mesh: machine_model_from_file(  # noqa: E731
                        self.config.machine_model_file, mesh)
                _calibrate()
                orig_names = {n.name for n in g.topo_order()}
                with telemetry.span("compile.search", mode="mesh_shapes"):
                    shape, g, choice, us, _ = search_mesh_shapes(
                        g, n_devices, self.config, axes=search_axes,
                        chip=machine.chip,
                        num_hosts=self.config.num_nodes,
                        calibrated=cost_model,
                        machine_factory=machine_factory)
                sizes = {a: 1 for a in ms.axis_names}
                sizes.update(shape)
                self.mesh = self._build_mesh(MeshShape(
                    tuple(sizes[a] for a in ms.axis_names), ms.axis_names))
                self.graph = g
                self._strategy = us.to_strategy(choice).overrides
                self._search_result = (us, choice)
                self._plan_source = "search"
                used_substitutions = True
                from .warmstart import store_plan

                store_plan(self, meta={"mode": "mesh_shapes",
                                       "evals": us.evals},
                           replay_names=orig_names)
            else:
                _calibrate()
                orig_names = {n.name for n in g.topo_order()}
                with telemetry.span("compile.search", mode="joint"):
                    g, choice, us = joint_graph_optimize(
                        g, self.mesh, self.config, cost_model)
                self.graph = g
                self._strategy = us.to_strategy(choice).overrides
                self._search_result = (us, choice)
                self._plan_source = "search"
                used_substitutions = True
                from .warmstart import store_plan

                store_plan(self, meta={"mode": "joint", "evals": us.evals},
                           replay_names=orig_names)
        else:
            if self._plan_source == "none":
                self._plan_source = "default"  # data-parallel fallback
            self._assign_strategy()
        hint = getattr(self, "_plan_source_hint", None)
        if hint is not None:
            # elastic replan: the recompile's outcome is relabeled so
            # every consumer (plan record, compile event, report,
            # ffcheck context) sees plan_source "replan"; the underlying
            # origin (search/cache/broadcast/...) is kept for the
            # decision record
            self._plan_origin = self._plan_source
            self._plan_source = hint
            self._plan_source_hint = None
        if self._plan_fingerprint is not None:
            # manifest-ready plan record: every checkpoint this model
            # writes carries the applied plan + its structural
            # fingerprint, so --auto-resume restores the plan from the
            # manifest (warmstart._checkpoint_plan) instead of paying a
            # from-scratch search after the weights already loaded
            from .parallel.strategies import Strategy

            self._plan_record = {
                "structural_fingerprint": self._plan_fingerprint,
                "plan_source": self._plan_source,
                "strategy": Strategy(self._strategy or {}).to_json(),
                "mesh_axes": {k: int(v)
                              for k, v in self.mesh.shape.items()},
            }
        if self.config.export_strategy_file:
            # persist the plan in effect (searched or imported) for replay
            # (--export-strategy, model.cc:3599-3608); only the coordinator
            # writes — in a multi-host run every process reaches this point
            # and all hosts would race on the same shared-filesystem path
            from .distributed import is_coordinator

            if is_coordinator():
                from .parallel.strategies import Strategy

                Strategy(self._strategy or {}).save(
                    self.config.export_strategy_file)
        if self.config.export_strategy_computation_graph_file:
            from .pcg.graph import export_dot

            export_dot(g, self.config.export_strategy_computation_graph_file)

        # --- logits node = last layer's op (rewrites may have replaced it:
        # the mapped output's producer is then the unique sink)
        if used_substitutions:
            marked = [n for n in g.topo_order()
                      if getattr(n, "_is_logits", False)]
            sinks = g.sinks()
            if marked:
                logits_node = marked[0]
            elif len(sinks) == 1:
                logits_node = sinks[0]
            else:
                raise RuntimeError(
                    "cannot identify logits node after substitution rewrite")
        else:
            logits_node = tensor_to_out[
                self.layers[-1].outputs[0].tensor_guid][0]

        # --- label sharding matches logits batch sharding (model.cc:3086-3124)
        label_spec = logits_node.outputs[0].partition_spec()
        batch_axes = label_spec[0] if len(label_spec) > 0 else None
        self.label_spec = PartitionSpec(batch_axes)

        # --- weight-update sharding: the update-dimension half of the
        # search, decided AFTER every branch materialized its placements
        # (the decision prices the live graph's assignments). The chosen
        # mode is what the executor places/pins and what the explain
        # report / drift monitor price.
        from .search.unity import choose_update_sharding

        if search_cost_model is None and self._warmstart is not None:
            # no local search ran (warm-start plan hit / checkpoint /
            # import / dp fallback): price the decision with the SAME
            # persisted calibration a cold --calibrate run consumed —
            # a roofline-only cost model could flip the auto decision
            # between a cold run and a warm restart of the identical job
            # (parity with the replayed strategy report, explain.py)
            from .search.cost_model import CostModel
            from .search.machine_model import machine_model_for_mesh

            search_cost_model = CostModel(
                machine_model_for_mesh(
                    self.mesh, num_hosts=self.config.num_nodes),
                opt_slots=self.optimizer.num_slots)
            self._warmstart.calibration_db.load_into(search_cost_model)
        self._update_sharding = choose_update_sharding(
            g, self.mesh, self.config, cost_model=search_cost_model,
            opt_slots=self.optimizer.num_slots)
        if jax.process_count() > 1:
            # the auto verdict prices with process-divergent cost models
            # (calibration + the warm-start DB live on process 0 only) and
            # its thresholds can land on opposite sides across hosts —
            # adopt the coordinator's decision everywhere so every process
            # pins the same update layout into the one jitted step
            from .distributed import broadcast_json, is_coordinator

            self._update_sharding = broadcast_json(
                self._update_sharding if is_coordinator() else None)
            if search_cost_model is not None:
                # keep the local cost model pricing the ADOPTED mode (the
                # strategy report / drift monitor must describe what runs)
                search_cost_model.update_sharding = (
                    self._update_sharding["enabled"])
                search_cost_model.param_gather = (
                    self._update_sharding.get("stage", 0) == 3)
                search_cost_model.overlap_update = (
                    self._update_sharding["enabled"]
                    and bool(self.config.overlap_collectives))

        self.executor = Executor(
            g, self.mesh, self.config, self.loss_type, self.metrics,
            self.optimizer, logits_node, self.label_spec,
            update_sharding=self._update_sharding,
        )
        # adopt the REALIZED record (the executor resolves the decision
        # into per-weight specs and may widen shards/axes beyond the dp
        # default, e.g. over `seq`): manifests, the strategy report, and
        # the decision event below must describe what runs
        self._update_sharding = self.executor.update_sharding
        telemetry.event(
            "weight_update_decision",
            enabled=self._update_sharding["enabled"],
            stage=self._update_sharding.get("stage", 0),
            shards=self._update_sharding["shards"],
            reason=self._update_sharding.get("reason", ""))
        # --- ffcheck compile gate (analysis/): static verification of the
        # materialized plan — sharding dataflow, memory liveness,
        # collective uniformity, donation/aliasing — on EVERY plan source
        # (all six adoption paths funnel through this point), BEFORE
        # init_variables touches device memory, so a predicted OOM or an
        # invalid sharding fails fast with a structured report instead of
        # a device error. Errors raise unless --no-verify-plan.
        from .analysis import verify_plan

        verify_plan(self, cost_model=search_cost_model)
        # --- SPMD fingerprint barrier (analysis/spmd.py, --spmd-barrier):
        # cross-host uniformity check of the step-executable ingredients
        # BEFORE the first step — a diverged process raises a structured
        # SPMDDivergenceError here instead of deadlocking a collective or
        # silently training a different program. The verdict rides into
        # the compile metrics record and strategy_report.json so
        # run_doctor --check can gate on it.
        self._spmd_barrier = None
        if self.config.spmd_barrier:
            from .analysis import spmd

            with telemetry.span("compile.spmd_barrier"):
                self._spmd_barrier = spmd.fingerprint_barrier(self)
            telemetry.event("spmd_barrier", **self._spmd_barrier)
        self._rng = jax.random.key(self.config.seed)
        self._params, self._state = self.executor.init_variables(self._rng)
        # optimizer slots inherit the (possibly update-sharded) param
        # placement via zeros_like; place_update_sharded is the explicit
        # guarantee (momentum-off scalar slots pass through untouched)
        # fresh-init placement of just-built zeros at compile — not a
        # plan transition, nothing pre-existing to verify a mapping for
        self._opt_slots = self.executor.place_update_sharded(  # fflint: ok unverified_transition
            self.executor.replicate(self.optimizer.init(self._params)))
        self._state = self.executor.replicate(self._state) if self._state else self._state
        self._step = self.executor.replicate(jnp.zeros((), jnp.int32))
        self._counters = self.executor.replicate(self.metrics.zero_counters())
        # --- ffpulse goodput anchor: cost-model forward FLOPs summed over
        # the compiled graph (x3 for fwd+bwd, the standard training
        # estimate) against the machine model's aggregate chip peak — the
        # two MFU factors record_step divides by measured step time. Best
        # effort: an op without a flops estimate just undercounts.
        self._goodput_anchor = None
        try:
            from .search.cost_model import _NON_COMPUTE
            from .search.machine_model import detect_chip

            fwd = 0.0
            for node in self.graph.topo_order():
                if (node.op_type in _NON_COMPUTE or not node.outputs
                        or not node.inputs):
                    continue
                try:
                    shapes_in = [pt.shape.logical_shape
                                 for pt in node.inputs]
                    shapes_out = [pt.shape.logical_shape
                                  for pt in node.outputs]
                    fwd += node.op_def.flops(node.params, shapes_in,
                                             shapes_out)
                except Exception:
                    continue
            if fwd > 0:
                num_chips = int(self.mesh.devices.size)
                self._goodput_anchor = {
                    "flops_per_step": 3.0 * fwd,
                    "peak_flops": detect_chip().peak_flops * num_chips,
                    "num_chips": num_chips,
                }
                telemetry.event("goodput_anchor", **self._goodput_anchor)
        except Exception:
            pass
        self._compiled = True

    def _assign_strategy(self):
        """Assign mesh axes to every op output / weight.

        Default = data parallel: batch dim (0) of every activation sharded
        over the `data` axis, weights replicated — the reference's
        data-parallel fallback (graph.cc:1939-1964). A searched or imported
        strategy overrides per-node specs via self._strategy."""
        from .machine import batch_axes_for
        from .parallel.ops import derive_parallel_assignment

        batch_axes = batch_axes_for(dict(self.mesh.shape))
        batch_deg = 1
        for ax in batch_axes:
            batch_deg *= self.mesh.shape.get(ax, 1)
        if self._strategy:
            # a broadcast/imported plan can carry names from a REWRITTEN
            # graph (e.g. the fused Experts node from fuse_moe_trio) that
            # don't exist in this graph; silently dropping them would fall
            # back to data parallel for those ops with no sign anything was
            # lost — make the mismatch visible
            present = {n.name for n in self.graph.topo_order()}
            dropped = sorted(set(self._strategy) - present)
            if dropped:
                import warnings

                warnings.warn(
                    "strategy contains placements for nodes not in this "
                    f"graph (dropped, falling back to data parallel): "
                    f"{dropped}", stacklevel=2)
        for node in self.graph.topo_order():
            ov = (self._strategy or {}).get(node.name, {})
            if node.is_parallel_op and node.inputs:
                # explicit parallel op: output placement derived from the
                # input's placement + the op's dim/degree params (unless the
                # strategy pins it explicitly below)
                if 0 not in ov.get("outputs", {}):
                    node.outputs[0].assign_axes(
                        derive_parallel_assignment(
                            node.op_type, node.params,
                            node.inputs[0].axis_assignment, self.mesh,
                        )
                    )
            else:
                for pt in node.outputs:
                    dims = pt.shape.dims
                    assignment = [()] * len(dims)
                    if (
                        batch_deg > 1
                        and len(dims) > 0
                        and dims[0].size % batch_deg == 0
                        and not _is_expert_buffer(node)
                    ):
                        # multi-host meshes compose (dcn, data) on the batch
                        assignment[0] = batch_axes
                    pt.assign_axes(tuple(assignment))
            if (node.op_type == OT.OP_INC_MULTIHEAD_ATTENTION
                    and batch_deg > 1):
                # default KV-cache placement: the slot dim rides the data
                # axes with the batch it serves — a replicated cache would
                # multiply per-chip HBM by the data degree. A searched/
                # imported plan (e.g. head-parallel attention also sharding
                # the cache feature dim over `model`) overrides below.
                # (The PAGED op takes no such default on purpose: its
                # pool's leading dim is physical blocks shared across
                # slots by prefix reuse, so it stays whole on the batch
                # axes — only a head-parallel plan shards its feature dim.)
                for ws in node.weight_specs:
                    if not ws.trainable and ws.shape[0] % batch_deg == 0:
                        node.weight_axes.setdefault(
                            ws.name,
                            PartitionSpec(
                                batch_axes[0] if len(batch_axes) == 1
                                else tuple(batch_axes),
                                *([None] * (len(ws.shape) - 1))))
            if (node.op_type == OT.OP_PIPE_BLOCKS
                    and self.mesh.shape.get(AXIS_PIPE, 1) > 1):
                # default pipe-axis sharding of the stacked block weights:
                # each stage stores only its layers (+ optimizer slots),
                # and the shard_map schedule consumes exactly this layout —
                # no per-step weight collectives
                for ws in node.weight_specs:
                    node.weight_axes.setdefault(
                        ws.name,
                        PartitionSpec(AXIS_PIPE, *([None] * (len(ws.shape) - 1))),
                    )
            for i, spec_axes in ov.get("outputs", {}).items():
                node.outputs[i].assign_axes(spec_axes)
            node.weight_axes.update(ov.get("weights", {}))

    # ================================================== training API

    def _input_partition_spec(self, name: str):
        """PartitionSpec of the graph input named `name`, or None when no
        OP_INPUT source carries that name (callers place replicated). The
        ONE resolution point for input placement — the fit loop, the
        dataloader, and the pipelined engine all go through here."""
        for node in self.graph.sources():
            if node.op_type == OT.OP_INPUT and node.name == name:
                return node.outputs[0].partition_spec()
        return None

    def _make_batch(self, x_arrays: dict, labels):
        specs = {}
        for name in x_arrays:
            spec = self._input_partition_spec(name)
            if spec is not None:
                specs[name] = spec
        xs = self.executor.shard_batch(x_arrays, specs)
        y = jax.device_put(
            labels, jax.sharding.NamedSharding(self.mesh, self.label_spec)
        )
        return xs, y

    def enable_checkpointing(self, directory: str, every_n_steps: int = 0,
                             every_t_seconds: float = 0.0, keep: int = 3):
        """Attach the resilience subsystem (resilience/): async snapshots
        every N steps / T seconds during fit, SIGTERM-drains to a final
        snapshot, and `auto_resume`-able committed checkpoints. The
        programmatic twin of --checkpoint-dir/--checkpoint-every."""
        from .resilience import CheckpointPolicy, ResilienceManager

        self._resilience = ResilienceManager(
            self, directory,
            CheckpointPolicy(every_n_steps=every_n_steps,
                             every_t_seconds=every_t_seconds),
            keep=keep)
        return self._resilience

    def enable_telemetry(self, directory: str):
        """Attach the observability subsystem (telemetry/): Chrome-trace
        spans + JSONL run metrics under `directory`. The session becomes
        the process-wide sink only WHILE this model is inside compile/fit
        (so search/resilience/dataloader hooks land in the same files
        without other models leaking events in between). The programmatic
        twin of --telemetry-dir."""
        from . import telemetry
        from .telemetry import log as fflog

        if self._telemetry is None:
            self._telemetry = telemetry.TelemetrySession(directory)
        else:
            import os

            if os.path.abspath(directory) != self._telemetry.directory:
                # e.g. --telemetry-dir A at compile + Telemetry("B")
                # callback: the first session wins; say so instead of
                # letting the user tail an empty directory
                fflog.warning(
                    "enable_telemetry(%r) ignored: this model's telemetry "
                    "session already writes to %s",
                    directory, self._telemetry.directory)
        return self._telemetry

    def get_telemetry(self):
        """The model's TelemetrySession, or None when telemetry is off."""
        return self._telemetry

    def enable_diagnostics(self, directory: str = "",
                           drift_threshold: Optional[float] = None,
                           abort_on: Optional[Sequence[str]] = None,
                           recalibrate: bool = False, rules=None):
        """Attach the diagnostics subsystem (diagnostics/): strategy
        explain report at compile, online cost-model drift monitoring and
        run-health anomaly rules during fit, artifacts next to the
        telemetry session's (strategy_report.json/md, alerts.jsonl). The
        programmatic twin of --diagnostics; `directory` enables telemetry
        there first when no session exists yet."""
        from .diagnostics import DiagnosticsManager

        if directory:
            self.enable_telemetry(directory)
        elif self._telemetry is None and self.config.telemetry_dir:
            self.enable_telemetry(self.config.telemetry_dir)
        if self._telemetry is None:
            raise ValueError(
                "diagnostics requires telemetry: pass a directory, set "
                "--telemetry-dir, or call enable_telemetry() first")
        if self._diagnostics is None:
            self._diagnostics = DiagnosticsManager(
                self, self._telemetry,
                drift_threshold=(self.config.drift_threshold
                                 if drift_threshold is None
                                 else drift_threshold),
                abort_on=tuple(self.config.health_abort_on
                               if abort_on is None else abort_on),
                recalibrate=recalibrate, rules=rules)
        elif (drift_threshold is not None or abort_on is not None
                or recalibrate or rules is not None):
            # e.g. --diagnostics attached a manager at compile and a keras
            # Diagnostics(abort_on=...) callback asks for different
            # settings later: apply what can be applied live (abort set,
            # drift threshold) rather than silently dropping an explicit
            # abort request; rule objects are already running, so a new
            # rule set can't be swapped in — say so
            from .telemetry import log as fflog

            diag = self._diagnostics
            if abort_on is not None:
                diag.health.set_abort_on(tuple(abort_on))
            if drift_threshold is not None:
                diag.drift_threshold = float(drift_threshold)
                if diag.drift is not None:
                    diag.drift.threshold = float(drift_threshold)
            if recalibrate:
                from .diagnostics.drift import make_recalibration_state

                diag._recalibrate = True
                if diag.drift is not None \
                        and diag.drift.recompile_state is None:
                    diag.drift.recompile_state = \
                        make_recalibration_state(self)
            if rules is not None:
                fflog.warning(
                    "enable_diagnostics: custom rules ignored — this "
                    "model's diagnostics manager already runs its rule "
                    "set (pass rules on the FIRST enable_diagnostics "
                    "call)")
        return self._diagnostics

    def get_diagnostics(self):
        """The model's DiagnosticsManager, or None when diagnostics is
        off."""
        return self._diagnostics

    def _maybe_enable_diagnostics(self):
        """Config-driven lazy attach (mirrors the telemetry lazy attach);
        --diagnostics without --telemetry-dir warns once instead of
        silently doing nothing."""
        from .telemetry import log as fflog

        if self._diagnostics is not None or not self.config.diagnostics:
            return self._diagnostics
        if self._telemetry is None and not self.config.telemetry_dir:
            if not getattr(self, "_diag_warned", False):
                self._diag_warned = True
                fflog.warning(
                    "--diagnostics ignored: no --telemetry-dir (the "
                    "report/alert artifacts need a telemetry directory)")
            return None
        return self.enable_diagnostics()

    def _ensure_step_profiler(self):
        """The model's ffscope StepProfiler (scope/profile.py), created
        on first use from config (--profile-every; trace dirs live
        under <telemetry-dir>/ffscope when a telemetry dir exists)."""
        prof = getattr(self, "_scope_prof", None)
        if prof is None:
            import os

            from .scope.profile import StepProfiler

            root = (os.path.join(self.config.telemetry_dir, "ffscope")
                    if self.config.telemetry_dir else None)
            prof = self._scope_prof = StepProfiler(
                every=self.config.profile_every, trace_root=root)
        return prof

    def profile_step(self):
        """Arm a one-shot op-grain profile capture: the next fit step
        runs under `jax.profiler` tracing and its attributed per-op
        device time lands in strategy_report.json's `profile` section
        (the programmatic twin of --profile-every K)."""
        self._ensure_step_profiler().arm()

    def enable_elastic(self, **kwargs):
        """Attach the elastic re-planning controller (elastic/) to this
        model — the programmatic twin of --elastic. kwargs pass through
        to ElasticController (cooldown_steps, horizon_steps, dry_run,
        visible_devices_fn for tests). Reuses/attaches diagnostics when
        configured so the drift trigger stream is live."""
        from .elastic import ElasticController

        diag = self._maybe_enable_diagnostics()
        self._elastic = ElasticController(self, diag, **kwargs)
        return self._elastic

    def _maybe_enable_elastic(self, diag):
        """Config-driven lazy attach (--elastic), mirroring the
        diagnostics lazy attach; an existing controller (enable_elastic)
        is reused, picking up diagnostics if it attached later."""
        if self._elastic is not None:
            if diag is not None and self._elastic.diag is None:
                self._elastic.attach_diagnostics(diag)
            return self._elastic
        if not self.config.elastic:
            return None
        from .elastic import ElasticController

        self._elastic = ElasticController(self, diag)
        return self._elastic

    def _py_step(self) -> int:
        """The device step counter as a host int — THE checkpoint step
        numbering convention (fit's policy decisions, explicit saves, and
        the keras ModelCheckpoint all go through here)."""
        return int(np.asarray(jax.device_get(self._step)))

    def _nonfinite_localization(self, loss_val) -> dict:
        """The sanitizer's (op, phase, step) attribution for a
        non-finite loss, as extra keys for the health-rule step record
        (NaNLossRule folds them into its alert). Empty when the loss is
        finite, the sanitizer is off, or nothing was localized. The one
        effects_barrier drains the probe callbacks of the step that
        produced the NaN — paid only on the already-dead path."""
        import math as _math

        if (loss_val is None or _math.isfinite(loss_val)
                or not self.config.sanitize_numerics):
            return {}
        from . import sanitize

        jax.effects_barrier()
        info = sanitize.get_monitor().first_nonfinite()
        if info is None:
            return {}
        return {"nonfinite_op": info["op"],
                "nonfinite_phase": info["phase"],
                "nonfinite_step": info["step"]}

    def set_fault_hook(self, hook):
        """Install a per-step failure-injection hook (resilience/fault.py):
        called with the global step after each optimizer step + checkpoint
        decision; raising simulates mid-fit death. Test-only."""
        self._fault_hook = hook

    def _epoch_order(self, num_samples: int, epoch: int,
                     shuffle: bool) -> np.ndarray:
        """Sample order for one epoch. Shuffles are keyed on (config.seed,
        absolute epoch) — NOT the global numpy RNG — so a preempted run
        that resumes mid-epoch replays the exact order the uninterrupted
        run saw, making resume bit-exact. The absolute index includes
        `_epoch_base` (epochs completed by previous fit() calls), so
        repeated fit(epochs=1) calls — the keras per-epoch loop — get a
        fresh order every epoch instead of re-training one fixed order."""
        if not shuffle:
            return np.arange(num_samples)
        rs = np.random.RandomState(
            (self.config.seed * 1_000_003
             + self._epoch_base + epoch) % (2 ** 32))
        return rs.permutation(num_samples)

    def fit(self, x: Union[np.ndarray, Sequence[np.ndarray], dict], y: np.ndarray,
            epochs: int = -1, batch_size: int = -1, shuffle: bool = True,
            verbose: bool = True, pipeline_steps: Optional[int] = None):
        """Training loop (parity: flexflow_cffi.py:2058-2100), made
        preemption-safe: policy-gated async checkpoints between steps, a
        SIGTERM drain-and-final-snapshot path, and --auto-resume restart
        from the newest committed checkpoint's (epoch, batch) cursor.

        With `pipeline_steps > 1` (or --pipeline-steps) the loop routes
        through the pipelined execution engine (engine/): chunks of N
        steps run as one donated lax.scan dispatch over batches a
        background thread prefetched onto the mesh, with checkpoints/
        preemption at chunk boundaries — bit-identical losses/params to
        the default eager loop (docs/performance.md).

        With telemetry on (--telemetry-dir / enable_telemetry) every step
        emits a trace span and a JSONL record splitting wall time into
        data-wait vs device time plus the blocking slice of any checkpoint
        save (reconstructed per step from the chunk window in pipelined
        mode); `verbose=False` drops the epoch progress lines to debug
        level (they also honor FF_LOG_LEVEL and emit on host 0 only)."""
        assert self._compiled, "call compile() before fit()"
        from . import telemetry
        from .telemetry import log as fflog

        if self._telemetry is None and self.config.telemetry_dir:
            self.enable_telemetry(self.config.telemetry_dir)
        tel = self._telemetry
        if tel is not None:
            # active only for the duration of THIS model's fit (the
            # matching deactivate is in the loop's finally below) —
            # another model training afterwards in the same process must
            # not leak events into this model's artifacts
            telemetry.activate(tel)
            # idempotent: covers sessions attached after compile (keras
            # Telemetry callback, manual enable_telemetry)
            tel.write_manifest(self)
            # ffpulse: MFU/tokens-per-sec anchors from the compile-time
            # cost model, and continuous export when configured
            anchor = getattr(self, "_goodput_anchor", None)
            if anchor is not None:
                tel.set_goodput(anchor["flops_per_step"],
                                anchor["peak_flops"])
            if self.config.metrics_interval or self.config.metrics_port:
                tel.start_exporter(
                    interval_s=self.config.metrics_interval,
                    port=self.config.metrics_port)
        if self.config.sanitize_numerics:
            # a fresh fit gets a fresh provenance window: stale
            # non-finite reports from an earlier (diverged) fit in the
            # same process must not win the min-step localization of
            # THIS run's first NaN
            from . import sanitize

            jax.effects_barrier()
            sanitize.get_monitor().reset()
        diag = self._maybe_enable_diagnostics()
        if diag is not None and diag.report is None:
            # diagnostics attached after compile (keras Diagnostics
            # callback, manual enable): write the explain report and arm
            # the drift monitor now
            diag.on_compile()
        elastic = self._maybe_enable_elastic(diag)
        # ffscope (scope/): flight-recorder sizing, sampled op-grain
        # profiling, hang watchdog. The recorder itself is always on —
        # config only resizes/disables the ring.
        from .scope import flightrec
        flightrec.configure(capacity=self.config.flight_events or None,
                            enabled=self.config.flight_events > 0)
        scope_prof = getattr(self, "_scope_prof", None)
        if scope_prof is None and self.config.profile_every > 0:
            scope_prof = self._ensure_step_profiler()
        watchdog = None
        if self.config.watchdog_timeout > 0:
            from .scope.watchdog import HangWatchdog

            try:
                host_idx = jax.process_index()
            except Exception:
                host_idx = 0
            wd_dir = (tel.directory if tel is not None
                      else self.config.telemetry_dir
                      or self.config.checkpoint_dir or None)

            def _wd_alert(info, _diag=diag):
                if _diag is not None:
                    _diag._alerts.record(
                        "alert", rule="hang_watchdog", level="error",
                        step=info.get("last_step"),
                        stalled_s=info.get("stalled_s"),
                        deadline_s=info.get("deadline_s"),
                        lagging_host=info.get("lagging_host"),
                        message="hang watchdog fired: no step-boundary "
                                "progress (flight.json dumped)")

            watchdog = HangWatchdog(
                timeout_s=self.config.watchdog_timeout,
                multiplier=self.config.watchdog_multiplier,
                directory=wd_dir, host_index=host_idx,
                abort=self.config.watchdog_abort,
                on_fire=_wd_alert).start()
        epoch_log = fflog.info if verbose else fflog.debug
        if self.config.profiling and not getattr(self, "_profiled", False):
            # --profiling: per-op kernel table, printed once per compile
            # (the reference prints per-kernel times every launch under
            # m->profiling, linear_kernels.cu:95-117); the rows also land
            # in the report's `profile` section (source: standalone) so
            # the doctor renders one measured-vs-predicted table for both
            # this and the ffscope xplane source
            from .profiling import (print_operator_profile,
                                    profile_section_from_rows)

            rows = print_operator_profile(self.graph)
            self._profiled = True
            if diag is not None and rows:
                diag.on_profile(profile_section_from_rows(rows))
        if epochs < 0:
            epochs = self.config.epochs
        if batch_size < 0:
            batch_size = self.config.batch_size
        x_dict = self._as_input_dict(x)
        num_samples = y.shape[0]
        num_batches = num_samples // batch_size
        if pipeline_steps is None:
            pipeline_steps = self.config.pipeline_steps
        pipeline_steps = max(1, int(pipeline_steps))
        engine = None
        step_fn = None
        health_every = max(1, int(self.config.health_sample_every))
        health_win = [0.0, 0.0, 0.0, 0]  # step/data-wait/save sums, count
        if pipeline_steps > 1:
            from .engine import PipelinedEngine

            engine = PipelinedEngine(self, pipeline_steps)
        else:
            step_fn = (self.executor._train_step
                       or self.executor.build_train_step())

        resil = self._resilience
        if resil is None and self.config.checkpoint_dir:
            from .resilience import ResilienceManager

            resil = self._resilience = ResilienceManager.from_config(self)
        start_epoch = 0
        if (resil is not None and self.config.auto_resume
                and not self._auto_resumed):
            # at most once per model object: a second fit() (keras drives
            # one fit(epochs=1) per epoch) must NOT rewind live training
            # state back to the on-disk checkpoint
            self._auto_resumed = True
            # peek the manifest BEFORE restoring: a stale checkpoint
            # (older than this model's live progress) must be rejected
            # without first rewinding params/opt state to it
            peek = resil.peek_latest()
            if peek is not None:
                path, extras = peek
                cur = extras.get("cursor") or {}
                # cursor epochs are ABSOLUTE (epochs completed since
                # compile); this fit call's within-loop index is relative
                # to the epochs this model object already ran
                abs_epoch = int(cur.get("epoch", 0))
                if abs_epoch < self._epoch_base:
                    import warnings

                    warnings.warn(
                        f"auto-resume: checkpoint {path} is older than "
                        f"this model's live progress (epoch {abs_epoch} < "
                        f"{self._epoch_base}) — ignored", stacklevel=2)
                else:
                    with telemetry.span("resume.restore", path=path):
                        resil.restore_path(path)
                    start_epoch = abs_epoch - self._epoch_base
                    # the batch offset sticks to its ABSOLUTE epoch: when
                    # fit is driven one epoch at a time (keras), the epoch
                    # containing it may only be reached by a later call
                    self._resume_cursor = (
                        abs_epoch, int(cur.get("batch", 0)))
                    telemetry.instant("resume", path=path, epoch=abs_epoch)
                    telemetry.event(
                        "resume", path=path, epoch=abs_epoch,
                        batch=int(cur.get("batch", 0)))
        py_step = self._py_step()
        if elastic is not None and elastic.maybe_replan(py_step):
            # fit-entry capacity check: a preempted/restored fleet
            # re-plans BEFORE the first step so the whole epoch runs on
            # the new mesh (the pipelined engine re-reads the model's
            # executor/mesh per chunk; the eager step_fn is rebuilt here)
            if engine is None:
                step_fn = (self.executor._train_step
                           or self.executor.build_train_step())
        # derived token rate: labels shaped (N, seq, ...) carry seq tokens
        # per example (trailing size-1 dims collapse; plain (N, 1) labels
        # degenerate to 1 token = 1 example)
        tokens_per_example = int(np.prod(y.shape[1:])) if y.ndim > 1 else 1
        # ffscope attribution joins trace scopes back to these names;
        # the report's op set (when diagnostics wrote one) is the
        # contract — every report op gets a measured column
        prof_names = None
        if scope_prof is not None:
            if diag is not None and diag.report is not None:
                prof_names = [o["name"] for o in diag.report["ops"]]
            else:
                prof_names = [n.name for n in self.graph.topo_order()]

        import contextlib

        from .diagnostics.health import HealthAbort
        from .resilience.fault import SimulatedPreemption
        from .resilience.policy import PreemptionHandler

        if diag is not None and resil is not None:
            # staleness clock starts at fit start; every commit re-feeds it
            diag.note_checkpoint_commit(time.time())
        preempt = PreemptionHandler() if resil is not None else None
        preempted = False
        with contextlib.ExitStack() as stack:
            if preempt is not None:
                stack.enter_context(preempt)
            if self.config.xprof_dir:
                # opt-in device-level timeline: the whole fit runs under
                # jax.profiler.trace, so XProf/TensorBoard shows the XLA
                # step right where the host-side trace shows its dispatch
                stack.enter_context(
                    jax.profiler.trace(self.config.xprof_dir))
            try:
                for epoch in range(start_epoch, epochs):
                    abs_e = self._epoch_base + epoch
                    order = self._epoch_order(num_samples, epoch, shuffle)
                    t0 = time.time()
                    b0 = 0
                    if (self._resume_cursor is not None
                            and abs_e >= self._resume_cursor[0]):
                        if abs_e == self._resume_cursor[0]:
                            b0 = self._resume_cursor[1]
                            if b0 >= num_batches and b0 > 0:
                                import warnings

                                warnings.warn(
                                    f"resume cursor batch {b0} does not "
                                    f"fit {num_batches} batches (batch "
                                    f"size changed?) — restarting the "
                                    f"epoch", stacklevel=2)
                                b0 = 0
                        self._resume_cursor = None
                    if engine is not None:
                        # pipelined engine: fused chunk dispatches with
                        # prefetch; raises HealthAbort/SimulatedPreemption
                        # into the same handlers as the eager loop below
                        py_step, preempted = engine.run_epoch(
                            x_dict=x_dict, y=y, order=order, b0=b0,
                            num_batches=num_batches,
                            batch_size=batch_size, abs_e=abs_e,
                            py_step=py_step, tel=tel, diag=diag,
                            resil=resil, preempt=preempt,
                            fault_hook=self._fault_hook,
                            tokens_per_example=tokens_per_example)
                        if preempted:
                            fflog.warning(
                                "preempted at step %d (chunk boundary): "
                                "final checkpoint committed, stopping "
                                "fit", py_step)
                            flightrec.dump("sigterm")
                            return
                        b0_eager = num_batches  # epoch fully covered
                    else:
                        b0_eager = b0
                    for b in range(b0_eager, num_batches):
                        t_it0 = time.perf_counter() if tel is not None else 0.0
                        with telemetry.span("step", step=py_step + 1):
                            with telemetry.span("data_wait"):
                                idx = order[b * batch_size : (b + 1) * batch_size]
                                xb = {k: v[idx] for k, v in x_dict.items()}
                                yb = y[idx]
                                batch = self._make_batch(xb, yb)
                            data_wait = (time.perf_counter() - t_it0
                                         if tel is not None else 0.0)
                            self._rng, sub = jax.random.split(self._rng)
                            capturing = (
                                scope_prof is not None
                                and scope_prof.should_capture(py_step + 1)
                                and scope_prof.begin(py_step + 1))
                            (
                                self._params,
                                self._state,
                                self._opt_slots,
                                self._step,
                                self._counters,
                                lval,
                            ) = step_fn(
                                self._params, self._state, self._opt_slots,
                                self._step, self._counters, sub, batch,
                            )
                            py_step += 1
                            if capturing:
                                # drain before stop_trace so the step's
                                # device work lands inside the capture
                                jax.block_until_ready(self._params)
                                section = scope_prof.end(
                                    py_step, prof_names)
                                if section is not None and diag is not None:
                                    diag.on_profile(section)
                            flightrec.note_step(py_step)
                            if watchdog is not None:
                                watchdog.beat(py_step)
                            # the cursor names the NEXT batch to run on
                            # resume; epochs are ABSOLUTE (since compile)
                            if b + 1 >= num_batches:
                                cursor = {"epoch": abs_e + 1, "batch": 0}
                            else:
                                cursor = {"epoch": abs_e, "batch": b + 1}
                            t_save0 = (time.perf_counter()
                                       if tel is not None else 0.0)
                            if resil is not None:
                                if preempt.preempted:
                                    # preemption notice: drain the in-flight
                                    # async save, then one final synchronous
                                    # snapshot — the only blocking save
                                    telemetry.instant("preempted",
                                                      step=py_step)
                                    resil.finalize(py_step, cursor,
                                                   final_save=True)
                                    preempted = True
                                else:
                                    resil.maybe_save(py_step, cursor)
                        if tel is not None:
                            save_lat = time.perf_counter() - t_save0
                            loss_val = None
                            sampled = (diag is not None
                                       and py_step % health_every == 0)
                            if sampled:
                                # the scalar loss fetch is a device sync
                                # and happens ONLY with diagnostics on —
                                # BEFORE step_time is read, so the drained
                                # device work lands inside this step's own
                                # timed window (fetching after it would
                                # leave every window measuring dispatch
                                # only, and the drift monitor would
                                # compare the predicted makespan against
                                # host overhead)
                                loss_val = float(np.asarray(
                                    jax.device_get(lval)))
                            step_time = time.perf_counter() - t_it0
                            tel.record_step(
                                py_step, abs_e, step_time, data_wait,
                                save_lat, batch_size, tokens_per_example)
                            if diag is not None:
                                if resil is not None:
                                    diag.note_checkpoint_commit(
                                        resil.last_commit_walltime())
                                # --health-sample-every K: with the drain
                                # thinned to every K-th step, the steps in
                                # between measure dispatch only while the
                                # sampled step absorbs the drained device
                                # work — feeding rules that raw bimodal
                                # stream would seed spike/stall/drift
                                # baselines on dispatch-only windows. So
                                # rules see ONE record per window with
                                # the K-step AVERAGE (the pipelined
                                # chunk/N attribution applied to the
                                # eager loop); K=1 reduces to the
                                # per-step record exactly.
                                hw = health_win
                                hw[0] += step_time
                                hw[1] += data_wait
                                hw[2] += save_lat
                                hw[3] += 1
                                if sampled:
                                    k = hw[3]
                                    w_t, w_dw, w_sv = (hw[0] / k,
                                                       hw[1] / k,
                                                       hw[2] / k)
                                    health_win = [0.0, 0.0, 0.0, 0]
                                    rec = {
                                        "step": py_step, "epoch": abs_e,
                                        "t": time.time(),
                                        "step_time_s": w_t,
                                        "data_wait_s": w_dw,
                                        "save_latency_s": w_sv,
                                        "device_time_s": max(
                                            0.0, w_t - w_dw - w_sv),
                                        "loss": loss_val,
                                    }
                                    rec.update(
                                        self._nonfinite_localization(
                                            loss_val))
                                    diag.on_step(rec)
                        if self._fault_hook is not None:
                            self._fault_hook(py_step)
                        if (elastic is not None and not preempted
                                and elastic.maybe_replan(py_step)):
                            # the re-plan migrated executor + state in
                            # place at this step boundary — the captured
                            # step callable belongs to the old executor
                            step_fn = (self.executor._train_step
                                       or self.executor.build_train_step())
                        if preempted:
                            telemetry.event("preempted", step=py_step)
                            fflog.warning(
                                "preempted at step %d: final checkpoint "
                                "committed, stopping fit", py_step)
                            flightrec.dump("sigterm")
                            return
                    jax.block_until_ready(self._params)
                    dt = time.time() - t0
                    thru = (num_batches - b0) * batch_size / dt
                    epoch_log(
                        f"epoch {epoch}: {self.get_perf_metrics()} "
                        f"ELAPSED TIME = {dt:.4f}s, "
                        f"THROUGHPUT = {thru:.2f} samples/s"
                    )
                    telemetry.event("epoch", epoch=abs_e, duration_s=dt,
                                    examples_per_sec=thru)
            except SimulatedPreemption:
                # injected death: die exactly as a real kill would — no
                # drain, no final save, and the in-flight async write must
                # not commit after the "kill"; only checkpoints already
                # committed at this instant survive for auto_resume
                flightrec.dump("SimulatedPreemption")
                if resil is not None:
                    resil.checkpointer.abort()
                raise
            except HealthAbort:
                # a health rule listed in --health-abort-on fired: stop
                # training with artifacts intact. Drain the in-flight
                # async save but do NOT final-snapshot — a NaN'd model is
                # not worth committing over the last good checkpoint
                flightrec.dump("HealthAbort")
                if resil is not None:
                    resil.finalize()
                fflog.error(
                    "fit aborted by diagnostics at step %d (see %s)",
                    py_step, diag.alerts_path if diag else "alerts.jsonl")
                raise
            except BaseException as e:
                # anything else that kills the fit (executor exception,
                # SPMDDivergenceError, the watchdog's interrupt) leaves
                # the flight record behind — the post-mortem artifact a
                # crash otherwise never writes
                flightrec.dump(type(e).__name__)
                raise
            else:
                # the next fit() call continues the absolute epoch count
                # (fresh shuffle orders for keras's repeated fit(epochs=1))
                self._epoch_base += epochs
                if resil is not None:
                    resil.finalize()
            finally:
                if watchdog is not None:
                    watchdog.stop()
                if scope_prof is not None:
                    scope_prof.abandon()  # a capture left open by a raise
                if tel is not None:
                    # artifacts must exist however fit ends (normal return,
                    # preemption, injected death): summary then trace dump.
                    # The in-flight checkpoint writer was already drained
                    # on every exit path, so no late events are lost by
                    # deactivating here.
                    if diag is not None:
                        diag.on_fit_end()
                    tel.write_summary()
                    tel.write_metrics_snapshot(reason="fit_end")
                    tel.flush()
                    telemetry.deactivate(tel)

    def eval(self, x, y, batch_size: int = -1):
        assert self._compiled
        if batch_size < 0:
            batch_size = self.config.batch_size
        x_dict = self._as_input_dict(x)
        num_batches = y.shape[0] // batch_size
        eval_fn = self.executor._eval_step or self.executor.build_eval_step()
        counters = self.metrics.zero_counters()
        for b in range(num_batches):
            sl = slice(b * batch_size, (b + 1) * batch_size)
            xb = {k: v[sl] for k, v in x_dict.items()}
            batch = self._make_batch(xb, y[sl])
            counters = eval_fn(self._params, self._state, counters, batch)
        return PerfMetrics(counters, self.metrics)

    def _as_input_dict(self, x) -> dict:
        input_names = [t.name for t in self._input_tensors
                       if not hasattr(t, "constant_value")]
        if isinstance(x, dict):
            return x
        if isinstance(x, np.ndarray) or hasattr(x, "shape"):
            x = [x]
        if len(x) != len(input_names):
            raise ValueError(
                f"model has {len(input_names)} inputs {input_names}, got {len(x)} arrays"
            )
        return dict(zip(input_names, x))

    # ------------------------------------------------ granular API (parity
    # with C++ train loops: transformer.cc:183-197)

    def start_batch(self, x, y):
        self._current_batch = self._make_batch(self._as_input_dict(x), y)

    def forward(self, seq_length: int = -1):
        assert self._current_batch is not None, "call start_batch first"
        fwd = self.executor._forward_fn or self.executor.build_forward()
        xs, _ = self._current_batch
        self._cached_logits, new_state = fwd(
            self._params, self._state,
            xs, self.config.computation_mode == CompMode.COMP_MODE_TRAINING,
        )
        self._state = new_state
        return self._cached_logits

    def zero_gradients(self):
        self._grads = None

    def backward(self, seq_length: int = -1):
        assert self._current_batch is not None
        xs, labels = self._current_batch
        inner = self.executor.make_loss_fn(self._state, xs, labels, self._rng)

        def loss_fn(p):
            l, (logits, _, ce_sum) = inner(p)
            return l, (logits, ce_sum)

        (lval, (logits, ce_sum)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(self._params)
        self._grads = grads
        self._cached_logits = logits
        self._counters = self.metrics.compute(
            self._counters, logits, self.executor.expand_labels(labels),
            from_logits=not self.executor.last_op_is_softmax,
            scce_sum=ce_sum,
        )
        return lval

    def update(self):
        assert self._grads is not None, "call backward first"
        self._params, self._opt_slots = self.optimizer.update(
            self._grads, self._params, self._opt_slots, self._step
        )
        self._step = self._step + 1
        self._grads = None

    def init_operators(self):
        """No-op on TPU: per-device OpMeta initialization (reference
        init_operators → per-op INIT tasks) has no analog — jit handles it."""

    def reset_metrics(self):
        self._counters = self.metrics.zero_counters()

    def set_learning_rate(self, lr: float):
        """Change the optimizer's learning rate mid-training (the keras
        LearningRateScheduler hook; reference optimizer.cc set_learning_rate
        swaps the kernel constant the same way). The rate is a trace-time
        constant of the fused train step, so the cached executable is
        dropped — the next batch retraces with the new rate (one compile per
        distinct rate, amortized over the epoch that scheduled it)."""
        assert self._compiled, "call compile() before set_learning_rate()"
        if float(lr) == float(self.optimizer.lr):
            return
        self.optimizer.set_learning_rate(lr)
        self.executor._train_step = None
        # chunked executables bake in the same rate constant
        self.executor._chunk_steps.clear()

    def get_perf_metrics(self) -> PerfMetrics:
        return PerfMetrics(jax.device_get(self._counters), self.metrics)

    # ------------------------------------------------ weights I/O
    # (reference ParallelTensorBase::set_tensor/get_tensor)

    def _resolve_weight_owner(self, layer_name: str) -> str:
        """Tied-weight nodes (shared_op) store no parameters of their own —
        reads/writes go to the source layer's set (O(1) via the alias map
        built at compile)."""
        return getattr(self, "_weight_alias", {}).get(layer_name, layer_name)

    def get_weight(self, layer_name: str, weight_name: str) -> np.ndarray:
        layer_name = self._resolve_weight_owner(layer_name)
        return np.asarray(self._params[layer_name][weight_name])

    def set_weight(self, layer_name: str, weight_name: str, value: np.ndarray):
        layer_name = self._resolve_weight_owner(layer_name)
        old = self._params[layer_name][weight_name]
        self._params[layer_name][weight_name] = jax.device_put(
            jnp.asarray(value, old.dtype), old.sharding
        )

    def create_data_loader(self, batch_tensor: Tensor, full_array: np.ndarray):
        from .dataloader import SingleDataLoader

        return SingleDataLoader(self, batch_tensor, full_array)

    def _build_mesh(self, shape):
        """Build this model's mesh, honouring `mesh_device_offset`: a
        nonzero offset carves the mesh out of jax.devices()[offset:], so
        two compiles with disjoint (offset, shape) windows place on
        disjoint chips — the disaggregated serving sub-meshes."""
        off = int(getattr(self.config, "mesh_device_offset", 0) or 0)
        devices = jax.devices()
        if off:
            if off >= len(devices):
                raise ValueError(
                    f"mesh_device_offset {off} >= device count "
                    f"{len(devices)}")
            devices = devices[off:]
        return build_mesh(shape, devices=devices)

    # ------------------------------------------------ serving (serving/)

    def serve(self, **kwargs):
        """Build a ServingEngine on this trained model: compiles the
        single-token *decode* graph from the same PCG (causal attention
        becomes incremental attention over sharded KV-cache state, priced
        and placed by the same Unity search + warm-start plan cache the
        trainer uses), adopts this model's weights by name, and runs
        Orca-style continuous batching over a fixed slot set, with a
        paged block-pool KV cache (COW prefix sharing, chunked prefill
        interleaved with decode) by default (docs/serving.md). kwargs
        override ServingSpec fields — slots, max_seq_len, prefill_chunk,
        kv_layout ("paged"|"contiguous"), kv_block_size, kv_num_blocks,
        prefix_sharing, config_overrides, strategy, ...

        `disaggregate=True` (or --serve-disaggregate) instead builds a
        DisaggregatedServingEngine: prefill and decode compile as TWO
        independent Unity plans on disjoint sub-meshes (serve_prefill_chips
        sizes the prefill side), with each request's KV handed off
        through a verified, priced fftrans transfer program
        (docs/serving.md "Disaggregated serving").

        `speculate=True, draft_model=<small compiled LM>` builds a
        SpeculativeServingEngine: the drafter proposes K tokens per
        round and the target verifies them in one batched call, gated
        by an acceptance-calibrated payoff inequality — token streams
        stay bit-identical to plain decode (serve_draft_chips places
        the drafter on a disjoint sub-mesh; docs/serving.md
        "Speculative decoding")."""
        assert self._compiled, "call compile() before serve()"
        # fail fast on chip-budget flags that exceed THIS process's
        # visible devices, naming the flag — a bad sub-mesh carve
        # otherwise surfaces as an opaque mesh-factorization error
        n_dev = len(jax.devices())
        for flag, field in (("--serve-prefill-chips", "serve_prefill_chips"),
                            ("--serve-draft-chips", "serve_draft_chips")):
            chips = int(getattr(self.config, field, 0) or 0)
            if chips >= n_dev:
                raise ValueError(
                    f"{flag}={chips} but only {n_dev} device(s) are "
                    f"visible; both sides of the split need at least "
                    f"one chip")
        disaggregate = kwargs.pop(
            "disaggregate",
            bool(getattr(self.config, "serve_disaggregate", False)))
        speculate = kwargs.pop("speculate", False)
        if disaggregate and speculate:
            raise ValueError(
                "serve(): disaggregate=True and speculate=True are "
                "mutually exclusive for now (speculative decoding of "
                "the disaggregated decode pool is a ROADMAP item)")
        if disaggregate:
            kwargs.pop("draft_model", None)
            from .serving import DisaggregatedServingEngine

            return DisaggregatedServingEngine(self, **kwargs)
        if speculate:
            from .serving import SpeculativeServingEngine

            return SpeculativeServingEngine(self, **kwargs)
        kwargs.pop("draft_model", None)
        from .serving import ServingEngine

        return ServingEngine(self, **kwargs)

    # ------------------------------------------------ checkpoint / export

    def save_checkpoint(self, path: str):
        """Synchronous atomic checkpoint of the full training state into
        the checkpoint root `path` (resilience/checkpointer.py). Capability
        beyond the reference, which has none (SURVEY §5)."""
        from .resilience import ResilienceManager

        # keep=0: explicit save_checkpoint calls never prune — a user
        # saving milestones must not silently lose all but the newest few
        mgr = ResilienceManager(self, path, keep=0)
        mgr.save(self._py_step(), blocking=True)
        return mgr.checkpointer.last_committed

    def load_checkpoint(self, path: str):
        """Restore the newest committed checkpoint under root `path` (or a
        single checkpoint dir), resharding onto this model's mesh/Strategy
        — the saving run's mesh may differ (resilience/reshard.py)."""
        from .resilience import latest_checkpoint, restore_model

        target = path
        import os

        if not os.path.exists(os.path.join(path, "manifest.json")):
            found = latest_checkpoint(path)
            if found is None:
                raise FileNotFoundError(
                    f"no committed checkpoint under {path!r} (expected a "
                    f"step_*/manifest.json layout; checkpoints written by "
                    f"the pre-resilience orbax format are not readable — "
                    f"re-save with save_checkpoint)")
            target = found
        restore_model(self, target)
        return self

    def export_dot(self, path: str = "") -> str:
        """PCG DOT export (reference --compgraph flag / print_dot)."""
        from .pcg.graph import export_dot

        assert self.graph is not None, "call compile() first"
        return export_dot(self.graph, path or None)

    def print_layers(self, id: int = -1):
        for i, l in enumerate(self.layers):
            if id < 0 or i == id:
                print(f"[{i}] {l.name} {l.op_type.name} "
                      f"in={[t.dims for t in l.inputs]} "
                      f"out={[t.dims for t in l.outputs]}")


from .pcg.graph import is_expert_buffer as _is_expert_buffer  # noqa: E402
