"""Model zoo: builder functions reproducing every reference example family
(SURVEY §2.6: AlexNet, ResNet-50, resnext-50, InceptionV3, Transformer/BERT,
DLRM, XDL, candle_uno, MLP_Unify, MNIST MLP, MoE) on the FFModel API, plus
the TPU-native flagship Transformer LM used by bench.py.
"""

from .alexnet import build_alexnet
from .candle_uno import build_candle_uno
from .dlrm import DLRMConfig, build_dlrm
from .inception import build_inception_v3
from .mlp import build_mlp_unify, build_mnist_mlp
from .moe import MoeConfig, build_moe
from .resnet import build_resnet50, build_resnext50
from .transformer import (
    TRANSFORMER_LM_ZOO,
    TransformerConfig,
    TransformerLMConfig,
    build_transformer,
    build_transformer_lm,
    build_transformer_lm_decode,
    build_transformer_lm_pipelined,
    transformer_lm_param_count,
    transformer_lm_state_bytes_per_chip,
)
from .xdl import build_xdl
