"""AlexNet (CIFAR-10 head): examples/cpp/AlexNet/alexnet.cc:70-84."""

from __future__ import annotations

from ..fftype import ActiMode


def build_alexnet(ff, batch_size: int | None = None, num_classes: int = 10,
                  image_hw: int = 229):
    bs = batch_size or ff.config.batch_size
    input = ff.create_tensor((bs, 3, image_hw, image_hw), name="input")
    t = ff.conv2d(input, 64, 11, 11, 4, 4, 2, 2, ActiMode.AC_MODE_RELU,
                  name="conv1")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="pool1")
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU,
                  name="conv2")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="pool2")
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU,
                  name="conv3")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU,
                  name="conv4")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU,
                  name="conv5")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="pool3")
    t = ff.flat(t, name="flat")
    t = ff.dense(t, 4096, ActiMode.AC_MODE_RELU, name="fc6")
    t = ff.dense(t, 4096, ActiMode.AC_MODE_RELU, name="fc7")
    t = ff.dense(t, num_classes, name="fc8")
    t = ff.softmax(t, name="softmax")
    return input, t
