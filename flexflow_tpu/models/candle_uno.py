"""CANDLE Uno: examples/cpp/candle_uno/candle_uno.cc — seven input feature
streams; cell/drug streams pass through a shared-architecture feature tower
(bias-free dense 4192 ×3: build_feature_model, candle_uno.cc:49-57), then
concat + final dense tower and a scalar head; MSE loss."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Sequence

from ..fftype import ActiMode


@dataclass
class CandleUnoConfig:
    dense_layers: Sequence[int] = (4192, 4192, 4192)
    dense_feature_layers: Sequence[int] = (4192, 4192, 4192)
    # input name → feature type (candle_uno.cc:40-47)
    input_features: Dict[str, str] = field(default_factory=lambda: {
        "dose1": "dose",
        "dose2": "dose",
        "cell.rnaseq": "cell.rnaseq",
        "drug1.descriptors": "drug.descriptors",
        "drug1.fingerprints": "drug.fingerprints",
        "drug2.descriptors": "drug.descriptors",
        "drug2.fingerprints": "drug.fingerprints",
    })
    feature_shapes: Dict[str, int] = field(default_factory=lambda: {
        "dose": 1,
        "cell.rnaseq": 942,
        "drug.descriptors": 5270,
        "drug.fingerprints": 2048,
    })


def _feature_tower(ff, input, layers, prefix):
    t = input
    for i, h in enumerate(layers):
        t = ff.dense(t, h, ActiMode.AC_MODE_RELU, use_bias=False,
                     name=f"{prefix}fc{i}")
    return t


def build_candle_uno(ff, config: CandleUnoConfig | None = None,
                     batch_size: int | None = None):
    c = config or CandleUnoConfig()
    bs = batch_size or ff.config.batch_size
    # cell/drug feature types get an encoder tower (candle_uno.cc:90-103)
    towered = {ft for ft in c.feature_shapes
               if ft.split(".")[0] in ("cell", "drug")}
    all_inputs, encoded = [], []
    for name, ftype in c.input_features.items():
        shape = c.feature_shapes[ftype]
        inp = ff.create_tensor((bs, shape), name=name.replace(".", "_"))
        all_inputs.append(inp)
        if ftype in towered:
            encoded.append(
                _feature_tower(ff, inp, c.dense_feature_layers,
                               f"{name.replace('.', '_')}_")
            )
        else:
            encoded.append(inp)
    out = ff.concat(encoded, -1, name="concat")
    for i, h in enumerate(c.dense_layers):
        out = ff.dense(out, h, ActiMode.AC_MODE_RELU, use_bias=False,
                       name=f"top_fc{i}")
    out = ff.dense(out, 1, use_bias=False, name="head")
    return tuple(all_inputs), out
