"""DLRM: examples/cpp/DLRM/dlrm.cc — sparse embedding towers (AGGR_MODE_SUM,
fp16 tables cast to fp32: create_emb, dlrm.cc:67-82), bottom MLP over dense
features, concat interaction (interact_features, dlrm.cc:84-101), top MLP
with sigmoid head. Defaults follow DLRMConfig (dlrm.cc:26-42)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

from ..fftype import ActiMode, AggrMode, DataType
from ..initializer import UniformInitializer


@dataclass
class DLRMConfig:
    sparse_feature_size: int = 64
    embedding_size: Sequence[int] = (1000000,) * 4
    embedding_bag_size: int = 1
    mlp_bot: Sequence[int] = (4, 64, 64)
    mlp_top: Sequence[int] = (64, 64, 2)
    sigmoid_bot: int = -1
    sigmoid_top: int = -1
    arch_interaction_op: str = "cat"


def _create_mlp(ff, input, dims, sigmoid_layer, prefix):
    """dlrm.cc:44-65 (xdl.cc:38-59 identical): dims[0] is the input width;
    emit len-1 bias-free dense layers, relu except sigmoid at
    `sigmoid_layer`."""
    t = input
    for i in range(len(dims) - 1):
        act = (ActiMode.AC_MODE_SIGMOID if i == sigmoid_layer
               else ActiMode.AC_MODE_RELU)
        t = ff.dense(t, dims[i + 1], act, use_bias=False,
                     name=f"{prefix}fc{i}")
    return t


def _create_emb(ff, input, vocab, out_dim, idx):
    rng = (1.0 / vocab) ** 0.5
    t = ff.embedding(input, vocab, out_dim, AggrMode.AGGR_MODE_SUM,
                     dtype=DataType.DT_HALF,
                     kernel_initializer=UniformInitializer(0, -rng, rng),
                     name=f"emb{idx}")
    return ff.cast(t, DataType.DT_FLOAT, name=f"emb{idx}_cast")


def build_dlrm(ff, config: DLRMConfig | None = None,
               batch_size: int | None = None):
    """Returns ((sparse_inputs..., dense_input), output). Loss:
    LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE (dlrm.cc compile)."""
    c = config or DLRMConfig()
    bs = batch_size or ff.config.batch_size
    sparse_inputs = [
        ff.create_tensor((bs, c.embedding_bag_size), DataType.DT_INT64,
                         name=f"sparse{i}")
        for i in range(len(c.embedding_size))
    ]
    dense_input = ff.create_tensor((bs, c.mlp_bot[0]), name="dense_input")
    ly = [
        _create_emb(ff, s, c.embedding_size[i], c.sparse_feature_size, i)
        for i, s in enumerate(sparse_inputs)
    ]
    x = _create_mlp(ff, dense_input, c.mlp_bot, c.sigmoid_bot, "bot_")
    if c.arch_interaction_op != "cat":
        raise NotImplementedError(
            f"interaction {c.arch_interaction_op!r} (reference supports cat "
            "only, dlrm.cc:84-101)"
        )
    z = ff.concat([x] + ly, -1, name="interact")
    # the reference hardcodes mlp_top.size()-2 at the call site and leaves
    # sigmoid_top dead (dlrm.cc:165); honor the field when explicitly set
    sig_top = c.sigmoid_top if c.sigmoid_top >= 0 else len(c.mlp_top) - 2
    out = _create_mlp(ff, z, c.mlp_top, sig_top, "top_")
    return tuple(sparse_inputs) + (dense_input,), out
