"""InceptionV3: examples/cpp/InceptionV3/inception.cc:27-176 (block structure
and channel counts copied faithfully; NCHW, concat on channel axis 1)."""

from __future__ import annotations

from ..fftype import ActiMode, PoolType

RELU = ActiMode.AC_MODE_RELU


def _inception_a(ff, x, pool_features, p):
    t1 = ff.conv2d(x, 64, 1, 1, 1, 1, 0, 0, RELU, name=f"{p}b1")
    t2 = ff.conv2d(x, 48, 1, 1, 1, 1, 0, 0, RELU, name=f"{p}b2a")
    t2 = ff.conv2d(t2, 64, 5, 5, 1, 1, 2, 2, RELU, name=f"{p}b2b")
    t3 = ff.conv2d(x, 64, 1, 1, 1, 1, 0, 0, RELU, name=f"{p}b3a")
    t3 = ff.conv2d(t3, 96, 3, 3, 1, 1, 1, 1, RELU, name=f"{p}b3b")
    t3 = ff.conv2d(t3, 96, 3, 3, 1, 1, 1, 1, RELU, name=f"{p}b3c")
    t4 = ff.pool2d(x, 3, 3, 1, 1, 1, 1, PoolType.POOL_AVG, name=f"{p}b4p")
    t4 = ff.conv2d(t4, pool_features, 1, 1, 1, 1, 0, 0, RELU, name=f"{p}b4c")
    return ff.concat([t1, t2, t3, t4], 1, name=f"{p}cat")


def _inception_b(ff, x, p):
    t1 = ff.conv2d(x, 384, 3, 3, 2, 2, 0, 0, name=f"{p}b1")
    t2 = ff.conv2d(x, 64, 1, 1, 1, 1, 0, 0, name=f"{p}b2a")
    t2 = ff.conv2d(t2, 96, 3, 3, 1, 1, 1, 1, name=f"{p}b2b")
    t2 = ff.conv2d(t2, 96, 3, 3, 2, 2, 0, 0, name=f"{p}b2c")
    t3 = ff.pool2d(x, 3, 3, 2, 2, 0, 0, name=f"{p}b3p")
    return ff.concat([t1, t2, t3], 1, name=f"{p}cat")


def _inception_c(ff, x, channels, p):
    t1 = ff.conv2d(x, 192, 1, 1, 1, 1, 0, 0, name=f"{p}b1")
    t2 = ff.conv2d(x, channels, 1, 1, 1, 1, 0, 0, name=f"{p}b2a")
    t2 = ff.conv2d(t2, channels, 1, 7, 1, 1, 0, 3, name=f"{p}b2b")
    t2 = ff.conv2d(t2, 192, 7, 1, 1, 1, 3, 0, name=f"{p}b2c")
    t3 = ff.conv2d(x, channels, 1, 1, 1, 1, 0, 0, name=f"{p}b3a")
    t3 = ff.conv2d(t3, channels, 7, 1, 1, 1, 3, 0, name=f"{p}b3b")
    t3 = ff.conv2d(t3, channels, 1, 7, 1, 1, 0, 3, name=f"{p}b3c")
    t3 = ff.conv2d(t3, channels, 7, 1, 1, 1, 3, 0, name=f"{p}b3d")
    t3 = ff.conv2d(t3, 192, 1, 7, 1, 1, 0, 3, name=f"{p}b3e")
    t4 = ff.pool2d(x, 3, 3, 1, 1, 1, 1, PoolType.POOL_AVG, name=f"{p}b4p")
    t4 = ff.conv2d(t4, 192, 1, 1, 1, 1, 0, 0, name=f"{p}b4c")
    return ff.concat([t1, t2, t3, t4], 1, name=f"{p}cat")


def _inception_d(ff, x, p):
    t1 = ff.conv2d(x, 192, 1, 1, 1, 1, 0, 0, name=f"{p}b1a")
    t1 = ff.conv2d(t1, 320, 3, 3, 2, 2, 0, 0, name=f"{p}b1b")
    t2 = ff.conv2d(x, 192, 1, 1, 1, 1, 0, 0, name=f"{p}b2a")
    t2 = ff.conv2d(t2, 192, 1, 7, 1, 1, 0, 3, name=f"{p}b2b")
    t2 = ff.conv2d(t2, 192, 7, 1, 1, 1, 3, 0, name=f"{p}b2c")
    t2 = ff.conv2d(t2, 192, 3, 3, 2, 2, 0, 0, name=f"{p}b2d")
    t3 = ff.pool2d(x, 3, 3, 2, 2, 0, 0, name=f"{p}b3p")
    return ff.concat([t1, t2, t3], 1, name=f"{p}cat")


def _inception_e(ff, x, p):
    t1 = ff.conv2d(x, 320, 1, 1, 1, 1, 0, 0, name=f"{p}b1")
    t2i = ff.conv2d(x, 384, 1, 1, 1, 1, 0, 0, name=f"{p}b2i")
    t2 = ff.conv2d(t2i, 384, 1, 3, 1, 1, 0, 1, name=f"{p}b2a")
    t3 = ff.conv2d(t2i, 384, 3, 1, 1, 1, 1, 0, name=f"{p}b2b")
    t3i = ff.conv2d(x, 448, 1, 1, 1, 1, 0, 0, name=f"{p}b3i")
    t3i = ff.conv2d(t3i, 384, 3, 3, 1, 1, 1, 1, name=f"{p}b3j")
    t4 = ff.conv2d(t3i, 384, 1, 3, 1, 1, 0, 1, name=f"{p}b3a")
    t5 = ff.conv2d(t3i, 384, 3, 1, 1, 1, 1, 0, name=f"{p}b3b")
    t6 = ff.pool2d(x, 3, 3, 1, 1, 1, 1, PoolType.POOL_AVG, name=f"{p}b4p")
    t6 = ff.conv2d(t6, 192, 1, 1, 1, 1, 0, 0, name=f"{p}b4c")
    return ff.concat([t1, t2, t3, t4, t5, t6], 1, name=f"{p}cat")


def build_inception_v3(ff, batch_size: int | None = None,
                       num_classes: int = 10, image_hw: int = 299):
    bs = batch_size or ff.config.batch_size
    input = ff.create_tensor((bs, 3, image_hw, image_hw), name="input")
    t = ff.conv2d(input, 32, 3, 3, 2, 2, 0, 0, RELU, name="stem1")
    t = ff.conv2d(t, 32, 3, 3, 1, 1, 0, 0, RELU, name="stem2")
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, RELU, name="stem3")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="stem_pool1")
    t = ff.conv2d(t, 80, 1, 1, 1, 1, 0, 0, RELU, name="stem4")
    t = ff.conv2d(t, 192, 3, 3, 1, 1, 1, 1, RELU, name="stem5")
    t = ff.pool2d(t, 3, 3, 2, 2, 0, 0, name="stem_pool2")
    t = _inception_a(ff, t, 32, "a1_")
    t = _inception_a(ff, t, 64, "a2_")
    t = _inception_a(ff, t, 64, "a3_")
    t = _inception_b(ff, t, "b1_")
    t = _inception_c(ff, t, 128, "c1_")
    t = _inception_c(ff, t, 160, "c2_")
    t = _inception_c(ff, t, 160, "c3_")
    t = _inception_c(ff, t, 192, "c4_")
    t = _inception_d(ff, t, "d1_")
    t = _inception_e(ff, t, "e1_")
    t = _inception_e(ff, t, "e2_")
    t = ff.pool2d(t, 8, 8, 1, 1, 0, 0, PoolType.POOL_AVG, name="avgpool")
    t = ff.flat(t, name="flat")
    t = ff.dense(t, num_classes, name="fc")
    t = ff.softmax(t, name="softmax")
    return input, t
