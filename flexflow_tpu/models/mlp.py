"""MLP models.

- `build_mnist_mlp`: examples/python/native/mnist_mlp.py:14-26 — dense 512
  relu ×2, dense 10, softmax; the reference's E2E accuracy-gate model.
- `build_mlp_unify`: examples/cpp/MLP_Unify/mlp.cc — two input towers of
  bias-free dense layers whose outputs are summed, then softmax.
"""

from __future__ import annotations

from typing import Sequence

from ..fftype import ActiMode


def build_mnist_mlp(ff, batch_size: int | None = None, in_dim: int = 784,
                    num_classes: int = 10):
    bs = batch_size or ff.config.batch_size
    input = ff.create_tensor((bs, in_dim), name="input")
    t = ff.dense(input, 512, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 512, ActiMode.AC_MODE_RELU, name="fc2")
    t = ff.dense(t, num_classes, name="fc3")
    t = ff.softmax(t, name="softmax")
    return input, t


def build_mlp_unify(ff, batch_size: int | None = None, in_dim: int = 1024,
                    hidden_dims: Sequence[int] = (8192, 8192, 8192, 8192)):
    bs = batch_size or ff.config.batch_size
    x1 = ff.create_tensor((bs, in_dim), name="input1")
    x2 = ff.create_tensor((bs, in_dim), name="input2")
    t1, t2 = x1, x2
    for i, h in enumerate(hidden_dims):
        t1 = ff.dense(t1, h, ActiMode.AC_MODE_RELU, use_bias=False,
                      name=f"t1_fc{i}")
        t2 = ff.dense(t2, h, ActiMode.AC_MODE_RELU, use_bias=False,
                      name=f"t2_fc{i}")
    t = ff.add(t1, t2, name="unify")
    t = ff.softmax(t, name="softmax")
    return (x1, x2), t
