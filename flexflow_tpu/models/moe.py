"""Mixture-of-Experts classifier: examples/cpp/mixture_of_experts/moe.cc —
the MNIST MoE model (moe.cc:137-160: one ff.moe block over flattened input,
then the reference encoder variant create_moe_encoder with attention +
residual layer_norm, moe.cc:100-124)."""

from __future__ import annotations

from dataclasses import dataclass

from ..fftype import ActiMode


@dataclass
class MoeConfig:
    # moe.h defaults
    num_exp: int = 5
    num_select: int = 2
    alpha: float = 2.0
    lambda_bal: float = 0.04
    hidden_size: int = 64
    num_attention_heads: int = 16
    num_encoder_layers: int = 6
    in_dim: int = 784
    num_classes: int = 10


def build_moe(ff, config: MoeConfig | None = None,
              batch_size: int | None = None, fused: bool = False):
    """The flat MNIST MoE (moe.cc:151-160): input → moe → softmax."""
    c = config or MoeConfig()
    bs = batch_size or ff.config.batch_size
    input = ff.create_tensor((bs, c.in_dim), name="input")
    t = ff.moe(input, c.num_exp, c.num_select, c.num_classes, c.alpha,
               c.lambda_bal, fused=fused)
    t = ff.softmax(t, name="softmax")
    return input, t


def build_moe_encoder(ff, config: MoeConfig | None = None,
                      batch_size: int | None = None, seq_length: int = 64,
                      fused: bool = True):
    """create_moe_encoder (moe.cc:100-124): per layer, attention + residual
    layer_norm, then MoE + residual layer_norm. Requires 3D (b, s, d) input;
    the MoE runs per flattened token (reference partitions the sample dim)."""
    c = config or MoeConfig()
    bs = batch_size or ff.config.batch_size
    input = ff.create_tensor((bs, seq_length, c.hidden_size), name="input")
    x = input
    for i in range(c.num_encoder_layers):
        a = ff.multihead_attention(
            x, x, x, c.hidden_size, c.num_attention_heads,
            name=f"enc{i}_attn",
        )
        x = ff.layer_norm(ff.add(a, x, name=f"enc{i}_res1"), [2],
                          name=f"enc{i}_ln1")
        flat = ff.reshape(x, (bs * seq_length, c.hidden_size),
                          name=f"enc{i}_flat")
        m = ff.moe(flat, c.num_exp, c.num_select, c.hidden_size, c.alpha,
                   c.lambda_bal, fused=fused)
        m = ff.reshape(m, (bs, seq_length, c.hidden_size),
                       name=f"enc{i}_unflat")
        x = ff.layer_norm(ff.add(m, x, name=f"enc{i}_res2"), [2],
                          name=f"enc{i}_ln2")
    t = ff.mean(x, [1], name="pool")
    t = ff.dense(t, c.num_classes, name="head")
    t = ff.softmax(t, name="softmax")
    return input, t
