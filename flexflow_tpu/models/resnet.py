"""ResNet-50 and ResNeXt-50.

- `build_resnet50`: examples/cpp/ResNet/resnet.cc:39-112 — BottleneckBlock
  (1x1 → 3x3(stride) → 1x1(4x), projection shortcut on shape change, relu
  after add), stages [3,4,6,3] at widths [64,128,256,512].
- `build_resnext50`: examples/cpp/resnext50/resnext.cc — grouped 3x3
  (cardinality 32) bottlenecks.
"""

from __future__ import annotations

from ..fftype import ActiMode, PoolType


def _bottleneck(ff, input, out_channels, stride, prefix, groups=1,
                group_width=None):
    """resnet.cc:39-60 — faithfully no intermediate activations (the
    reference comments out batch_norm and keeps convs AC_MODE_NONE), single
    relu after the residual add."""
    mid = group_width or out_channels
    t = ff.conv2d(input, mid, 1, 1, 1, 1, 0, 0, name=f"{prefix}c1")
    t = ff.conv2d(t, mid, 3, 3, stride, stride, 1, 1, groups=groups,
                  name=f"{prefix}c2")
    t = ff.conv2d(t, 4 * out_channels, 1, 1, 1, 1, 0, 0, name=f"{prefix}c3")
    if stride > 1 or input.dims[1] != 4 * out_channels:
        input = ff.conv2d(input, 4 * out_channels, 1, 1, stride, stride, 0, 0,
                          name=f"{prefix}proj")
    t = ff.add(input, t, name=f"{prefix}add")
    return ff.relu(t, name=f"{prefix}out")


def _resnet_backbone(ff, input, groups=1, width_per_group=None):
    t = ff.conv2d(input, 64, 7, 7, 2, 2, 3, 3, name="stem_conv")
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1, name="stem_pool")
    stages = ((64, 3), (128, 4), (256, 6), (512, 3))
    for si, (width, blocks) in enumerate(stages):
        for bi in range(blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            # ResNeXt: 3x3 runs at cardinality*width_per_group*2^stage
            gw = groups * width_per_group * (2 ** si) if width_per_group else None
            t = _bottleneck(ff, t, width, stride, f"s{si}b{bi}_",
                            groups=groups, group_width=gw)
    return t


def build_resnet50(ff, batch_size: int | None = None, num_classes: int = 10,
                   image_hw: int = 224):
    bs = batch_size or ff.config.batch_size
    input = ff.create_tensor((bs, 3, image_hw, image_hw), name="input")
    t = _resnet_backbone(ff, input)
    t = ff.pool2d(t, 7, 7, 1, 1, 0, 0, PoolType.POOL_AVG, name="avgpool")
    t = ff.flat(t, name="flat")
    t = ff.dense(t, num_classes, name="fc")
    t = ff.softmax(t, name="softmax")
    return input, t


def build_resnext50(ff, batch_size: int | None = None, num_classes: int = 10,
                    image_hw: int = 224, cardinality: int = 32,
                    width_per_group: int = 4):
    bs = batch_size or ff.config.batch_size
    input = ff.create_tensor((bs, 3, image_hw, image_hw), name="input")
    t = _resnet_backbone(ff, input, groups=cardinality,
                         width_per_group=width_per_group)
    t = ff.pool2d(t, 7, 7, 1, 1, 0, 0, PoolType.POOL_AVG, name="avgpool")
    t = ff.flat(t, name="flat")
    t = ff.dense(t, num_classes, name="fc")
    t = ff.softmax(t, name="softmax")
    return input, t
