"""Transformer models.

`build_transformer` reproduces the reference benchmark model
(examples/cpp/Transformer/transformer.cc:33-45,112-160): a stack of
`create_attention_encoder` blocks — MHA(hidden, heads) followed by
dense(hidden, relu, no bias) → dense(hidden, no bias) — on a
(batch, seq, hidden) float input, head dense(1), MSE loss. Defaults match
TransformerConfig (transformer.cc:79-85): hidden 1024, heads 16, layers 12,
seq 512.

`build_transformer_lm` is the TPU-native flagship: token embedding, pre-LN
causal blocks with residuals (flash-attention Pallas kernel), GELU MLP, and a
vocab head — the model bench.py measures, designed so megatron TP + data
parallel + optional seq-parallel shardings apply cleanly.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..fftype import ActiMode, DataType


@dataclass
class TransformerConfig:
    """Parity with transformer.cc:79-85."""

    hidden_size: int = 1024
    embedding_size: int = 1024
    num_heads: int = 16
    num_layers: int = 12
    sequence_length: int = 512


def create_attention_encoder(ff, input, hidden_dim, num_heads, kdim, vdim,
                             prefix=""):
    """transformer.cc:33-45 (no residuals, no layernorm — faithful)."""
    t = ff.multihead_attention(input, input, input, hidden_dim, num_heads,
                               kdim, vdim, name=f"{prefix}attn")
    t = ff.dense(t, hidden_dim, ActiMode.AC_MODE_RELU, use_bias=False,
                 name=f"{prefix}ffn1")
    return ff.dense(t, hidden_dim, use_bias=False, name=f"{prefix}ffn2")


def build_transformer(ff, config: TransformerConfig | None = None,
                      batch_size: int | None = None):
    """Returns (input_tensor, output_tensor). Loss should be
    LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE (transformer.cc:163)."""
    c = config or TransformerConfig()
    bs = batch_size or ff.config.batch_size
    input = ff.create_tensor((bs, c.sequence_length, c.hidden_size),
                             name="input")
    t = input
    for i in range(c.num_layers):
        t = create_attention_encoder(
            ff, t, c.hidden_size, c.num_heads,
            c.hidden_size // c.num_heads, c.hidden_size // c.num_heads,
            prefix=f"l{i}_",
        )
    t = ff.dense(t, 1, use_bias=False, name="head")
    return input, t


@dataclass
class TransformerLMConfig:
    """Flagship decoder-only LM (TPU-native; exceeds reference capability —
    the reference has no positional handling, residuals, or causal mask in
    its benchmark model)."""

    vocab_size: int = 32000
    hidden_size: int = 1024
    num_heads: int = 16
    num_layers: int = 12
    mlp_ratio: int = 4
    sequence_length: int = 512
    dtype: DataType = DataType.DT_FLOAT
    attention_impl: str = "flash"  # xla | flash | ring


def _lm_trunk(ff, c: TransformerLMConfig, h, attention):
    """The pre-LN block stack + final norm + vocab head, shared between
    the training builder and the causal-decode builder — ONE graph
    definition, two attention lowerings (`attention(x, name)` supplies
    either training MHA or incremental KV-cache attention). Layer names
    are identical on both paths, so trained parameters transfer to the
    decode graph by name (serving/decode_graph.adopt_params)."""
    for i in range(c.num_layers):
        p = f"l{i}_"
        a = ff.layer_norm(h, [2], name=f"{p}ln1")
        a = attention(a, f"{p}attn")
        h = ff.add(h, a, name=f"{p}res1")
        m = ff.layer_norm(h, [2], name=f"{p}ln2")
        m = ff.dense(m, c.mlp_ratio * c.hidden_size, name=f"{p}ffn1")
        m = ff.gelu(m, name=f"{p}gelu")
        m = ff.dense(m, c.hidden_size, name=f"{p}ffn2")
        h = ff.add(h, m, name=f"{p}res2")
    h = ff.layer_norm(h, [2], name="ln_f")
    return ff.dense(h, c.vocab_size, use_bias=False, name="lm_head")


def build_transformer_lm(ff, config: TransformerLMConfig | None = None,
                         batch_size: int | None = None):
    """Returns (tokens_input, logits). Loss:
    LOSS_SPARSE_CATEGORICAL_CROSSENTROPY over shifted labels."""
    c = config or TransformerLMConfig()
    bs = batch_size or ff.config.batch_size
    tokens = ff.create_tensor((bs, c.sequence_length), DataType.DT_INT32,
                              name="tokens")
    h = ff.embedding(tokens, c.vocab_size, c.hidden_size, name="wte")
    pos = ff.create_tensor((bs, c.sequence_length), DataType.DT_INT32,
                           name="positions")
    hp = ff.embedding(pos, c.sequence_length, c.hidden_size, name="wpe")
    h = ff.add(h, hp, name="embed_add")

    def attention(a, name):
        return ff.multihead_attention(
            a, a, a, c.hidden_size, c.num_heads, causal=True,
            impl=c.attention_impl, name=name,
        )

    logits = _lm_trunk(ff, c, h, attention)
    return tokens, logits


def build_transformer_lm_decode(ff, config: TransformerLMConfig | None = None,
                                slots: int | None = None,
                                max_seq_len: int | None = None,
                                impl: str = "auto",
                                kv_layout: str | None = None,
                                kv_block_size: int | None = None,
                                kv_num_blocks: int = 0):
    """The flagship LM's *decode* graph, built directly (the model-zoo
    twin of serving/decode_graph's generic replay): single-token query per
    continuous-batching slot, per-layer KV caches written at the
    position-indexed rows the `positions` input names. Same `_lm_trunk`,
    same layer names — a model trained with `build_transformer_lm` feeds
    this graph its weights unchanged. `kv_layout` mirrors the serving
    engine's (default: the config's --serve-kv-layout): "paged" adds the
    shared `page_table` input and block-pool caches, "contiguous" the
    per-slot region. Returns (tokens, positions, logits); compile with
    CompMode.COMP_MODE_INFERENCE."""
    c = config or TransformerLMConfig()
    n = slots or ff.config.serve_slots
    max_seq = max_seq_len or c.sequence_length
    layout = kv_layout or ff.config.serve_kv_layout
    tokens = ff.create_tensor((n, 1), DataType.DT_INT32, create_grad=False,
                              name="tokens")
    pos = ff.create_tensor((n, 1), DataType.DT_INT32, create_grad=False,
                           name="positions")
    if layout == "paged":
        bs = kv_block_size or ff.config.serve_kv_block_size
        table_width = -(-max_seq // bs)
        # capacity parity + the reserved scratch block — the same default
        # serving/decode_graph.resolve_pool_blocks lands on when the HBM
        # budget doesn't bind
        num_blocks = kv_num_blocks or n * table_width + 1
        page_table = ff.create_tensor(
            (n, table_width), DataType.DT_INT32, create_grad=False,
            name="page_table")

        def attention(a, name):
            return ff.paged_inc_multihead_attention(
                a, pos, page_table, c.hidden_size, c.num_heads, max_seq,
                bs, num_blocks, impl=impl, name=name,
            )
    else:
        def attention(a, name):
            return ff.inc_multihead_attention(
                a, pos, c.hidden_size, c.num_heads, max_seq, impl=impl,
                name=name,
            )

    h = ff.embedding(tokens, c.vocab_size, c.hidden_size, name="wte")
    hp = ff.embedding(pos, c.sequence_length, c.hidden_size, name="wpe")
    h = ff.add(h, hp, name="embed_add")
    logits = _lm_trunk(ff, c, h, attention)
    return tokens, pos, logits


def build_transformer_lm_pipelined(ff, config: TransformerLMConfig | None = None,
                                   batch_size: int | None = None,
                                   num_microbatches: int = 0):
    """The flagship LM with its block stack as ONE PipelineBlocks op: the
    layer dim shards over the `pipe` mesh axis (ppermute fill/drain
    pipeline, parallel/pipeline.py) — pipeline-parallel capability the
    reference's enum-only OP_PIPELINE never implements. Identical numerics
    to a sequential block stack by construction (same op, pipe axis 1)."""
    c = config or TransformerLMConfig()
    bs = batch_size or ff.config.batch_size
    tokens = ff.create_tensor((bs, c.sequence_length), DataType.DT_INT32,
                              name="tokens")
    h = ff.embedding(tokens, c.vocab_size, c.hidden_size, name="wte")
    pos = ff.create_tensor((bs, c.sequence_length), DataType.DT_INT32,
                           name="positions")
    hp = ff.embedding(pos, c.sequence_length, c.hidden_size, name="wpe")
    h = ff.add(h, hp, name="embed_add")
    h = ff.pipeline_blocks(h, c.num_layers, c.num_heads, c.mlp_ratio,
                           num_microbatches=num_microbatches, causal=True,
                           attention_impl=c.attention_impl, name="blocks")
    h = ff.layer_norm(h, [2], name="ln_f")
    logits = ff.dense(h, c.vocab_size, use_bias=False, name="lm_head")
    return tokens, logits


def transformer_lm_param_count(c: TransformerLMConfig) -> int:
    """Trainable parameter count of the flagship LM (embeddings + blocks
    + final norm + head) — the zoo sizing / FSDP-capacity arithmetic."""
    d, L, v = c.hidden_size, c.num_layers, c.vocab_size
    per_layer = (4 * d * d + 4 * d          # attention qkv+o (+ biases)
                 + 2 * c.mlp_ratio * d * d  # mlp up + down
                 + c.mlp_ratio * d + d      # mlp biases
                 + 4 * d)                   # 2× layernorm scale+bias
    return (v * d + c.sequence_length * d   # wte + wpe
            + L * per_layer
            + 2 * d                         # final norm
            + v * d)                        # lm_head


def transformer_lm_state_bytes_per_chip(c: TransformerLMConfig,
                                        opt_slots: int = 2,
                                        update_stage: int = 0,
                                        shards: int = 1) -> float:
    """Resident fp32 training-state bytes per chip — master + grad +
    `opt_slots` optimizer entries per parameter — under a given
    weight-update stage. Stage 2 shards masters/grads/slots 1/shards but
    keeps one gathered compute copy resident per weight; stage 3
    (ZeRO-3/FSDP) shards the weights at rest too, so per-chip model
    state shrinks ~1/shards and the zoo grows past what one chip can
    hold replicated."""
    n = float(transformer_lm_param_count(c)) * 4.0
    state = n * (2 + opt_slots)
    if update_stage >= 3 and shards > 1:
        return state / shards
    if update_stage >= 2 and shards > 1:
        return n + state / shards
    return state


# The model zoo bench.py / the smokes draw from, ordered by scale. The
# `-fsdp` tiers are sized so their REPLICATED training state (masters +
# grads + Adam slots ≈ 16 bytes/param) exceeds a single chip of the
# named HBM class while the 1/shards stage-3 layout fits — the ZeRO-3
# enabler for growing the zoo past one replicated chip (ROADMAP item 5).
TRANSFORMER_LM_ZOO: dict = {
    # CPU-smoke scale: tiny, runs everywhere
    "lm-smoke": TransformerLMConfig(
        vocab_size=512, hidden_size=128, num_heads=4, num_layers=2,
        sequence_length=128, attention_impl="xla"),
    # speculative-decoding drafter for lm-smoke: same vocab + positional
    # extent (a drafter must share the target's tokenizer and reach
    # every position it decodes at — serving/speculative.py), a quarter
    # the width and half the depth
    "lm-smoke-draft": TransformerLMConfig(
        vocab_size=512, hidden_size=32, num_heads=2, num_layers=1,
        sequence_length=128, attention_impl="xla"),
    # the reference benchmark scale (transformer.cc:79-85)
    "lm-base": TransformerLMConfig(
        vocab_size=32000, hidden_size=1024, num_heads=16, num_layers=12,
        sequence_length=512),
    # drafter tier for lm-base: SpecInfer-style ~20x-smaller LM sharing
    # the 32k vocab and 512-token extent
    "lm-base-draft": TransformerLMConfig(
        vocab_size=32000, hidden_size=256, num_heads=4, num_layers=4,
        sequence_length=512),
    # ~1.3B params: replicated Adam state ≈ 21 GB — over one 16 GB chip,
    # under it at 1/4 stage-3 shards
    "lm-xl-fsdp": TransformerLMConfig(
        vocab_size=32000, hidden_size=2048, num_heads=32, num_layers=24,
        sequence_length=1024),
    # ~6.7B params: replicated Adam state ≈ 107 GB — needs stage 3 even
    # on 95 GB-class chips once activations are counted
    "lm-xxl-fsdp": TransformerLMConfig(
        vocab_size=32000, hidden_size=4096, num_heads=32, num_layers=32,
        sequence_length=2048),
}


def transformer_lm_flops_per_token(c: TransformerLMConfig) -> float:
    """Analytic fwd+bwd FLOPs/token for MFU accounting (6N_matmul + attn).
    The wte/wpe lookups are gathers (no matmul FLOPs); only the lm_head's
    v×d projection counts among the embedding-sized params."""
    d, L, s, v = c.hidden_size, c.num_layers, c.sequence_length, c.vocab_size
    params_per_layer = 4 * d * d + 2 * c.mlp_ratio * d * d
    n_matmul_params = L * params_per_layer + v * d  # lm_head only
    flops = 6.0 * n_matmul_params
    flops += L * 12.0 * d * s / 2  # causal attention scores+values fwd+bwd
    return flops
