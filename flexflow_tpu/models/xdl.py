"""XDL: examples/cpp/XDL/xdl.cc — DLRM-style sparse embeddings concatenated
straight into a top MLP (no dense bottom tower); mlp_top (256,256,256,2),
where mlp_top[0] is the concat width and len-1 layers are emitted
(xdl.cc:43, same create_mlp as DLRM)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

from ..fftype import AggrMode, DataType
from ..initializer import UniformInitializer
from .dlrm import _create_mlp


@dataclass
class XDLConfig:
    sparse_feature_size: int = 64
    embedding_size: Sequence[int] = (1000000,) * 4
    embedding_bag_size: int = 1
    mlp_top: Sequence[int] = (256, 256, 256, 2)


def build_xdl(ff, config: XDLConfig | None = None,
              batch_size: int | None = None):
    c = config or XDLConfig()
    bs = batch_size or ff.config.batch_size
    sparse_inputs = [
        ff.create_tensor((bs, c.embedding_bag_size), DataType.DT_INT64,
                         name=f"sparse{i}")
        for i in range(len(c.embedding_size))
    ]
    ly = []
    for i, s in enumerate(sparse_inputs):
        rng = (1.0 / c.embedding_size[i]) ** 0.5
        t = ff.embedding(s, c.embedding_size[i], c.sparse_feature_size,
                         AggrMode.AGGR_MODE_SUM, dtype=DataType.DT_HALF,
                         kernel_initializer=UniformInitializer(0, -rng, rng),
                         name=f"emb{i}")
        ly.append(ff.cast(t, DataType.DT_FLOAT, name=f"emb{i}_cast"))
    z = ff.concat(ly, -1, name="interact")
    t = _create_mlp(ff, z, c.mlp_top, len(c.mlp_top) - 2, "top_")
    return tuple(sparse_inputs), t
