"""ctypes bindings for the native PCG core (native/src/pcg_core.cc).

The reference keeps its graph/search core in C++ (SURVEY §2.1); this module
loads our C++ equivalent, building it with make on first use (g++ is baked
into the image; pybind11 is not, hence ctypes). Every entry point has a
pure-Python fallback so the framework works without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                           "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libpcg_core.so")

_lib = None
_lib_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    try:
        # make's own dependency check rebuilds iff pcg_core.cc is newer
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True)
        lib = ctypes.CDLL(_LIB_PATH)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.ff_topo_order.restype = ctypes.c_int
        lib.ff_topo_order.argtypes = [ctypes.c_int32, ctypes.c_int32,
                                      i32p, i32p, i32p]
        lib.ff_bottlenecks.restype = ctypes.c_int
        lib.ff_bottlenecks.argtypes = lib.ff_topo_order.argtypes
        lib.ff_transitive_reduction.restype = ctypes.c_int
        lib.ff_transitive_reduction.argtypes = lib.ff_topo_order.argtypes
        lib.ff_idominators.restype = ctypes.c_int
        lib.ff_idominators.argtypes = lib.ff_topo_order.argtypes
        lib.ff_eval_makespan.restype = ctypes.c_double
        lib.ff_eval_makespan.argtypes = [
            ctypes.c_int32, ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int32, i32p, i32p]
        lib.ff_eval_makespan_axes.restype = ctypes.c_double
        lib.ff_eval_makespan_axes.argtypes = [
            ctypes.c_int32, ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double), i32p,
            ctypes.c_int32, i32p, i32p]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def _as_i32(a):
    return np.ascontiguousarray(a, dtype=np.int32)


def _ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def topo_order(n: int, src, dst) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    src, dst = _as_i32(src), _as_i32(dst)
    out = np.zeros(n, np.int32)
    rc = lib.ff_topo_order(n, len(src), _ptr(src), _ptr(dst), _ptr(out))
    return out if rc == 0 else None


def bottlenecks(n: int, src, dst) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    src, dst = _as_i32(src), _as_i32(dst)
    mask = np.zeros(n, np.int32)
    rc = lib.ff_bottlenecks(n, len(src), _ptr(src), _ptr(dst), _ptr(mask))
    return mask.astype(bool) if rc >= 0 else None


def transitive_reduction(n: int, src, dst) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    src, dst = _as_i32(src), _as_i32(dst)
    keep = np.zeros(len(src), np.int32)
    rc = lib.ff_transitive_reduction(n, len(src), _ptr(src), _ptr(dst),
                                     _ptr(keep))
    return keep.astype(bool) if rc == 0 else None


def idominators(n: int, src, dst) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    src, dst = _as_i32(src), _as_i32(dst)
    out = np.zeros(n, np.int32)
    rc = lib.ff_idominators(n, len(src), _ptr(src), _ptr(dst), _ptr(out))
    return out if rc == 0 else None


def eval_makespan(compute, comm, src, dst) -> Optional[float]:
    """Critical-path makespan with serialized compute (ff_eval_makespan):
    max(sum(compute), longest path of compute+comm). None if the native lib
    is unavailable; raises ValueError on a cyclic graph (the two cases must
    stay distinguishable so a cyclic candidate is rejected rather than
    silently re-costed by the Python fallback)."""
    lib = _load()
    if lib is None:
        return None
    co = np.ascontiguousarray(compute, np.float64)
    cm = np.ascontiguousarray(comm, np.float64)
    src, dst = _as_i32(src), _as_i32(dst)
    out = lib.ff_eval_makespan(
        len(co), co.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        cm.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(src), _ptr(src), _ptr(dst))
    if out < 0:
        raise ValueError("eval_makespan: graph has a cycle")
    return float(out)


def eval_makespan_axes(compute, comm, axis, src, dst) -> Optional[float]:
    """Resource-aware makespan (ff_eval_makespan_axes): adds per-ICI-axis
    link-occupancy lower bounds — comm tasks on the same mesh axis
    serialize, disjoint axes overlap (the TPU recast of the reference's
    horizontal machine-resource splits). axis[i] is an int id, -1 = none.
    None if the native lib is unavailable; ValueError on a cycle."""
    lib = _load()
    if lib is None:
        return None
    co = np.ascontiguousarray(compute, np.float64)
    cm = np.ascontiguousarray(comm, np.float64)
    ax = _as_i32(axis)
    src, dst = _as_i32(src), _as_i32(dst)
    out = lib.ff_eval_makespan_axes(
        len(co), co.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        cm.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), _ptr(ax),
        len(src), _ptr(src), _ptr(dst))
    if out < 0:
        raise ValueError("eval_makespan_axes: graph has a cycle")
    return float(out)
