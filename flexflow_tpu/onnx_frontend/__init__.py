"""ONNX frontend (reference python/flexflow/onnx/model.py, SURVEY §2.5)."""

from .model import ONNXModel
