"""ONNX graph → FFModel builders.

Reference: python/flexflow/onnx/model.py (`ONNXModel.apply` walking
graph.node with one handle_* per op type). The `onnx` package is not part
of this image's baked dependencies, so the import is lazy: construction
works anywhere, `apply` raises a clear error if onnx is missing.
"""

from __future__ import annotations

from ..fftype import ActiMode, DataType, PoolType


def _attrs(node):
    import onnx

    out = {}
    for a in node.attribute:
        out[a.name] = onnx.helper.get_attribute_value(a)
    return out


class ONNXModel:
    def __init__(self, filename: str):
        self.filename = filename
        self._model = None

    def _load(self):
        if self._model is None:
            try:
                import onnx
            except ImportError as e:  # pragma: no cover
                raise ImportError(
                    "the onnx package is required for ONNXModel; install "
                    "onnx or use the torch/keras frontends"
                ) from e
            self._model = onnx.load(self.filename)
        return self._model

    def apply(self, ffmodel, input_tensors: dict):
        """input_tensors: graph input name → FF Tensor. Returns the graph
        outputs as FF Tensors."""
        model = self._load()
        graph = model.graph
        env = dict(input_tensors)
        # initializers (weights) that feed ops like Gemm are consumed by the
        # corresponding FFModel builders; record their shapes
        inits = {i.name: i for i in graph.initializer}
        for node in graph.node:
            handler = getattr(self, f"_handle_{node.op_type.lower()}", None)
            if handler is None:
                raise NotImplementedError(f"ONNX op {node.op_type}")
            outs = handler(ffmodel, node, env, inits)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            for name, t in zip(node.output, outs):
                env[name] = t
        return [env[o.name] for o in graph.output]

    # ---------------------------------------------------------- handlers

    def _handle_gemm(self, ff, node, env, inits):
        x = env[node.input[0]]
        w = inits[node.input[1]]
        a = _attrs(node)
        # B is (N, K) when transB=1 (torch export), (K, N) otherwise
        out_dim = list(w.dims)[0] if a.get("transB", 0) else list(w.dims)[1]
        use_bias = len(node.input) > 2
        return ff.dense(x, out_dim, use_bias=use_bias, name=node.name or "")

    def _handle_matmul(self, ff, node, env, inits):
        if node.input[1] in inits:
            out_dim = list(inits[node.input[1]].dims)[-1]
            return ff.dense(env[node.input[0]], out_dim, use_bias=False,
                            name=node.name or "")
        return ff.batch_matmul(env[node.input[0]], env[node.input[1]])

    def _handle_conv(self, ff, node, env, inits):
        a = _attrs(node)
        w = inits[node.input[1]]
        oc = list(w.dims)[0]
        kh, kw = a.get("kernel_shape", [1, 1])
        sh, sw = a.get("strides", [1, 1])
        pads = a.get("pads", [0, 0, 0, 0])
        groups = a.get("group", 1)
        return ff.conv2d(env[node.input[0]], oc, kh, kw, sh, sw,
                         pads[0], pads[1], groups=groups,
                         use_bias=len(node.input) > 2, name=node.name or "")

    def _handle_maxpool(self, ff, node, env, inits):
        a = _attrs(node)
        kh, kw = a.get("kernel_shape", [1, 1])
        sh, sw = a.get("strides", [1, 1])
        pads = a.get("pads", [0, 0, 0, 0])
        return ff.pool2d(env[node.input[0]], kh, kw, sh, sw, pads[0],
                         pads[1], name=node.name or "")

    def _handle_averagepool(self, ff, node, env, inits):
        a = _attrs(node)
        kh, kw = a.get("kernel_shape", [1, 1])
        sh, sw = a.get("strides", [1, 1])
        pads = a.get("pads", [0, 0, 0, 0])
        return ff.pool2d(env[node.input[0]], kh, kw, sh, sw, pads[0],
                         pads[1], PoolType.POOL_AVG, name=node.name or "")

    def _handle_relu(self, ff, node, env, inits):
        return ff.relu(env[node.input[0]], name=node.name or "")

    def _handle_sigmoid(self, ff, node, env, inits):
        return ff.sigmoid(env[node.input[0]], name=node.name or "")

    def _handle_tanh(self, ff, node, env, inits):
        return ff.tanh(env[node.input[0]], name=node.name or "")

    def _handle_softmax(self, ff, node, env, inits):
        a = _attrs(node)
        return ff.softmax(env[node.input[0]], a.get("axis", -1),
                          name=node.name or "")

    def _handle_flatten(self, ff, node, env, inits):
        return ff.flat(env[node.input[0]], name=node.name or "")

    def _handle_add(self, ff, node, env, inits):
        return ff.add(env[node.input[0]], env[node.input[1]],
                      name=node.name or "")

    def _handle_sub(self, ff, node, env, inits):
        return ff.subtract(env[node.input[0]], env[node.input[1]],
                           name=node.name or "")

    def _handle_mul(self, ff, node, env, inits):
        return ff.multiply(env[node.input[0]], env[node.input[1]],
                           name=node.name or "")

    def _handle_concat(self, ff, node, env, inits):
        a = _attrs(node)
        return ff.concat([env[i] for i in node.input], a.get("axis", 0),
                         name=node.name or "")

    def _handle_dropout(self, ff, node, env, inits):
        a = _attrs(node)
        return ff.dropout(env[node.input[0]], a.get("ratio", 0.5),
                          name=node.name or "")

    def _handle_identity(self, ff, node, env, inits):
        return env[node.input[0]]

    def _handle_reshape(self, ff, node, env, inits):
        import onnx.numpy_helper as nh

        shape = nh.to_array(inits[node.input[1]]).tolist()
        x = env[node.input[0]]
        if -1 in shape:
            import math

            total = math.prod(x.dims)
            known = -math.prod(shape)
            shape = [total // known if s == -1 else s for s in shape]
        return ff.reshape(x, shape, name=node.name or "")

    def _handle_transpose(self, ff, node, env, inits):
        a = _attrs(node)
        return ff.transpose(env[node.input[0]], a["perm"],
                            name=node.name or "")
