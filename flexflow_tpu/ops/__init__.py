"""Operator library: importing this package populates the op registry."""

from .base import OpContext, OpDef, WeightSpec, get_op_def, register_op, registered_ops
from . import elementwise  # noqa: F401
from . import core  # noqa: F401
from . import shape_ops  # noqa: F401
from . import attention  # noqa: F401
from . import inc_attention  # noqa: F401
from . import moe  # noqa: F401
from . import pipeline_blocks  # noqa: F401

from .core import (
    BatchMatmulParams,
    BatchNormParams,
    Conv2DParams,
    DropoutParams,
    EmbeddingParams,
    LayerNormParams,
    LinearParams,
    Pool2DParams,
    SoftmaxParams,
)
from .attention import MultiHeadAttentionParams
from .inc_attention import (
    IncMultiHeadAttentionParams,
    PagedIncMultiHeadAttentionParams,
)
from .elementwise import ElementBinaryParams, ElementUnaryParams
from .moe import (
    AggregateParams,
    AggregateSpecParams,
    CacheParams,
    ExpertsParams,
    GroupByParams,
)
from .pipeline_blocks import PipelineBlocksParams
from .shape_ops import (
    CastParams,
    ConcatParams,
    GatherParams,
    ReduceParams,
    ReshapeParams,
    ReverseParams,
    SplitParams,
    TopKParams,
    TransposeParams,
)
