"""Multi-head attention.

Reference: src/ops/attention.cc (926 LoC) + attention.cu wrapping
`cudnnMultiHeadAttnForward` — a monolithic vendor kernel with weights packed
into a single tensor. TPU-native design instead expresses attention as
projections (MXU GEMMs) + a scaled-dot-product core with three interchangeable
implementations selected per placement:

  - "xla":    plain einsum softmax(QK^T)V — XLA fuses well for short seqs
  - "flash":  Pallas blockwise-softmax kernel (kernels/flash_attention.py) —
    O(seq) memory, used on the real chip for long sequences
  - "ring":   shard_map ring attention over the `seq` mesh axis
    (parallel/ring_attention.py) — the long-context path the reference lacks
    (SURVEY §5: no ring/Ulysses in FlexFlow)

Head-parallelism (the reference's attribute-parallel attention rewrite,
substitution.cc:create_partition_attention_combine) maps to sharding the head
dim of the projection weights over the `model` axis.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..fftype import DataType, OperatorType as OT
from .base import OpDef, WeightSpec, matmul_cast, register_op


@dataclass(frozen=True)
class MultiHeadAttentionParams:
    embed_dim: int
    num_heads: int
    kdim: int = 0  # 0 → embed_dim
    vdim: int = 0
    dropout: float = 0.0
    use_bias: bool = True
    add_bias_kv: bool = False
    add_zero_attn: bool = False
    causal: bool = False  # TPU-native addition (reference cuDNN op is unmasked)
    impl: str = "xla"  # xla | flash | ring


def _mha_dims(p: MultiHeadAttentionParams):
    kdim = p.kdim or p.embed_dim
    vdim = p.vdim or p.embed_dim
    return kdim, vdim


def _mha_infer(p: MultiHeadAttentionParams, in_shapes):
    q, k, v = in_shapes
    return [(q[0], q[1], p.embed_dim)]


def _mha_weights(p: MultiHeadAttentionParams, in_shapes):
    q, k, v = in_shapes
    kdim, vdim = _mha_dims(p)
    # per-head projection sizes follow attention.cc:70-80 (qProjSize = kdim/heads)
    ws = [
        WeightSpec("wq", (q[-1], p.embed_dim), DataType.DT_FLOAT),
        WeightSpec("wk", (k[-1], p.embed_dim), DataType.DT_FLOAT),
        WeightSpec("wv", (v[-1], p.embed_dim), DataType.DT_FLOAT),
        WeightSpec("wo", (p.embed_dim, p.embed_dim), DataType.DT_FLOAT),
    ]
    if p.use_bias:
        ws += [
            WeightSpec("bq", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
            WeightSpec("bk", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
            WeightSpec("bv", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
            WeightSpec("bo", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
        ]
    return ws


def sdpa_xla(q, k, v, *, causal: bool, scale: float):
    """Reference-semantics scaled dot-product attention, einsum form.
    q,k,v: (batch, heads, seq, head_dim)."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _mha_forward(p: MultiHeadAttentionParams, inputs, weights, state, ctx):
    q_in, k_in, v_in = inputs
    H = p.num_heads
    E = p.embed_dim
    hd = E // H

    def proj(x, w, b):
        xm, wm = matmul_cast(ctx, x, w.astype(x.dtype))
        y = jnp.dot(xm, wm, preferred_element_type=jnp.float32).astype(x.dtype)
        if b is not None:
            y = y + b.astype(y.dtype)
        return y

    q = proj(q_in, weights["wq"], weights.get("bq"))
    k = proj(k_in, weights["wk"], weights.get("bk"))
    v = proj(v_in, weights["wv"], weights.get("bv"))
    scale = 1.0 / math.sqrt(hd)

    if p.impl == "flash" and getattr(ctx, "flash_packed", True):
        # packed layout: the kernel selects heads with lane-offset block
        # index maps, so the projections' (b, s, H·hd) output feeds it
        # directly — no (b,s,h,d)→(b,h,s,d) HBM relayout in fwd OR bwd
        # (PERF.md measured those copies at ~0.8 ms per flagship step).
        # ctx.flash_packed=False (--flash-transposed) forces the
        # head-transposed kernels below — the relayout ablation baseline.
        from ..kernels.flash_attention import flash_attention_packed

        out = flash_attention_packed(q, k, v, num_heads=H, causal=p.causal,
                                     scale=scale)
        y = proj(out, weights["wo"], weights.get("bo"))
        return [y], state

    def split_heads(x):
        b, s, _ = x.shape
        return x.reshape(b, s, H, hd).transpose(0, 2, 1, 3)

    q, k, v = split_heads(q), split_heads(k), split_heads(v)

    if p.impl == "ring":
        from ..parallel.ring_attention import ring_attention

        out = ring_attention(q, k, v, causal=p.causal, scale=scale,
                             mesh=ctx.mesh,
                             overlap=getattr(ctx, "overlap_collectives", True))
    elif p.impl == "flash":
        # transposed-layout flash (flash_packed=False): same kernel math,
        # but the head split/merge above materializes the
        # (b,s,h,d)↔(b,h,s,d) relayouts the packed path avoids
        from ..kernels.flash_attention import flash_attention

        out = flash_attention(q, k, v, causal=p.causal, scale=scale)
    else:
        out = sdpa_xla(q, k, v, causal=p.causal, scale=scale)

    b, _, s, _ = out.shape
    out = out.transpose(0, 2, 1, 3).reshape(b, s, E)
    y = proj(out, weights["wo"], weights.get("bo"))
    return [y], state


def _mha_flops(p: MultiHeadAttentionParams, in_shapes, out_shapes):
    q, k, v = in_shapes
    b, sq, dq = q
    sk = k[1]
    E = p.embed_dim
    proj = 2.0 * b * (sq * dq * E + sk * k[2] * E + sk * v[2] * E + sq * E * E)
    attn = 2.0 * b * p.num_heads * sq * sk * (E // p.num_heads) * 2
    return proj + attn


register_op(
    OpDef(OT.OP_MULTIHEAD_ATTENTION, _mha_infer, _mha_forward, _mha_weights, _mha_flops)
)
