"""Operator definition framework.

The reference implements each operator as {FFModel builder, Op subclass with
Legion task launchers, Params struct, OpMeta, CUDA kernels}
(pattern documented at src/ops/linear.cc). On TPU the per-device kernel is XLA
HLO traced from a pure function, and the Legion launcher disappears: an
operator here is

  - a frozen Params dataclass (the analog of `*_params.h`, used for op dedup
    and as the simulator cache key — reference include/flexflow/operator_params.h)
  - shape/weight inference (the analog of the builder's output-shape logic)
  - a pure `forward` (params, inputs, weights, state) → (outputs, state)
    traced under jit; autodiff replaces hand-written backward tasks
  - an analytic flop/byte count used by the Unity cost model in place of
    on-device `measure_operator_cost` when microbenchmarks are disabled.

State is threaded functionally for the few stateful ops (BatchNorm running
stats, Cache) — the TPU equivalent of OpMeta mutable fields.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from ..fftype import DataType, OperatorType


@dataclass(frozen=True)
class WeightSpec:
    """Declares one trainable (or stateful) tensor of an operator."""

    name: str
    shape: tuple[int, ...]
    dtype: DataType
    initializer: str = "glorot_uniform"  # glorot_uniform|zeros|ones|normal|uniform
    trainable: bool = True


@dataclass
class OpContext:
    """Per-call execution context (the slim analog of OpMeta)."""

    training: bool = True
    rng: Any = None  # jax PRNG key folded per-op by the executor
    seq_length: int = -1
    profiling: bool = False
    mesh: Any = None  # global jax Mesh (for ops lowering to shard_map)
    # MXU input dtype for matmul/conv when activations are fp32 — the TPU
    # analog of the reference's cublas tensor-op math mode
    # (allow_tensor_op_math_conversion, include/flexflow/config.h): inputs
    # are cast to this dtype, accumulation stays fp32.
    matmul_dtype: Any = None
    # overlap-capable collectives (ring attention's double-buffered hop
    # pipeline): False compiles the serial compute-then-hop schedule —
    # the ablation baseline matching the cost model's serial pricing
    # (FFConfig.overlap_collectives)
    overlap_collectives: bool = True
    # False routes impl="flash" attention through the head-transposed
    # kernels instead of the packed relayout-free path — the kernel-layout
    # ablation baseline (FFConfig.flash_packed_layout)
    flash_packed: bool = True


def matmul_cast(ctx: OpContext, *arrays):
    """Cast fp32 matmul operands to the MXU input dtype (no-op when the
    policy is off or activations are already low-precision)."""
    md = getattr(ctx, "matmul_dtype", None)
    if md is None:
        return arrays if len(arrays) > 1 else arrays[0]
    import jax.numpy as jnp

    out = tuple(a.astype(md) if a.dtype == jnp.float32 else a for a in arrays)
    return out if len(out) > 1 else out[0]


class OpDef:
    """Registry entry for one OperatorType."""

    def __init__(
        self,
        op_type: OperatorType,
        infer_shapes: Callable,  # (params, in_shapes) -> list[tuple]
        forward: Callable,  # (params, inputs, weights, state, ctx) -> (outputs, state)
        weights: Optional[Callable] = None,  # (params, in_shapes) -> list[WeightSpec]
        flops: Optional[Callable] = None,  # (params, in_shapes, out_shapes) -> float
        num_outputs: int = 1,
    ):
        self.op_type = op_type
        self.infer_shapes = infer_shapes
        self.forward = forward
        self.weights = weights or (lambda params, in_shapes: [])
        self.flops = flops or _default_flops
        self.num_outputs = num_outputs


def _default_flops(params, in_shapes, out_shapes) -> float:
    # elementwise-ish default: one flop per output element
    total = 0
    for s in out_shapes:
        total += math.prod(s) if s else 1
    return float(total)


_REGISTRY: dict[OperatorType, OpDef] = {}


def register_op(op_def: OpDef):
    _REGISTRY[op_def.op_type] = op_def
    return op_def


def get_op_def(op_type: OperatorType) -> OpDef:
    if op_type not in _REGISTRY:
        raise KeyError(f"no OpDef registered for {op_type!r}")
    return _REGISTRY[op_type]


def registered_ops() -> dict[OperatorType, OpDef]:
    return dict(_REGISTRY)
