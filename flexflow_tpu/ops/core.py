"""Dense-compute operators: the MXU-bound core of the framework.

Reference kernels: src/ops/kernels/linear_kernels.cu (cuBLAS GEMM + cuDNN
activation), src/ops/conv_2d.cc + conv_2d_kernels.cu (cuDNN conv),
src/ops/pool_2d.cc, src/ops/batch_norm.cu, src/ops/layer_norm.cu (Welford),
src/ops/attention.cu (cudnnMultiHeadAttnForward), src/ops/embedding.cc,
src/ops/batch_matmul.cc, src/ops/kernels/softmax.cu, src/ops/dropout.cc.

TPU mapping: GEMMs/convs lower straight onto the MXU via jnp.dot/lax.conv
with bf16 accumulation policy controlled by FFConfig
(`allow_tensor_op_math_conversion` ≙ the reference's tensor-op math flag);
normalizations and activations are VPU ops that XLA fuses into the adjacent
GEMM's epilogue. Layouts: user-facing shapes keep the reference's NCHW
convention; XLA repacks internally for the TPU's native tiling.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..fftype import ActiMode, AggrMode, DataType, OperatorType as OT, PoolType, RegularizerMode
from .base import OpDef, WeightSpec, matmul_cast, register_op


def apply_activation(x, activation: ActiMode):
    if activation == ActiMode.AC_MODE_NONE:
        return x
    if activation == ActiMode.AC_MODE_RELU:
        return jax.nn.relu(x)
    if activation == ActiMode.AC_MODE_SIGMOID:
        return jax.nn.sigmoid(x)
    if activation == ActiMode.AC_MODE_TANH:
        return jnp.tanh(x)
    if activation == ActiMode.AC_MODE_GELU:
        return jax.nn.gelu(x, approximate=False)
    raise ValueError(f"unknown activation {activation}")


# ---------------------------------------------------------------- Linear

@dataclass(frozen=True)
class LinearParams:
    out_channels: int
    use_bias: bool = True
    activation: ActiMode = ActiMode.AC_MODE_NONE
    data_type: DataType = DataType.DT_FLOAT
    kernel_reg_type: RegularizerMode = RegularizerMode.REG_MODE_NONE
    kernel_reg_lambda: float = 0.0


def _linear_infer(p: LinearParams, in_shapes):
    (x,) = in_shapes
    return [tuple(x[:-1]) + (p.out_channels,)]


def _linear_weights(p: LinearParams, in_shapes):
    in_dim = in_shapes[0][-1]
    ws = [WeightSpec("kernel", (in_dim, p.out_channels), p.data_type, "glorot_uniform")]
    if p.use_bias:
        ws.append(WeightSpec("bias", (p.out_channels,), p.data_type, "zeros"))
    return ws


def _linear_forward(p: LinearParams, inputs, weights, state, ctx):
    (x,) = inputs
    xm, km = matmul_cast(ctx, x, weights["kernel"])
    y = jnp.dot(xm, km, preferred_element_type=jnp.float32)
    y = y.astype(x.dtype)
    if p.use_bias:
        y = y + weights["bias"]
    return [apply_activation(y, p.activation)], state


def _linear_flops(p: LinearParams, in_shapes, out_shapes):
    x = in_shapes[0]
    return 2.0 * math.prod(x) * p.out_channels


register_op(OpDef(OT.OP_LINEAR, _linear_infer, _linear_forward, _linear_weights, _linear_flops))


# ---------------------------------------------------------------- Conv2D

@dataclass(frozen=True)
class Conv2DParams:
    out_channels: int
    kernel_h: int
    kernel_w: int
    stride_h: int
    stride_w: int
    padding_h: int
    padding_w: int
    groups: int = 1
    use_bias: bool = True
    activation: ActiMode = ActiMode.AC_MODE_NONE


def _conv2d_out_hw(p: Conv2DParams, h, w):
    oh = (h + 2 * p.padding_h - p.kernel_h) // p.stride_h + 1
    ow = (w + 2 * p.padding_w - p.kernel_w) // p.stride_w + 1
    return oh, ow


def _conv2d_infer(p: Conv2DParams, in_shapes):
    n, c, h, w = in_shapes[0]
    oh, ow = _conv2d_out_hw(p, h, w)
    return [(n, p.out_channels, oh, ow)]


def _conv2d_weights(p: Conv2DParams, in_shapes):
    c = in_shapes[0][1]
    ws = [
        WeightSpec(
            "kernel",
            (p.out_channels, c // p.groups, p.kernel_h, p.kernel_w),
            DataType.DT_FLOAT,
            "glorot_uniform",
        )
    ]
    if p.use_bias:
        ws.append(WeightSpec("bias", (p.out_channels,), DataType.DT_FLOAT, "zeros"))
    return ws


def _conv2d_forward(p: Conv2DParams, inputs, weights, state, ctx):
    (x,) = inputs
    x = matmul_cast(ctx, x)
    # same-dtype conv without preferred_element_type: lax.conv's transpose
    # (VJP) requires matching operand dtypes, and the MXU accumulates fp32
    # internally for bf16 convs regardless of the output element type
    y = jax.lax.conv_general_dilated(
        x,
        weights["kernel"].astype(x.dtype),
        window_strides=(p.stride_h, p.stride_w),
        padding=[(p.padding_h, p.padding_h), (p.padding_w, p.padding_w)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=p.groups,
    ).astype(inputs[0].dtype)
    if p.use_bias:
        y = y + weights["bias"][None, :, None, None].astype(y.dtype)
    return [apply_activation(y, p.activation)], state


def _conv2d_flops(p: Conv2DParams, in_shapes, out_shapes):
    n, c, h, w = in_shapes[0]
    _, oc, oh, ow = out_shapes[0]
    return 2.0 * n * oc * oh * ow * (c // p.groups) * p.kernel_h * p.kernel_w


register_op(OpDef(OT.OP_CONV2D, _conv2d_infer, _conv2d_forward, _conv2d_weights, _conv2d_flops))


# ---------------------------------------------------------------- Pool2D

@dataclass(frozen=True)
class Pool2DParams:
    kernel_h: int
    kernel_w: int
    stride_h: int
    stride_w: int
    padding_h: int
    padding_w: int
    pool_type: PoolType = PoolType.POOL_MAX
    activation: ActiMode = ActiMode.AC_MODE_NONE


def _pool2d_infer(p: Pool2DParams, in_shapes):
    n, c, h, w = in_shapes[0]
    oh = (h + 2 * p.padding_h - p.kernel_h) // p.stride_h + 1
    ow = (w + 2 * p.padding_w - p.kernel_w) // p.stride_w + 1
    return [(n, c, oh, ow)]


def _pool2d_forward(p: Pool2DParams, inputs, weights, state, ctx):
    (x,) = inputs
    pads = ((0, 0), (0, 0), (p.padding_h, p.padding_h), (p.padding_w, p.padding_w))
    dims = (1, 1, p.kernel_h, p.kernel_w)
    strides = (1, 1, p.stride_h, p.stride_w)
    if p.pool_type == PoolType.POOL_MAX:
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        y = jax.lax.reduce_window(x, init, jax.lax.max, dims, strides, pads)
    else:
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides, pads)
        # cuDNN CUDNN_POOLING_AVERAGE_COUNT_INCLUDE_PADDING semantics
        y = summed / (p.kernel_h * p.kernel_w)
    return [apply_activation(y, p.activation)], state


register_op(OpDef(OT.OP_POOL2D, _pool2d_infer, _pool2d_forward))


# ---------------------------------------------------------------- Flat

def _flat_infer(p, in_shapes):
    n = in_shapes[0][0]
    return [(n, math.prod(in_shapes[0][1:]))]


def _flat_forward(p, inputs, weights, state, ctx):
    (x,) = inputs
    return [x.reshape(x.shape[0], -1)], state


register_op(OpDef(OT.OP_FLAT, _flat_infer, _flat_forward))


# ---------------------------------------------------------------- BatchNorm

@dataclass(frozen=True)
class BatchNormParams:
    relu: bool = True
    momentum: float = 0.1
    eps: float = 1e-5


def _bn_infer(p, in_shapes):
    return [in_shapes[0]]


def _bn_weights(p: BatchNormParams, in_shapes):
    c = in_shapes[0][1]
    return [
        WeightSpec("scale", (c,), DataType.DT_FLOAT, "ones"),
        WeightSpec("bias", (c,), DataType.DT_FLOAT, "zeros"),
        WeightSpec("running_mean", (c,), DataType.DT_FLOAT, "zeros", trainable=False),
        WeightSpec("running_var", (c,), DataType.DT_FLOAT, "ones", trainable=False),
    ]


def _bn_forward(p: BatchNormParams, inputs, weights, state, ctx):
    (x,) = inputs
    axes = (0, 2, 3)
    # statistics always in fp32 (mixed-precision policy: bf16 mean/var
    # accumulation loses too many mantissa bits)
    xf = x.astype(jnp.float32)
    if ctx.training:
        mean = jnp.mean(xf, axes)
        var = jnp.var(xf, axes)
        state = dict(state or {})
        state["running_mean"] = (
            (1 - p.momentum) * weights["running_mean"].astype(jnp.float32)
            + p.momentum * mean
        )
        state["running_var"] = (
            (1 - p.momentum) * weights["running_var"].astype(jnp.float32)
            + p.momentum * var
        )
    else:
        mean = weights["running_mean"].astype(jnp.float32)
        var = weights["running_var"].astype(jnp.float32)
    inv = jax.lax.rsqrt(var + p.eps)
    y = (xf - mean[None, :, None, None]) * inv[None, :, None, None]
    y = y.astype(x.dtype)
    y = y * weights["scale"][None, :, None, None] + weights["bias"][None, :, None, None]
    if p.relu:
        y = jax.nn.relu(y)
    return [y], state


register_op(OpDef(OT.OP_BATCHNORM, _bn_infer, _bn_forward, _bn_weights))


# ---------------------------------------------------------------- LayerNorm

@dataclass(frozen=True)
class LayerNormParams:
    axes: tuple[int, ...]
    elementwise_affine: bool = True
    eps: float = 1e-5


def _ln_infer(p, in_shapes):
    return [in_shapes[0]]


def _ln_weights(p: LayerNormParams, in_shapes):
    if not p.elementwise_affine:
        return []
    shape = tuple(in_shapes[0][a] for a in p.axes)
    return [
        WeightSpec("scale", shape, DataType.DT_FLOAT, "ones"),
        WeightSpec("bias", shape, DataType.DT_FLOAT, "zeros"),
    ]


def _ln_forward(p: LayerNormParams, inputs, weights, state, ctx):
    (x,) = inputs
    axes = tuple(a % x.ndim for a in p.axes)
    if p.elementwise_affine:
        # fused Pallas kernel for the tiling-friendly common case (one
        # HBM pass instead of XLA's off-roofline convert+reduce fusion;
        # kernels/layer_norm.py)
        from ..kernels.layer_norm import fused_layer_norm_or_none

        fused = fused_layer_norm_or_none(
            x, weights["scale"], weights["bias"], axes, p.eps)
        if fused is not None:
            return [fused], state
    xf = x.astype(jnp.float32)  # fp32 statistics under mixed precision
    mean = jnp.mean(xf, axes, keepdims=True)
    var = jnp.var(xf, axes, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + p.eps)
    if p.elementwise_affine:
        # affine still in f32 (matching the fused kernel's semantics; a
        # bf16·f32 product would also silently promote activations), one
        # final cast to the activation dtype
        bshape = [x.shape[a] if a in axes else 1 for a in range(x.ndim)]
        y = y * weights["scale"].reshape(bshape) + weights["bias"].reshape(bshape)
    return [y.astype(x.dtype)], state


register_op(OpDef(OT.OP_LAYERNORM, _ln_infer, _ln_forward, _ln_weights))


# ---------------------------------------------------------------- Softmax

@dataclass(frozen=True)
class SoftmaxParams:
    dim: int = -1


def _softmax_infer(p, in_shapes):
    return [in_shapes[0]]


def _softmax_forward(p: SoftmaxParams, inputs, weights, state, ctx):
    (x,) = inputs
    # fp32 exponentials/normalization, output back in the activation dtype
    y = jax.nn.softmax(x.astype(jnp.float32), axis=p.dim).astype(x.dtype)
    return [y], state


register_op(OpDef(OT.OP_SOFTMAX, _softmax_infer, _softmax_forward))


# ---------------------------------------------------------------- Dropout

@dataclass(frozen=True)
class DropoutParams:
    rate: float
    seed: int = 0


def _dropout_infer(p, in_shapes):
    return [in_shapes[0]]


def _dropout_forward(p: DropoutParams, inputs, weights, state, ctx):
    (x,) = inputs
    if not ctx.training or p.rate <= 0.0:
        return [x], state
    keep = 1.0 - p.rate
    mask = jax.random.bernoulli(ctx.rng, keep, x.shape)
    return [jnp.where(mask, x / keep, 0.0).astype(x.dtype)], state


register_op(OpDef(OT.OP_DROPOUT, _dropout_infer, _dropout_forward))


# ---------------------------------------------------------------- BatchMatmul

@dataclass(frozen=True)
class BatchMatmulParams:
    a_seq_length_dim: int = -1
    b_seq_length_dim: int = -1


def _bmm_infer(p, in_shapes):
    a, b = in_shapes
    if a[:-2] != b[:-2]:
        raise ValueError(f"batch dims mismatch: {a} vs {b}")
    if a[-1] != b[-2]:
        raise ValueError(f"contraction mismatch: {a} vs {b}")
    return [tuple(a[:-2]) + (a[-2], b[-1])]


def _bmm_forward(p: BatchMatmulParams, inputs, weights, state, ctx):
    a, b = inputs
    if ctx.seq_length >= 0:
        # truncated-sequence batches (FFIterationConfig::seq_length,
        # reference include/flexflow/config.h:162-167)
        if p.a_seq_length_dim >= 0:
            a = jax.lax.slice_in_dim(a, 0, ctx.seq_length, axis=p.a_seq_length_dim)
        if p.b_seq_length_dim >= 0:
            b = jax.lax.slice_in_dim(b, 0, ctx.seq_length, axis=p.b_seq_length_dim)
    am, bm = matmul_cast(ctx, a, b)
    y = jnp.matmul(am, bm, preferred_element_type=jnp.float32).astype(a.dtype)
    return [y], state


def _bmm_flops(p, in_shapes, out_shapes):
    a, b = in_shapes
    return 2.0 * math.prod(out_shapes[0]) * a[-1]


register_op(OpDef(OT.OP_BATCHMATMUL, _bmm_infer, _bmm_forward, flops=_bmm_flops))


# ---------------------------------------------------------------- Embedding

@dataclass(frozen=True)
class EmbeddingParams:
    num_entries: int
    out_channels: int
    aggr: AggrMode = AggrMode.AGGR_MODE_NONE
    data_type: DataType = DataType.DT_FLOAT


def _embedding_infer(p: EmbeddingParams, in_shapes):
    x = in_shapes[0]
    if p.aggr == AggrMode.AGGR_MODE_NONE:
        return [tuple(x) + (p.out_channels,)]
    return [tuple(x[:-1]) + (p.out_channels,)]


def _embedding_weights(p: EmbeddingParams, in_shapes):
    return [
        WeightSpec(
            "kernel", (p.num_entries, p.out_channels), p.data_type, "glorot_uniform"
        )
    ]


def _embedding_forward(p: EmbeddingParams, inputs, weights, state, ctx):
    (ids,) = inputs
    table = weights["kernel"]
    # gather rides the VPU; for giant tables sharded over the model axis GSPMD
    # turns this into an all-to-all — same role as the reference's custom
    # scatter/gather kernels (src/ops/kernels/embedding_kernels.cu)
    emb = jnp.take(table, ids.astype(jnp.int32), axis=0)
    if p.aggr == AggrMode.AGGR_MODE_SUM:
        emb = jnp.sum(emb, axis=-2)
    elif p.aggr == AggrMode.AGGR_MODE_AVG:
        emb = jnp.mean(emb, axis=-2)
    return [emb], state


register_op(
    OpDef(OT.OP_EMBEDDING, _embedding_infer, _embedding_forward, _embedding_weights)
)
