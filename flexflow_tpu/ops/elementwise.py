"""Elementwise operators.

Reference: src/ops/element_unary.cc/.cu (relu/sigmoid/tanh/elu/exp/sin/cos/
rsqrt/pow/scalar_*/identity/gelu, inplace support) and
src/ops/element_binary.cc + element_binary_kernels.cu (add/sub/mul/div/max/min
with cuDNN OpTensor broadcasting). On TPU these are single VPU-bound HLO ops
that XLA fuses into neighbors — the reference's FusedOp machinery
(src/ops/fused.cc) is unnecessary; fusion falls out of jit.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..fftype import OperatorType as OT
from .base import OpDef, register_op


@dataclass(frozen=True)
class ElementUnaryParams:
    op_type: OT
    inplace: bool = True  # kept for parity; XLA manages buffers itself
    scalar: float = 0.0


@dataclass(frozen=True)
class ElementBinaryParams:
    op_type: OT
    inplace_a: bool = False


_UNARY_FNS = {
    OT.OP_EXP: jnp.exp,
    OT.OP_LOG: jnp.log,
    OT.OP_SIN: jnp.sin,
    OT.OP_COS: jnp.cos,
    OT.OP_RELU: jax.nn.relu,
    OT.OP_IDENTITY: lambda x: x,
    OT.OP_GELU: lambda x: jax.nn.gelu(x, approximate=False),
    OT.OP_SIGMOID: jax.nn.sigmoid,
    OT.OP_TANH: jnp.tanh,
    OT.OP_ELU: jax.nn.elu,
    OT.OP_RSQRT: jax.lax.rsqrt,
    OT.OP_SQRT: jnp.sqrt,
    OT.OP_CEIL: jnp.ceil,
    OT.OP_ROUND: jnp.round,
    OT.OP_LOGICAL_NOT: jnp.logical_not,
    OT.OP_LEAKYRELU: jax.nn.leaky_relu,
}

_SCALAR_FNS = {
    OT.OP_SCALAR_MULTIPLY: lambda x, c: x * c,
    OT.OP_SCALAR_ADD: lambda x, c: x + c,
    OT.OP_SCALAR_SUB: lambda x, c: x - c,
    OT.OP_SCALAR_TRUE_DIV: lambda x, c: x / c,
    OT.OP_SCALAR_FLOOR_DIV: lambda x, c: jnp.floor_divide(x, c),
    OT.OP_POW: lambda x, c: jnp.power(x, c),
}

_BINARY_FNS = {
    OT.OP_EW_ADD: jnp.add,
    OT.OP_EW_SUB: jnp.subtract,
    OT.OP_EW_MUL: jnp.multiply,
    OT.OP_EW_DIV: jnp.divide,
    OT.OP_EW_MAX: jnp.maximum,
    OT.OP_EW_MIN: jnp.minimum,
    OT.OP_EW_EQUAL: jnp.equal,
    OT.OP_EW_GREATER: jnp.greater,
    OT.OP_EW_LESS: jnp.less,
}


def _unary_infer(params, in_shapes):
    return [in_shapes[0]]


def _unary_forward(params, inputs, weights, state, ctx):
    (x,) = inputs
    if params.op_type in _SCALAR_FNS:
        y = _SCALAR_FNS[params.op_type](x, params.scalar)
    else:
        y = _UNARY_FNS[params.op_type](x)
    return [y], state


def _binary_infer(params, in_shapes):
    a, b = in_shapes
    return [jnp.broadcast_shapes(tuple(a), tuple(b))]


def _binary_forward(params, inputs, weights, state, ctx):
    a, b = inputs
    return [_BINARY_FNS[params.op_type](a, b)], state


for _ot in list(_UNARY_FNS) + list(_SCALAR_FNS):
    register_op(OpDef(_ot, _unary_infer, _unary_forward))

for _ot in _BINARY_FNS:
    register_op(OpDef(_ot, _binary_infer, _binary_forward))
