"""Incremental (decode-phase) multi-head self-attention over a KV cache.

The serving engine's core op (serving/): the reference snapshot predates
FlexFlow's serving rewrite — this is its IncMultiHeadSelfAttention recast
TPU-natively. Where training attention (ops/attention.py) recomputes K/V
for the whole sequence every step, the decode op threads a **first-class
stateful parallel tensor** per layer: `cache_k`/`cache_v`, shape
(slots, max_seq_len + 1, embed_dim), declared as non-trainable weight
specs so the executor places them by the searched plan exactly like any
parameter — the slot dim rides the `data` axis with the batch, and a
head-parallel plan shards the feature dim over `model`, splitting each
chip's cache down to its own heads (the KV-cache placement Unity prices).

One forward call processes q_len tokens per slot at arbitrary,
per-element positions:

  - **position-indexed KV write**: the new K/V rows scatter into the cache
    at `positions` (a (slots, q_len) int32 input). Row `max_seq_len` is a
    scratch row — elements whose position is clipped there (empty slots,
    prefill padding) leave every real cache row untouched, which is how
    the continuous-batching engine runs a fixed-shape executable while
    slots sit at different sequence positions.
  - **masked read**: query row i of slot s attends cache rows
    [0, positions[s, i]] — intra-chunk causality during prefill falls out
    of the per-row positions; q_len=1 is the decode iteration.

The same two properties make q_len=K+1 the speculative VERIFY call
(serving/speculative.py): the drafter's K proposals plus the slot's last
token feed at positions [L..L+K], every row's write lands BEFORE the
masked read, and rows beyond a row's own position are invisible to it —
so rejected proposals need no device-side erase. The engine just rewinds
its host cursor: any stale row at or below a later call's query frontier
is overwritten by that call's own scatter before it becomes readable,
and rows beyond the frontier stay masked forever.

Weight names match OP_MULTIHEAD_ATTENTION's (wq/wk/wv/wo + biases), so a
trained model's parameters transfer to its decode graph by name. On TPU
the q_len=1 path routes through the Pallas decode kernel
(kernels/flash_attention.flash_decode_attention); CPU meshes use the
reference einsum so tier-1 exercises serving end-to-end.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..fftype import DataType, OperatorType as OT
from .base import OpDef, WeightSpec, matmul_cast, register_op


@dataclass(frozen=True)
class IncMultiHeadAttentionParams:
    embed_dim: int
    num_heads: int
    max_seq_len: int  # real cache rows; row max_seq_len is the scratch row
    use_bias: bool = True
    impl: str = "auto"  # auto: flash decode on TPU (q_len=1), einsum else


def _inc_mha_infer(p: IncMultiHeadAttentionParams, in_shapes):
    x, positions = in_shapes
    return [(x[0], x[1], p.embed_dim)]


def _inc_mha_weights(p: IncMultiHeadAttentionParams, in_shapes):
    x = in_shapes[0]
    slots = x[0]
    ws = [
        WeightSpec("wq", (x[-1], p.embed_dim), DataType.DT_FLOAT),
        WeightSpec("wk", (x[-1], p.embed_dim), DataType.DT_FLOAT),
        WeightSpec("wv", (x[-1], p.embed_dim), DataType.DT_FLOAT),
        WeightSpec("wo", (p.embed_dim, p.embed_dim), DataType.DT_FLOAT),
    ]
    if p.use_bias:
        ws += [
            WeightSpec("bq", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
            WeightSpec("bk", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
            WeightSpec("bv", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
            WeightSpec("bo", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
        ]
    # the KV cache: stateful (non-trainable), zero-initialized, threaded
    # functionally through the executor's state dict like BatchNorm stats
    ws += [
        WeightSpec("cache_k", (slots, p.max_seq_len + 1, p.embed_dim),
                   DataType.DT_FLOAT, "zeros", trainable=False),
        WeightSpec("cache_v", (slots, p.max_seq_len + 1, p.embed_dim),
                   DataType.DT_FLOAT, "zeros", trainable=False),
    ]
    return ws


def _inc_mha_forward(p: IncMultiHeadAttentionParams, inputs, weights,
                     state, ctx):
    x, positions = inputs
    slots, q_len, _ = x.shape
    H, E = p.num_heads, p.embed_dim
    hd = E // H

    def proj(t, w, b):
        tm, wm = matmul_cast(ctx, t, w.astype(t.dtype))
        y = jnp.dot(tm, wm, preferred_element_type=jnp.float32).astype(t.dtype)
        if b is not None:
            y = y + b.astype(y.dtype)
        return y

    q = proj(x, weights["wq"], weights.get("bq"))
    k = proj(x, weights["wk"], weights.get("bk"))
    v = proj(x, weights["wv"], weights.get("bv"))
    scale = 1.0 / math.sqrt(hd)

    ck, cv = weights["cache_k"], weights["cache_v"]
    positions = positions.astype(jnp.int32)
    # position-indexed write; >= max_seq_len clips to the scratch row, so
    # padded/empty elements never disturb live cache state
    write_pos = jnp.clip(positions, 0, p.max_seq_len)
    slot_idx = jnp.arange(slots, dtype=jnp.int32)[:, None]
    # scratch-bound elements write ZEROS, not their (garbage) K/V: a pad
    # element's hidden state can be NaN (OOB position-embedding gather
    # fills NaN), and although every read of the scratch row is masked,
    # softmax zeros times a NaN V row would still poison the live rows'
    # contraction — the cache must only ever hold finite values
    live = (positions >= 0) & (positions < p.max_seq_len)
    kw = jnp.where(live[..., None], k, 0.0)
    vw = jnp.where(live[..., None], v, 0.0)
    ck = ck.at[slot_idx, write_pos].set(kw.astype(ck.dtype))
    cv = cv.at[slot_idx, write_pos].set(vw.astype(cv.dtype))

    use_flash = (p.impl == "flash"
                 or (p.impl == "auto" and jax.default_backend() == "tpu"))
    if use_flash and q_len == 1:
        from ..kernels.flash_attention import flash_decode_attention

        out = flash_decode_attention(
            q, ck.astype(q.dtype), cv.astype(q.dtype),
            write_pos[:, 0] + 1, num_heads=H, scale=scale)
    else:
        from ..kernels.flash_attention import decode_attention_reference

        out = decode_attention_reference(
            q, ck.astype(q.dtype), cv.astype(q.dtype), write_pos,
            num_heads=H, scale=scale)
    y = proj(out, weights["wo"], weights.get("bo"))
    return [y], {"cache_k": ck, "cache_v": cv}


def _inc_mha_flops(p: IncMultiHeadAttentionParams, in_shapes, out_shapes):
    x = in_shapes[0]
    slots, q_len = x[0], x[1]
    E = p.embed_dim
    # four projections of the q_len new tokens + attention of each query
    # against the full cache (the serving cost model prices the worst-case
    # full-cache read; the kernel skips dead blocks at run time)
    proj = 2.0 * slots * q_len * (3 * x[-1] * E + E * E)
    attn = 2.0 * slots * p.num_heads * q_len * (p.max_seq_len + 1) * (
        E // p.num_heads) * 2
    return proj + attn


register_op(OpDef(OT.OP_INC_MULTIHEAD_ATTENTION, _inc_mha_infer,
                  _inc_mha_forward, _inc_mha_weights, _inc_mha_flops))


# ===================================================================== paged
# Paged variant (vLLM/PagedAttention, SOSP '23): the per-layer KV cache is a
# shared BLOCK POOL `pool_k`/`pool_v` of shape (num_blocks, block_size,
# embed) plus a per-slot PAGE TABLE input (slots, blocks_per_slot) int32
# mapping logical block j of a slot to a physical pool block. The pool is
# still a first-class stateful parallel tensor (non-trainable weight spec):
# Unity places and prices it — the feature dim shards over `model` under a
# head-parallel plan exactly like the contiguous cache — and it is donated
# through the decode step like any state.
#
# Physical block 0 is the RESERVED SCRATCH BLOCK, the paged equivalent of
# the contiguous layout's scratch row `max_seq_len`: an element whose
# position clips out of [0, max_seq_len) writes ZEROS into block 0, so
# padded/empty elements never disturb a live block and the pool only ever
# holds finite values (same NaN-poisoning guard as the contiguous write).
# The block-sharing invariant is host-side: the engine's BlockManager
# guarantees (COW) that a physical block referenced by more than one page
# table is never the target of a write — the device op writes wherever the
# table points.


@dataclass(frozen=True)
class PagedIncMultiHeadAttentionParams:
    embed_dim: int
    num_heads: int
    max_seq_len: int    # logical cache rows per slot (capacity)
    block_size: int     # pool rows per block
    num_blocks: int     # physical pool blocks, block 0 = reserved scratch
    use_bias: bool = True
    impl: str = "auto"  # auto: paged flash decode on TPU (q_len=1)

    @property
    def blocks_per_slot(self) -> int:
        """Page-table width: logical blocks covering max_seq_len rows."""
        return -(-self.max_seq_len // self.block_size)


def _paged_mha_infer(p: PagedIncMultiHeadAttentionParams, in_shapes):
    x, positions, page_table = in_shapes
    if page_table[-1] != p.blocks_per_slot:
        raise ValueError(
            f"page_table width {page_table[-1]} != blocks_per_slot "
            f"{p.blocks_per_slot} (= ceil({p.max_seq_len}/{p.block_size}))")
    return [(x[0], x[1], p.embed_dim)]


def _paged_mha_weights(p: PagedIncMultiHeadAttentionParams, in_shapes):
    x = in_shapes[0]
    ws = [
        WeightSpec("wq", (x[-1], p.embed_dim), DataType.DT_FLOAT),
        WeightSpec("wk", (x[-1], p.embed_dim), DataType.DT_FLOAT),
        WeightSpec("wv", (x[-1], p.embed_dim), DataType.DT_FLOAT),
        WeightSpec("wo", (p.embed_dim, p.embed_dim), DataType.DT_FLOAT),
    ]
    if p.use_bias:
        ws += [
            WeightSpec("bq", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
            WeightSpec("bk", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
            WeightSpec("bv", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
            WeightSpec("bo", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
        ]
    # the block pool: ONE tensor per layer shared by every slot (a block
    # mapped into N page tables is stored once — the prefix-sharing win),
    # so per-chip accounting counts it once, not per slot
    ws += [
        WeightSpec("pool_k", (p.num_blocks, p.block_size, p.embed_dim),
                   DataType.DT_FLOAT, "zeros", trainable=False),
        WeightSpec("pool_v", (p.num_blocks, p.block_size, p.embed_dim),
                   DataType.DT_FLOAT, "zeros", trainable=False),
    ]
    return ws


def _paged_mha_forward(p: PagedIncMultiHeadAttentionParams, inputs, weights,
                       state, ctx):
    x, positions, page_table = inputs
    slots, q_len, _ = x.shape
    H, E = p.num_heads, p.embed_dim
    hd = E // H
    bs = p.block_size
    W = p.blocks_per_slot

    def proj(t, w, b):
        tm, wm = matmul_cast(ctx, t, w.astype(t.dtype))
        y = jnp.dot(tm, wm, preferred_element_type=jnp.float32).astype(t.dtype)
        if b is not None:
            y = y + b.astype(y.dtype)
        return y

    q = proj(x, weights["wq"], weights.get("bq"))
    k = proj(x, weights["wk"], weights.get("bk"))
    v = proj(x, weights["wv"], weights.get("bv"))
    scale = 1.0 / math.sqrt(hd)

    pk, pv = weights["pool_k"], weights["pool_v"]
    positions = positions.astype(jnp.int32)
    page_table = page_table.astype(jnp.int32)
    live = (positions >= 0) & (positions < p.max_seq_len)
    # position → (physical block, in-block offset) through the page table;
    # dead elements route to the scratch block (0) and write zeros — see
    # the contiguous op's scratch-row rationale (NaN'd pad hidden states
    # must never reach the pool even though reads mask them)
    pos_c = jnp.clip(positions, 0, p.max_seq_len - 1)
    logical = pos_c // bs                       # (slots, q_len) in [0, W)
    offset = pos_c % bs
    phys = jnp.take_along_axis(page_table, logical, axis=1)
    phys = jnp.where(live, phys, 0)
    kw = jnp.where(live[..., None], k, 0.0)
    vw = jnp.where(live[..., None], v, 0.0)
    pk = pk.at[phys, offset].set(kw.astype(pk.dtype))
    pv = pv.at[phys, offset].set(vw.astype(pv.dtype))

    use_flash = (p.impl == "flash"
                 or (p.impl == "auto" and jax.default_backend() == "tpu"))
    if use_flash and q_len == 1:
        from ..kernels.flash_attention import paged_flash_decode_attention

        out = paged_flash_decode_attention(
            q, pk.astype(q.dtype), pv.astype(q.dtype), page_table,
            jnp.where(live[:, 0], pos_c[:, 0] + 1, 0),
            num_heads=H, scale=scale)
    else:
        # reference path (CPU tier-1 + the kernel's numerics oracle):
        # gather each slot's logical cache view from the pool, then run
        # the SAME masked einsum as the contiguous op — token identity
        # between the layouts reduces to the gather being the identity
        # on live rows
        kc = pk[page_table].reshape(slots, W * bs, E).astype(q.dtype)
        vc = pv[page_table].reshape(slots, W * bs, E).astype(q.dtype)
        from ..kernels.flash_attention import decode_attention_reference

        read_pos = jnp.where(live, pos_c, -1)
        out = decode_attention_reference(
            q, kc, vc, read_pos, num_heads=H, scale=scale)
    y = proj(out, weights["wo"], weights.get("bo"))
    return [y], {"pool_k": pk, "pool_v": pv}


def _paged_mha_flops(p: PagedIncMultiHeadAttentionParams, in_shapes,
                     out_shapes):
    x = in_shapes[0]
    slots, q_len = x[0], x[1]
    E = p.embed_dim
    # same shape as the contiguous op's count: projections of the new
    # tokens + worst-case full-capacity cache read per query (the kernel
    # skips dead blocks at run time; the pricer keeps the upper bound)
    proj = 2.0 * slots * q_len * (3 * x[-1] * E + E * E)
    attn = 2.0 * slots * p.num_heads * q_len * (
        p.blocks_per_slot * p.block_size) * (E // p.num_heads) * 2
    return proj + attn


register_op(OpDef(OT.OP_PAGED_INC_MULTIHEAD_ATTENTION, _paged_mha_infer,
                  _paged_mha_forward, _paged_mha_weights, _paged_mha_flops))
