"""Incremental (decode-phase) multi-head self-attention over a KV cache.

The serving engine's core op (serving/): the reference snapshot predates
FlexFlow's serving rewrite — this is its IncMultiHeadSelfAttention recast
TPU-natively. Where training attention (ops/attention.py) recomputes K/V
for the whole sequence every step, the decode op threads a **first-class
stateful parallel tensor** per layer: `cache_k`/`cache_v`, shape
(slots, max_seq_len + 1, embed_dim), declared as non-trainable weight
specs so the executor places them by the searched plan exactly like any
parameter — the slot dim rides the `data` axis with the batch, and a
head-parallel plan shards the feature dim over `model`, splitting each
chip's cache down to its own heads (the KV-cache placement Unity prices).

One forward call processes q_len tokens per slot at arbitrary,
per-element positions:

  - **position-indexed KV write**: the new K/V rows scatter into the cache
    at `positions` (a (slots, q_len) int32 input). Row `max_seq_len` is a
    scratch row — elements whose position is clipped there (empty slots,
    prefill padding) leave every real cache row untouched, which is how
    the continuous-batching engine runs a fixed-shape executable while
    slots sit at different sequence positions.
  - **masked read**: query row i of slot s attends cache rows
    [0, positions[s, i]] — intra-chunk causality during prefill falls out
    of the per-row positions; q_len=1 is the decode iteration.

Weight names match OP_MULTIHEAD_ATTENTION's (wq/wk/wv/wo + biases), so a
trained model's parameters transfer to its decode graph by name. On TPU
the q_len=1 path routes through the Pallas decode kernel
(kernels/flash_attention.flash_decode_attention); CPU meshes use the
reference einsum so tier-1 exercises serving end-to-end.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..fftype import DataType, OperatorType as OT
from .base import OpDef, WeightSpec, matmul_cast, register_op


@dataclass(frozen=True)
class IncMultiHeadAttentionParams:
    embed_dim: int
    num_heads: int
    max_seq_len: int  # real cache rows; row max_seq_len is the scratch row
    use_bias: bool = True
    impl: str = "auto"  # auto: flash decode on TPU (q_len=1), einsum else


def _inc_mha_infer(p: IncMultiHeadAttentionParams, in_shapes):
    x, positions = in_shapes
    return [(x[0], x[1], p.embed_dim)]


def _inc_mha_weights(p: IncMultiHeadAttentionParams, in_shapes):
    x = in_shapes[0]
    slots = x[0]
    ws = [
        WeightSpec("wq", (x[-1], p.embed_dim), DataType.DT_FLOAT),
        WeightSpec("wk", (x[-1], p.embed_dim), DataType.DT_FLOAT),
        WeightSpec("wv", (x[-1], p.embed_dim), DataType.DT_FLOAT),
        WeightSpec("wo", (p.embed_dim, p.embed_dim), DataType.DT_FLOAT),
    ]
    if p.use_bias:
        ws += [
            WeightSpec("bq", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
            WeightSpec("bk", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
            WeightSpec("bv", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
            WeightSpec("bo", (p.embed_dim,), DataType.DT_FLOAT, "zeros"),
        ]
    # the KV cache: stateful (non-trainable), zero-initialized, threaded
    # functionally through the executor's state dict like BatchNorm stats
    ws += [
        WeightSpec("cache_k", (slots, p.max_seq_len + 1, p.embed_dim),
                   DataType.DT_FLOAT, "zeros", trainable=False),
        WeightSpec("cache_v", (slots, p.max_seq_len + 1, p.embed_dim),
                   DataType.DT_FLOAT, "zeros", trainable=False),
    ]
    return ws


def _inc_mha_forward(p: IncMultiHeadAttentionParams, inputs, weights,
                     state, ctx):
    x, positions = inputs
    slots, q_len, _ = x.shape
    H, E = p.num_heads, p.embed_dim
    hd = E // H

    def proj(t, w, b):
        tm, wm = matmul_cast(ctx, t, w.astype(t.dtype))
        y = jnp.dot(tm, wm, preferred_element_type=jnp.float32).astype(t.dtype)
        if b is not None:
            y = y + b.astype(y.dtype)
        return y

    q = proj(x, weights["wq"], weights.get("bq"))
    k = proj(x, weights["wk"], weights.get("bk"))
    v = proj(x, weights["wv"], weights.get("bv"))
    scale = 1.0 / math.sqrt(hd)

    ck, cv = weights["cache_k"], weights["cache_v"]
    positions = positions.astype(jnp.int32)
    # position-indexed write; >= max_seq_len clips to the scratch row, so
    # padded/empty elements never disturb live cache state
    write_pos = jnp.clip(positions, 0, p.max_seq_len)
    slot_idx = jnp.arange(slots, dtype=jnp.int32)[:, None]
    # scratch-bound elements write ZEROS, not their (garbage) K/V: a pad
    # element's hidden state can be NaN (OOB position-embedding gather
    # fills NaN), and although every read of the scratch row is masked,
    # softmax zeros times a NaN V row would still poison the live rows'
    # contraction — the cache must only ever hold finite values
    live = (positions >= 0) & (positions < p.max_seq_len)
    kw = jnp.where(live[..., None], k, 0.0)
    vw = jnp.where(live[..., None], v, 0.0)
    ck = ck.at[slot_idx, write_pos].set(kw.astype(ck.dtype))
    cv = cv.at[slot_idx, write_pos].set(vw.astype(cv.dtype))

    use_flash = (p.impl == "flash"
                 or (p.impl == "auto" and jax.default_backend() == "tpu"))
    if use_flash and q_len == 1:
        from ..kernels.flash_attention import flash_decode_attention

        out = flash_decode_attention(
            q, ck.astype(q.dtype), cv.astype(q.dtype),
            write_pos[:, 0] + 1, num_heads=H, scale=scale)
    else:
        from ..kernels.flash_attention import decode_attention_reference

        out = decode_attention_reference(
            q, ck.astype(q.dtype), cv.astype(q.dtype), write_pos,
            num_heads=H, scale=scale)
    y = proj(out, weights["wo"], weights.get("bo"))
    return [y], {"cache_k": ck, "cache_v": cv}


def _inc_mha_flops(p: IncMultiHeadAttentionParams, in_shapes, out_shapes):
    x = in_shapes[0]
    slots, q_len = x[0], x[1]
    E = p.embed_dim
    # four projections of the q_len new tokens + attention of each query
    # against the full cache (the serving cost model prices the worst-case
    # full-cache read; the kernel skips dead blocks at run time)
    proj = 2.0 * slots * q_len * (3 * x[-1] * E + E * E)
    attn = 2.0 * slots * p.num_heads * q_len * (p.max_seq_len + 1) * (
        E // p.num_heads) * 2
    return proj + attn


register_op(OpDef(OT.OP_INC_MULTIHEAD_ATTENTION, _inc_mha_infer,
                  _inc_mha_forward, _inc_mha_weights, _inc_mha_flops))
