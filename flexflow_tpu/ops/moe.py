"""Mixture-of-Experts operators: Group_by, Aggregate, AggregateSpec, Cache.

Reference: src/ops/group_by.cc/.cu (token→expert scatter with capacity factor
alpha), src/ops/aggregate.cc/.cu (gate-weighted combine + load-balance term in
backward), src/ops/aggregate_spec.cc (speculative variant with replicated
labels), src/ops/cache.cc (cross-batch activation cache with staleness score,
include/flexflow/ops/cache.h:14-65).

TPU design notes:
- The reference's CUDA kernels do data-dependent scatter/gather. Under jit we
  need static shapes, so expert buffers are padded to the same
  `capacity = ceil(alpha * k * batch / n)` the reference uses — its alpha
  capacity factor exists for exactly this reason (static allocation).
- Token ranking within an expert is a cumsum over a one-hot routing matrix —
  all dense VPU math, no serialization; overflow tokens are dropped exactly
  like the reference (group_by.cu drops rows beyond expert capacity).
- Both Group_by and Aggregate derive slots from the same deterministic
  (sample-major) ordering so they agree without communicating, mirroring the
  reference pair.
- The load-balance gradient the reference injects in aggregate's backward
  (lambda_bal) is exposed here as an auxiliary loss accumulated into op state
  ("aux_loss"); the loss module adds it to the scalar objective so autodiff
  produces the same gate gradients.
- Expert parallelism = sharding the stacked expert dim over the `expert`/
  `model` mesh axis; the gather in aggregate then lowers to an all-to-all.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..fftype import DataType, OperatorType as OT
from .base import OpDef, WeightSpec, register_op


def expert_capacity(n: int, k: int, batch: int, alpha: float) -> int:
    return max(1, int(math.ceil(alpha * k * batch / n)))


def _routing_slots(assign, n: int, capacity: int):
    """assign: (batch, k) int expert ids → (slot, valid) each (batch, k).

    slot[i,j] = rank of token (i,j) among tokens routed to assign[i,j], in
    sample-major order; valid = rank < capacity."""
    b, k = assign.shape
    flat = assign.reshape(-1).astype(jnp.int32)  # (b*k,)
    onehot = jax.nn.one_hot(flat, n, dtype=jnp.int32)  # (b*k, n)
    ranks = jnp.cumsum(onehot, axis=0) - onehot  # rank among same-expert tokens
    slot = jnp.take_along_axis(ranks, flat[:, None], axis=1)[:, 0]
    valid = slot < capacity
    return slot.reshape(b, k), valid.reshape(b, k)


# ---------------------------------------------------------------- Group_by

@dataclass(frozen=True)
class GroupByParams:
    n: int
    alpha: float


def _group_by_infer(p: GroupByParams, in_shapes):
    data, assign = in_shapes
    batch, dim = data
    k = assign[1]
    cap = expert_capacity(p.n, k, batch, p.alpha)
    return [(cap, dim) for _ in range(p.n)]


def _group_by_forward(p: GroupByParams, inputs, weights, state, ctx):
    data, assign = inputs
    batch, dim = data.shape
    k = assign.shape[1]
    cap = expert_capacity(p.n, k, batch, p.alpha)
    slot, valid = _routing_slots(assign, p.n, cap)

    # scatter token rows into (n, cap, dim); dropped tokens land in a trash slot
    flat_assign = assign.reshape(-1).astype(jnp.int32)
    flat_slot = jnp.where(valid.reshape(-1), slot.reshape(-1), cap)
    token_rows = jnp.repeat(data, k, axis=0) if k > 1 else data
    buffers = jnp.zeros((p.n, cap + 1, dim), dtype=data.dtype)
    buffers = buffers.at[flat_assign, flat_slot].set(token_rows)
    outs = [buffers[e, :cap] for e in range(p.n)]
    return outs, state


register_op(
    OpDef(OT.OP_GROUP_BY, _group_by_infer, _group_by_forward, num_outputs=-1)
)


# ---------------------------------------------------------------- Aggregate

@dataclass(frozen=True)
class AggregateParams:
    n: int
    lambda_bal: float = 0.0


def _aggregate_infer(p: AggregateParams, in_shapes):
    # inputs: gate_preds (b,k), gate_assign (b,k), true_gate_assign (b,k),
    #         full_gate_grads (b,n), exp_pred_1..n (cap, out_dim)
    gate_preds = in_shapes[0]
    out_dim = in_shapes[4][1]
    return [(gate_preds[0], out_dim)]


def _aggregate_forward(p: AggregateParams, inputs, weights, state, ctx):
    gate_preds, gate_assign = inputs[0], inputs[1]
    exp_preds = jnp.stack(inputs[4 : 4 + p.n])  # (n, cap, dim)
    b, k = gate_assign.shape
    cap = exp_preds.shape[1]
    slot, valid = _routing_slots(gate_assign, p.n, cap)

    e_idx = gate_assign.astype(jnp.int32)  # (b, k)
    rows = exp_preds[e_idx, jnp.where(valid, slot, 0)]  # (b, k, dim)
    rows = jnp.where(valid[..., None], rows, 0.0)
    out = jnp.einsum("bk,bkd->bd", gate_preds.astype(rows.dtype), rows)

    if p.lambda_bal > 0.0:
        # load-balance auxiliary objective (reference injects the equivalent
        # gradient by hand in aggregate.cu backward): mean tokens-per-expert
        # × mean gate probability per expert, Shazeer-style.
        full_gate = inputs[3]  # (b, n) softmax over all experts
        counts = jnp.sum(
            jax.nn.one_hot(e_idx.reshape(-1), p.n, dtype=full_gate.dtype), axis=0
        )
        frac_tokens = counts / (b * k)
        frac_probs = jnp.mean(full_gate, axis=0)
        aux = p.n * jnp.sum(frac_tokens * frac_probs)
        state = dict(state or {})
        state["aux_loss"] = p.lambda_bal * aux
    return [out], state


register_op(OpDef(OT.OP_AGGREGATE, _aggregate_infer, _aggregate_forward))


# ---------------------------------------------------------------- AggregateSpec

@dataclass(frozen=True)
class AggregateSpecParams:
    n: int
    lambda_bal: float = 0.0


def _agg_spec_infer(p: AggregateSpecParams, in_shapes):
    # speculative variant: emits per-token-copy rows (k*b, dim) so each
    # expert's prediction is scored against (replicated) labels — see
    # model.cc:2875 replicating labels when last op is OP_AGG_SPEC
    gate_preds = in_shapes[0]
    out_dim = in_shapes[4][1]
    b, k = gate_preds
    return [(k * b, out_dim)]


def _agg_spec_forward(p: AggregateSpecParams, inputs, weights, state, ctx):
    gate_preds, gate_assign = inputs[0], inputs[1]
    exp_preds = jnp.stack(inputs[4 : 4 + p.n])
    b, k = gate_assign.shape
    cap = exp_preds.shape[1]
    slot, valid = _routing_slots(gate_assign, p.n, cap)
    e_idx = gate_assign.astype(jnp.int32)
    rows = exp_preds[e_idx, jnp.where(valid, slot, 0)]
    rows = jnp.where(valid[..., None], rows, 0.0)  # (b, k, dim)
    out = rows.transpose(1, 0, 2).reshape(k * b, -1)
    return [out], state


register_op(OpDef(OT.OP_AGG_SPEC, _agg_spec_infer, _agg_spec_forward))


# ---------------------------------------------------------------- Cache

@dataclass(frozen=True)
class CacheParams:
    num_batches: int
    data_type: DataType = DataType.DT_FLOAT


def _cache_infer(p: CacheParams, in_shapes):
    return [in_shapes[0]]


def _cache_weights(p: CacheParams, in_shapes):
    return [
        WeightSpec(
            "cached", in_shapes[0], p.data_type, "zeros", trainable=False
        )
    ]


def _cache_forward(p: CacheParams, inputs, weights, state, ctx):
    (x,) = inputs
    state = dict(state or {})
    if ctx.training:
        # training: pass through and refresh the cache (reference
        # cache_update task); staleness scoring is host-side via
        # RecompileState triggers.
        state["cached"] = x.astype(jnp.dtype(weights["cached"].dtype))
        return [x], state
    return [weights["cached"].astype(x.dtype)], state


register_op(
    OpDef(OT.OP_CACHE, _cache_infer, _cache_forward, _cache_weights)
)
