"""Mixture-of-Experts operators: Group_by, Aggregate, AggregateSpec, Cache.

Reference: src/ops/group_by.cc/.cu (token→expert scatter with capacity factor
alpha), src/ops/aggregate.cc/.cu (gate-weighted combine + load-balance term in
backward), src/ops/aggregate_spec.cc (speculative variant with replicated
labels), src/ops/cache.cc (cross-batch activation cache with staleness score,
include/flexflow/ops/cache.h:14-65).

TPU design notes:
- The reference's CUDA kernels do data-dependent scatter/gather. Under jit we
  need static shapes, so expert buffers are padded to the same
  `capacity = ceil(alpha * k * batch / n)` the reference uses — its alpha
  capacity factor exists for exactly this reason (static allocation).
- Token ranking within an expert is a cumsum over a one-hot routing matrix —
  all dense VPU math, no serialization; overflow tokens are dropped exactly
  like the reference (group_by.cu drops rows beyond expert capacity).
- Both Group_by and Aggregate derive slots from the same deterministic
  (sample-major) ordering so they agree without communicating, mirroring the
  reference pair.
- The load-balance gradient the reference injects in aggregate's backward
  (lambda_bal) is exposed here as an auxiliary loss accumulated into op state
  ("aux_loss"); the loss module adds it to the scalar objective so autodiff
  produces the same gate gradients.
- Expert parallelism = sharding the stacked expert dim over the `expert`/
  `model` mesh axis; the gather in aggregate then lowers to an all-to-all.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..fftype import DataType, OperatorType as OT
from .base import OpDef, WeightSpec, register_op


def expert_capacity(n: int, k: int, batch: int, alpha: float) -> int:
    return max(1, int(math.ceil(alpha * k * batch / n)))


def _routing_slots(assign, n: int, capacity: int):
    """assign: (batch, k) int expert ids → (slot, valid) each (batch, k).

    slot[i,j] = rank of token (i,j) among tokens routed to assign[i,j], in
    sample-major order; valid = rank < capacity."""
    b, k = assign.shape
    flat = assign.reshape(-1).astype(jnp.int32)  # (b*k,)
    onehot = jax.nn.one_hot(flat, n, dtype=jnp.int32)  # (b*k, n)
    ranks = jnp.cumsum(onehot, axis=0) - onehot  # rank among same-expert tokens
    slot = jnp.take_along_axis(ranks, flat[:, None], axis=1)[:, 0]
    valid = slot < capacity
    return slot.reshape(b, k), valid.reshape(b, k)




def _scatter_to_buffers(data, assign, n: int, cap: int, slot, valid):
    """Scatter token rows into stacked (n, cap, dim) expert buffers; dropped
    tokens land in a trash slot (group_by.cu semantics). Shared by Group_by
    and the fused Experts op so routing can never desynchronize."""
    k = assign.shape[1]
    flat_assign = assign.reshape(-1).astype(jnp.int32)
    flat_slot = jnp.where(valid.reshape(-1), slot.reshape(-1), cap)
    token_rows = jnp.repeat(data, k, axis=0) if k > 1 else data
    buffers = jnp.zeros((n, cap + 1, data.shape[1]), dtype=data.dtype)
    buffers = buffers.at[flat_assign, flat_slot].set(token_rows)
    return buffers[:, :cap]


def _gather_expert_rows(stacked, assign, slot, valid):
    """Gather each token's expert output row from stacked (n, cap, dim);
    dropped tokens read as zeros (aggregate.cu semantics). Returns
    (rows (b, k, dim), expert_idx (b, k))."""
    e_idx = assign.astype(jnp.int32)
    rows = stacked[e_idx, jnp.where(valid, slot, 0)]
    return jnp.where(valid[..., None], rows, 0.0), e_idx


# ---------------------------------------------------------------- Group_by

@dataclass(frozen=True)
class GroupByParams:
    n: int
    alpha: float


def _group_by_infer(p: GroupByParams, in_shapes):
    data, assign = in_shapes
    batch, dim = data
    k = assign[1]
    cap = expert_capacity(p.n, k, batch, p.alpha)
    return [(cap, dim) for _ in range(p.n)]


def _group_by_forward(p: GroupByParams, inputs, weights, state, ctx):
    data, assign = inputs
    batch, dim = data.shape
    k = assign.shape[1]
    cap = expert_capacity(p.n, k, batch, p.alpha)
    slot, valid = _routing_slots(assign, p.n, cap)
    buffers = _scatter_to_buffers(data, assign, p.n, cap, slot, valid)
    outs = [buffers[e] for e in range(p.n)]
    return outs, state


register_op(
    OpDef(OT.OP_GROUP_BY, _group_by_infer, _group_by_forward, num_outputs=-1)
)


# ---------------------------------------------------------------- Aggregate

@dataclass(frozen=True)
class AggregateParams:
    n: int
    lambda_bal: float = 0.0


def _aggregate_infer(p: AggregateParams, in_shapes):
    # inputs: gate_preds (b,k), gate_assign (b,k), true_gate_assign (b,k),
    #         full_gate_grads (b,n), exp_pred_1..n (cap, out_dim)
    gate_preds = in_shapes[0]
    out_dim = in_shapes[4][1]
    return [(gate_preds[0], out_dim)]


def _aggregate_forward(p: AggregateParams, inputs, weights, state, ctx):
    gate_preds, gate_assign = inputs[0], inputs[1]
    exp_preds = jnp.stack(inputs[4 : 4 + p.n])  # (n, cap, dim)
    b, k = gate_assign.shape
    cap = exp_preds.shape[1]
    slot, valid = _routing_slots(gate_assign, p.n, cap)
    rows, e_idx = _gather_expert_rows(exp_preds, gate_assign, slot, valid)
    out = jnp.einsum("bk,bkd->bd", gate_preds.astype(rows.dtype), rows)

    if p.lambda_bal > 0.0:
        # load-balance auxiliary objective (reference injects the equivalent
        # gradient by hand in aggregate.cu backward): mean tokens-per-expert
        # × mean gate probability per expert, Shazeer-style.
        full_gate = inputs[3]  # (b, n) softmax over all experts
        counts = jnp.sum(
            jax.nn.one_hot(e_idx.reshape(-1), p.n, dtype=full_gate.dtype), axis=0
        )
        frac_tokens = counts / (b * k)
        frac_probs = jnp.mean(full_gate, axis=0)
        aux = p.n * jnp.sum(frac_tokens * frac_probs)
        state = dict(state or {})
        state["aux_loss"] = p.lambda_bal * aux
    return [out], state


register_op(OpDef(OT.OP_AGGREGATE, _aggregate_infer, _aggregate_forward))


# ---------------------------------------------------------------- AggregateSpec

@dataclass(frozen=True)
class AggregateSpecParams:
    n: int
    lambda_bal: float = 0.0


def _agg_spec_infer(p: AggregateSpecParams, in_shapes):
    # speculative variant: emits per-token-copy rows (k*b, dim) so each
    # expert's prediction is scored against (replicated) labels — see
    # model.cc:2875 replicating labels when last op is OP_AGG_SPEC
    gate_preds = in_shapes[0]
    out_dim = in_shapes[4][1]
    b, k = gate_preds
    return [(k * b, out_dim)]


def _agg_spec_forward(p: AggregateSpecParams, inputs, weights, state, ctx):
    gate_preds, gate_assign = inputs[0], inputs[1]
    exp_preds = jnp.stack(inputs[4 : 4 + p.n])
    b, k = gate_assign.shape
    cap = exp_preds.shape[1]
    slot, valid = _routing_slots(gate_assign, p.n, cap)
    rows, _ = _gather_expert_rows(exp_preds, gate_assign, slot, valid)
    out = rows.transpose(1, 0, 2).reshape(k * b, -1)
    return [out], state


register_op(OpDef(OT.OP_AGG_SPEC, _agg_spec_infer, _agg_spec_forward))


# ---------------------------------------------------------------- Cache

@dataclass(frozen=True)
class CacheParams:
    num_batches: int
    data_type: DataType = DataType.DT_FLOAT


def _cache_infer(p: CacheParams, in_shapes):
    return [in_shapes[0]]


def _cache_weights(p: CacheParams, in_shapes):
    return [
        WeightSpec(
            "cached", in_shapes[0], p.data_type, "zeros", trainable=False
        ),
        # staleness score of the cached activation (cache.h:14-65's
        # score function, kept on-device as a non-trainable stat)
        WeightSpec("score", (), DataType.DT_FLOAT, "zeros",
                   trainable=False),
    ]


def cache_score(x, cached) -> jnp.ndarray:
    """Staleness of `cached` w.r.t. the live activation `x` — the
    reference's CacheScore (cache.h:14-65, cache.cu score kernel): relative
    moving difference, 0 = identical, →1 = fully drifted. RecompileState
    triggers read this to decide cache invalidation / re-optimization
    (moe.cc:180-204's experiment)."""
    xf = x.astype(jnp.float32)
    cf = cached.astype(jnp.float32)
    num = jnp.sum(jnp.abs(xf - cf))
    den = jnp.sum(jnp.abs(xf)) + 1e-8
    return jnp.minimum(num / den, 1.0)


def _cache_forward(p: CacheParams, inputs, weights, state, ctx):
    (x,) = inputs
    state = dict(state or {})
    if ctx.training:
        # training: score the previous cache against the live batch, then
        # pass through and refresh (reference cache_update task); the score
        # is exposed in op state for RecompileState triggers.
        state["score"] = cache_score(x, weights["cached"])
        state["cached"] = x.astype(jnp.dtype(weights["cached"].dtype))
        return [x], state
    return [weights["cached"].astype(x.dtype)], state


register_op(
    OpDef(OT.OP_CACHE, _cache_infer, _cache_forward, _cache_weights)
)


# ---------------------------------------------------------------- Experts
# TPU-native addition (no analog in the reference training snapshot): the
# group_by → per-expert dense → aggregate trio fused into ONE op over a
# *stacked* expert weight (n, in, hidden). Why: separate per-expert Dense
# layers can only be expert-parallelized by placing whole ops on different
# devices (the reference's attribute-parallel machine views); a stacked
# weight makes expert parallelism a plain sharding of dim 0 over the
# `expert` mesh axis, so GSPMD lowers the token exchange to all_to_all over
# ICI. Routing math (capacity, slot ranking, dropping) matches
# group_by.cu/aggregate.cu semantics exactly.

@dataclass(frozen=True)
class ExpertsParams:
    n: int
    hidden_size: int
    alpha: float = 1.0
    lambda_bal: float = 0.0
    use_bias: bool = True
    activation: str = "relu"  # relu | gelu | none


def _experts_infer(p: ExpertsParams, in_shapes):
    data = in_shapes[0]  # (b, d)
    return [(data[0], p.hidden_size)]


def _experts_weights(p: ExpertsParams, in_shapes):
    d = in_shapes[0][1]
    ws = [WeightSpec("kernel", (p.n, d, p.hidden_size), DataType.DT_FLOAT)]
    if p.use_bias:
        ws.append(
            WeightSpec("bias", (p.n, p.hidden_size), DataType.DT_FLOAT, "zeros")
        )
    return ws


def _experts_forward(p: ExpertsParams, inputs, weights, state, ctx):
    data, gate_values, gate_assign = inputs  # (b,d), (b,k), (b,k)
    b, d = data.shape
    k = gate_assign.shape[1]
    cap = expert_capacity(p.n, k, b, p.alpha)
    slot, valid = _routing_slots(gate_assign, p.n, cap)
    buffers = _scatter_to_buffers(data, gate_assign, p.n, cap, slot, valid)

    # stacked expert dense — one batched MXU matmul over all experts
    kern = weights["kernel"].astype(buffers.dtype)
    h = jnp.einsum("ncd,ndh->nch", buffers, kern)
    if p.use_bias:
        h = h + weights["bias"].astype(h.dtype)[:, None, :]
    if p.activation == "relu":
        h = jax.nn.relu(h)
    elif p.activation == "gelu":
        h = jax.nn.gelu(h)

    # gather back + gate-weighted combine (aggregate semantics)
    rows, e_idx = _gather_expert_rows(h, gate_assign, slot, valid)
    out = jnp.einsum("bk,bkh->bh", gate_values.astype(rows.dtype), rows)

    if p.lambda_bal > 0.0:
        counts = jnp.sum(
            jax.nn.one_hot(e_idx.reshape(-1), p.n, dtype=jnp.float32), axis=0
        )
        frac_tokens = counts / (b * k)
        # gate_values are the top-k probabilities; renormalize as proxy
        probs = jnp.zeros((b, p.n), jnp.float32)
        probs = probs.at[jnp.arange(b)[:, None], e_idx].set(
            gate_values.astype(jnp.float32)
        )
        frac_probs = jnp.mean(probs, axis=0)
        aux = p.n * jnp.sum(frac_tokens * frac_probs)
        state = dict(state or {})
        state["aux_loss"] = p.lambda_bal * aux
    return [out], state


def _experts_flops(p: ExpertsParams, in_shapes, out_shapes):
    b, d = in_shapes[0]
    k = in_shapes[2][1]
    cap = expert_capacity(p.n, k, b, p.alpha)
    return 2.0 * p.n * cap * d * p.hidden_size


register_op(
    OpDef(
        OT.OP_EXPERTS,
        _experts_infer,
        _experts_forward,
        _experts_weights,
        _experts_flops,
    )
)
