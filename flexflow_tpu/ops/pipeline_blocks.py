"""PipelineBlocks: L stacked pre-LN transformer blocks as ONE op.

TPU-native design (no analog in the reference, whose OP_PIPELINE is an
unimplemented enum — ffconst.h:159): stacking the repeated blocks' weights
on a leading layer dim makes pipeline parallelism a plain sharding of that
dim over the `pipe` mesh axis; the op's forward then runs the ppermute
fill/drain schedule of parallel/pipeline.py when the mesh has a pipe axis,
and the identical sequential scan otherwise — so a pipelined model shares
numerics with its single-chip build by construction. Each block is wrapped
in jax.checkpoint so in-flight microbatches hold O(1) activations per
stage (the memory property 1F1B-style schedules exist for)."""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..fftype import DataType, OperatorType as OT
from .base import OpDef, WeightSpec, register_op


@dataclass(frozen=True)
class PipelineBlocksParams:
    num_layers: int
    num_heads: int
    mlp_ratio: int = 4
    num_microbatches: int = 0  # 0 → 2 · pipe-axis size
    causal: bool = True
    attention_impl: str = "xla"  # xla | flash (ring needs the seq axis)


def _pb_infer(p: PipelineBlocksParams, in_shapes):
    return [in_shapes[0]]


def _pb_weights(p: PipelineBlocksParams, in_shapes):
    d = in_shapes[0][-1]
    h = p.mlp_ratio * d
    L = p.num_layers
    F = DataType.DT_FLOAT
    return [
        WeightSpec("ln1_scale", (L, d), F, "ones"),
        WeightSpec("ln1_bias", (L, d), F, "zeros"),
        WeightSpec("wqkv", (L, d, 3 * d), F),
        WeightSpec("wo", (L, d, d), F),
        WeightSpec("ln2_scale", (L, d), F, "ones"),
        WeightSpec("ln2_bias", (L, d), F, "zeros"),
        WeightSpec("w1", (L, d, h), F),
        WeightSpec("b1", (L, h), F, "zeros"),
        WeightSpec("w2", (L, h, d), F),
        WeightSpec("b2", (L, d), F, "zeros"),
    ]


def _ln(x, scale, bias):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + 1e-5)
    return (y * scale + bias).astype(x.dtype)


def _make_block_fn(num_heads: int, causal: bool, attention_impl: str):
    if attention_impl == "flash":
        from ..kernels.flash_attention import flash_attention_packed
    elif attention_impl == "xla":
        from .attention import sdpa_xla
    else:
        raise ValueError(
            f"PipelineBlocks supports attention_impl 'xla' or 'flash', "
            f"got {attention_impl!r} (ring attention needs the seq axis, "
            f"which the pipe schedule does not thread)")

    def block(w, x):  # w: one layer's weights; x: (mb, s, d)
        d = x.shape[-1]
        hd = d // num_heads

        a = _ln(x, w["ln1_scale"], w["ln1_bias"])
        qkv = a @ w["wqkv"].astype(a.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        if attention_impl == "flash":
            # packed layout: heads selected by the kernel's lane-offset
            # index maps — no head transpose relayout
            o = flash_attention_packed(q, k, v, num_heads=num_heads,
                                       causal=causal,
                                       scale=1.0 / math.sqrt(hd))
        else:
            def heads(t):
                b, s, _ = t.shape
                return t.reshape(b, s, num_heads, hd).transpose(0, 2, 1, 3)

            o = sdpa_xla(heads(q), heads(k), heads(v), causal=causal,
                         scale=1.0 / math.sqrt(hd))
            b, _, s, _ = o.shape
            o = o.transpose(0, 2, 1, 3).reshape(b, s, d)
        x = x + o @ w["wo"].astype(o.dtype)

        m = _ln(x, w["ln2_scale"], w["ln2_bias"])
        m = jax.nn.gelu(m @ w["w1"].astype(m.dtype)
                        + w["b1"].astype(m.dtype))
        m = m @ w["w2"].astype(m.dtype) + w["b2"].astype(m.dtype)
        return x + m

    # O(1) activations per in-flight microbatch: recompute inside bwd
    return jax.checkpoint(block)


def _pb_forward(p: PipelineBlocksParams, inputs, weights, state, ctx):
    from ..parallel.pipeline import pipeline_apply

    (x,) = inputs
    out = pipeline_apply(
        weights, x,
        _make_block_fn(p.num_heads, p.causal, p.attention_impl),
        mesh=ctx.mesh, num_microbatches=p.num_microbatches,
    )
    return [out], state


def _pb_flops(p: PipelineBlocksParams, in_shapes, out_shapes):
    b, s, d = in_shapes[0]
    per_layer = 2.0 * b * s * (4 * d * d + 2 * p.mlp_ratio * d * d)
    attn = 4.0 * b * p.num_heads * s * s * (d // p.num_heads)
    return p.num_layers * (per_layer + attn)


register_op(
    OpDef(OT.OP_PIPE_BLOCKS, _pb_infer, _pb_forward, _pb_weights, _pb_flops)
)
