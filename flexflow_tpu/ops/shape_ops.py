"""Shape/data-movement operators.

Reference: src/ops/{concat,split,reshape,transpose,flat,reverse,cast,gather,
reduce,mean,topk}.cc with custom CUDA kernels. On TPU every one of these is a
layout/copy HLO that XLA either elides (bitcast) or fuses; none need custom
kernels. Semantics (axis conventions, keepdims, torch.gather indexing) follow
the reference's Python API which presents NumPy dim order.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..fftype import DataType, OperatorType as OT, dtype_to_jnp
from .base import OpDef, register_op


# ---------------------------------------------------------------- Concat

@dataclass(frozen=True)
class ConcatParams:
    axis: int
    n: int = 2


def _concat_infer(p: ConcatParams, in_shapes):
    base = list(in_shapes[0])
    ax = p.axis % len(base)
    base[ax] = sum(s[ax] for s in in_shapes)
    return [tuple(base)]


def _concat_forward(p, inputs, weights, state, ctx):
    return [jnp.concatenate(inputs, axis=p.axis)], state


register_op(OpDef(OT.OP_CONCAT, _concat_infer, _concat_forward))


# ---------------------------------------------------------------- Split

@dataclass(frozen=True)
class SplitParams:
    sizes: tuple[int, ...]
    axis: int


def _split_infer(p: SplitParams, in_shapes):
    base = in_shapes[0]
    ax = p.axis % len(base)
    outs = []
    for sz in p.sizes:
        s = list(base)
        s[ax] = sz
        outs.append(tuple(s))
    return outs


def _split_forward(p: SplitParams, inputs, weights, state, ctx):
    (x,) = inputs
    ax = p.axis % x.ndim
    offsets = [0]
    for sz in p.sizes:
        offsets.append(offsets[-1] + sz)
    outs = [
        jax.lax.slice_in_dim(x, offsets[i], offsets[i + 1], axis=ax)
        for i in range(len(p.sizes))
    ]
    return outs, state


register_op(
    OpDef(OT.OP_SPLIT, _split_infer, _split_forward, num_outputs=-1)
)


# ---------------------------------------------------------------- Reshape

@dataclass(frozen=True)
class ReshapeParams:
    shape: tuple[int, ...]


def _reshape_infer(p: ReshapeParams, in_shapes):
    n_in = math.prod(in_shapes[0])
    if math.prod(p.shape) != n_in:
        raise ValueError(f"cannot reshape {in_shapes[0]} to {p.shape}")
    return [tuple(p.shape)]


def _reshape_forward(p, inputs, weights, state, ctx):
    return [inputs[0].reshape(p.shape)], state


register_op(OpDef(OT.OP_RESHAPE, _reshape_infer, _reshape_forward))


# ---------------------------------------------------------------- Transpose

@dataclass(frozen=True)
class TransposeParams:
    perm: tuple[int, ...]


def _transpose_infer(p: TransposeParams, in_shapes):
    x = in_shapes[0]
    return [tuple(x[i] for i in p.perm)]


def _transpose_forward(p, inputs, weights, state, ctx):
    return [jnp.transpose(inputs[0], p.perm)], state


register_op(OpDef(OT.OP_TRANSPOSE, _transpose_infer, _transpose_forward))


# ---------------------------------------------------------------- Reverse

@dataclass(frozen=True)
class ReverseParams:
    axis: int


def _reverse_infer(p, in_shapes):
    return [in_shapes[0]]


def _reverse_forward(p, inputs, weights, state, ctx):
    return [jnp.flip(inputs[0], axis=p.axis)], state


register_op(OpDef(OT.OP_REVERSE, _reverse_infer, _reverse_forward))


# ---------------------------------------------------------------- Cast

@dataclass(frozen=True)
class CastParams:
    dtype: DataType


def _cast_infer(p, in_shapes):
    return [in_shapes[0]]


def _cast_forward(p: CastParams, inputs, weights, state, ctx):
    return [inputs[0].astype(dtype_to_jnp(p.dtype))], state


register_op(OpDef(OT.OP_CAST, _cast_infer, _cast_forward))


# ---------------------------------------------------------------- Gather

@dataclass(frozen=True)
class GatherParams:
    dim: int


def _gather_infer(p: GatherParams, in_shapes):
    return [in_shapes[1]]  # index shape (torch.gather semantics)


def _gather_forward(p: GatherParams, inputs, weights, state, ctx):
    x, index = inputs
    return [jnp.take_along_axis(x, index.astype(jnp.int32), axis=p.dim)], state


register_op(OpDef(OT.OP_GATHER, _gather_infer, _gather_forward))


# ---------------------------------------------------------------- Reduce / Mean

@dataclass(frozen=True)
class ReduceParams:
    op_type: OT
    axes: tuple[int, ...]
    keepdims: bool = False


_REDUCE_FNS = {
    OT.OP_REDUCE_SUM: jnp.sum,
    OT.OP_REDUCE_MEAN: jnp.mean,
    OT.OP_REDUCE_MAX: jnp.max,
    OT.OP_REDUCE_MIN: jnp.min,
    OT.OP_REDUCE_PROD: jnp.prod,
    OT.OP_MEAN: jnp.mean,
}


def _reduce_infer(p: ReduceParams, in_shapes):
    x = list(in_shapes[0])
    axes = sorted(a % len(x) for a in p.axes)
    if p.keepdims:
        for a in axes:
            x[a] = 1
        return [tuple(x)]
    return [tuple(s for i, s in enumerate(x) if i not in axes)]


def _reduce_forward(p: ReduceParams, inputs, weights, state, ctx):
    fn = _REDUCE_FNS[p.op_type]
    return [fn(inputs[0], axis=tuple(p.axes), keepdims=p.keepdims)], state


for _ot in _REDUCE_FNS:
    register_op(OpDef(_ot, _reduce_infer, _reduce_forward))


# ---------------------------------------------------------------- TopK

@dataclass(frozen=True)
class TopKParams:
    k: int
    sorted: bool = True


def _topk_infer(p: TopKParams, in_shapes):
    x = list(in_shapes[0])
    x[-1] = p.k
    return [tuple(x), tuple(x)]


def _topk_forward(p: TopKParams, inputs, weights, state, ctx):
    values, indices = jax.lax.top_k(inputs[0], p.k)
    return [values, indices], state


register_op(OpDef(OT.OP_TOPK, _topk_infer, _topk_forward, num_outputs=2))
