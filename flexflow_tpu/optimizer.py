"""Optimizers: SGD (momentum/nesterov/weight-decay) and Adam.

Reference: include/flexflow/optimizer.h:27-118 + src/runtime/optimizer.cc +
optimizer_kernel.cu. The reference maintains two sync paths — PS (gather to
replica 0, update, broadcast) and NCCL (per-shard ncclAllReduce + local
update). On TPU both collapse into one: gradients produced by jit are already
reduced across data-parallel replicas by GSPMD (the psum is inserted where the
batch-sharded loss meets replicated weights — the exact role of
`ncclAllReduce` in optimizer_kernel.cu:88), and the update below runs sharded
element-wise on whatever sharding each parameter carries. Optimizer slots
(momentum `v`, Adam `m`) inherit the parameter's sharding, giving ZeRO-style
sharded optimizer state for free whenever parameters are sharded.

Under weight-update sharding (--weight-update-sharding, or Unity's
choose_update_sharding deciding the plan is memory- or grad-sync-bound) the
executor additionally pins grads / fp32 masters / slots of data-parallel
weights to a 1/dp layout along the gradient-reduction axes before and after
`update`, so the replicated-weight psum above lowers to an overlappable
reduce-scatter, these updates run on each replica's shard only, and the
updated-param all-gather is deferred into each consumer's first use next
step (ZeRO, Rajbhandari et al. SC'20; Xu et al. 2020). The optimizers here
need no change for that: `update` is element-wise over pytree leaves, so it
is bit-identical whichever slice of the reduced gradient a replica owns —
exactly why the sharded and replicated trajectories match bit-for-bit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp


class Optimizer:
    """Pytree-functional optimizer. `init(params)` → slots, `update(grads,
    params, slots, step)` → (new_params, new_slots)."""

    @property
    def num_slots(self) -> int:
        """Optimizer state entries per weight (for the search's memory
        model): SGD momentum 1 (0 without momentum), Adam 2."""
        return 1

    def init(self, params):
        raise NotImplementedError

    def update(self, grads, params, slots, step):
        raise NotImplementedError

    def next(self):
        """Per-iteration hook (reference Optimizer::next used by Adam to fold
        beta^t factors); stateless here since `step` is threaded in-jit."""

    def set_learning_rate(self, lr: float):
        """Change the learning rate (reference SGDOptimizer/AdamOptimizer
        set_learning_rate — keras LearningRateScheduler's hook). The new
        value takes effect at the next train-step (re)build: the rate is a
        compile-time constant of the jitted step, so the executor drops its
        cached executable when this changes (FFModel.set_learning_rate)."""
        if not hasattr(self, "lr"):
            raise ValueError('Optimizer must have a "lr" attribute.')
        self.lr = float(lr)


@dataclass
class SGDOptimizer(Optimizer):
    lr: float = 0.01
    momentum: float = 0.0
    nesterov: bool = False
    weight_decay: float = 0.0

    @property
    def num_slots(self) -> int:
        return 1 if self.momentum > 0.0 else 0

    def init(self, params):
        if self.momentum == 0.0:
            return {"v": jax.tree.map(lambda p: jnp.zeros((), p.dtype), params)}
        return {"v": jax.tree.map(jnp.zeros_like, params)}

    def update(self, grads, params, slots, step):
        def upd(g, p, v):
            g = g + self.weight_decay * p
            if self.momentum > 0.0:
                v = self.momentum * v + g
                g = g + self.momentum * v if self.nesterov else v
            return p - self.lr * g, v

        flat = jax.tree.map(upd, grads, params, slots["v"])
        new_params = jax.tree.map(lambda t: t[0], flat, is_leaf=lambda t: isinstance(t, tuple))
        new_v = jax.tree.map(lambda t: t[1], flat, is_leaf=lambda t: isinstance(t, tuple))
        return new_params, {"v": new_v}


@dataclass
class AdamOptimizer(Optimizer):
    alpha: float = 0.001
    beta1: float = 0.9
    beta2: float = 0.999
    weight_decay: float = 0.0
    epsilon: float = 1e-8

    @property
    def num_slots(self) -> int:
        return 2  # m and v

    @property
    def lr(self) -> float:
        """Keras-facing alias (reference AdamOptimizer exposes alpha as the
        scheduler-settable rate)."""
        return self.alpha

    @lr.setter
    def lr(self, value: float):
        self.alpha = float(value)

    def init(self, params):
        return {
            "m": jax.tree.map(jnp.zeros_like, params),
            "v": jax.tree.map(jnp.zeros_like, params),
        }

    def update(self, grads, params, slots, step):
        # bias-corrected step size, matching adam_update in
        # optimizer_kernel.cu:186-220 (alpha_t folded per iteration)
        t = step.astype(jnp.float32) + 1.0
        alpha_t = self.alpha * jnp.sqrt(1.0 - self.beta2**t) / (1.0 - self.beta1**t)

        def upd(g, p, m, v):
            g = g + self.weight_decay * p
            m = self.beta1 * m + (1.0 - self.beta1) * g
            v = self.beta2 * v + (1.0 - self.beta2) * g * g
            p = p - alpha_t * m / (jnp.sqrt(v) + self.epsilon)
            return p, m, v

        flat = jax.tree.map(upd, grads, params, slots["m"], slots["v"])
        is_tup = lambda t: isinstance(t, tuple)
        return (
            jax.tree.map(lambda t: t[0], flat, is_leaf=is_tup),
            {
                "m": jax.tree.map(lambda t: t[1], flat, is_leaf=is_tup),
                "v": jax.tree.map(lambda t: t[2], flat, is_leaf=is_tup),
            },
        )
