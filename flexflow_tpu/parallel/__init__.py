"""Parallelization package: parallel operators, strategies, ring attention.

The reference keeps parallelism first-class as PCG operators
(src/parallel_ops/*, SURVEY §2.3); here the same four ops exist as IR nodes
whose runtime lowering is GSPMD sharding constraints (collectives over ICI
inserted by XLA), and strategies are per-node mesh-axis assignments.
"""

from .ops import (
    CombineParams,
    FusedParallelOpParams,
    ParallelOpInfo,
    PipelineParams,
    ReductionParams,
    RepartitionParams,
    ReplicateParams,
    allgather_matmul,
    apply_parallel_op_shape,
)
from .strategies import (
    Strategy,
    expert_parallel_moe,
    megatron_transformer,
    sequence_parallel_attention,
)
