"""Parallelization operators: Repartition, Combine, Replicate, Reduction,
FusedParallelOp, Pipeline.

Reference: src/parallel_ops/{partition,combine,replicate,reduction,
fused_parallel_op}.cc — each is a PCG node that changes a tensor's
parallelization state (per-dim degree / replica dims) and whose execution is
data movement (Legion partition copies, SURVEY §2.3).

TPU-native lowering: the *runtime* body of every parallel op is the identity —
the executor pins each node's output with `with_sharding_constraint`, so the
degree change becomes an XLA collective over ICI exactly where the reference
would launch a partition-copy task:

  Repartition (degree up on dim d)  → resharding: dynamic-slice / all_to_all
  Combine     (degree down on dim d)→ all_gather along the freed mesh axis
  Replicate   (new replica dim)     → broadcast (implicit in GSPMD)
  Reduction   (drop replica dim)    → psum / reduce_scatter (inserted by XLA
                                      when the producer's contraction was
                                      sharded over the reduced axis)

The *IR-level* shape transform (apply_parallel_op_shape) is what Unity search
rewrites operate on, and the cost model charges the communication bytes these
transforms imply (see search/cost_model.py).

The reference leaves OP_PIPELINE as an enum with no implementation
(ffconst.h:159, SURVEY §2.3); here PipelineParams is likewise a stage
MARKER only (runtime identity — enum parity). Working pipeline parallelism
lives in the OP_PIPE_BLOCKS op instead: stacked homogeneous blocks whose
layer dim shards over the `pipe` mesh axis, scheduled as a
`jax.lax.ppermute` fill/drain microbatch pipeline (parallel/pipeline.py) —
the capability the reference never implemented.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Tuple

from ..fftype import OperatorType as OT
from ..tensor import ParallelDim, ParallelTensorShape
from ..ops.base import OpDef, register_op


@dataclass(frozen=True)
class RepartitionParams:
    """Increase partition degree along `dim` by `degree`×
    (partition.cc:132 create_input_partition). `axes` optionally names the
    mesh axes the new degree rides (their size product must equal
    `degree`) — the MachineView device binding; empty = inferred from the
    degree at assignment time."""

    dim: int
    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class CombineParams:
    """Decrease partition degree along `dim` by `degree`× (combine.cc:135).
    `axes` optionally names the mesh axes being freed."""

    dim: int
    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ReplicateParams:
    """Add a replica dim of extent `degree` (replicate.cc). `axes`
    optionally names the mesh axes the replicas map onto."""

    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ReductionParams:
    """Sum-reduce a replica dim of extent `degree` (reduction.cc: forward
    kernel sums num_replicas slices — here XLA's psum). `axes` optionally
    names the mesh axes summed over."""

    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class PipelineParams:
    """Stage boundary marker. OP_PIPELINE is enum-only in the reference."""

    stage: int = 0


@dataclass(frozen=True)
class ParallelOpInfo:
    op_type: OT
    dim: int
    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class FusedParallelOpParams:
    """Sequence of parallel transforms fused into one resharding
    (fused_parallel_op.cc)."""

    ops: Tuple[ParallelOpInfo, ...]


def apply_parallel_op_shape(
    shape: ParallelTensorShape, op_type: OT, params
) -> ParallelTensorShape:
    """IR shape transform for one parallel op (search rewrites use this)."""
    dims = list(shape.dims)
    axes = getattr(params, "axes", ())
    if op_type == OT.OP_REPARTITION:
        d = dims[params.dim]
        dims[params.dim] = replace(d, degree=d.degree * params.degree,
                                   axes=d.axes + tuple(axes))
    elif op_type == OT.OP_COMBINE:
        d = dims[params.dim]
        if d.degree % params.degree != 0:
            raise ValueError(
                f"combine degree {params.degree} does not divide {d.degree}"
            )
        new_axes = d.axes
        if axes and new_axes[-len(axes):] == tuple(axes):
            new_axes = new_axes[:-len(axes)]
        elif d.degree // params.degree == 1:
            new_axes = ()
        dims[params.dim] = replace(d, degree=d.degree // params.degree,
                                   axes=new_axes)
    elif op_type == OT.OP_REPLICATE:
        dims.append(
            ParallelDim(
                size=params.degree, degree=params.degree,
                is_replica_dim=True, axes=tuple(axes)
            )
        )
    elif op_type == OT.OP_REDUCTION:
        for i in range(len(dims) - 1, -1, -1):
            if dims[i].is_replica_dim:
                if dims[i].degree != params.degree:
                    raise ValueError(
                        f"reduction degree {params.degree} != replica degree "
                        f"{dims[i].degree}"
                    )
                dims.pop(i)
                break
        else:
            raise ValueError("reduction with no replica dim")
    elif op_type == OT.OP_FUSED_PARALLEL:
        s = shape
        for info in params.ops:
            sub = _INFO_PARAMS[info.op_type](info)
            s = apply_parallel_op_shape(s, info.op_type, sub)
        return s
    elif op_type == OT.OP_PIPELINE:
        pass
    else:
        raise ValueError(f"not a parallel op: {op_type}")
    return ParallelTensorShape(tuple(dims), shape.dtype)


_INFO_PARAMS = {
    OT.OP_REPARTITION: lambda i: RepartitionParams(i.dim, i.degree, i.axes),
    OT.OP_COMBINE: lambda i: CombineParams(i.dim, i.degree, i.axes),
    OT.OP_REPLICATE: lambda i: ReplicateParams(i.degree, i.axes),
    OT.OP_REDUCTION: lambda i: ReductionParams(i.degree, i.axes),
}


def _identity_infer(params, in_shapes):
    return [in_shapes[0]]


def _identity_forward(params, inputs, weights, state, ctx):
    # Runtime body is the identity: the executor's sharding constraint on the
    # node's output performs the actual resharding (ICI collective).
    return [inputs[0]], state


def _zero_flops(params, in_shapes, out_shapes):
    return 0.0


for _ot in (
    OT.OP_REPARTITION,
    OT.OP_COMBINE,
    OT.OP_REPLICATE,
    OT.OP_REDUCTION,
    OT.OP_PIPELINE,
    OT.OP_FUSED_PARALLEL,
):
    register_op(
        OpDef(_ot, _identity_infer, _identity_forward, flops=_zero_flops)
    )


def derive_parallel_assignment(op_type: OT, params, in_assignment, mesh):
    """Mesh-axis assignment for an explicit parallel-op node's output, derived
    from its input's assignment (the runtime half of the op: the executor pins
    the output with this spec, producing the resharding collective).

    Repartition picks the first mesh axis whose size equals the requested
    degree and which the tensor doesn't already use — the analog of the
    mapper choosing fresh devices for a higher-degree machine view."""
    a = [list(x) for x in in_assignment]
    declared = tuple(getattr(params, "axes", ()))
    if op_type == OT.OP_REPARTITION:
        if declared:
            # the rewrite named its axes (MachineView binding): use them —
            # but a mesh axis may shard a tensor at most once (same check
            # as the inference path's "unused axis" scan)
            used = {ax for entry in a for ax in entry}
            dup = used.intersection(declared)
            if dup or len(set(declared)) != len(declared):
                raise ValueError(
                    f"repartition(axes={declared}): axes already sharding "
                    f"this tensor ({sorted(used)})")
            a[params.dim].extend(declared)
        else:
            used = {ax for entry in a for ax in entry}
            for name, size in mesh.shape.items():
                if size == params.degree and name not in used:
                    a[params.dim].append(name)
                    break
            else:
                raise ValueError(
                    f"repartition(degree={params.degree}): no unused mesh "
                    f"axis of that size in {dict(mesh.shape)}"
                )
    elif op_type == OT.OP_COMBINE:
        if declared and a[params.dim][-len(declared):] == list(declared):
            del a[params.dim][-len(declared):]
        else:
            removed = 1
            while removed < params.degree and a[params.dim]:
                removed *= mesh.shape[a[params.dim].pop()]
            if removed != params.degree:
                raise ValueError(
                    f"combine(degree={params.degree}) cannot unshard "
                    f"assignment {in_assignment[params.dim]} over "
                    f"{dict(mesh.shape)}"
                )
    elif op_type == OT.OP_FUSED_PARALLEL:
        cur = tuple(tuple(x) for x in a)
        for info in params.ops:
            sub = _INFO_PARAMS.get(info.op_type)
            if sub is not None:
                cur = derive_parallel_assignment(
                    info.op_type, sub(info), cur, mesh
                )
        return cur
    # Replicate / Reduction / Pipeline: replication and partial-sum state are
    # implicit under GSPMD; the assignment passes through unchanged.
    return tuple(tuple(x) for x in a)
