"""Parallelization operators: Repartition, Combine, Replicate, Reduction,
FusedParallelOp, Pipeline.

Reference: src/parallel_ops/{partition,combine,replicate,reduction,
fused_parallel_op}.cc — each is a PCG node that changes a tensor's
parallelization state (per-dim degree / replica dims) and whose execution is
data movement (Legion partition copies, SURVEY §2.3).

TPU-native lowering: the *runtime* body of every parallel op is the identity —
the executor pins each node's output with `with_sharding_constraint`, so the
degree change becomes an XLA collective over ICI exactly where the reference
would launch a partition-copy task:

  Repartition (degree up on dim d)  → resharding: dynamic-slice / all_to_all
  Combine     (degree down on dim d)→ all_gather along the freed mesh axis
  Replicate   (new replica dim)     → broadcast (implicit in GSPMD)
  Reduction   (drop replica dim)    → psum / reduce_scatter (inserted by XLA
                                      when the producer's contraction was
                                      sharded over the reduced axis)

The *IR-level* shape transform (apply_parallel_op_shape) is what Unity search
rewrites operate on, and the cost model charges the communication bytes these
transforms imply (see search/cost_model.py).

The reference leaves OP_PIPELINE as an enum with no implementation
(ffconst.h:159, SURVEY §2.3); here PipelineParams is likewise a stage
MARKER only (runtime identity — enum parity). Working pipeline parallelism
lives in the OP_PIPE_BLOCKS op instead: stacked homogeneous blocks whose
layer dim shards over the `pipe` mesh axis, scheduled as a
`jax.lax.ppermute` fill/drain microbatch pipeline (parallel/pipeline.py) —
the capability the reference never implemented.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Tuple

from ..fftype import OperatorType as OT
from ..tensor import ParallelDim, ParallelTensorShape
from ..ops.base import OpDef, register_op


@dataclass(frozen=True)
class RepartitionParams:
    """Increase partition degree along `dim` by `degree`×
    (partition.cc:132 create_input_partition). `axes` optionally names the
    mesh axes the new degree rides (their size product must equal
    `degree`) — the MachineView device binding; empty = inferred from the
    degree at assignment time."""

    dim: int
    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class CombineParams:
    """Decrease partition degree along `dim` by `degree`× (combine.cc:135).
    `axes` optionally names the mesh axes being freed."""

    dim: int
    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ReplicateParams:
    """Add a replica dim of extent `degree` (replicate.cc). `axes`
    optionally names the mesh axes the replicas map onto."""

    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ReductionParams:
    """Sum-reduce a replica dim of extent `degree` (reduction.cc: forward
    kernel sums num_replicas slices — here XLA's psum). `axes` optionally
    names the mesh axes summed over."""

    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class PipelineParams:
    """Stage boundary marker. OP_PIPELINE is enum-only in the reference."""

    stage: int = 0


@dataclass(frozen=True)
class ParallelOpInfo:
    op_type: OT
    dim: int
    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class FusedParallelOpParams:
    """Sequence of parallel transforms fused into one resharding
    (fused_parallel_op.cc)."""

    ops: Tuple[ParallelOpInfo, ...]


def apply_parallel_op_shape(
    shape: ParallelTensorShape, op_type: OT, params
) -> ParallelTensorShape:
    """IR shape transform for one parallel op (search rewrites use this)."""
    dims = list(shape.dims)
    axes = getattr(params, "axes", ())
    if op_type == OT.OP_REPARTITION:
        d = dims[params.dim]
        dims[params.dim] = replace(d, degree=d.degree * params.degree,
                                   axes=d.axes + tuple(axes))
    elif op_type == OT.OP_COMBINE:
        d = dims[params.dim]
        if d.degree % params.degree != 0:
            raise ValueError(
                f"combine degree {params.degree} does not divide {d.degree}"
            )
        new_axes = d.axes
        if axes and new_axes[-len(axes):] == tuple(axes):
            new_axes = new_axes[:-len(axes)]
        elif d.degree // params.degree == 1:
            new_axes = ()
        dims[params.dim] = replace(d, degree=d.degree // params.degree,
                                   axes=new_axes)
    elif op_type == OT.OP_REPLICATE:
        dims.append(
            ParallelDim(
                size=params.degree, degree=params.degree,
                is_replica_dim=True, axes=tuple(axes)
            )
        )
    elif op_type == OT.OP_REDUCTION:
        for i in range(len(dims) - 1, -1, -1):
            if dims[i].is_replica_dim:
                if dims[i].degree != params.degree:
                    raise ValueError(
                        f"reduction degree {params.degree} != replica degree "
                        f"{dims[i].degree}"
                    )
                dims.pop(i)
                break
        else:
            raise ValueError("reduction with no replica dim")
    elif op_type == OT.OP_FUSED_PARALLEL:
        s = shape
        for info in params.ops:
            sub = _INFO_PARAMS[info.op_type](info)
            s = apply_parallel_op_shape(s, info.op_type, sub)
        return s
    elif op_type == OT.OP_PIPELINE:
        pass
    else:
        raise ValueError(f"not a parallel op: {op_type}")
    return ParallelTensorShape(tuple(dims), shape.dtype)


_INFO_PARAMS = {
    OT.OP_REPARTITION: lambda i: RepartitionParams(i.dim, i.degree, i.axes),
    OT.OP_COMBINE: lambda i: CombineParams(i.dim, i.degree, i.axes),
    OT.OP_REPLICATE: lambda i: ReplicateParams(i.degree, i.axes),
    OT.OP_REDUCTION: lambda i: ReductionParams(i.degree, i.axes),
}


def _identity_infer(params, in_shapes):
    return [in_shapes[0]]


def _identity_forward(params, inputs, weights, state, ctx):
    # Runtime body is the identity: the executor's sharding constraint on the
    # node's output performs the actual resharding (ICI collective).
    return [inputs[0]], state


def _zero_flops(params, in_shapes, out_shapes):
    return 0.0


for _ot in (
    OT.OP_REPARTITION,
    OT.OP_COMBINE,
    OT.OP_REPLICATE,
    OT.OP_REDUCTION,
    OT.OP_PIPELINE,
    OT.OP_FUSED_PARALLEL,
):
    register_op(
        OpDef(_ot, _identity_infer, _identity_forward, flops=_zero_flops)
    )


def ring_permutation(n: int) -> list:
    """THE ring-rotation schedule: shard i sends to (i+1) mod n — a
    complete bijection on range(n). Every ring body (ring attention's KV
    rotation, the decomposed allgather-matmul, the ring reduce-scatter,
    the ppermute hop calibrator) builds its ppermute permutation through
    this ONE helper, and the ffcheck collective-uniformity pass
    (analysis/collectives.py) validates exactly this function's output
    for every ring the plan will run — a partial or duplicated
    permutation would make ppermute zero-fill the missing destinations
    and silently corrupt the ring. (The pipeline fill/drain shift in
    parallel/pipeline.py is deliberately NOT a ring and does not use
    this.)"""
    return [(i, (i + 1) % n) for i in range(n)]


# ------------------------------------------------- decomposed collective matmul
# The async/overlapped twin of the tp all_gather→matmul pairs GSPMD inserts
# when a feature-sharded activation feeds an op expecting the full feature
# dim (tp_col after a feat/sp producer, the attention O-projection after a
# head-sharded core). Instead of one blocking all_gather followed by one
# big matmul, the gather is DECOMPOSED into n−1 neighbor hops each
# overlapped with the partial matmul of the block already resident (Wang
# et al., ASPLOS '23 — the same double-buffered ppermute schedule as
# parallel/ring_attention.py): while x's block k rotates to the neighbor,
# the local MXU contracts block k against the matching rows of w. Exact:
# after n steps every shard has accumulated Σ_src x_src @ w[src rows] =
# (all_gather(x) @ w), with the collective entirely hidden behind compute
# when the per-block matmul dominates the hop (the long-seq regime).


def _ag_matmul_local(x_blk, w, *, axis_name: str, n: int, overlap: bool):
    """Per-shard body: x_blk (..., k/n) is this shard's block of the
    contraction dim; w (k, m) holds all rows locally. Rotate x blocks
    around the ring, contracting each against its source's row slice."""
    import jax
    import jax.numpy as jnp

    idx = jax.lax.axis_index(axis_name)
    k_loc = x_blk.shape[-1]
    acc = jnp.zeros(x_blk.shape[:-1] + (w.shape[-1],), jnp.float32)
    perm = ring_permutation(n)
    for step in range(n):
        x_nxt = None
        if overlap and step < n - 1:
            # hop for block step+1 issued BEFORE the matmul of block step
            x_nxt = jax.lax.ppermute(x_blk, axis_name, perm)
        # the block held at `step` originated on shard (idx - step) mod n;
        # contract it against that shard's rows of w
        src = jax.lax.rem(idx - step + n, n)
        w_rows = jax.lax.dynamic_slice_in_dim(w, src * k_loc, k_loc, axis=0)
        acc = acc + jnp.dot(x_blk, w_rows.astype(x_blk.dtype),
                            preferred_element_type=jnp.float32)
        if step < n - 1:
            if not overlap:
                x_nxt = jax.lax.ppermute(x_blk, axis_name, perm)
            x_blk = x_nxt
    return acc.astype(x_blk.dtype)


def allgather_matmul(x, w, *, mesh=None, axis_name: str | None = None,
                     batch_axis: str | None = None, overlap: bool = True):
    """Decomposed all_gather→matmul: `x` (..., k) with its last dim sharded
    over `axis_name`, `w` (k, m) replicated along that axis; returns the
    full x @ w (replicated over `axis_name`, batch sharding preserved) —
    numerically the gathered matmul, scheduled as n overlapped
    block-matmul + ppermute steps. Falls back to a plain dot when there is
    no mesh / the axis has size 1. `overlap=False` is the serial ablation
    baseline (hop after each block's matmul)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..machine import AXIS_DATA, AXIS_MODEL
    from .smap import shard_map

    axis_name = axis_name or AXIS_MODEL
    batch_axis = batch_axis or AXIS_DATA
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        return jnp.dot(x, w.astype(x.dtype),
                       preferred_element_type=jnp.float32).astype(x.dtype)
    n = mesh.shape[axis_name]
    if x.shape[-1] % n != 0:
        raise ValueError(
            f"allgather_matmul: contraction dim {x.shape[-1]} not "
            f"divisible by axis {axis_name!r} size {n}")
    import functools

    nd = x.ndim
    b_entry = batch_axis if mesh.shape.get(batch_axis, 1) > 1 else None
    xspec = P(b_entry, *([None] * (nd - 2)), axis_name)
    ospec = P(b_entry, *([None] * (nd - 1)))
    fn = shard_map(
        functools.partial(_ag_matmul_local, axis_name=axis_name, n=n,
                          overlap=overlap),
        mesh=mesh,
        in_specs=(xspec, P(None, None)),
        out_specs=ospec,
        check_vma=False,
    )
    return fn(x, w)


# ------------------------------------------------- weight-update sharding
# ZeRO (Rajbhandari et al., SC '20) / TPU weight-update sharding (Xu et
# al., 2020): every data-parallel replica redundantly stores fp32 masters
# + optimizer slots and redundantly runs the identical update. Sharding
# the update 1/dp along the gradient-reduction axes keeps the math
# bit-identical (the same reduced gradient elements feed the same
# element-wise update — each replica just owns a slice) while optimizer
# state shrinks by the replica count and the grad all-reduce splits into
# an overlappable reduce-scatter + a deferred all-gather. The helpers
# below are the ONE shared definition of "which dim shards over which
# axes" — the executor's placement, the cost model's memory/comm pricing,
# and the tests all resolve through them so runtime and search cannot
# disagree.


def choose_update_dim(shape, assignment, axes, axis_sizes) -> Optional[int]:
    """The dim of a weight `shape` to shard for the ZeRO-style update, or
    None when no dim is shardable. `assignment` is the weight's existing
    per-dim axis assignment (tuples of mesh-axis names), `axes` the update
    axes (the axes the gradient is reduced over). Picks the FIRST dim
    whose size divides by (existing degree × update degree) — first, not
    largest, so the choice is a deterministic function of the spec alone.
    Weights already sharded over any update axis are skipped (their
    optimizer state is already distributed along it)."""
    deg = 1
    for ax in axes:
        deg *= axis_sizes.get(ax, 1)
    if deg <= 1:
        return None
    used = {ax for entry in (assignment or ()) for ax in entry}
    if used.intersection(axes):
        return None
    for i, size in enumerate(shape):
        have = 1
        if assignment and i < len(assignment):
            for ax in assignment[i]:
                have *= axis_sizes.get(ax, 1)
        if size % (have * deg) == 0:
            return i
    return None


def grad_sync_axes(out_axes, weight_axes) -> Tuple[str, ...]:
    """The mesh axes a trainable weight's gradient is reduced over: every
    axis its consumers' activations shard that the weight itself does not
    (the axes the NCCL allreduce of optimizer_kernel.cu:78-110 spans) —
    sorted, so executor placement and cost-model pricing compose the same
    PartitionSpec entry."""
    return tuple(sorted(set(out_axes) - set(weight_axes)))


def _spec_assignment(spec, ndim):
    """PartitionSpec (or None) → per-dim axis tuples."""
    entries = []
    for i in range(ndim):
        e = spec[i] if spec is not None and i < len(spec) else None
        if e is None:
            entries.append(())
        elif isinstance(e, (tuple, list)):
            entries.append(tuple(e))
        else:
            entries.append((e,))
    return tuple(entries)


def weight_update_spec(shape, base_spec, axes, axis_sizes):
    """PartitionSpec of a weight's fp32 master / grad / optimizer slots
    under weight-update sharding: `base_spec` (the plan's compute
    placement) with the update `axes` appended onto the dim
    `choose_update_dim` picks. None when the weight is not shardable
    (stays replicated — partial coverage is fine; the update there is the
    replicated baseline, still bit-identical)."""
    from jax.sharding import PartitionSpec

    assignment = _spec_assignment(base_spec, len(shape))
    dim = choose_update_dim(shape, assignment, axes, axis_sizes)
    if dim is None:
        return None
    entries = []
    for i, entry in enumerate(assignment):
        merged = entry + tuple(axes) if i == dim else entry
        if not merged:
            entries.append(None)
        elif len(merged) == 1:
            entries.append(merged[0])
        else:
            entries.append(tuple(merged))
    return PartitionSpec(*entries)


def _rs_local(x, *, axis_name: str, n: int, overlap: bool):
    """Per-shard ring reduce-scatter body: `x` (m, ...) is this shard's
    full local contribution; returns the (m/n, ...) chunk this shard owns
    of the cross-shard sum. The packet destined for chunk c starts on
    shard (c+1) mod n and travels n−1 hops, accumulating each host's
    local chunk c — the double-buffered idiom of
    parallel/ring_attention.py: each hop has no data dependence on the
    local chunk slice/add beside it, so the latency-hiding scheduler
    overlaps them. `overlap=False` is the serial hop-THEN-add ablation —
    forced with an optimization barrier, because XLA schedules by data
    dependence, not trace order (merely reordering the statements would
    compile to the identical program)."""
    import jax

    idx = jax.lax.axis_index(axis_name)
    m = x.shape[0]
    chunk = m // n
    perm = ring_permutation(n)

    def take(src, c):
        return jax.lax.dynamic_slice_in_dim(src, c * chunk, chunk, axis=0)

    acc = take(x, jax.lax.rem(idx - 1 + n, n))
    for t in range(1, n):
        moved = jax.lax.ppermute(acc, axis_name, perm)
        src = x
        if not overlap:
            # serialize: the barrier makes the local slice depend on the
            # hop's arrival, so the add cannot issue behind the permute
            moved, src = jax.lax.optimization_barrier((moved, x))
        acc = moved + take(src, jax.lax.rem(idx - 1 - t + 2 * n, n))
    return acc


def ring_reduce_scatter(x, *, mesh=None, axis_name: str | None = None,
                        overlap: bool = True):
    """Decomposed reduce-scatter over `axis_name`: `x` (n·m, ...) holds
    each shard's full local contribution along dim 0 (sharded n-ways);
    returns the (m, ...) cross-shard sum scattered along the same axis —
    the explicit overlappable twin of the reduce-scatter GSPMD emits for
    the sharded weight update, scheduled as n−1 double-buffered ppermute
    hops (the grad-sync ablation in bench.py measures exactly this
    schedule against the serial one). Falls back to a plain psum-free
    identity when there is no mesh / the axis has size 1."""
    import functools

    from jax.sharding import PartitionSpec as P

    from ..machine import AXIS_DATA
    from .smap import shard_map

    axis_name = axis_name or AXIS_DATA
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        return x
    n = mesh.shape[axis_name]
    if x.shape[0] % (n * n) != 0:
        raise ValueError(
            f"ring_reduce_scatter: dim 0 of {x.shape} must divide by "
            f"{axis_name!r} size {n} twice (local chunking)")
    nd = x.ndim
    fn = shard_map(
        functools.partial(_rs_local, axis_name=axis_name, n=n,
                          overlap=overlap),
        mesh=mesh,
        in_specs=(P(axis_name, *([None] * (nd - 1))),),
        out_specs=P(axis_name, *([None] * (nd - 1))),
        check_vma=False,
    )
    return fn(x)


def _ag_local(x, *, axis_name: str, n: int, dim: int, overlap: bool):
    """Per-shard ring all-gather body: `x` is this shard's chunk along
    `dim`; returns the full concatenation of every shard's chunk in
    shard-index order — the gather twin of `_rs_local`. Hop t+1 has no
    data dependence on the local chunk write beside it (the write
    consumes the block that already arrived), so the latency-hiding
    scheduler issues each hop BEFORE the use of the block it carries —
    the same double-buffered idiom as ring attention. `overlap=False` is
    the serial ablation: the barrier makes each hop depend on the
    previous local write, so the ring serializes hop-then-write."""
    import jax
    import jax.numpy as jnp

    idx = jax.lax.axis_index(axis_name)
    chunk = x.shape[dim]
    perm = ring_permutation(n)
    shape = x.shape[:dim] + (n * chunk,) + x.shape[dim + 1:]
    out = jnp.zeros(shape, x.dtype)
    out = jax.lax.dynamic_update_slice_in_dim(out, x, idx * chunk, axis=dim)
    blk = x
    for t in range(1, n):
        moved = jax.lax.ppermute(blk, axis_name, perm)
        if not overlap:
            moved, out = jax.lax.optimization_barrier((moved, out))
        src = jax.lax.rem(idx - t + n, n)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, moved, src * chunk, axis=dim)
        blk = moved
    return out


def ring_all_gather(x, *, mesh=None, axis_name: str | None = None,
                    dim: int = 0, overlap: bool = True,
                    in_spec=None, out_spec=None):
    """Decomposed all-gather over `axis_name`: `x` sharded along `dim`
    over the axis; returns the full array replicated over that axis —
    the RS twin of `ring_reduce_scatter`, scheduled as n−1
    double-buffered ppermute hops (hop-before-use). This is the explicit
    overlappable form of the param gather the ZeRO-3 (stage-3) executor
    issues per layer; `overlap=False` is the serial ablation
    (--no-overlap-collectives) and bench.py's microbench baseline.

    `in_spec`/`out_spec` optionally carry the tensor's OTHER mesh axes
    through the shard_map unchanged (a weight whose update dim merges
    ('model', 'data') gathers only 'data'; the update axes sit minor on
    the dim — weight_update_spec appends them — so chunks concatenate in
    ring order within each outer shard). Falls back to the identity when
    there is no mesh / the axis has size 1."""
    import functools

    from jax.sharding import PartitionSpec as P

    from ..machine import AXIS_DATA
    from .smap import shard_map

    axis_name = axis_name or AXIS_DATA
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        return x
    n = mesh.shape[axis_name]
    nd = x.ndim
    if in_spec is None:
        in_spec = P(*([None] * dim), axis_name, *([None] * (nd - dim - 1)))
    if out_spec is None:
        out_spec = P(*([None] * nd))
    fn = shard_map(
        functools.partial(_ag_local, axis_name=axis_name, n=n, dim=dim,
                          overlap=overlap),
        mesh=mesh,
        in_specs=(in_spec,),
        out_specs=out_spec,
        check_vma=False,
    )
    return fn(x)


def derive_parallel_assignment(op_type: OT, params, in_assignment, mesh):
    """Mesh-axis assignment for an explicit parallel-op node's output, derived
    from its input's assignment (the runtime half of the op: the executor pins
    the output with this spec, producing the resharding collective).

    Repartition picks the first mesh axis whose size equals the requested
    degree and which the tensor doesn't already use — the analog of the
    mapper choosing fresh devices for a higher-degree machine view."""
    a = [list(x) for x in in_assignment]
    declared = tuple(getattr(params, "axes", ()))
    if op_type == OT.OP_REPARTITION:
        if declared:
            # the rewrite named its axes (MachineView binding): use them —
            # but a mesh axis may shard a tensor at most once (same check
            # as the inference path's "unused axis" scan)
            used = {ax for entry in a for ax in entry}
            dup = used.intersection(declared)
            if dup or len(set(declared)) != len(declared):
                raise ValueError(
                    f"repartition(axes={declared}): axes already sharding "
                    f"this tensor ({sorted(used)})")
            a[params.dim].extend(declared)
        else:
            used = {ax for entry in a for ax in entry}
            for name, size in mesh.shape.items():
                if size == params.degree and name not in used:
                    a[params.dim].append(name)
                    break
            else:
                raise ValueError(
                    f"repartition(degree={params.degree}): no unused mesh "
                    f"axis of that size in {dict(mesh.shape)}"
                )
    elif op_type == OT.OP_COMBINE:
        if declared and a[params.dim][-len(declared):] == list(declared):
            del a[params.dim][-len(declared):]
        else:
            removed = 1
            while removed < params.degree and a[params.dim]:
                removed *= mesh.shape[a[params.dim].pop()]
            if removed != params.degree:
                raise ValueError(
                    f"combine(degree={params.degree}) cannot unshard "
                    f"assignment {in_assignment[params.dim]} over "
                    f"{dict(mesh.shape)}"
                )
    elif op_type == OT.OP_FUSED_PARALLEL:
        cur = tuple(tuple(x) for x in a)
        for info in params.ops:
            sub = _INFO_PARAMS.get(info.op_type)
            if sub is not None:
                cur = derive_parallel_assignment(
                    info.op_type, sub(info), cur, mesh
                )
        return cur
    # Replicate / Reduction / Pipeline: replication and partial-sum state are
    # implicit under GSPMD; the assignment passes through unchanged.
    return tuple(tuple(x) for x in a)
