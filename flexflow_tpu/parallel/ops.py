"""Parallelization operators: Repartition, Combine, Replicate, Reduction,
FusedParallelOp, Pipeline.

Reference: src/parallel_ops/{partition,combine,replicate,reduction,
fused_parallel_op}.cc — each is a PCG node that changes a tensor's
parallelization state (per-dim degree / replica dims) and whose execution is
data movement (Legion partition copies, SURVEY §2.3).

TPU-native lowering: the *runtime* body of every parallel op is the identity —
the executor pins each node's output with `with_sharding_constraint`, so the
degree change becomes an XLA collective over ICI exactly where the reference
would launch a partition-copy task:

  Repartition (degree up on dim d)  → resharding: dynamic-slice / all_to_all
  Combine     (degree down on dim d)→ all_gather along the freed mesh axis
  Replicate   (new replica dim)     → broadcast (implicit in GSPMD)
  Reduction   (drop replica dim)    → psum / reduce_scatter (inserted by XLA
                                      when the producer's contraction was
                                      sharded over the reduced axis)

The *IR-level* shape transform (apply_parallel_op_shape) is what Unity search
rewrites operate on, and the cost model charges the communication bytes these
transforms imply (see search/cost_model.py).

The reference leaves OP_PIPELINE as an enum with no implementation
(ffconst.h:159, SURVEY §2.3); here PipelineParams is likewise a stage
MARKER only (runtime identity — enum parity). Working pipeline parallelism
lives in the OP_PIPE_BLOCKS op instead: stacked homogeneous blocks whose
layer dim shards over the `pipe` mesh axis, scheduled as a
`jax.lax.ppermute` fill/drain microbatch pipeline (parallel/pipeline.py) —
the capability the reference never implemented.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Tuple

from ..fftype import OperatorType as OT
from ..tensor import ParallelDim, ParallelTensorShape
from ..ops.base import OpDef, register_op


@dataclass(frozen=True)
class RepartitionParams:
    """Increase partition degree along `dim` by `degree`×
    (partition.cc:132 create_input_partition). `axes` optionally names the
    mesh axes the new degree rides (their size product must equal
    `degree`) — the MachineView device binding; empty = inferred from the
    degree at assignment time."""

    dim: int
    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class CombineParams:
    """Decrease partition degree along `dim` by `degree`× (combine.cc:135).
    `axes` optionally names the mesh axes being freed."""

    dim: int
    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ReplicateParams:
    """Add a replica dim of extent `degree` (replicate.cc). `axes`
    optionally names the mesh axes the replicas map onto."""

    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ReductionParams:
    """Sum-reduce a replica dim of extent `degree` (reduction.cc: forward
    kernel sums num_replicas slices — here XLA's psum). `axes` optionally
    names the mesh axes summed over."""

    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class PipelineParams:
    """Stage boundary marker. OP_PIPELINE is enum-only in the reference."""

    stage: int = 0


@dataclass(frozen=True)
class ParallelOpInfo:
    op_type: OT
    dim: int
    degree: int
    axes: Tuple[str, ...] = ()


@dataclass(frozen=True)
class FusedParallelOpParams:
    """Sequence of parallel transforms fused into one resharding
    (fused_parallel_op.cc)."""

    ops: Tuple[ParallelOpInfo, ...]


def apply_parallel_op_shape(
    shape: ParallelTensorShape, op_type: OT, params
) -> ParallelTensorShape:
    """IR shape transform for one parallel op (search rewrites use this)."""
    dims = list(shape.dims)
    axes = getattr(params, "axes", ())
    if op_type == OT.OP_REPARTITION:
        d = dims[params.dim]
        dims[params.dim] = replace(d, degree=d.degree * params.degree,
                                   axes=d.axes + tuple(axes))
    elif op_type == OT.OP_COMBINE:
        d = dims[params.dim]
        if d.degree % params.degree != 0:
            raise ValueError(
                f"combine degree {params.degree} does not divide {d.degree}"
            )
        new_axes = d.axes
        if axes and new_axes[-len(axes):] == tuple(axes):
            new_axes = new_axes[:-len(axes)]
        elif d.degree // params.degree == 1:
            new_axes = ()
        dims[params.dim] = replace(d, degree=d.degree // params.degree,
                                   axes=new_axes)
    elif op_type == OT.OP_REPLICATE:
        dims.append(
            ParallelDim(
                size=params.degree, degree=params.degree,
                is_replica_dim=True, axes=tuple(axes)
            )
        )
    elif op_type == OT.OP_REDUCTION:
        for i in range(len(dims) - 1, -1, -1):
            if dims[i].is_replica_dim:
                if dims[i].degree != params.degree:
                    raise ValueError(
                        f"reduction degree {params.degree} != replica degree "
                        f"{dims[i].degree}"
                    )
                dims.pop(i)
                break
        else:
            raise ValueError("reduction with no replica dim")
    elif op_type == OT.OP_FUSED_PARALLEL:
        s = shape
        for info in params.ops:
            sub = _INFO_PARAMS[info.op_type](info)
            s = apply_parallel_op_shape(s, info.op_type, sub)
        return s
    elif op_type == OT.OP_PIPELINE:
        pass
    else:
        raise ValueError(f"not a parallel op: {op_type}")
    return ParallelTensorShape(tuple(dims), shape.dtype)


_INFO_PARAMS = {
    OT.OP_REPARTITION: lambda i: RepartitionParams(i.dim, i.degree, i.axes),
    OT.OP_COMBINE: lambda i: CombineParams(i.dim, i.degree, i.axes),
    OT.OP_REPLICATE: lambda i: ReplicateParams(i.degree, i.axes),
    OT.OP_REDUCTION: lambda i: ReductionParams(i.degree, i.axes),
}


def _identity_infer(params, in_shapes):
    return [in_shapes[0]]


def _identity_forward(params, inputs, weights, state, ctx):
    # Runtime body is the identity: the executor's sharding constraint on the
    # node's output performs the actual resharding (ICI collective).
    return [inputs[0]], state


def _zero_flops(params, in_shapes, out_shapes):
    return 0.0


for _ot in (
    OT.OP_REPARTITION,
    OT.OP_COMBINE,
    OT.OP_REPLICATE,
    OT.OP_REDUCTION,
    OT.OP_PIPELINE,
    OT.OP_FUSED_PARALLEL,
):
    register_op(
        OpDef(_ot, _identity_infer, _identity_forward, flops=_zero_flops)
    )


# ------------------------------------------------- decomposed collective matmul
# The async/overlapped twin of the tp all_gather→matmul pairs GSPMD inserts
# when a feature-sharded activation feeds an op expecting the full feature
# dim (tp_col after a feat/sp producer, the attention O-projection after a
# head-sharded core). Instead of one blocking all_gather followed by one
# big matmul, the gather is DECOMPOSED into n−1 neighbor hops each
# overlapped with the partial matmul of the block already resident (Wang
# et al., ASPLOS '23 — the same double-buffered ppermute schedule as
# parallel/ring_attention.py): while x's block k rotates to the neighbor,
# the local MXU contracts block k against the matching rows of w. Exact:
# after n steps every shard has accumulated Σ_src x_src @ w[src rows] =
# (all_gather(x) @ w), with the collective entirely hidden behind compute
# when the per-block matmul dominates the hop (the long-seq regime).


def _ag_matmul_local(x_blk, w, *, axis_name: str, n: int, overlap: bool):
    """Per-shard body: x_blk (..., k/n) is this shard's block of the
    contraction dim; w (k, m) holds all rows locally. Rotate x blocks
    around the ring, contracting each against its source's row slice."""
    import jax
    import jax.numpy as jnp

    idx = jax.lax.axis_index(axis_name)
    k_loc = x_blk.shape[-1]
    acc = jnp.zeros(x_blk.shape[:-1] + (w.shape[-1],), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        x_nxt = None
        if overlap and step < n - 1:
            # hop for block step+1 issued BEFORE the matmul of block step
            x_nxt = jax.lax.ppermute(x_blk, axis_name, perm)
        # the block held at `step` originated on shard (idx - step) mod n;
        # contract it against that shard's rows of w
        src = jax.lax.rem(idx - step + n, n)
        w_rows = jax.lax.dynamic_slice_in_dim(w, src * k_loc, k_loc, axis=0)
        acc = acc + jnp.dot(x_blk, w_rows.astype(x_blk.dtype),
                            preferred_element_type=jnp.float32)
        if step < n - 1:
            if not overlap:
                x_nxt = jax.lax.ppermute(x_blk, axis_name, perm)
            x_blk = x_nxt
    return acc.astype(x_blk.dtype)


def allgather_matmul(x, w, *, mesh=None, axis_name: str | None = None,
                     batch_axis: str | None = None, overlap: bool = True):
    """Decomposed all_gather→matmul: `x` (..., k) with its last dim sharded
    over `axis_name`, `w` (k, m) replicated along that axis; returns the
    full x @ w (replicated over `axis_name`, batch sharding preserved) —
    numerically the gathered matmul, scheduled as n overlapped
    block-matmul + ppermute steps. Falls back to a plain dot when there is
    no mesh / the axis has size 1. `overlap=False` is the serial ablation
    baseline (hop after each block's matmul)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..machine import AXIS_DATA, AXIS_MODEL
    from .smap import shard_map

    axis_name = axis_name or AXIS_MODEL
    batch_axis = batch_axis or AXIS_DATA
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        return jnp.dot(x, w.astype(x.dtype),
                       preferred_element_type=jnp.float32).astype(x.dtype)
    n = mesh.shape[axis_name]
    if x.shape[-1] % n != 0:
        raise ValueError(
            f"allgather_matmul: contraction dim {x.shape[-1]} not "
            f"divisible by axis {axis_name!r} size {n}")
    import functools

    nd = x.ndim
    b_entry = batch_axis if mesh.shape.get(batch_axis, 1) > 1 else None
    xspec = P(b_entry, *([None] * (nd - 2)), axis_name)
    ospec = P(b_entry, *([None] * (nd - 1)))
    fn = shard_map(
        functools.partial(_ag_matmul_local, axis_name=axis_name, n=n,
                          overlap=overlap),
        mesh=mesh,
        in_specs=(xspec, P(None, None)),
        out_specs=ospec,
        check_vma=False,
    )
    return fn(x, w)


def derive_parallel_assignment(op_type: OT, params, in_assignment, mesh):
    """Mesh-axis assignment for an explicit parallel-op node's output, derived
    from its input's assignment (the runtime half of the op: the executor pins
    the output with this spec, producing the resharding collective).

    Repartition picks the first mesh axis whose size equals the requested
    degree and which the tensor doesn't already use — the analog of the
    mapper choosing fresh devices for a higher-degree machine view."""
    a = [list(x) for x in in_assignment]
    declared = tuple(getattr(params, "axes", ()))
    if op_type == OT.OP_REPARTITION:
        if declared:
            # the rewrite named its axes (MachineView binding): use them —
            # but a mesh axis may shard a tensor at most once (same check
            # as the inference path's "unused axis" scan)
            used = {ax for entry in a for ax in entry}
            dup = used.intersection(declared)
            if dup or len(set(declared)) != len(declared):
                raise ValueError(
                    f"repartition(axes={declared}): axes already sharding "
                    f"this tensor ({sorted(used)})")
            a[params.dim].extend(declared)
        else:
            used = {ax for entry in a for ax in entry}
            for name, size in mesh.shape.items():
                if size == params.degree and name not in used:
                    a[params.dim].append(name)
                    break
            else:
                raise ValueError(
                    f"repartition(degree={params.degree}): no unused mesh "
                    f"axis of that size in {dict(mesh.shape)}"
                )
    elif op_type == OT.OP_COMBINE:
        if declared and a[params.dim][-len(declared):] == list(declared):
            del a[params.dim][-len(declared):]
        else:
            removed = 1
            while removed < params.degree and a[params.dim]:
                removed *= mesh.shape[a[params.dim].pop()]
            if removed != params.degree:
                raise ValueError(
                    f"combine(degree={params.degree}) cannot unshard "
                    f"assignment {in_assignment[params.dim]} over "
                    f"{dict(mesh.shape)}"
                )
    elif op_type == OT.OP_FUSED_PARALLEL:
        cur = tuple(tuple(x) for x in a)
        for info in params.ops:
            sub = _INFO_PARAMS.get(info.op_type)
            if sub is not None:
                cur = derive_parallel_assignment(
                    info.op_type, sub(info), cur, mesh
                )
        return cur
    # Replicate / Reduction / Pipeline: replication and partial-sum state are
    # implicit under GSPMD; the assignment passes through unchanged.
    return tuple(tuple(x) for x in a)
