"""Pipeline parallelism over the `pipe` mesh axis.

The reference leaves OP_PIPELINE as an enum with no implementation
(ffconst.h:159, SURVEY §2.3) — this module EXCEEDS reference capability with
a working microbatched pipeline: L homogeneous blocks (stacked weights,
leading dim L) are split into P = |pipe| stages; inside `shard_map` each
stage holds its L/P layers, activations hop stage-to-stage via
`jax.lax.ppermute` over neighbor ICI links, and a `lax.scan` over
M + P - 1 ticks runs the classic fill/steady/drain schedule with M
microbatches in flight.

Schedule note: the forward is the GPipe fill-drain order; the backward is
its exact autodiff transpose (reverse fill-drain — ppermute's transpose
reverses the ring), so gradients are EXACT w.r.t. the unpipelined
computation. A literal 1F1B interleave of fwd/bwd microbatches (a
memory-scheduling refinement, not a numerics change) would need a custom
VJP schedule; activation memory is instead bounded the standard JAX way —
wrap `block_fn` in `jax.checkpoint` (pipeline_blocks does).

Invalid-slot routing: during fill/drain every stage still executes its
block on placeholder data (SPMD executes everywhere), but placeholder
outputs only ever reach placeholder slots and the final emission selects
valid microbatches, so numerics — forward and backward — match the
sequential computation exactly (verified in tests/test_pipeline.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..machine import AXIS_DATA, AXIS_PIPE
from .smap import shard_map


def _sequential(stacked, x, block_fn):
    """Reference semantics: apply the L stacked blocks in order."""
    def step(a, w_one):
        return block_fn(w_one, a), None

    out, _ = jax.lax.scan(step, x, stacked)
    return out


def _pipelined_local(stacked_shard, x, *, block_fn, axis_name: str,
                     num_stages: int, num_micro: int):
    """Per-stage body (inside shard_map). stacked_shard: this stage's
    (L/P, ...) weights; x: (b_local, ...) activations (replicated over the
    pipe axis)."""
    p_idx = jax.lax.axis_index(axis_name)
    b = x.shape[0]
    m = num_micro
    if b % m != 0:
        raise ValueError(
            f"pipeline: local batch {b} does not divide into "
            f"{m} microbatches (global batch must be a multiple of "
            f"data-axis size × num_microbatches)")
    mb = b // m
    mbs = x.reshape((m, mb) + x.shape[1:])

    def stage(a):
        def layer(a, w_one):
            return block_fn(w_one, a), None

        out, _ = jax.lax.scan(layer, a, stacked_shard)
        return out

    # stage p -> p+1 hops; stage 0 receives zeros (unused: it reads fresh
    # microbatches), the last stage's output leaves the ring via `emit`
    perm = [(i, i + 1) for i in range(num_stages - 1)]
    ticks = m + num_stages - 1

    def tick(buf, t):
        mb_idx = jnp.clip(t, 0, m - 1)
        my_in = jnp.where(p_idx == 0, mbs[mb_idx], buf)
        out = stage(my_in)
        nxt = jax.lax.ppermute(out, axis_name, perm)
        return nxt, out

    _, emits = jax.lax.scan(tick, jnp.zeros_like(mbs[0]),
                            jnp.arange(ticks))
    # the last stage's emissions at ticks P-1 .. P-1+M-1 are microbatches
    # 0 .. M-1; other stages' emissions are placeholder data
    y = emits[num_stages - 1:].reshape(x.shape)
    y = jax.lax.psum(
        jnp.where(p_idx == num_stages - 1, y, jnp.zeros_like(y)),
        axis_name,
    )
    return y


def pipeline_apply(
    stacked, x, block_fn, *,
    mesh: Mesh | None = None,
    num_microbatches: int = 0,
    axis_name: str = AXIS_PIPE,
    batch_axis: str = AXIS_DATA,
):
    """Apply L stacked homogeneous blocks to x, pipelined over `axis_name`
    when the mesh has one (falls back to the sequential scan otherwise —
    the two paths are numerically identical).

    stacked: pytree whose leaves all have leading dim L (block index);
    x: (batch, ...) global array; block_fn(one_block_weights, x) -> x'.
    num_microbatches 0 → 2·P (double-buffered steady state); the local
    batch must divide by it."""
    num_layers = jax.tree.leaves(stacked)[0].shape[0]
    if mesh is None or mesh.shape.get(axis_name, 1) <= 1:
        return _sequential(stacked, x, block_fn)
    p = mesh.shape[axis_name]
    if num_layers % p != 0:
        raise ValueError(
            f"pipeline: {num_layers} blocks do not divide over "
            f"{p} pipeline stages")
    m = num_microbatches or 2 * p

    w_spec = jax.tree.map(lambda _: P(axis_name), stacked)
    x_spec = P(batch_axis if mesh.shape.get(batch_axis, 1) > 1 else None)
    fn = shard_map(
        functools.partial(
            _pipelined_local, block_fn=block_fn, axis_name=axis_name,
            num_stages=p, num_micro=m,
        ),
        mesh=mesh,
        in_specs=(w_spec, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )
    return fn(stacked, x)
