"""Ring attention: sequence/context parallelism over the ICI ring.

The reference has **no long-context support** (SURVEY §5: "no ring attention,
no Ulysses"); its only sequence notion is a seq_length iteration config. This
module provides the TPU-native capability the reference lacks: queries stay
resident on their sequence shard while K/V blocks rotate around the `seq`
mesh axis via `jax.lax.ppermute`, overlapping each hop with the local
block-attention compute. Combined across steps with the same online-softmax
(running max / denominator) used by flash attention, the result is exact
attention over the full sequence with per-chip memory O(s_local · d) and
communication that rides neighbor-to-neighbor ICI links only.

Used by MultiHeadAttention(impl="ring") together with the
`sequence_parallel_attention` strategy (seq dim sharded over AXIS_SEQ).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..machine import AXIS_DATA, AXIS_MODEL, AXIS_SEQ

shard_map = jax.shard_map


def _ring_local(q, k, v, *, axis_name: str, n: int, causal: bool,
                scale: float):
    """Per-shard body (inside shard_map). q,k,v: (b, h, s_local, d) local.

    Unrolled over the `n` ring steps (n = seq-axis size, small and static) so
    XLA can overlap each collective-permute with the previous block's
    compute, and the final rotation — whose result would be discarded — is
    skipped entirely."""
    idx = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    qf = q.astype(jnp.float32)

    m = jnp.full((b, h, s_loc), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, s_loc), jnp.float32)
    o = jnp.zeros((b, h, s_loc, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_blk, v_blk = k, v

    for step in range(n):
        # the block we hold at `step` originated on shard (idx - step) mod n
        src = jax.lax.rem(idx - step + n, n)
        logits = jnp.einsum(
            "bhqd,bhkd->bhqk", qf, k_blk.astype(jnp.float32)
        ) * scale
        if causal:
            q_pos = idx * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, (s_loc, s_loc), 0
            )
            k_pos = src * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, (s_loc, s_loc), 1
            )
            mask = q_pos >= k_pos  # (s_loc, s_loc) with global offsets
            logits = jnp.where(mask[None, None], logits, -1e30)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        # guard fully-masked steps: keep contributions zero until live
        p = jnp.exp(logits - m_new[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        alpha = jnp.where(
            jnp.isfinite(m), jnp.exp(m - m_new), jnp.zeros_like(m)
        )
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32)
        )
        m = m_new
        if step < n - 1:
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)

    return (o / l[..., None]).astype(q.dtype)


def ring_attention(
    q, k, v, *, causal: bool = False, scale: float | None = None,
    mesh: Mesh | None = None, axis_name: str = AXIS_SEQ,
    batch_axis: str = AXIS_DATA, head_axis: str = AXIS_MODEL,
):
    """Exact attention with the seq dim sharded over `axis_name`.

    q,k,v: (batch, heads, seq, head_dim) global arrays (call under jit).
    Falls back to single-shard attention when no mesh / seq axis size 1."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        from ..ops.attention import sdpa_xla

        return sdpa_xla(q, k, v, causal=causal, scale=scale)

    spec = P(
        batch_axis if mesh.shape.get(batch_axis, 1) > 1 else None,
        head_axis if mesh.shape.get(head_axis, 1) > 1 else None,
        axis_name,
        None,
    )
    fn = shard_map(
        functools.partial(
            _ring_local, axis_name=axis_name, n=mesh.shape[axis_name],
            causal=causal, scale=scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
